#!/usr/bin/env python
"""Headline benchmark: the full Aiyagari Table II sweep (σ ∈ {1,3,5} ×
ρ ∈ {0, 0.3, 0.6, 0.9} — 12 general-equilibrium solves) as one batched XLA
program on the local device(s).

Baseline: the reference solves ONE calibration cell in 27.12 min
(``economy.solve()``, notebook cell 19 output; BASELINE.md) and runs Table II
by editing the notebook one cell at a time (SURVEY.md §2.4), so the
reference-equivalent work is 12 × 1627.2 s.  ``vs_baseline`` is the speedup
factor (baseline seconds / measured seconds).

Defensive by design (round-1 post-mortem, VERDICT.md): the axon TPU tunnel
can hang backend *initialization* indefinitely, so the ambient backend is
probed in a SUBPROCESS with a timeout before this process ever touches a
device; on probe failure or repeated runtime faults the bench falls back to
CPU and still emits its JSON line with a ``backend`` field.

Prints ONE JSON line:
  {"metric": "table2_sweep_wall_s", "value": <s>, "unit": "s",
   "vs_baseline": <speedup>, "backend": "...", "n_devices": N,
   "egm_gridpoints_per_sec_per_chip": ..., "r_star_f32_f64_max_bp": ...,
   "iteration_skew": ..., "compile_s": ...}

Extra BASELINE.md tracked metrics carried as fields on the same line:
 - ``egm_gridpoints_per_sec_per_chip``: total EGM work / wall / chips, where
   one EGM backward step touches a_count × labor_states policy knots
   (SURVEY.md §3.2's hot loop, minus the degenerate 4× aggregate-state
   duplication this framework eliminates).
 - ``r_star_f32_f64_max_bp``: max over the 12 cells of |r*(this backend,
   f32) − r*(CPU, f64 oracle)| in basis points — the 1 bp equivalence line
   (BASELINE.md).  The oracle runs in a subprocess because a TPU process
   cannot host a float64 backend.
"""

import json
import os
import subprocess
import sys
import time

REFERENCE_CELL_SECONDS = 27.12 * 60.0   # notebook cell 19 (BASELINE.md)
N_CELLS = 12
A_COUNT = 32
LABOR_STATES = 7
SWEEP_KWARGS = dict(a_count=A_COUNT, dist_count=500)

_ORACLE_CODE = """
import json, jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
from aiyagari_hark_tpu.utils.backend import enable_compilation_cache
enable_compilation_cache()
import jax.numpy as jnp
from aiyagari_hark_tpu.parallel.sweep import run_table2_sweep
from aiyagari_hark_tpu.utils.config import SweepConfig
res = run_table2_sweep(SweepConfig(), dtype=jnp.float64, **{kwargs!r})
print("ORACLE=" + json.dumps([float(x) for x in res.r_star_pct]))
"""


def _repo_dir() -> str:
    return os.path.dirname(os.path.abspath(__file__))


def _probe_default_backend(timeout_s: float = 120.0):
    from aiyagari_hark_tpu.utils.backend import probe_ambient_backend
    return probe_ambient_backend(timeout_s)


def _force_cpu() -> None:
    from aiyagari_hark_tpu.utils.backend import force_cpu_platform
    force_cpu_platform()


def _oracle_r_star(timeout_s: float = 1800.0):
    """The 12-cell r* vector from the CPU float64 oracle (subprocess), or
    None if it failed — the bench must not die with the oracle."""
    code = _ORACLE_CODE.format(kwargs=SWEEP_KWARGS)
    try:
        out = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=timeout_s, cwd=_repo_dir())
    except subprocess.TimeoutExpired:
        print("[bench] CPU f64 oracle timed out", file=sys.stderr)
        return None
    for line in out.stdout.splitlines():
        if line.startswith("ORACLE="):
            return json.loads(line.split("=", 1)[1])
    print(f"[bench] CPU f64 oracle failed:\n{out.stderr[-800:]}",
          file=sys.stderr)
    return None


def main():
    from aiyagari_hark_tpu.utils.backend import enable_compilation_cache
    from aiyagari_hark_tpu.utils.timing import PhaseTimer, device_trace

    cache_dir = enable_compilation_cache()
    print(f"[bench] persistent compilation cache: {cache_dir}",
          file=sys.stderr)
    timer = PhaseTimer()
    with timer.phase("probe"):
        ambient = _probe_default_backend()
    if ambient is None:
        print("[bench] ambient backend probe hung/failed -> forcing CPU",
              file=sys.stderr)
        _force_cpu()
    else:
        print(f"[bench] ambient backend probe: {ambient}", file=sys.stderr)

    import jax

    from aiyagari_hark_tpu.parallel.sweep import (_batched_solver,
                                                  run_table2_sweep)
    from aiyagari_hark_tpu.utils.config import SweepConfig

    sweep = SweepConfig()   # full Table II: 3 sigmas x 4 rhos
    trace_dir = os.environ.get("AIYAGARI_TRACE_DIR")

    # The axon TPU tunnel intermittently faults on first execution of a
    # freshly compiled program; retry with cleared caches, and fall back to
    # CPU for the final attempt so the round always records a number.
    # Attempt 2 pins dist_method="scatter" so a Pallas-kernel compile
    # problem on an accelerator cannot cost the accelerator number.
    attempts = 4
    res = None
    backend = "unknown"
    n_devices = 0
    for attempt in range(attempts):
        kwargs = dict(SWEEP_KWARGS)
        if attempt == 1:
            kwargs["dist_method"] = "scatter"
        try:
            backend = jax.default_backend()   # inside the loop: init may fail
            n_devices = len(jax.devices())
            print(f"[bench] attempt {attempt + 1}/{attempts}: "
                  f"backend={backend} devices={n_devices} "
                  f"kwargs={kwargs}", file=sys.stderr)
            # compile_s must describe the backend this attempt runs on, not
            # accumulate failed attempts on a different backend
            timer.seconds.pop("compile", None)
            timer.counts.pop("compile", None)
            with timer.phase("compile"):
                run_table2_sweep(sweep, **kwargs)   # compile + warm-up
            with timer.phase("sweep"), device_trace(trace_dir):
                res = run_table2_sweep(sweep, **kwargs)  # timed, cached
            break
        except Exception as e:   # noqa: BLE001 — device faults surface as
            # JaxRuntimeError; anything else is equally fatal for a bench run
            print(f"[bench] attempt {attempt + 1}/{attempts} failed: "
                  f"{type(e).__name__}: {str(e)[:300]}", file=sys.stderr)
            try:
                jax.clear_caches()
                _batched_solver.cache_clear()
            except Exception:   # noqa: BLE001 — cache teardown is best-effort
                pass
            if attempt == attempts - 2:
                print("[bench] falling back to CPU for final attempt",
                      file=sys.stderr)
                _force_cpu()
            time.sleep(5.0 * (attempt + 1))
    if res is None:
        print("[bench] all attempts failed (including CPU fallback)",
              file=sys.stderr)
        sys.exit(1)
    wall = res.wall_seconds

    # EGM throughput: knots touched per backward step x total steps summed
    # over all 12 cells' bisection midpoints, per second per chip.
    total_egm_steps = float(res.egm_iters.sum())
    gridpoints_per_sec_per_chip = (
        total_egm_steps * A_COUNT * LABOR_STATES / wall / max(n_devices, 1))

    with timer.phase("oracle_f64"):
        oracle = _oracle_r_star()
    if oracle is not None:
        # r* is in percent; 1 bp = 0.01 percentage points.
        max_bp = max(abs(a - b) for a, b in
                     zip([float(x) for x in res.r_star_pct], oracle)) * 100.0
    else:
        max_bp = None

    baseline = REFERENCE_CELL_SECONDS * N_CELLS
    print(f"[bench] phase breakdown:\n{timer.summary()}", file=sys.stderr)
    print(f"[bench] Table II r* (%):\n{res.table()}", file=sys.stderr)
    print(f"[bench] per-cell work (egm+dist steps): "
          f"{res.total_work().tolist()} skew={res.iteration_skew():.2f}",
          file=sys.stderr)
    print(json.dumps({
        "metric": "table2_sweep_wall_s",
        "value": round(wall, 4),
        "unit": "s",
        "vs_baseline": round(baseline / wall, 1),
        "backend": backend,
        "n_devices": n_devices,
        "egm_gridpoints_per_sec_per_chip": round(gridpoints_per_sec_per_chip),
        "r_star_f32_f64_max_bp": (None if max_bp is None
                                  else round(max_bp, 3)),
        "iteration_skew": round(res.iteration_skew(), 3),
        "compile_s": round(timer.seconds.get("compile", float("nan")), 2),
    }))


if __name__ == "__main__":
    main()
