#!/usr/bin/env python
"""Headline benchmark: the full Aiyagari Table II sweep (σ ∈ {1,3,5} ×
ρ ∈ {0, 0.3, 0.6, 0.9} — 12 general-equilibrium solves) as one batched XLA
program on the local device(s).

Baseline: the reference solves ONE calibration cell in 27.12 min
(``economy.solve()``, notebook cell 19 output; BASELINE.md) and runs Table II
by editing the notebook one cell at a time (SURVEY.md §2.4), so the
reference-equivalent work is 12 × 1627.2 s.  ``vs_baseline`` is the speedup
factor (baseline seconds / measured seconds).

Defensive by design (round-1 post-mortem, VERDICT.md): the axon TPU tunnel
can hang backend *initialization* indefinitely, so the ambient backend is
probed in a SUBPROCESS with a timeout before this process ever touches a
device; on probe failure or repeated runtime faults the bench falls back to
CPU and still emits its JSON line with a ``backend`` field.

Prints ONE JSON line:
  {"metric": "table2_sweep_wall_s", "value": <s>, "unit": "s",
   "vs_baseline": <speedup>, "backend": "...", "n_devices": N,
   "egm_gridpoints_per_sec_per_chip": ..., "r_star_f32_f64_max_bp": ...,
   "iteration_skew": ..., "compile_s": ...}

Extra BASELINE.md tracked metrics carried as fields on the same line:
 - ``egm_gridpoints_per_sec_per_chip``: total EGM work / wall / chips, where
   one EGM backward step touches a_count × labor_states policy knots
   (SURVEY.md §3.2's hot loop, minus the degenerate 4× aggregate-state
   duplication this framework eliminates).
 - ``r_star_f32_f64_max_bp``: max over the 12 cells of |r*(this backend,
   f32) − r*(CPU, f64 oracle)| in basis points — the 1 bp equivalence line
   (BASELINE.md).  The oracle runs in a subprocess because a TPU process
   cannot host a float64 backend.
 - ``flops_per_sec`` / ``mfu_pct``: achieved model FLOP rate of the sweep
   and its percent of chip peak, from the per-cell work counters and the
   per-step FLOP model in ``_sweep_flops`` (VERDICT r2 weak-item 1: the
   notebook-size sweep is latency-bound, MFU << 1% — now a number, not
   prose).
 - ``fine_grid_wall_s`` / ``fine_grid_flops_per_sec`` / ``fine_grid_mfu_pct``:
   the at-scale configuration (BASELINE config 2: 1000-pt assets x 15
   income states, 1000-pt histogram, one GE cell) where the dense
   distribution matmuls actually feed the MXU — previously README prose
   ("0.26 s cached"), now a tracked metric with a regression guard.
"""

import json
import os
import subprocess
import sys
import time

REFERENCE_CELL_SECONDS = 27.12 * 60.0   # notebook cell 19 (BASELINE.md)
N_CELLS = 12
A_COUNT = 32
LABOR_STATES = 7
DIST_COUNT = 500
SWEEP_KWARGS = dict(a_count=A_COUNT, dist_count=DIST_COUNT)
# BASELINE config 2 — the at-scale single-cell GE solve (README/DESIGN §4).
FINE_A_COUNT = 1000
FINE_LABOR_STATES = 15
FINE_DIST_COUNT = 1000


def _model_flops(egm_iters: float, dist_iters: float, a_count: int,
                 n_states: int, d_count: int, dense_dist: bool) -> float:
    """Model FLOPs executed by the counted inner-loop work.

    Per EGM backward step (``household.egm_step``): the expectation matmul
    ``[A,N] x [N,N]`` is 2*A*N^2 FLOPs; interp/elementwise add ~12*A*N.
    Per distribution step: the dense path (``_push_forward_dense``) runs the
    per-state lottery matvecs ``[N,D,D] x [D]`` (2*N*D^2) plus the labor-mix
    matmul ``[D,N] x [N,N]`` (2*D*N^2); the scatter path replaces the D^2
    matvecs with an O(D*N) scatter (~6 FLOPs/point), keeping the mix matmul.
    """
    egm = egm_iters * (2.0 * a_count * n_states ** 2
                       + 12.0 * a_count * n_states)
    per_dist = 2.0 * d_count * n_states ** 2
    per_dist += (2.0 * n_states * d_count ** 2 if dense_dist
                 else 6.0 * d_count * n_states)
    return egm + dist_iters * per_dist


def _peak_flops_per_chip(backend: str) -> float | None:
    """Nominal peak FLOP/s of one chip for the MFU denominator.

    TPU v5-lite (v5e): 197e12 bf16 MXU peak — the honest ceiling even
    though this framework runs f32 matmuls at ``precision=HIGHEST`` (which
    costs multiple bf16 passes), because MFU is about how much of the
    silicon the problem could engage.  CPU gets no MFU (no meaningful
    single-number peak for this host).
    """
    if backend not in ("tpu", "axon"):
        return None
    try:
        import jax
        kind = jax.devices()[0].device_kind.lower()
    except Exception:   # noqa: BLE001 — device query is best-effort
        kind = ""
    if "v5 lite" in kind or "v5e" in kind or "v5lite" in kind:
        return 197e12
    if "v4" in kind:
        return 275e12
    if "v5p" in kind or "v5" in kind:
        return 459e12
    return 197e12   # unknown TPU: assume the v5e class this repo targets

_ORACLE_CODE = """
import json, jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
from aiyagari_hark_tpu.utils.backend import enable_compilation_cache
enable_compilation_cache()
import jax.numpy as jnp
from aiyagari_hark_tpu.parallel.sweep import run_table2_sweep
from aiyagari_hark_tpu.utils.config import SweepConfig
res = run_table2_sweep(SweepConfig(), dtype=jnp.float64, **{kwargs!r})
print("ORACLE=" + json.dumps([float(x) for x in res.r_star_pct]))
"""


def _repo_dir() -> str:
    return os.path.dirname(os.path.abspath(__file__))


def _probe_default_backend(timeout_s: float = 120.0):
    from aiyagari_hark_tpu.utils.backend import probe_ambient_backend
    return probe_ambient_backend(timeout_s)


def _force_cpu() -> None:
    from aiyagari_hark_tpu.utils.backend import force_cpu_platform
    force_cpu_platform()


def _oracle_r_star(timeout_s: float = 1800.0):
    """The 12-cell r* vector from the CPU float64 oracle (subprocess), or
    None if it failed — the bench must not die with the oracle."""
    code = _ORACLE_CODE.format(kwargs=SWEEP_KWARGS)
    try:
        out = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=timeout_s, cwd=_repo_dir())
    except subprocess.TimeoutExpired:
        print("[bench] CPU f64 oracle timed out", file=sys.stderr)
        return None
    for line in out.stdout.splitlines():
        if line.startswith("ORACLE="):
            return json.loads(line.split("=", 1)[1])
    print(f"[bench] CPU f64 oracle failed:\n{out.stderr[-800:]}",
          file=sys.stderr)
    return None


def _fine_grid_metrics(backend: str, timer) -> dict:
    """Time the fine-grid GE solve (compile excluded via a warm-up call) and
    FLOP-account it.  Failures only cost the fine-grid fields — the sweep
    metrics must survive (same defensive posture as the rest of the bench)."""
    import jax

    from aiyagari_hark_tpu.models.equilibrium import solve_calibration_lean

    dist_method = "dense" if backend in ("tpu", "axon") else "auto"
    kwargs = dict(labor_states=FINE_LABOR_STATES, a_count=FINE_A_COUNT,
                  dist_count=FINE_DIST_COUNT, dist_method=dist_method)

    @jax.jit
    def solve_fine():
        r = solve_calibration_lean(1.0, 0.3, **kwargs)
        return r.r_star, r.egm_iters, r.dist_iters

    try:
        with timer.phase("fine_compile"):
            jax.block_until_ready(solve_fine())          # compile + warm-up
        with timer.phase("fine_grid"):
            t0 = time.perf_counter()
            r_star, egm_it, dist_it = jax.block_until_ready(solve_fine())
            fine_wall = time.perf_counter() - t0
    except Exception as e:   # noqa: BLE001 — report sweep metrics regardless
        print(f"[bench] fine-grid cell failed: {type(e).__name__}: "
              f"{str(e)[:300]}", file=sys.stderr)
        return {"fine_grid_wall_s": None, "fine_grid_flops_per_sec": None,
                "fine_grid_mfu_pct": None}

    flops = _model_flops(
        float(egm_it), float(dist_it), FINE_A_COUNT, FINE_LABOR_STATES,
        FINE_DIST_COUNT, dense_dist=(dist_method == "dense"))
    peak = _peak_flops_per_chip(backend)
    mfu = None if peak is None else 100.0 * flops / fine_wall / peak
    print(f"[bench] fine grid ({FINE_A_COUNT}x{FINE_LABOR_STATES}, "
          f"D={FINE_DIST_COUNT}, {dist_method}): r*={float(r_star):.4%} "
          f"wall={fine_wall:.3f}s FLOPs={flops:.3e} "
          f"-> {flops / fine_wall:.3e} FLOP/s"
          + (f" = {mfu:.2f}% of peak" if mfu is not None else ""),
          file=sys.stderr)
    return {"fine_grid_wall_s": round(fine_wall, 4),
            "fine_grid_flops_per_sec": round(flops / fine_wall),
            "fine_grid_mfu_pct": None if mfu is None else round(mfu, 3)}


def main():
    from aiyagari_hark_tpu.utils.backend import enable_compilation_cache
    from aiyagari_hark_tpu.utils.timing import PhaseTimer, device_trace

    cache_dir = enable_compilation_cache()
    print(f"[bench] persistent compilation cache: {cache_dir}",
          file=sys.stderr)
    timer = PhaseTimer()
    with timer.phase("probe"):
        ambient = _probe_default_backend()
    if ambient is None:
        print("[bench] ambient backend probe hung/failed -> forcing CPU",
              file=sys.stderr)
        _force_cpu()
    else:
        print(f"[bench] ambient backend probe: {ambient}", file=sys.stderr)

    import jax

    from aiyagari_hark_tpu.parallel.sweep import (_batched_solver,
                                                  run_table2_sweep)
    from aiyagari_hark_tpu.utils.config import SweepConfig

    sweep = SweepConfig()   # full Table II: 3 sigmas x 4 rhos
    trace_dir = os.environ.get("AIYAGARI_TRACE_DIR")

    # The axon TPU tunnel intermittently faults on first execution of a
    # freshly compiled program; retry with cleared caches, and fall back to
    # CPU for the final attempt so the round always records a number.
    # Degrade the distribution method down the measured-performance ladder
    # (pallas-grid default -> dense MXU matvecs -> scatter) so a
    # Pallas/Mosaic compile problem costs one retry, not the accelerator
    # number, and a dense-path problem still leaves the portable scatter.
    attempts = 4
    res = None
    backend = "unknown"
    n_devices = 0
    for attempt in range(attempts):
        kwargs = dict(SWEEP_KWARGS)
        if attempt == 1:
            kwargs["dist_method"] = "dense"
        elif attempt == 2:
            kwargs["dist_method"] = "scatter"
        try:
            backend = jax.default_backend()   # inside the loop: init may fail
            n_devices = len(jax.devices())
            print(f"[bench] attempt {attempt + 1}/{attempts}: "
                  f"backend={backend} devices={n_devices} "
                  f"kwargs={kwargs}", file=sys.stderr)
            # compile_s must describe the backend this attempt runs on, not
            # accumulate failed attempts on a different backend
            timer.seconds.pop("compile", None)
            timer.counts.pop("compile", None)
            with timer.phase("compile"):
                run_table2_sweep(sweep, **kwargs)   # compile + warm-up
            with timer.phase("sweep"), device_trace(trace_dir):
                res = run_table2_sweep(sweep, **kwargs)  # timed, cached
            break
        except Exception as e:   # noqa: BLE001 — device faults surface as
            # JaxRuntimeError; anything else is equally fatal for a bench run
            print(f"[bench] attempt {attempt + 1}/{attempts} failed: "
                  f"{type(e).__name__}: {str(e)[:300]}", file=sys.stderr)
            try:
                jax.clear_caches()
                _batched_solver.cache_clear()
            except Exception:   # noqa: BLE001 — cache teardown is best-effort
                pass
            if attempt == attempts - 2:
                print("[bench] falling back to CPU for final attempt",
                      file=sys.stderr)
                _force_cpu()
            time.sleep(5.0 * (attempt + 1))
    if res is None:
        print("[bench] all attempts failed (including CPU fallback)",
              file=sys.stderr)
        sys.exit(1)
    wall = res.wall_seconds

    # EGM throughput: knots touched per backward step x total steps summed
    # over all 12 cells' bisection midpoints, per second per chip.
    total_egm_steps = float(res.egm_iters.sum())
    gridpoints_per_sec_per_chip = (
        total_egm_steps * A_COUNT * LABOR_STATES / wall / max(n_devices, 1))

    # FLOP accounting (VERDICT r2 weak-item 1): model FLOPs from the
    # counters, vs the chip's nominal peak.  The result records which
    # distribution method actually executed.
    dist_method = res.dist_method if res.dist_method != "auto" else "scatter"
    sweep_flops = _model_flops(
        total_egm_steps, float(res.dist_iters.sum()), A_COUNT, LABOR_STATES,
        DIST_COUNT, dense_dist=(dist_method in ("dense", "pallas")))
    flops_per_sec = sweep_flops / wall
    peak = _peak_flops_per_chip(backend)
    mfu_pct = (None if peak is None
               else 100.0 * flops_per_sec / (peak * max(n_devices, 1)))
    print(f"[bench] sweep FLOPs {sweep_flops:.3e} ({dist_method} dist path) "
          f"-> {flops_per_sec:.3e} FLOP/s"
          + (f" = {mfu_pct:.4f}% of peak" if mfu_pct is not None else ""),
          file=sys.stderr)

    # At-scale configuration (BASELINE config 2): one fine-grid GE cell.
    fine = _fine_grid_metrics(backend, timer)

    with timer.phase("oracle_f64"):
        oracle = _oracle_r_star()
    if oracle is not None:
        # r* is in percent; 1 bp = 0.01 percentage points.
        max_bp = max(abs(a - b) for a, b in
                     zip([float(x) for x in res.r_star_pct], oracle)) * 100.0
    else:
        max_bp = None

    baseline = REFERENCE_CELL_SECONDS * N_CELLS
    print(f"[bench] phase breakdown:\n{timer.summary()}", file=sys.stderr)
    print(f"[bench] Table II r* (%):\n{res.table()}", file=sys.stderr)
    print(f"[bench] per-cell work (egm+dist steps): "
          f"{res.total_work().tolist()} skew={res.iteration_skew():.2f}",
          file=sys.stderr)
    print(json.dumps({
        "metric": "table2_sweep_wall_s",
        "value": round(wall, 4),
        "unit": "s",
        "vs_baseline": round(baseline / wall, 1),
        "backend": backend,
        "n_devices": n_devices,
        "egm_gridpoints_per_sec_per_chip": round(gridpoints_per_sec_per_chip),
        "r_star_f32_f64_max_bp": (None if max_bp is None
                                  else round(max_bp, 3)),
        "iteration_skew": round(res.iteration_skew(), 3),
        "compile_s": round(timer.seconds.get("compile", float("nan")), 2),
        "flops_per_sec": round(flops_per_sec),
        "mfu_pct": None if mfu_pct is None else round(mfu_pct, 4),
        "dist_method": dist_method,
        **fine,
    }))


if __name__ == "__main__":
    main()
