#!/usr/bin/env python
"""Headline benchmark: the full Aiyagari Table II sweep (σ ∈ {1,3,5} ×
ρ ∈ {0, 0.3, 0.6, 0.9} — 12 general-equilibrium solves) as one batched XLA
program on the local device(s).

Baseline: the reference solves ONE calibration cell in 27.12 min
(``economy.solve()``, notebook cell 19 output; BASELINE.md) and runs Table II
by editing the notebook one cell at a time (SURVEY.md §2.4), so the
reference-equivalent work is 12 × 1627.2 s.  ``vs_baseline`` is the speedup
factor (baseline seconds / measured seconds).

Defensive by design (round-1 post-mortem, VERDICT.md): the axon TPU tunnel
can hang backend *initialization* indefinitely, so the ambient backend is
probed in a SUBPROCESS with a timeout before this process ever touches a
device; on probe failure or repeated runtime faults the bench falls back to
CPU and still emits its JSON line with a ``backend`` field.

Durability (VERDICT r3 weak-item 1): every successful measurement on an
accelerator is IMMEDIATELY written to ``bench_tpu_last.json`` and committed
to git, phase by phase — a tunnel wedge later in the run (or in a later
process) can only cost freshness, never the record.  The r3 headline
existed only in prose because the driver's capture attempt hit a wedged
tunnel hours after the measurement.

Timing honesty (measured r3 gotcha): through the tunneled device,
``block_until_ready`` does not reliably block for XLA executables and
identical inputs can be served cached — every timed run here perturbs its
inputs (1e-9 on ρ) and stops the clock only after full host
materialization (``run_table2_sweep``'s wall semantics).

Prints ONE JSON line:
  {"metric": "table2_sweep_wall_s", "value": <s>, "unit": "s",
   "vs_baseline": <speedup>, "backend": "...", "n_devices": N, ...}

Extra BASELINE.md tracked metrics carried as fields on the same line:
 - ``egm_gridpoints_per_sec_per_chip``: total EGM work / wall / chips, where
   one EGM backward step touches a_count × labor_states policy knots
   (SURVEY.md §3.2's hot loop, minus the degenerate 4× aggregate-state
   duplication this framework eliminates).  The wall in the denominator is
   the WHOLE timed sweep — LAUNCH-WALL-INCLUSIVE: every per-iteration
   dispatch, host round trip, and bisection-level overhead is in it, so on
   a latency-bound backend the number measures launch overhead, not
   hardware arithmetic (the measured ~0.06%-MFU regime, BASELINE.md).
   Provenance matters when comparing rounds: the committed records mix
   machines — r02's sweep ran on a tunneled TPU (~1.1M; the durable
   ``bench_tpu_last.json`` TPU capture is 1.44M), r03/r04/r05 on CPU hosts
   (~160-174k) — so the ``backend`` field on each record is part of the
   metric's identity and the 174k-vs-1.44M swing is a machine change, NOT
   a regression (the sentinel's worse-than-worst-prior gate absorbs it).
 - ``r_star_f32_f64_max_bp``: max over the 12 cells of |r*(this backend,
   f32) − r*(CPU, f64 oracle)| in basis points — the 1 bp equivalence line
   (BASELINE.md).  The oracle runs in a subprocess because a TPU process
   cannot host a float64 backend.
 - ``flops_per_sec`` / ``mfu_pct``: achieved model FLOP rate of the sweep
   and its percent of chip peak, from the per-cell work counters and the
   per-step FLOP model in ``_model_flops``.
 - ``pallas_vs_dense_max_bp`` / ``dense_sweep_wall_s``: compiled-Mosaic
   correctness and the lane-grid kernel's A/B margin, recorded durably on
   every accelerator run (VERDICT r3 weak-item 4: "identical r* on chip"
   was previously asserted nowhere durable).
 - ``lanes_scaling``: the framework's scaling thesis measured — the sweep
   at 12/24/48/96 lanes (finer σ×ρ×sd lattices), cells/sec and MFU vs
   lane count (VERDICT r3 weak-item 3: the thesis was untested past 24).
 - ``fine_grid_*``: the at-scale configuration (BASELINE config 2: 1000-pt
   assets × 15 income states, 1000-pt histogram).  Both the accelerator's
   methods (dense MXU matvecs vs scatter) AND the CPU number are recorded
   side by side (VERDICT r3 weak-item 3/4: settle CPU-vs-TPU honestly),
   plus a 4-lane batched variant — the lanes thesis applied to the config
   where a single cell is HBM-bandwidth-bound.
 - ``dispatch_roundtrip_s`` / ``sweep_repeat_walls_s``: the fixed-overhead
   attribution (VERDICT r4 weak-item 5) — a trivial same-arity program's
   honest round-trip vs the compiled sweep's repeat floor.
 - ``sharded_sweep_*``: the lane-grid kernel dispatched under a sharded
   1-device ``cells`` mesh on the chip — the multi-chip scaling path's
   composition witness (VERDICT r4 weak-item 2c).
 - ``welfare_sweep_compile_s`` / ``welfare_sweep_wall_s``: the round-3
   compile-wedge class shown gone on the hardware that suffered it —
   ``tax_rate_sweep(with_welfare=True)`` at tiny size, sentinel-guarded
   (VERDICT r4 weak-item 3).
"""

import json
import os
import subprocess
import sys
import time

REFERENCE_CELL_SECONDS = 27.12 * 60.0   # notebook cell 19 (BASELINE.md)
N_CELLS = 12
A_COUNT = 32
LABOR_STATES = 7
DIST_COUNT = 500
SWEEP_KWARGS = dict(a_count=A_COUNT, dist_count=DIST_COUNT)
PERTURB = 1e-6          # timed-run input perturbation (see module docstring).
# Must sit ABOVE float32 resolution at the perturbed values: the accelerator
# process runs f32 (x64 stays off outside the oracle subprocess) and f32
# spacing at rho=0.3 is ~3e-8, so a 1e-9 nudge would be annihilated by the
# cast and re-present bit-identical inputs to the warm-up.  1e-6 survives the
# cast everywhere and moves r* by far less than the 1 bp budget.
# BASELINE config 2 — the at-scale single-cell GE solve (README/DESIGN §4).
FINE_A_COUNT = 1000
FINE_LABOR_STATES = 15
FINE_DIST_COUNT = 1000
# Lane-scaling lattice: lanes = 12 × len(sd panel).  All sd ≤ 0.4 (Table II
# panel B's own cap — higher risk at crra=5, rho=0.9 pushes r* toward the
# borrowing-constraint regime and the bisection bracket edge).
LANES_SD_PANELS = {
    12: (0.2,),
    24: (0.2, 0.4),
    48: (0.15, 0.2, 0.3, 0.4),
    96: (0.125, 0.15, 0.175, 0.2, 0.25, 0.3, 0.35, 0.4),
}


# The FLOP model and chip-peak table live in ``utils.timing`` now (one
# accounting for the sweep, lanes-scaling, and fine-grid phases — ISSUE 2
# satellite); the old private names stay as aliases for callers/tests.
from aiyagari_hark_tpu.utils.timing import (  # noqa: E402
    model_flops as _model_flops,
    peak_flops_per_chip as _peak_flops_per_chip,
    record_flop_fields,
)

_ORACLE_CODE = """
import json, jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
from aiyagari_hark_tpu.utils.backend import enable_compilation_cache
enable_compilation_cache()
import jax.numpy as jnp
from aiyagari_hark_tpu.parallel.sweep import run_table2_sweep
from aiyagari_hark_tpu.utils.config import SweepConfig
res = run_table2_sweep(SweepConfig(), dtype=jnp.float64, **{kwargs!r})
print("ORACLE=" + json.dumps([float(x) for x in res.r_star_pct]))
"""

_FINE_CPU_CODE = """
import json, time, jax
jax.config.update("jax_platforms", "cpu")
from aiyagari_hark_tpu.utils.backend import enable_compilation_cache
enable_compilation_cache()
from aiyagari_hark_tpu.models.equilibrium import solve_calibration_lean

def solve(rho):
    r = solve_calibration_lean(1.0, rho, labor_states={ns},
                               a_count={na}, dist_count={nd},
                               dist_method="auto")
    return float(r.r_star), float(r.egm_iters), float(r.dist_iters)

solve(0.3)                                  # compile + warm-up
t0 = time.perf_counter()
r, egm, dist = solve(0.3 + {perturb})       # perturbed, honest wall
wall = time.perf_counter() - t0
print("FINECPU=" + json.dumps({{"wall_s": wall, "r_star": r,
                                "egm_iters": egm, "dist_iters": dist}}))
"""


def _repo_dir() -> str:
    return os.path.dirname(os.path.abspath(__file__))


def _probe_default_backend(timeout_s: float | None = None):
    """Subprocess backend probe.  ``None`` delegates to the shared
    env-tunable default (``utils.backend.default_probe_timeout_s``,
    180 s — raised after two rounds of driver-time captures fell back to
    CPU on a merely-slow tunnel; VERDICT r4 minor item 6)."""
    from aiyagari_hark_tpu.utils.backend import probe_ambient_backend
    return probe_ambient_backend(timeout_s)


def _force_cpu() -> None:
    from aiyagari_hark_tpu.utils.backend import force_cpu_platform
    force_cpu_platform()


def _persist_tpu_evidence(record: dict) -> None:
    """Write the accelerator measurement to ``bench_tpu_last.json`` and
    git-commit it RIGHT NOW (VERDICT r3 weak-item 1): a later tunnel wedge
    — in this run or a future capture — can then only cost freshness,
    never the record.  Best-effort: a read-only checkout or dirty index
    must not take down the bench."""
    from aiyagari_hark_tpu.utils.checkpoint import atomic_write_json

    path = os.path.join(_repo_dir(), "bench_tpu_last.json")
    try:
        # atomic (tmp + rename, ISSUE 3 satellite): a kill mid-write must
        # not leave a truncated evidence file for a later CPU fallback to
        # embed as "the committed TPU record"
        atomic_write_json(path, record, indent=1, sort_keys=True)
        print(f"[bench] persisted TPU evidence -> {path}", file=sys.stderr)
    except OSError as e:
        print(f"[bench] could not write {path}: {e}", file=sys.stderr)
        return
    try:
        subprocess.run(["git", "add", "bench_tpu_last.json"],
                       cwd=_repo_dir(), capture_output=True, timeout=30)
        out = subprocess.run(
            ["git", "commit", "-m", "Persist TPU bench measurement",
             "--only", "bench_tpu_last.json"],
            cwd=_repo_dir(), capture_output=True, text=True, timeout=30)
        if out.returncode == 0:
            print("[bench] committed bench_tpu_last.json", file=sys.stderr)
    except (OSError, subprocess.TimeoutExpired) as e:
        print(f"[bench] git persist skipped: {e}", file=sys.stderr)


def _oracle_r_star(timeout_s: float = 1800.0):
    """The 12-cell r* vector from the CPU float64 oracle (subprocess), or
    None if it failed — the bench must not die with the oracle."""
    code = _ORACLE_CODE.format(kwargs=SWEEP_KWARGS)
    try:
        out = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=timeout_s, cwd=_repo_dir())
    except subprocess.TimeoutExpired:
        print("[bench] CPU f64 oracle timed out", file=sys.stderr)
        return None
    for line in out.stdout.splitlines():
        if line.startswith("ORACLE="):
            return json.loads(line.split("=", 1)[1])
    print(f"[bench] CPU f64 oracle failed:\n{out.stderr[-800:]}",
          file=sys.stderr)
    return None


def _fine_cpu_metrics(timeout_s: float = 600.0):
    """The fine-grid cell on ONE CPU core (subprocess — the bench process
    may hold the TPU), for the honest side-by-side (VERDICT r3 weak-item
    3).  Returns the parsed dict or None."""
    code = _FINE_CPU_CODE.format(ns=FINE_LABOR_STATES, na=FINE_A_COUNT,
                                 nd=FINE_DIST_COUNT, perturb=PERTURB)
    # the metric is labeled "one CPU core": pin XLA's CPU thread pool so
    # the label is honest on any host (this box has 1 core; a bigger host
    # would otherwise record a whole-host number against one chip)
    env = dict(os.environ)
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_cpu_multi_thread_eigen=false"
                          " intra_op_parallelism_threads=1").strip()
    try:
        out = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=timeout_s, cwd=_repo_dir(), env=env)
    except subprocess.TimeoutExpired:
        print("[bench] fine-grid CPU subprocess timed out", file=sys.stderr)
        return None
    for line in out.stdout.splitlines():
        if line.startswith("FINECPU="):
            return json.loads(line.split("=", 1)[1])
    print(f"[bench] fine-grid CPU subprocess failed:\n{out.stderr[-500:]}",
          file=sys.stderr)
    return None


def _pack3(res):
    """Stack (r_star, egm_iters, dist_iters) into ONE array so the timed
    wall contains a single device->host transfer (the round-5 packing
    rationale, ``parallel/sweep._batched_solver``); the counters ride
    along exactly in the float dtype (values ≪ 2^24)."""
    import jax.numpy as jnp

    f = res.r_star.dtype
    return jnp.stack([res.r_star, res.egm_iters.astype(f),
                      res.dist_iters.astype(f)])


def _timed_fine_solve(dist_method: str, timer, phase: str):
    """Compile + honestly time one fine-grid GE solve with the given
    distribution method.  Returns (wall, r_star, egm_iters, dist_iters)."""
    import jax
    import numpy as np

    from aiyagari_hark_tpu.models.equilibrium import solve_calibration_lean

    kwargs = dict(labor_states=FINE_LABOR_STATES, a_count=FINE_A_COUNT,
                  dist_count=FINE_DIST_COUNT, dist_method=dist_method)

    @jax.jit
    def solve_fine(rho):
        return _pack3(solve_calibration_lean(1.0, rho, **kwargs))

    with timer.phase(f"{phase}_compile"):
        jax.block_until_ready(solve_fine(0.3))       # compile + warm-up
    with timer.phase(phase):
        t0 = time.perf_counter()
        r_star, egm_it, dist_it = np.asarray(solve_fine(0.3 + PERTURB))
        wall = time.perf_counter() - t0
    return wall, float(r_star), float(egm_it), float(dist_it)


def _timed_fine_lanes(n_lanes: int, dist_method: str, timer):
    """The fine-grid config batched over ``n_lanes`` ρ-cells — the lanes
    thesis applied at scale.  Returns (wall, total_egm, total_dist)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from aiyagari_hark_tpu.models.equilibrium import solve_calibration_lean

    kwargs = dict(labor_states=FINE_LABOR_STATES, a_count=FINE_A_COUNT,
                  dist_count=FINE_DIST_COUNT, dist_method=dist_method)
    rhos = jnp.linspace(0.0, 0.9, n_lanes)

    @jax.jit
    def solve_lanes(rho_vec):
        def one(rho):
            # one stacked output per lane -> one [L, 3] transfer total
            return _pack3(solve_calibration_lean(1.0, rho, **kwargs))
        return jax.vmap(one)(rho_vec)

    with timer.phase("fine_lanes_compile"):
        jax.block_until_ready(solve_lanes(rhos))     # compile + warm-up
    with timer.phase("fine_lanes"):
        t0 = time.perf_counter()
        packed = np.asarray(solve_lanes(rhos + PERTURB))   # [L, 3]
        wall = time.perf_counter() - t0
    _, egm_it, dist_it = packed.T
    return wall, float(egm_it.sum()), float(dist_it.sum())


class _HazardSentinel:
    """Compile-hazard guard shared by the phases that have wedged the
    tunnel (fine-grid dense, round 4; welfare value recovery, round 3).

    A sentinel file is written immediately before the risky compile and
    removed only on success, so a hang-and-kill, a clean in-process
    failure, and a crash all leave it in place; the next run finds it and
    skips/demotes instead of re-wedging.  Recovery back to the risky path
    is explicit, not automatic: the force env var re-attempts despite the
    sentinel (clearing it on success), or delete the file by hand —
    without the override the demotion would be permanent, since a demoted
    run never reaches the success line that clears it (round-4 review).
    (A file, not a field sniffed from bench_tpu_last.json: the bench
    process overwrites that record several times before these phases run,
    and a fallback's success would overwrite the failure signature — both
    made a record-based check self-clearing.)"""

    def __init__(self, filename: str, force_env: str, what: str):
        self.filename = filename
        self.force_env = force_env
        self.what = what

    def path(self) -> str:
        return os.path.join(_repo_dir(), self.filename)

    def pending(self) -> bool:
        """True when a previous attempt never reached its success line
        (and the force override is unset)."""
        if os.environ.get(self.force_env):
            return False
        return os.path.exists(self.path())

    def write(self) -> None:
        from aiyagari_hark_tpu.utils.checkpoint import atomic_write_text

        try:
            atomic_write_text(
                self.path(),
                f"{self.what} in flight; presence at bench start "
                f"skips/demotes the phase.\nRetry with "
                f"{self.force_env}=1 (clears this file on success) "
                "or delete this file.\n")
        except OSError as e:
            print(f"[bench] could not write {self.filename}: {e}",
                  file=sys.stderr)

    def clear(self) -> None:
        try:
            os.remove(self.path())
        except OSError:
            pass


_FINE_SENTINEL = _HazardSentinel(
    ".bench_fine_dense_pending", "AIYAGARI_BENCH_FORCE_DENSE",
    "fine-grid dense attempt (the round-4 incident: the D=1000 dense "
    "compile hung the tunnel's remote-compile service for 50 minutes)")
_WELFARE_SENTINEL = _HazardSentinel(
    ".bench_welfare_pending", "AIYAGARI_BENCH_FORCE_WELFARE",
    "welfare-sweep TPU compile (the round-3 wedge class)")


def _fine_grid_metrics(backend: str, timer) -> dict:
    """The at-scale configuration, measured honestly on BOTH sides:
    the accelerator's dense and scatter methods, a 4-lane batched variant,
    and the one-CPU-core number — side by side in the JSON (VERDICT r3
    weak-item 3: the r3 record showed the accelerator losing this config
    to a CPU core, but only one side was ever in the artifact).  Failures
    only cost fine-grid fields — the sweep metrics must survive, and a
    failed primary method must not strand the other measurements (the
    round-4 incident: a dense-compile hang early-returned with every
    fine-grid field null)."""
    on_accel = backend in ("tpu", "axon")
    peak = _peak_flops_per_chip(backend)
    out: dict = {}
    if peak.assumed:
        out["fine_grid_peak_flops_assumed"] = True

    # -- primary method (dense matvecs on the accelerator, scatter on CPU);
    # on a failed primary, fall through to the next method so the record
    # still carries an accelerator number.
    if on_accel:
        methods = ["dense", "scatter"]
        if _FINE_SENTINEL.pending():
            print("[bench] fine-grid dense demoted to scatter: sentinel "
                  f"{_FINE_SENTINEL.filename} present (a previous dense "
                  "attempt never reached success)", file=sys.stderr)
            methods = ["scatter"]
            # the demotion itself is part of the record: without it a
            # demoted run's artifact is indistinguishable from a healthy
            # scatter-primary run (round-4 review)
            out["fine_grid_dense_demoted"] = True
    else:
        methods = ["auto"]
    primary = methods[0]
    for method in methods:
        if method == "dense":
            _FINE_SENTINEL.write()
        try:
            wall, r_star, egm_it, dist_it = _timed_fine_solve(
                method, timer, "fine_grid")
        except Exception as e:   # noqa: BLE001 — try the next method (the
            # sentinel stays: a clean failure this run may hang the next)
            print(f"[bench] fine-grid cell ({method}) failed: "
                  f"{type(e).__name__}: {str(e)[:300]}", file=sys.stderr)
            # a failed attempt nulls the WALL and records which method
            # failed — it must NOT claim fine_grid_method (the r05 record
            # carried method="dense" with every derived field null, which
            # read as "dense ran"); a later method's success overwrites
            # the nulls, and record_null_violations pins the invariant
            out.update({"fine_grid_wall_s": None,
                        "fine_grid_failed_method": method,
                        "fine_grid_flops_per_sec": None,
                        "fine_grid_mfu_pct": None})
            if method == "dense":
                # preserve the failure in the artifact — the scatter
                # fallback's success will overwrite the nulls above
                out["fine_grid_dense_error"] = (
                    f"{type(e).__name__}: {str(e)[:160]}")
            continue
        # NOTE: the sentinel is NOT cleared here — the 4-lane dense batch
        # below compiles a strictly larger dense program, so the hazard
        # window extends through it; the clear happens after the lanes
        # phase (round-4 review)
        primary = method
        flops = _model_flops(egm_it, dist_it, FINE_A_COUNT,
                             FINE_LABOR_STATES, FINE_DIST_COUNT,
                             dense_dist=(method == "dense"))
        out.update({
            "fine_grid_wall_s": round(wall, 4),
            "fine_grid_method": method,
        })
        # one spelling for flops/mfu/provenance fields (ISSUE 10
        # satellite, utils.timing.record_flop_fields): stamps
        # fine_grid_{flops_per_sec, mfu_pct, peak_flops_assumed,
        # flops_provenance}
        record_flop_fields(out, "fine_grid_", egm_it, dist_it, wall,
                           FINE_A_COUNT, FINE_LABOR_STATES,
                           FINE_DIST_COUNT,
                           dense_dist=(method == "dense"),
                           backend=backend)
        print(f"[bench] fine grid ({FINE_A_COUNT}x{FINE_LABOR_STATES}, "
              f"D={FINE_DIST_COUNT}, {method}): r*={r_star:.4%} "
              f"wall={wall:.3f}s -> {flops / wall:.3e} FLOP/s",
              file=sys.stderr)
        break

    # -- accelerator A/B: the scatter method on the same chip (only when
    # the primary was dense — otherwise scatter IS the primary number)
    if (on_accel and primary == "dense"
            and out.get("fine_grid_wall_s") is not None):
        try:
            wall_sc, r_sc, _, _ = _timed_fine_solve("scatter", timer,
                                                    "fine_scatter")
            out["fine_grid_scatter_wall_s"] = round(wall_sc, 4)
            print(f"[bench] fine grid scatter-on-accel: r*={r_sc:.4%} "
                  f"wall={wall_sc:.3f}s", file=sys.stderr)
        except Exception as e:   # noqa: BLE001
            print(f"[bench] fine-grid scatter A/B failed: "
                  f"{type(e).__name__}: {str(e)[:200]}", file=sys.stderr)
            out["fine_grid_scatter_wall_s"] = None

    # -- the lanes thesis at scale: 4 fine-grid cells in one program
    # (skipped when no single-cell method produced a number — the batched
    # variant of a failing program can only fail slower)
    if out.get("fine_grid_wall_s") is None:
        out.update({"fine_grid_lanes4_wall_s": None,
                    "fine_grid_lanes4_cells_per_sec": None,
                    "fine_grid_lanes4_mfu_pct": None})
    else:
        try:
            wall4, egm4, dist4 = _timed_fine_lanes(4, primary, timer)
            out.update({
                "fine_grid_lanes4_wall_s": round(wall4, 4),
                "fine_grid_lanes4_cells_per_sec": round(4.0 / wall4, 4),
            })
            record_flop_fields(out, "fine_grid_lanes4_", egm4, dist4,
                               wall4, FINE_A_COUNT, FINE_LABOR_STATES,
                               FINE_DIST_COUNT,
                               dense_dist=(primary == "dense"),
                               backend=backend)
            print(f"[bench] fine grid x4 lanes ({primary}): "
                  f"wall={wall4:.3f}s -> {4.0 / wall4:.3f} cells/s",
                  file=sys.stderr)
            if primary == "dense":
                # the whole dense family (single-cell + 4-lane batch)
                # compiled and ran — only now is the hazard cleared
                _FINE_SENTINEL.clear()
        except Exception as e:   # noqa: BLE001 — sentinel stays on failure
            print(f"[bench] fine-grid 4-lane batch failed: "
                  f"{type(e).__name__}: {str(e)[:200]}", file=sys.stderr)
            out.update({"fine_grid_lanes4_wall_s": None,
                        "fine_grid_lanes4_cells_per_sec": None,
                        "fine_grid_lanes4_mfu_pct": None})

    # -- the honest other side: one CPU core, in a subprocess (recorded
    # even when every accelerator method failed — half a comparison still
    # beats an empty record)
    if on_accel:
        with timer.phase("fine_cpu"):
            cpu = _fine_cpu_metrics()
        out["fine_grid_cpu_wall_s"] = (None if cpu is None
                                       else round(cpu["wall_s"], 4))
        if cpu is not None:
            # FLOP-account the CPU side from ITS OWN counters (scatter
            # path), so the record carries a fine-grid FLOP rate even
            # when every accelerator method failed — the r5 nulls came
            # from exactly that stranding (utils.timing.model_flops)
            cpu_flops = _model_flops(cpu["egm_iters"], cpu["dist_iters"],
                                     FINE_A_COUNT, FINE_LABOR_STATES,
                                     FINE_DIST_COUNT, dense_dist=False)
            out["fine_grid_cpu_flops_per_sec"] = round(
                cpu_flops / cpu["wall_s"])
        if cpu is not None and out.get("fine_grid_wall_s") is not None:
            print(f"[bench] fine grid on one CPU core: "
                  f"wall={cpu['wall_s']:.3f}s (accel {primary} "
                  f"{out['fine_grid_wall_s']:.3f}s)", file=sys.stderr)
    else:
        out["fine_grid_cpu_wall_s"] = out["fine_grid_wall_s"]
        out["fine_grid_cpu_flops_per_sec"] = out.get(
            "fine_grid_flops_per_sec")
    return out


def _warm_scheduled_metrics(timer, sweep_kwargs: dict, base_res) -> dict:
    """The ISSUE 2 tentpole measured end-to-end: a second sweep scheduled
    from the first one's sidecar (measured per-cell work ordering +
    verified warm-started brackets).  Records the post-scheduling
    straggler ratio, the warm sweep's wall, and the inner-loop step
    reduction bracket warm-starts bought — next to the lock-step-
    equivalent headline those numbers must beat (acceptance: scheduled
    skew < 1.6 on the 12-cell sweep, inner steps down >= 25%)."""
    from aiyagari_hark_tpu.parallel.sweep import run_table2_sweep
    from aiyagari_hark_tpu.utils.config import SweepConfig

    out: dict = {}
    sidecar = os.path.join(_repo_dir(), ".bench_sweep_sidecar.npz")
    cfg = SweepConfig(schedule="balanced", warm_brackets=True,
                      sidecar_path=sidecar)
    try:
        # write/refresh the sidecar from a scheduled cold pass (also the
        # warm executable's compile), then measure the warm-started sweep
        with timer.phase("warm_sweep_compile"):
            run_table2_sweep(cfg, **sweep_kwargs)
        with timer.phase("warm_sweep"):
            res = run_table2_sweep(cfg, perturb=PERTURB, **sweep_kwargs)
        base_steps = float(base_res.total_work().sum())
        warm_steps = float(res.total_work().sum())
        # NaN-safe: a quarantine-exhausted cell is NaN-masked in BOTH runs
        # (the SweepResult contract) — compare the finite cells and record
        # null (valid JSON, unlike NaN) if nothing is comparable
        import numpy as _np
        diffs = _np.abs(_np.asarray(res.r_star_pct)
                        - _np.asarray(base_res.r_star_pct)) * 100.0
        finite = diffs[_np.isfinite(diffs)]
        max_bp = float(finite.max()) if finite.size else None
        out.update({
            "warm_sweep_wall_s": round(res.wall_seconds, 4),
            "warm_sweep_inner_steps": int(warm_steps),
            "warm_inner_step_reduction_pct": round(
                100.0 * (1.0 - warm_steps / max(base_steps, 1.0)), 1),
            "warm_scheduled_iteration_skew": round(
                res.scheduled_iteration_skew(), 3),
            "warm_vs_base_max_bp": (None if max_bp is None
                                    else round(max_bp, 4)),
        })
        print(f"[bench] warm scheduled sweep: wall={res.wall_seconds:.3f}s "
              f"inner steps {int(base_steps)} -> {int(warm_steps)} "
              f"(-{out['warm_inner_step_reduction_pct']}%), "
              f"post-scheduling skew "
              f"{out['warm_scheduled_iteration_skew']}, "
              f"max |Δr*|={max_bp:.4f} bp", file=sys.stderr)
    except Exception as e:   # noqa: BLE001 — the tentpole phase must not
        # cost the record its headline fields
        print(f"[bench] warm scheduled sweep failed: {type(e).__name__}: "
              f"{str(e)[:300]}", file=sys.stderr)
        out["warm_sweep_error"] = f"{type(e).__name__}: {str(e)[:160]}"
    return out


def _precision_ladder_metrics(timer, sweep_kwargs: dict, base_res) -> dict:
    """The ISSUE 5 tentpole measured end-to-end: the 12-cell sweep under
    ``precision="mixed"`` (cheap-dtype descent, reference polish — DESIGN
    §5) against the reference-policy headline.  Emits the ``precision_*``
    record fields: the per-phase step split, the polish fraction (the
    share of steps still paying reference precision), the r* agreement
    with the reference sweep in basis points, and the wall-clock speedup.
    Runs on every backend — the acceptance numbers are CPU numbers too;
    on the TPU this is the phase where the dense distribution matmuls
    become MXU-eligible for the descent iterations."""
    import numpy as np

    from aiyagari_hark_tpu.parallel.sweep import run_table2_sweep
    from aiyagari_hark_tpu.utils.config import SweepConfig

    out: dict = {}
    kwargs = dict(sweep_kwargs)
    kwargs["precision"] = "mixed"
    try:
        with timer.phase("precision_compile"):
            run_table2_sweep(SweepConfig(), **kwargs)   # compile + warm-up
        with timer.phase("precision_mixed"):
            res = run_table2_sweep(SweepConfig(), perturb=PERTURB, **kwargs)
        descent = int(res.descent_steps.sum())
        polish = int(res.polish_steps.sum())
        diffs = np.abs(np.asarray(res.r_star_pct)
                       - np.asarray(base_res.r_star_pct)) * 100.0
        finite = diffs[np.isfinite(diffs)]
        max_bp = float(finite.max()) if finite.size else None
        out.update({
            "precision_policy": "mixed",
            "precision_descent_steps": descent,
            "precision_polish_steps": polish,
            "precision_polish_frac": round(res.polish_frac(), 4),
            "precision_escalations": int(res.precision_escalations.sum()),
            "precision_mixed_wall_s": round(res.wall_seconds, 4),
            "mixed_r_star_vs_ref_max_bp": (None if max_bp is None
                                           else round(max_bp, 4)),
            "mixed_speedup": round(
                base_res.wall_seconds / max(res.wall_seconds, 1e-9), 3),
        })
        bp_txt = ("n/a (no finite cells)" if max_bp is None
                  else f"{max_bp:.4f} bp")
        print(f"[bench] mixed-precision sweep: wall={res.wall_seconds:.3f}s "
              f"({out['mixed_speedup']}x ref) descent={descent} "
              f"polish={polish} (frac {out['precision_polish_frac']}), "
              f"max |Δr*|={bp_txt}, "
              f"{out['precision_escalations']} escalations",
              file=sys.stderr)
    except Exception as e:   # noqa: BLE001 — the precision phase must not
        # cost the record its headline fields
        print(f"[bench] mixed-precision sweep failed: {type(e).__name__}: "
              f"{str(e)[:300]}", file=sys.stderr)
        out["precision_error"] = f"{type(e).__name__}: {str(e)[:160]}"
    return out


# Wall-present-but-derived-null pairs the bench must never emit (ISSUE 5
# satellite: BENCH_r05's TPU record carried fine_grid_method="dense" with
# every derived field null).  Each entry: (wall field, derived field,
# accel_only) — accel_only fields (MFU needs a chip peak) may be null on
# CPU records but never on tpu/axon ones.
_NULL_SENTINEL_PAIRS = (
    ("fine_grid_wall_s", "fine_grid_flops_per_sec", False),
    ("fine_grid_wall_s", "fine_grid_mfu_pct", True),
    ("fine_grid_lanes4_wall_s", "fine_grid_lanes4_cells_per_sec", False),
    ("fine_grid_lanes4_wall_s", "fine_grid_lanes4_mfu_pct", True),
    ("fine_grid_cpu_wall_s", "fine_grid_cpu_flops_per_sec", False),
)


def record_null_violations(record: dict) -> list:
    """Fields whose wall time is present but whose derived rate/MFU field
    is null — the class of stranding the fine-grid phase shipped twice
    (VERDICT r5, BENCH_r05 ``last_tpu``).  A failed phase must null the
    WALL too (the honest "did not run"), never a derived field alone.
    Returns ``(wall_field, derived_field)`` pairs; pinned by
    ``tests/test_bench_smoke.py`` against both synthetic records and the
    record this bench emits."""
    on_accel = record.get("backend") in ("tpu", "axon")
    bad = []
    for wall_field, derived, accel_only in _NULL_SENTINEL_PAIRS:
        if accel_only and not on_accel:
            continue
        if wall_field not in record:
            continue
        if record[wall_field] is not None and record.get(derived) is None:
            bad.append((wall_field, derived))
    return bad


def _compile_cold_warm(timer, sweep_kwargs: dict) -> dict:
    """Cold vs warm compile attribution (ISSUE 2 tentpole part 4): the
    headline ``compile_s`` conflates XLA compilation with a
    persistent-cache load, so the sweep's compile cost was charged to
    every run's trajectory even when the cache served it.  This probe
    drops the in-process executable caches and re-prepares the SAME sweep
    program with the persistent compilation cache enabled: the wall is
    the warm (cache-served) compile, and the ``CompileCounter`` records
    how many programs were actually recompiled (``cache_misses`` — 0 on a
    healthy cache) vs served (``cache_hits``)."""
    import jax

    from aiyagari_hark_tpu.parallel.sweep import (_batched_solver,
                                                  run_table2_sweep)
    from aiyagari_hark_tpu.utils.config import SweepConfig
    from aiyagari_hark_tpu.utils.timing import CompileCounter

    out: dict = {}
    try:
        jax.clear_caches()
        _batched_solver.cache_clear()
        with CompileCounter() as counter, timer.phase("compile_warm"):
            run_table2_sweep(SweepConfig(), **sweep_kwargs)
        out["compile_warm_s"] = round(timer.seconds["compile_warm"], 2)
        out["compile_warm_cache_hits"] = counter.cache_hits
        out["compile_warm_cache_misses"] = counter.cache_misses
        print(f"[bench] warm re-compile: {out['compile_warm_s']:.2f}s "
              f"({counter.cache_hits} cache-served, "
              f"{counter.cache_misses} recompiled)", file=sys.stderr)
    except Exception as e:   # noqa: BLE001
        print(f"[bench] warm-compile probe failed: {type(e).__name__}: "
              f"{str(e)[:200]}", file=sys.stderr)
    return out


def _overhead_decomposition(timer, sweep_kwargs: dict) -> dict:
    """Attribute the sweep's fixed per-call cost (VERDICT r4 weak-item 5:
    ``lanes_scaling`` fits wall ≈ 0.7 s + lanes/10, so at 12 lanes ~45% of
    the headline is a lane-independent floor).  Two probes, no profiler
    dependency (the tunneled device does not serve profiler traces):

    (1) ``dispatch_roundtrip_s`` — a trivial jitted program with the
        sweep's PRE-round-5 output arity (six separate [12] outs, six
        host materializations), timed the same honest way (perturbed
        input, full host materialization).  This is everything that is
        NOT solving: Python dispatch, tunnel RPC, executable invocation,
        device→host transfers.  ``dispatch_roundtrip_packed_s`` is the
        same program returning ONE stacked [6,12] array — the shape the
        sweep actually uses since the round-5 single-transfer packing
        (``parallel/sweep._batched_solver``); the difference between the
        two attributes the per-transfer cost directly.
    (2) ``sweep_repeat_walls_s`` — the already-compiled 12-cell sweep
        timed 3 more times; the min is the sweep's true per-call floor and
        the spread separates stable overhead from tunnel jitter.

    fixed_overhead ≈ dispatch_roundtrip_s → the floor is tunnel/runtime
    per-invocation cost, not framework work; the decomposition lands in
    DESIGN §4 either way."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from aiyagari_hark_tpu.parallel.sweep import run_table2_sweep
    from aiyagari_hark_tpu.utils.config import SweepConfig

    out: dict = {}

    @jax.jit
    def trivial(x):
        return (x + 1.0, x * 2.0, x - 1.0, x * 0.5, x + 2.0, x * 3.0)

    @jax.jit
    def trivial_packed(x):
        return jnp.stack([x + 1.0, x * 2.0, x - 1.0, x * 0.5, x + 2.0,
                          x * 3.0])

    x = jnp.linspace(0.0, 1.0, N_CELLS, dtype=jnp.float32)
    try:
        jax.block_until_ready(trivial(x))            # compile + warm-up
        jax.block_until_ready(trivial_packed(x))

        def time_six(dx):
            t0 = time.perf_counter()
            outs = trivial(x + dx)
            for o in outs:
                np.asarray(o)                        # host materialization
            return time.perf_counter() - t0

        def time_packed(dx):
            t0 = time.perf_counter()
            np.asarray(trivial_packed(x + dx))
            return time.perf_counter() - t0

        walls, walls_packed = [], []
        with timer.phase("dispatch_probe"):
            for i in range(5):
                # alternate which probe goes first: back-to-back calls
                # ride a freshly warmed tunnel, so a fixed order would
                # systematically favor whichever runs second
                first, second = ((time_six, time_packed) if i % 2 == 0
                                 else (time_packed, time_six))
                a = first((i + 1) * PERTURB)
                b = second((i + 1) * PERTURB * 1.5)
                w6, wp = (a, b) if i % 2 == 0 else (b, a)
                walls.append(w6)
                walls_packed.append(wp)
        out["dispatch_roundtrip_s"] = round(float(np.median(walls)), 4)
        out["dispatch_roundtrip_all_s"] = [round(w, 4) for w in walls]
        out["dispatch_roundtrip_packed_s"] = round(
            float(np.median(walls_packed)), 4)
        print(f"[bench] dispatch round-trip (median of 5): 6 outputs "
              f"{out['dispatch_roundtrip_s']:.4f}s, packed "
              f"{out['dispatch_roundtrip_packed_s']:.4f}s "
              f"(all 6-out: {out['dispatch_roundtrip_all_s']})",
              file=sys.stderr)
    except Exception as e:   # noqa: BLE001 — a probe failure must not
        # cost the record its headline fields
        print(f"[bench] dispatch probe failed: {type(e).__name__}: "
              f"{str(e)[:200]}", file=sys.stderr)

    try:
        sweep_walls = []
        with timer.phase("sweep_repeats"):
            for i in range(3):
                res = run_table2_sweep(SweepConfig(),
                                       perturb=PERTURB * (i + 2),
                                       **sweep_kwargs)
                sweep_walls.append(round(res.wall_seconds, 4))
        out["sweep_repeat_walls_s"] = sweep_walls
        print(f"[bench] 12-cell sweep repeats: {sweep_walls} "
              f"(min {min(sweep_walls):.3f}s)", file=sys.stderr)
    except Exception as e:   # noqa: BLE001
        print(f"[bench] sweep repeats failed: {type(e).__name__}: "
              f"{str(e)[:200]}", file=sys.stderr)
    return out


def _sharded_sweep_metrics(timer, sweep_kwargs: dict,
                           ref_r_star) -> dict:
    """The pallas-grid × sharded-mesh composition ON the chip (VERDICT r4
    weak-item 2c): the declared multi-chip scaling path is the lane-grid
    kernel dispatched under a ``NamedSharding``-sharded ``cells`` axis, and
    until this phase no sharded execution had ever run with the compiled
    kernel (every mesh test resolves to CPU/scatter).  A 1-device mesh
    exercises the composition — GSPMD partitioning around the Mosaic
    custom call — which is what a single chip can witness; the CPU-side
    scale story is ``tests/test_parallel.py``'s 8-virtual-device
    interpret-mode twin."""
    import jax

    from aiyagari_hark_tpu.parallel.mesh import make_mesh
    from aiyagari_hark_tpu.parallel.sweep import run_table2_sweep
    from aiyagari_hark_tpu.utils.config import SweepConfig

    out: dict = {}
    try:
        mesh = make_mesh(("cells",), devices=jax.devices()[:1])
        with timer.phase("sharded_sweep_compile"):
            run_table2_sweep(SweepConfig(), mesh=mesh, **sweep_kwargs)
        with timer.phase("sharded_sweep"):
            res = run_table2_sweep(SweepConfig(), mesh=mesh,
                                   perturb=PERTURB, **sweep_kwargs)
        max_bp = max(abs(float(a) - float(b))
                     for a, b in zip(res.r_star_pct, ref_r_star)) * 100.0
        out["sharded_sweep_wall_s"] = round(res.wall_seconds, 4)
        out["sharded_sweep_dist_method"] = res.dist_method
        out["sharded_vs_unsharded_max_bp"] = round(max_bp, 4)
        print(f"[bench] sharded 1-device-mesh sweep ({res.dist_method}): "
              f"wall={res.wall_seconds:.3f}s max |Δr*|={max_bp:.4f} bp",
              file=sys.stderr)
    except Exception as e:   # noqa: BLE001
        print(f"[bench] sharded sweep failed: {type(e).__name__}: "
              f"{str(e)[:300]}", file=sys.stderr)
        out["sharded_sweep_wall_s"] = None
        out["sharded_sweep_error"] = f"{type(e).__name__}: {str(e)[:160]}"
    return out


def _welfare_sweep_metrics(timer) -> dict:
    """The round-3 wedge class, shown gone on the hardware that suffered it
    (VERDICT r4 weak-item 3): a tiny ``tax_rate_sweep(with_welfare=True)``
    compiled and executed on the accelerator, with the compile wall
    recorded.  Round 3's iterative value recovery was an XLA compile
    pathology here (>10 min, killing it wedged the tunnel); the bounded LU
    recovery (``models/fiscal.py``) is believed to fix it — this phase is
    the committed artifact that SHOWS it.  Sentinel-guarded exactly like
    the fine-grid dense phase: a hang-and-kill leaves the sentinel, and
    the next run skips instead of re-wedging (force a retry with
    ``AIYAGARI_BENCH_FORCE_WELFARE=1`` or delete the file)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from aiyagari_hark_tpu.models.fiscal import tax_rate_sweep

    out: dict = {"welfare_sweep_compile_s": None,
                 "welfare_sweep_wall_s": None}
    if _WELFARE_SENTINEL.pending():
        print("[bench] welfare sweep skipped: a previous attempt never "
              "completed (sentinel present; AIYAGARI_BENCH_FORCE_WELFARE=1 "
              "to retry)", file=sys.stderr)
        out["welfare_sweep_skipped"] = "hazard-sentinel"
        return out
    kwargs = dict(labor_states=5, a_count=16, dist_count=64, max_bisect=12)
    taus = np.linspace(0.0, 0.45, 4)
    _WELFARE_SENTINEL.write()
    try:
        t0 = time.perf_counter()
        with timer.phase("welfare_compile"):
            res = tax_rate_sweep(jnp.asarray(taus), 0.96, 2.0, 0.36, 0.08,
                                 with_welfare=True, **kwargs)
            np.asarray(res.welfare)   # host materialization — through the
            # tunnel block_until_ready does not reliably block (r3 gotcha)
        compile_s = time.perf_counter() - t0
        with timer.phase("welfare_sweep"):
            t0 = time.perf_counter()
            res = tax_rate_sweep(jnp.asarray(taus + PERTURB), 0.96, 2.0,
                                 0.36, 0.08, with_welfare=True, **kwargs)
            welfare = np.asarray(res.welfare)        # host materialization
            wall = time.perf_counter() - t0
        # compile + execute both finished: the hazard this sentinel guards
        # (a wedging TPU compile, the round-3 incident class) is over —
        # clear it NOW, before the finiteness check, so a merely
        # non-finite RESULT records a value error without latching a
        # permanent skip of future runs (ADVICE r5 #1: the old
        # raise-after-success path left the sentinel in place forever).
        _WELFARE_SENTINEL.clear()
        out["welfare_sweep_compile_s"] = round(compile_s, 2)
        out["welfare_sweep_wall_s"] = round(wall, 4)
        if not np.isfinite(welfare).all():
            out["welfare_sweep_error"] = (
                f"non-finite welfare: {welfare.tolist()}"[:160])
            print(f"[bench] welfare sweep executed but produced non-finite "
                  f"values: {welfare.tolist()} (recorded as "
                  f"welfare_sweep_error; sentinel cleared — compile+execute "
                  f"succeeded)", file=sys.stderr)
            return out
        print(f"[bench] welfare sweep (4 lanes, with_welfare=True): "
              f"compile={compile_s:.2f}s wall={wall:.3f}s "
              f"welfare={welfare.round(4).tolist()}", file=sys.stderr)
    except Exception as e:   # noqa: BLE001 — sentinel stays on failure
        print(f"[bench] welfare sweep failed: {type(e).__name__}: "
              f"{str(e)[:300]}", file=sys.stderr)
        out["welfare_sweep_error"] = f"{type(e).__name__}: {str(e)[:160]}"
    return out


def _lanes_scaling(timer, sweep_kwargs: dict) -> list:
    """The scaling thesis, measured: the Table II sweep at 12/24/48/96
    lanes (finer sd panels), cells/sec and MFU per lane count (VERDICT r3
    weak-item 3 — DESIGN §4 claims "scaling comes from MORE LANES" and the
    largest previously measured batch was 24).

    Scheduled (ISSUE 5 satellite): the ladder used to launch every lane
    count as ONE lock-step batch — measured skew grew 2.563 → 5.275 from
    12 to 96 lanes and cells/sec REGRESSED past 24 lanes (BENCH_r05
    ``lanes_scaling``), so the thesis was being measured through exactly
    the straggler pathology the PR-2 scheduler exists to remove.  The
    ladder now routes through ``SweepConfig(schedule="balanced")`` like
    the main sweep and records ``iteration_skew_scheduled`` (the
    within-bucket ratio the hardware actually pays) alongside the raw
    lock-step-equivalent number."""
    from aiyagari_hark_tpu.parallel.sweep import run_table2_sweep
    from aiyagari_hark_tpu.utils.config import SweepConfig

    peak = _peak_flops_per_chip("tpu")
    entries = []
    for lanes, sds in LANES_SD_PANELS.items():
        cfg = SweepConfig(labor_sd=sds, schedule="balanced")
        try:
            with timer.phase(f"lanes{lanes}_compile"):
                run_table2_sweep(cfg, **sweep_kwargs)    # compile + warm-up
            with timer.phase(f"lanes{lanes}"):
                res = run_table2_sweep(cfg, perturb=PERTURB, **sweep_kwargs)
        except Exception as e:   # noqa: BLE001 — record the lanes we got
            print(f"[bench] lanes={lanes} failed: {type(e).__name__}: "
                  f"{str(e)[:200]}", file=sys.stderr)
            break
        dense = res.dist_method in ("dense", "pallas")
        flops = _model_flops(float(res.egm_iters.sum()),
                             float(res.dist_iters.sum()), A_COUNT,
                             LABOR_STATES, DIST_COUNT, dense_dist=dense)
        entry = {
            "lanes": lanes,
            "wall_s": round(res.wall_seconds, 4),
            "cells_per_sec": round(lanes / res.wall_seconds, 3),
            "mfu_pct": (None if peak.value is None else
                        round(100.0 * flops / res.wall_seconds
                              / peak.value, 4)),
            "iteration_skew": round(res.iteration_skew(), 3),
            # the within-bucket ratio the scheduled launches actually pay
            "iteration_skew_scheduled": round(
                res.scheduled_iteration_skew(), 3),
            "n_buckets": (0 if res.bucket is None
                          else int(res.bucket.max()) + 1),
        }
        entries.append(entry)
        print(f"[bench] lanes={lanes:3d}: wall={entry['wall_s']:.3f}s "
              f"-> {entry['cells_per_sec']:.2f} cells/s "
              f"skew={entry['iteration_skew']:.2f} "
              f"(scheduled {entry['iteration_skew_scheduled']:.2f} over "
              f"{entry['n_buckets']} buckets)", file=sys.stderr)
    return entries


def _pallas_dense_ab(timer, sweep_kwargs: dict, pallas_r_star) -> dict:
    """Re-run the 12-cell sweep on the dense XLA path and compare r* with
    the lane-grid Pallas kernel's — the compiled-Mosaic correctness
    evidence, recorded durably every accelerator round (VERDICT r3
    weak-item 4)."""
    from aiyagari_hark_tpu.parallel.sweep import run_table2_sweep
    from aiyagari_hark_tpu.utils.config import SweepConfig

    kwargs = dict(sweep_kwargs)
    kwargs["dist_method"] = "dense"
    sweep = SweepConfig()
    with timer.phase("dense_ab_compile"):
        run_table2_sweep(sweep, **kwargs)                # compile + warm-up
    with timer.phase("dense_ab"):
        res = run_table2_sweep(sweep, perturb=PERTURB, **kwargs)
    max_bp = max(abs(float(a) - float(b))
                 for a, b in zip(pallas_r_star, res.r_star_pct)) * 100.0
    print(f"[bench] pallas-vs-dense A/B: dense wall={res.wall_seconds:.3f}s "
          f"max |Δr*|={max_bp:.4f} bp", file=sys.stderr)
    return {"pallas_vs_dense_max_bp": round(max_bp, 4),
            "dense_sweep_wall_s": round(res.wall_seconds, 4)}


# Serving smoke (ISSUE 4): tiny cells — the serving claims under test are
# about caching/batching/compile reuse, not the economics, so the workload
# is the 12-cell Table II lattice at smoke-test grid sizes.
SERVE_SMOKE_KWARGS = dict(a_count=10, dist_count=32, labor_states=3,
                          r_tol=1e-5, max_bisect=24)


def _serve_smoke() -> dict:
    """The 12-cell serving acceptance run (``--serve-smoke``): a cold
    replay warms the store and compiles the ladder, a SHUFFLED exact-hit
    replay must serve sub-millisecond hits with zero XLA compiles, and a
    neighbor replay (every ρ nudged) must cut total bisection evaluations
    vs solving the same shifted cells cold.  Emits the ``serve_*`` record
    fields (``serve.ServeMetrics.snapshot`` plus the phase comparisons)."""
    import numpy as np

    from aiyagari_hark_tpu.serve import EquilibriumService, make_query
    from aiyagari_hark_tpu.utils.timing import (
        CompileCounter,
        peak_flops_per_chip,
    )

    import jax

    backend = jax.default_backend()
    kw = dict(SERVE_SMOKE_KWARGS)
    cells = [(s, r) for s in (1.0, 3.0, 5.0) for r in (0.0, 0.3, 0.6, 0.9)]
    svc = EquilibriumService(start_worker=False, max_batch=4,
                             ladder=(1, 2, 4))

    # phase 1: cold replay — fills the store, compiles the ladder shapes
    t0 = time.perf_counter()
    futs = [svc.submit(make_query(s, r, **kw)) for s, r in cells]
    svc.flush()
    base = [f.result(0) for f in futs]
    cold_wall = time.perf_counter() - t0
    print(f"[bench] serve smoke: cold replay of {len(cells)} cells in "
          f"{cold_wall:.2f}s (paths: "
          f"{[r.path for r in base].count('cold')} cold / "
          f"{[r.path for r in base].count('near')} near)", file=sys.stderr)

    # phase 2: shuffled exact-hit replay — zero compiles, sub-ms hits
    order = np.random.default_rng(0).permutation(len(cells))
    with CompileCounter() as c_hits:
        for i in order:
            s, r = cells[int(i)]
            fut = svc.submit(make_query(s, r, **kw))
            assert fut.done(), "exact replay must resolve at submit"
            fut.result(0)

    # phase 3: neighbor replay — near-hit warm starts vs a cold control.
    # ρ shifts DOWN: ρ=0.95 in f64 (dist_tol 1e-11) sits in the
    # slow-mixing regime where the inner loop honestly exits MAX_ITER —
    # the smoke's job is measuring warm-start savings, not probing the
    # convergence frontier (that is test_solver_health's).
    shifted = [(s, r - 0.05) for s, r in cells]
    futs = [svc.submit(make_query(s, r, **kw)) for s, r in shifted]
    svc.flush()
    warm = [f.result(0) for f in futs]
    control = EquilibriumService(start_worker=False, max_batch=4,
                                 ladder=(1, 2, 4))
    futs = [control.submit(make_query(s, r, **kw)) for s, r in shifted]
    control.flush()
    cold_ctl = [f.result(0) for f in futs]
    warm_evals = sum(r.bisect_iters for r in warm)
    cold_evals = sum(r.bisect_iters for r in cold_ctl)
    warm_work = sum(r.egm_iters + r.dist_iters for r in warm)
    cold_work = sum(r.egm_iters + r.dist_iters for r in cold_ctl)

    snap = svc.metrics.snapshot()
    peak = peak_flops_per_chip(backend)
    record = {
        "metric": "serve_smoke",
        "backend": backend,
        "peak_flops_assumed": peak.assumed,
        "serve_smoke_cells": len(cells),
        "serve_cold_replay_wall_s": round(cold_wall, 3),
        # acceptance: zero compiles across the shuffled exact replay
        # (one executable per ladder shape, warmed in phase 1)
        "serve_hit_replay_compiles": c_hits.compile_events,
        "serve_hit_under_1ms": (snap["serve_hit_p50_ms"] is not None
                                and snap["serve_hit_p50_ms"] < 1.0),
        # acceptance: warm starts cut bisection evaluations on the
        # neighbor replay (and total inner-loop work rides along)
        "serve_near_rate_neighbor_replay": round(
            [r.path for r in warm].count("near") / len(warm), 4),
        "serve_warm_bisect_evals": int(warm_evals),
        "serve_cold_bisect_evals": int(cold_evals),
        "serve_warm_evals_reduction_pct": round(
            100.0 * (1.0 - warm_evals / max(cold_evals, 1)), 2),
        "serve_warm_work_reduction_pct": round(
            100.0 * (1.0 - warm_work / max(cold_work, 1)), 2),
    }
    record.update(snap)
    control.close()
    svc.close()
    print(f"[bench] serve smoke: hit p50={snap['serve_hit_p50_ms']}ms "
          f"compiles(replay)={c_hits.compile_events} "
          f"warm evals {warm_evals} vs cold {cold_evals} "
          f"(-{record['serve_warm_evals_reduction_pct']}%)",
          file=sys.stderr)
    return record


def _scenario_smoke() -> dict:
    """The ``--scenario-smoke`` acceptance run (ISSUE 9): the non-Aiyagari
    families ride the whole stack on CPU — a balanced Huggett sweep with
    certification and a quarantine drill, a serve replay (cold fill,
    zero-compile exact-hit replay, near-hit neighbor replay), and a small
    Epstein-Zin certified sweep — emitting the ``scenario_*`` record
    (per-scenario cells/sec, warm-replay compile count, cert verdicts)."""
    import numpy as np

    import jax

    jax.config.update("jax_enable_x64", True)
    from aiyagari_hark_tpu.parallel.sweep import run_sweep
    from aiyagari_hark_tpu.scenarios import scenario_names
    from aiyagari_hark_tpu.serve import EquilibriumService, make_query
    from aiyagari_hark_tpu.utils.config import SweepConfig
    from aiyagari_hark_tpu.utils.timing import CompileCounter

    backend = jax.default_backend()
    record = {"metric": "scenario_smoke", "backend": backend,
              "scenario_names": list(scenario_names())}

    # -- phase 1: Huggett balanced sweep, certified, quarantine drill ----
    hkw = dict(a_count=12, dist_count=48, labor_states=3, r_tol=1e-5,
               max_bisect=20, egm_tol=1e-5, dist_tol=1e-9,
               borrow_limit=-2.0)
    hcfg = SweepConfig(crra_values=(1.5, 3.0), rho_values=(0.3, 0.6),
                       schedule="balanced", n_buckets=2, certify=True)
    res = run_sweep("huggett", sweep=hcfg, **hkw)   # warm-up + compile
    t0 = time.perf_counter()
    timed = run_sweep("huggett", sweep=hcfg, perturb=1e-6, **hkw)
    wall = time.perf_counter() - t0
    cert = np.asarray(timed.cert_level)
    record.update({
        "scenario_huggett_cells": int(len(timed.rows)),
        "scenario_huggett_sweep_wall_s": round(wall, 3),
        "scenario_huggett_cells_per_sec": round(len(timed.rows) / wall,
                                                3),
        "scenario_huggett_failed_cells": int(
            len(timed.failed_cells())),
        "scenario_huggett_cert_certified": int((cert == 0).sum()),
        "scenario_huggett_cert_marginal": int((cert == 1).sum()),
        "scenario_huggett_cert_failed": int((cert == 2).sum()),
    })
    drill = run_sweep("huggett", sweep=hcfg.replace(certify=False),
                      inject_fault={"cell": 1, "at_iter": 2,
                                    "mode": "nan"},
                      max_retries=2, **hkw)
    record["scenario_huggett_quarantine_recovered"] = bool(
        int(drill.retries[1]) >= 1 and not len(drill.failed_cells()))
    print(f"[bench] scenario smoke: huggett sweep "
          f"{record['scenario_huggett_cells_per_sec']} cells/s, cert "
          f"C/M/F {record['scenario_huggett_cert_certified']}/"
          f"{record['scenario_huggett_cert_marginal']}/"
          f"{record['scenario_huggett_cert_failed']}, quarantine "
          f"recovered={record['scenario_huggett_quarantine_recovered']}",
          file=sys.stderr)

    # -- phase 2: Huggett serve replay -----------------------------------
    cells = [(s, r) for s in (1.5, 3.0) for r in (0.3, 0.6)]
    svc = EquilibriumService(start_worker=False, max_batch=4,
                             ladder=(1, 2, 4), donor_cutoff=1.0,
                             certify_before_cache=True)
    t0 = time.perf_counter()
    futs = [svc.submit(make_query(s, r, scenario="huggett", **hkw))
            for s, r in cells]
    svc.flush()
    cold = [f.result(0) for f in futs]
    cold_wall = time.perf_counter() - t0
    with CompileCounter() as c_hits:
        for s, r in cells:
            fut = svc.submit(make_query(s, r, scenario="huggett", **hkw))
            assert fut.done(), "exact replay must resolve at submit"
            fut.result(0)
    futs = [svc.submit(make_query(s, r + 0.05, scenario="huggett",
                                  **hkw)) for s, r in cells]
    svc.flush()
    near = [f.result(0) for f in futs]
    snap = svc.metrics.snapshot()
    record.update({
        "scenario_serve_cold_wall_s": round(cold_wall, 3),
        "scenario_serve_cold_paths": [r.path for r in cold],
        # acceptance: the warmed exact replay compiles NOTHING
        "scenario_serve_hit_replay_compiles": c_hits.compile_events,
        "scenario_serve_hit_p50_ms": snap["serve_hit_p50_ms"],
        "scenario_serve_near_rate": round(
            [r.path for r in near].count("near") / len(near), 4),
        "scenario_serve_certified": snap["serve_certified"],
        "scenario_serve_scenarios": snap["serve_scenarios"],
    })
    svc.close()
    print(f"[bench] scenario smoke: serve hit p50="
          f"{snap['serve_hit_p50_ms']}ms, replay compiles="
          f"{c_hits.compile_events}, near rate="
          f"{record['scenario_serve_near_rate']}", file=sys.stderr)

    # -- phase 3: Epstein-Zin certified mini-sweep -----------------------
    ekw = dict(a_count=10, dist_count=32, labor_states=3, r_tol=1e-4,
               max_bisect=12, egm_tol=1e-5, dist_tol=1e-8, ez_rho=2.0)
    ecfg = SweepConfig(crra_values=(2.0, 6.0), rho_values=(0.3,),
                       certify=True)
    t0 = time.perf_counter()
    ez = run_sweep("epstein_zin", sweep=ecfg, **ekw)
    ez_wall = time.perf_counter() - t0
    ez_cert = np.asarray(ez.cert_level)
    record.update({
        "scenario_ez_cells": int(len(ez.rows)),
        "scenario_ez_sweep_wall_s": round(ez_wall, 3),
        "scenario_ez_cells_per_sec": round(len(ez.rows) / ez_wall, 3),
        "scenario_ez_cert_certified": int((ez_cert == 0).sum()),
        "scenario_ez_cert_failed": int((ez_cert == 2).sum()),
        # risk aversion up at fixed EIS -> r* down (the EZ oracle)
        "scenario_ez_gamma_monotone": bool(
            float(ez.col("r_star")[1]) < float(ez.col("r_star")[0])),
    })
    print(f"[bench] scenario smoke: epstein_zin "
          f"{record['scenario_ez_cells_per_sec']} cells/s, cert "
          f"C/F {record['scenario_ez_cert_certified']}/"
          f"{record['scenario_ez_cert_failed']}, gamma-monotone="
          f"{record['scenario_ez_gamma_monotone']}", file=sys.stderr)
    return record


# Integrity smoke (ISSUE 6): certification/recheck economics measured at
# the committed-golden 12-cell configuration (tests/data/
# table2_golden_test.json — real f64 physics, so the certificate
# thresholds are exercised at their production scale), corruption drills
# at smoke-test grid sizes (detection is scale-independent).
INTEGRITY_SMOKE_KWARGS = dict(a_count=24, dist_count=150)
INTEGRITY_DRILL_KWARGS = dict(a_count=10, dist_count=32, labor_states=3,
                              r_tol=1e-5, max_bisect=24)
INTEGRITY_RECHECK_FRACTION = 0.25


def _integrity_smoke() -> dict:
    """The ``--integrity-smoke`` acceptance run (DESIGN §9): certify the
    12-cell golden sweep under reference AND mixed precision (every cell
    must come back CERTIFIED at default thresholds), measure the
    certification + recheck overheads against the sweep wall, and run
    every deterministic corruption drill — ledger bit flip, disk-store
    truncation/perturbation, post-solve lane perturbation (sweep SDC and
    serve path), shifted policy — asserting injected == detected."""
    import numpy as np

    import jax

    # The integrity acceptance is a CPU float64 statement (the golden
    # cells and the certificate thresholds are f64 physics); the smoke
    # runs standalone before any backend initializes, so pinning the
    # platform here is safe — same pattern as the bench's f64 oracle.
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

    from aiyagari_hark_tpu.parallel.sweep import (
        _canonical_dtype,
        _hashable_kwargs,
        run_table2_sweep,
    )
    from aiyagari_hark_tpu.utils.config import SweepConfig
    from aiyagari_hark_tpu.verify import CERT_CHECKS, certify_packed_rows

    backend = jax.default_backend()
    kw = dict(INTEGRITY_SMOKE_KWARGS)

    # phase 1: warm-up — compiles the sweep, certifier AND recheck
    # executables (the recheck's sample-sized launch is its own XLA
    # shape) so the timed overheads measure steady-state defense cost,
    # not compiles
    t0 = time.perf_counter()
    run_table2_sweep(
        SweepConfig(certify=True,
                    recheck_fraction=INTEGRITY_RECHECK_FRACTION), **kw)
    print(f"[bench] integrity smoke: warm-up in "
          f"{time.perf_counter() - t0:.1f}s", file=sys.stderr)

    # phase 2: timed reference run — certification on, SDC recheck on
    res = run_table2_sweep(
        SweepConfig(certify=True,
                    recheck_fraction=INTEGRITY_RECHECK_FRACTION),
        perturb=PERTURB, **kw)
    cert_overhead = res.certify_wall_seconds / max(res.wall_seconds, 1e-9)
    recheck_overhead = (res.recheck_wall_seconds
                        / max(res.wall_seconds, 1e-9))

    # per-check max residuals: re-grade the final rows through the (warm)
    # certifier.  The certifier reads columns 0 (r*), 1 (capital) and 6
    # (status) of a packed row; the labor column is not used, so a
    # placeholder is exact here.
    rows = np.stack(
        [res.r_star_pct / 100.0, res.capital, np.ones(len(res.capital)),
         res.bisect_iters, res.egm_iters, res.dist_iters, res.status,
         res.descent_steps, res.polish_steps,
         res.precision_escalations], axis=1).astype(np.float64)
    cells = np.stack([res.crra, res.labor_ar, res.labor_sd], axis=1)
    mk = dict(kw)
    mk.setdefault("dist_method", "auto")
    mk.setdefault("egm_method", "xla")
    certs = certify_packed_rows(rows, cells, _canonical_dtype(None),
                                _hashable_kwargs(mk))
    resid = np.asarray([[c.residual for c in cert.checks]
                        for cert in certs])

    # phase 3: mixed-precision certification (precision-aware thresholds)
    resm = run_table2_sweep(SweepConfig(certify=True), perturb=PERTURB,
                            precision="mixed", **kw)

    # phase 4: corruption drills — every injection must be detected by
    # the layer that first loads or certifies it
    injected, detected, detail = _integrity_drills()

    record = {
        "metric": "integrity_smoke",
        "backend": backend,
        "integrity_cells": len(cells),
        "integrity_cert_levels": [int(v) for v in res.cert_level],
        "integrity_mixed_cert_levels": [int(v) for v in resm.cert_level],
        "integrity_all_certified": bool((res.cert_level == 0).all()),
        "integrity_mixed_all_certified": bool(
            (resm.cert_level == 0).all()),
        # NaN residuals (an unevaluated check on a failed-status cell)
        # must not poison the JSON record: report None there — the
        # all_certified flag above is already false in that case
        **{f"integrity_max_{name}": (
            round(float(resid[:, j].max()), 10)
            if np.isfinite(resid[:, j].max()) else None)
           for j, name in enumerate(CERT_CHECKS)},
        "integrity_sweep_wall_s": round(res.wall_seconds, 3),
        "integrity_certify_wall_s": round(res.certify_wall_seconds, 3),
        "integrity_recheck_wall_s": round(res.recheck_wall_seconds, 3),
        # acceptance: certification + checksum verification < 10% of the
        # sweep wall at recheck_fraction=0 (the recheck is priced
        # separately — it deliberately re-solves cells)
        "integrity_cert_overhead_frac": round(cert_overhead, 4),
        "integrity_overhead_under_10pct": bool(cert_overhead < 0.10),
        "integrity_recheck_fraction": INTEGRITY_RECHECK_FRACTION,
        "integrity_recheck_overhead_frac": round(recheck_overhead, 4),
        "integrity_recheck_suspects": int(res.sdc_suspected.sum()),
        # acceptance: injected == detected, per drill and in total
        "integrity_injected": injected,
        "integrity_detected": detected,
        "integrity_injection_detail": detail,
    }
    print(f"[bench] integrity smoke: cert levels {record['integrity_cert_levels']} "
          f"(mixed {record['integrity_mixed_cert_levels']}), cert overhead "
          f"{100 * cert_overhead:.1f}%, recheck overhead "
          f"{100 * recheck_overhead:.1f}%, injected {injected} == "
          f"detected {detected}", file=sys.stderr)
    if injected != detected:
        print("[bench] integrity smoke: INJECTED != DETECTED — a "
              "corruption slipped through a detection layer",
              file=sys.stderr)
    return record


def _integrity_drills():
    """The deterministic corruption drill battery (tiny grids): returns
    (injected, detected, per-drill detail).  Each drill corrupts exactly
    one artifact and checks the responsible layer caught it."""
    import warnings as _warnings

    import numpy as np

    from aiyagari_hark_tpu.models.equilibrium import solve_calibration
    from aiyagari_hark_tpu.parallel.sweep import run_table2_sweep
    from aiyagari_hark_tpu.serve import (
        CertificationFailed,
        EquilibriumService,
        make_query,
    )
    from aiyagari_hark_tpu.utils.config import SweepConfig
    from aiyagari_hark_tpu.verify import (
        certify_equilibrium,
        corrupt_ledger_row,
        corrupt_store_entry,
        perturbed_policy,
    )

    kw = dict(INTEGRITY_DRILL_KWARGS)
    cfg = SweepConfig(crra_values=(1.0, 3.0), rho_values=(0.3, 0.6))
    detail = {}

    # drill 1: post-solve lane bit flip in the sweep -> bitwise recheck
    res = run_table2_sweep(cfg.replace(recheck_fraction=1.0),
                           inject_sdc={"cell": 1, "bit": 24}, **kw)
    detail["sweep_lane_bitflip"] = int(res.sdc_suspected.sum())

    # drill 2: ledger row bit flip between flush and resume -> resume
    # checksum verification quarantines + recomputes
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        ledger = os.path.join(td, "ledger.npz")
        from aiyagari_hark_tpu.utils.resilience import (
            Interrupted,
            clear_interrupt,
        )

        try:
            run_table2_sweep(cfg, resume_path=ledger,
                             inject_preempt={"after_bucket": 0,
                                             "mode": "flag"}, **kw)
            raise AssertionError("preemption injection did not fire")
        except Interrupted:
            # the injected flag must not bleed into the next drill (or
            # into the bench's own preemption guard)
            clear_interrupt()
        corrupt_ledger_row(ledger, cell=1, bit=21)
        with _warnings.catch_warnings(record=True) as w:
            _warnings.simplefilter("always")
            resumed = run_table2_sweep(cfg, resume_path=ledger, **kw)
        caught = any("checksum verification failed" in str(x.message)
                     for x in w)
        clean = run_table2_sweep(cfg, **kw)
        bit_identical = bool(np.array_equal(clean.r_star_pct,
                                            resumed.r_star_pct))
        detail["ledger_row_bitflip"] = int(caught and bit_identical)

    # drills 3+4: disk-store perturbation (parses fine, wrong bytes) and
    # truncation (unreadable) -> checksum/format eviction + deletion
    with tempfile.TemporaryDirectory() as td:
        svc = EquilibriumService(start_worker=False, max_batch=4,
                                 ladder=(1, 2, 4), disk_path=td)
        svc.query(3.0, 0.6, **kw)
        svc.close()
        path = corrupt_store_entry(td, mode="perturb", amplitude=1e-3)
        with _warnings.catch_warnings(record=True):
            _warnings.simplefilter("always")
            svc2 = EquilibriumService(start_worker=False, max_batch=4,
                                      ladder=(1, 2, 4), disk_path=td)
        evictions = svc2.store.integrity_counts()[
            "store_corrupt_evictions"]
        detail["store_perturbation"] = int(evictions == 1
                                           and not os.path.exists(path))
        svc2.query(3.0, 0.6, **kw)     # re-solve repopulates
        svc2.close()
        corrupt_store_entry(td, mode="truncate")
        with _warnings.catch_warnings(record=True):
            _warnings.simplefilter("always")
            svc3 = EquilibriumService(start_worker=False, max_batch=4,
                                      ladder=(1, 2, 4), disk_path=td)
        detail["store_truncation"] = int(
            svc3.store.integrity_counts()["store_corrupt_evictions"] == 1)
        svc3.close()

    # drill 5: off-by-one grid shift on a policy -> certification FAILED
    full = solve_calibration(3.0, 0.6, **kw)
    bad = full._replace(policy=perturbed_policy(full.policy, mode="shift"))
    detail["shifted_policy"] = int(
        certify_equilibrium(bad, crra=3.0, labor_ar=0.6, **kw).failed)

    # drill 6: serve-path lane perturbation -> certify_before_cache FAILS
    # the future and never caches
    svc = EquilibriumService(start_worker=False, max_batch=4,
                             ladder=(1, 2, 4), certify_before_cache=True,
                             inject_corrupt_lane={"at_launch": 0,
                                                  "lane": 0,
                                                  "amplitude": 3e-3})
    fut = svc.submit(make_query(3.0, 0.6, **kw))
    svc.flush()
    try:
        fut.result(0)
        served_failed = False
    except CertificationFailed:
        served_failed = True
    detail["serve_lane_perturbation"] = int(served_failed
                                            and svc.store.known() == 0)
    svc.close()

    injected = len(detail)
    detected = int(sum(detail.values()))
    return injected, detected, detail


# Observability smoke (ISSUE 7): trace/metrics/journal economics measured
# on the committed-golden 12-cell configuration (obs-disabled results must
# stay bit-identical to tests/data/table2_golden_test.json), event-drill
# battery at smoke-test grid sizes (the contract is scale-independent).
OBS_SMOKE_KWARGS = dict(a_count=24, dist_count=150)
OBS_DRILL_KWARGS = dict(a_count=10, dist_count=32, labor_states=3,
                        r_tol=1e-5, max_bisect=24)
OBS_OVERHEAD_BUDGET = 0.02


def _obs_smoke() -> dict:
    """The ``--obs-smoke`` acceptance run (DESIGN §10): run the 12-cell
    golden CPU sweep with tracing + metrics + journal on, assert the
    Chrome trace loads (valid JSON, >0 complete events, sane span
    nesting), the metrics snapshot round-trips and renders as Prometheus
    text, measure ``obs_overhead_frac`` (enabled vs disabled wall,
    acceptance < 2%), pin obs-disabled results bit-identical to the
    committed goldens AND obs-enabled results bit-identical to disabled,
    and re-run every injection drill with the journal enabled asserting
    injected == recorded typed events."""
    import tempfile

    import numpy as np

    import jax

    # CPU float64, like the integrity smoke: the golden cells are f64
    # physics and the smoke runs standalone before any backend initializes.
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

    import jax.numpy as jnp

    from aiyagari_hark_tpu.obs import (
        MetricsRegistry,
        ObsConfig,
        build_obs,
        read_journal,
        trace_nesting_ok,
    )
    from aiyagari_hark_tpu.parallel.sweep import run_table2_sweep
    from aiyagari_hark_tpu.utils.config import SweepConfig
    from aiyagari_hark_tpu.utils.timing import CompileCounter

    backend = jax.default_backend()
    kw = dict(OBS_SMOKE_KWARGS)
    golden_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "tests", "data", "table2_golden_test.json")
    with open(golden_path) as f:
        golden = json.load(f)
    assert golden["config"] == kw, "golden drifted from OBS_SMOKE_KWARGS"

    # phase 1: warm-up (compiles the sweep executable; obs never changes
    # the compiled program, so one warm-up serves both timed modes)
    t0 = time.perf_counter()
    run_table2_sweep(SweepConfig(), dtype=jnp.float64, **kw)
    print(f"[bench] obs smoke: warm-up in {time.perf_counter() - t0:.1f}s",
          file=sys.stderr)

    with tempfile.TemporaryDirectory() as td:
        # phases 2+3: timed obs-DISABLED vs obs-ENABLED runs, INTERLEAVED
        # (off, on, off, on) so slow machine-wide drift — thermal
        # throttling, a co-tenant waking up — lands on both modes instead
        # of penalizing whichever ran later; best-of per mode then
        # rejects the per-run spikes.  The last enabled run uses a bundle
        # built here (shared, not owned) so the registry/journal/trace
        # stay inspectable after the run closes.
        trace_path = os.path.join(td, "trace.json")
        journal_path = os.path.join(td, "events.jsonl")
        obs = build_obs(ObsConfig(enabled=True, trace_path=trace_path,
                                  journal_path=journal_path))
        walls_off, walls_on, res_off, res_on = [], [], None, None
        for _ in range(2):
            t0 = time.perf_counter()
            res_off = run_table2_sweep(SweepConfig(), dtype=jnp.float64,
                                       **kw)
            walls_off.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            with CompileCounter() as cc:
                res_on = run_table2_sweep(SweepConfig(),
                                          dtype=jnp.float64, obs=obs,
                                          **kw)
            walls_on.append(time.perf_counter() - t0)
        cc.publish(obs.registry)        # the CompileCounter mirror
        obs.close()                     # flushes the Chrome trace

        overhead = min(walls_on) / max(min(walls_off), 1e-9) - 1.0

        # acceptance: bit-identity — obs-enabled vs disabled, and
        # disabled vs the committed golden
        on_off_identical = bool(
            np.array_equal(res_on.r_star_pct, res_off.r_star_pct)
            and np.array_equal(res_on.saving_rate_pct,
                               res_off.saving_rate_pct)
            and np.array_equal(res_on.status, res_off.status))
        golden_r = np.asarray(golden["r_star_pct"], dtype=np.float64)
        golden_identical = bool(
            np.array_equal(np.asarray(res_off.r_star_pct), golden_r))
        golden_max_diff = float(
            np.max(np.abs(np.asarray(res_off.r_star_pct) - golden_r)))

        # acceptance: the Chrome trace loads and nests sanely
        with open(trace_path) as f:
            trace = json.load(f)
        events = trace["traceEvents"]
        complete = [e for e in events if e.get("ph") == "X"]
        nesting_ok = trace_nesting_ok(trace)
        span_names = sorted({e["name"] for e in complete})

        # acceptance: the metrics snapshot round-trips (and renders)
        snap = obs.registry.snapshot()
        roundtrip_ok = MetricsRegistry.restore(snap).snapshot() == snap
        prom_text = obs.registry.prometheus_text()

        journal = read_journal(journal_path, run_id=obs.run_id)

    # phase 4: the event-contract drill battery, journal enabled
    injected, detected, detail = _obs_drills()

    record = {
        "metric": "obs_smoke",
        "backend": backend,
        "obs_run_id": obs.run_id,
        "obs_smoke_cells": len(golden_r),
        # trace acceptance
        "obs_trace_events": len(complete),
        "obs_trace_loads": bool(len(complete) > 0),
        "obs_trace_nesting_ok": bool(nesting_ok),
        "obs_trace_span_names": span_names,
        # metrics acceptance
        "obs_metrics_count": len(snap),
        "obs_snapshot_roundtrip": bool(roundtrip_ok),
        "obs_prometheus_bytes": len(prom_text.encode()),
        # journal
        "obs_journal_events": len(journal),
        # overhead acceptance: enabled-vs-disabled < 2%
        "obs_wall_off_s": round(min(walls_off), 4),
        "obs_wall_on_s": round(min(walls_on), 4),
        "obs_overhead_frac": round(max(0.0, overhead), 4),
        "obs_overhead_under_2pct": bool(overhead
                                        < OBS_OVERHEAD_BUDGET),
        # bit-identity acceptance
        "obs_on_vs_off_bit_identical": on_off_identical,
        "obs_golden_bit_identical": golden_identical,
        "obs_golden_max_abs_diff": golden_max_diff,
        # event-contract acceptance: injected == recorded, per drill
        "obs_injected": injected,
        "obs_detected": detected,
        "obs_injection_detail": detail,
    }
    print(f"[bench] obs smoke: {len(complete)} trace events "
          f"(nesting {'ok' if nesting_ok else 'BROKEN'}), "
          f"{len(snap)} metrics (roundtrip "
          f"{'ok' if roundtrip_ok else 'BROKEN'}), "
          f"{len(journal)} journal events, overhead "
          f"{100 * max(0.0, overhead):.2f}%, injected {injected} == "
          f"detected {detected}", file=sys.stderr)
    if injected != detected:
        print("[bench] obs smoke: INJECTED != DETECTED — a lifecycle "
              "seam failed to journal its event", file=sys.stderr)
    return record


def _obs_drills():
    """The event-contract drill battery (tiny grids): every deterministic
    injection the previous PRs built, re-run with the journal enabled;
    each drill counts 1 iff exactly the matching typed event(s) landed.
    Returns (injected, detected, per-drill detail)."""
    import tempfile
    import warnings as _warnings

    from aiyagari_hark_tpu.obs import ObsConfig, build_obs, read_journal
    from aiyagari_hark_tpu.parallel.sweep import run_table2_sweep
    from aiyagari_hark_tpu.serve import (
        CertificationFailed,
        EquilibriumService,
        make_query,
    )
    from aiyagari_hark_tpu.utils.config import SweepConfig
    from aiyagari_hark_tpu.utils.resilience import (
        Interrupted,
        RetryPolicy,
        clear_interrupt,
    )
    from aiyagari_hark_tpu.verify import (
        corrupt_ledger_row,
        corrupt_store_entry,
    )

    kw = dict(OBS_DRILL_KWARGS)
    cfg = SweepConfig(crra_values=(1.0, 3.0), rho_values=(0.3, 0.6))
    detail = {}

    def events(path, etype, run_id=None):
        return read_journal(path, event=etype, run_id=run_id)

    with tempfile.TemporaryDirectory() as td:
        def jp(name):
            return os.path.join(td, name + ".jsonl")

        # drill 1: quarantined fault -> exactly one QUARANTINE
        run_table2_sweep(cfg, obs=ObsConfig(enabled=True,
                                            journal_path=jp("q")),
                         inject_fault={"cell": 1, "at_iter": 1,
                                       "mode": "nan"},
                         max_retries=2, **kw)
        q = events(jp("q"), "QUARANTINE")
        detail["quarantine_fault"] = int(len(q) == 1
                                         and q[0]["cell"] == 1)

        # drill 2: SDC lane bit flip -> exactly one SDC_SUSPECTED
        run_table2_sweep(cfg.replace(recheck_fraction=1.0),
                         obs=ObsConfig(enabled=True,
                                       journal_path=jp("sdc")),
                         inject_sdc={"cell": 1, "bit": 24},
                         quarantine=False, **kw)
        s = events(jp("sdc"), "SDC_SUSPECTED")
        detail["sdc_bit_flip"] = int(len(s) == 1 and s[0]["cell"] == 1)

        # drill 3: transient device fault -> exactly one RETRY_TRANSIENT
        run_table2_sweep(cfg, obs=ObsConfig(enabled=True,
                                            journal_path=jp("t")),
                         inject_transient={"at_call": 0, "times": 1},
                         retry=RetryPolicy(sleep=lambda s: None), **kw)
        detail["transient_fault"] = int(
            len(events(jp("t"), "RETRY_TRANSIENT")) == 1)

        # drills 4-6: preemption -> INTERRUPTED; corrupted ledger row ->
        # INTEGRITY_FAILED on the resume that also journals RESUME_RESTORE
        ledger = os.path.join(td, "ledger.npz")
        try:
            run_table2_sweep(cfg, resume_path=ledger,
                             obs=ObsConfig(enabled=True,
                                           journal_path=jp("pre")),
                             inject_preempt={"after_bucket": 0,
                                             "mode": "flag"}, **kw)
            raise AssertionError("preemption injection did not fire")
        except Interrupted:
            clear_interrupt()
        detail["preemption"] = int(
            len(events(jp("pre"), "INTERRUPTED")) == 1)
        corrupt_ledger_row(ledger, cell=1, bit=21)
        with _warnings.catch_warnings():
            _warnings.simplefilter("ignore")
            run_table2_sweep(cfg, resume_path=ledger,
                             obs=ObsConfig(enabled=True,
                                           journal_path=jp("res")),
                             **kw)
        integ = events(jp("res"), "INTEGRITY_FAILED")
        detail["ledger_corruption"] = int(len(integ) == 1
                                          and integ[0]["cells"] == [1])
        detail["resume_restore"] = int(
            len(events(jp("res"), "RESUME_RESTORE")) == 1)

        # drill 7: expired deadline -> exactly one DEADLINE_EXCEEDED
        t = [0.0]
        svc = EquilibriumService(start_worker=False, max_batch=4,
                                 ladder=(1, 2, 4), clock=lambda: t[0],
                                 obs=ObsConfig(enabled=True,
                                               journal_path=jp("dl")))
        fut = svc.submit(make_query(3.0, 0.6, **kw), deadline=0.5)
        t[0] = 1.0
        svc.flush()
        assert fut.exception(0) is not None
        svc.close()
        detail["serve_deadline"] = int(
            len(events(jp("dl"), "DEADLINE_EXCEEDED")) == 1)

        # drill 8: corrupt disk-store entry -> one STORE_EVICT_CORRUPT
        store_dir = os.path.join(td, "store")
        svc = EquilibriumService(start_worker=False, max_batch=4,
                                 ladder=(1, 2, 4), disk_path=store_dir)
        svc.query(3.0, 0.6, **kw)
        svc.close()
        corrupt_store_entry(store_dir, mode="perturb", amplitude=1e-3)
        with _warnings.catch_warnings():
            _warnings.simplefilter("ignore")
            svc = EquilibriumService(
                start_worker=False, max_batch=4, ladder=(1, 2, 4),
                disk_path=store_dir,
                obs=ObsConfig(enabled=True, journal_path=jp("ev")))
            svc.close()
        detail["store_eviction"] = int(
            len(events(jp("ev"), "STORE_EVICT_CORRUPT")) == 1)

        # drill 9: serve-path lane corruption under certify_before_cache
        # -> exactly one CERT_FAILED
        svc = EquilibriumService(
            start_worker=False, max_batch=4, ladder=(1, 2, 4),
            certify_before_cache=True,
            inject_corrupt_lane={"at_launch": 0, "lane": 0,
                                 "amplitude": 3e-3},
            obs=ObsConfig(enabled=True, journal_path=jp("cf")))
        fut = svc.submit(make_query(3.0, 0.6, **kw))
        svc.flush()
        try:
            fut.result(0)
            cert_failed = False
        except CertificationFailed:
            cert_failed = True
        svc.close()
        detail["serve_cert_failure"] = int(
            cert_failed and len(events(jp("cf"), "CERT_FAILED")) == 1)

    injected = len(detail)
    detected = int(sum(detail.values()))
    return injected, detected, detail


# Profile smoke (ISSUE 10): measured-cost-attribution acceptance on the
# same committed-golden 12-cell configuration as the obs smoke; the
# overhead budget covers obs AND the cost ledger together.
PROFILE_OVERHEAD_BUDGET = 0.02


def _profile_smoke() -> dict:
    """The ``--profile-smoke`` acceptance run (DESIGN §10b): run the
    12-cell golden CPU sweep with the performance tier on
    (``ObsConfig(profile=True)``), assert profiling-enabled results
    bit-identical to the committed goldens, obs+profile overhead < 2%
    against plain runs, ``profile_*`` record fields non-null (the
    cost-analysis fields may be null only with a recorded reason in
    ``profile_cost_sources``), the analytic-vs-measured FLOP cross-check
    recorded, and the bench-regression sentinel clean on the committed
    history."""
    import tempfile

    import numpy as np

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

    import jax.numpy as jnp

    from aiyagari_hark_tpu.obs import ObsConfig, build_obs, read_journal
    from aiyagari_hark_tpu.obs.regress import (
        REGRESSED,
        SEVERITY_NAMES,
        evaluate_history,
        load_bench_history,
    )
    from aiyagari_hark_tpu.parallel.sweep import run_table2_sweep
    from aiyagari_hark_tpu.utils.config import SweepConfig
    from aiyagari_hark_tpu.utils.timing import model_flops

    backend = jax.default_backend()
    kw = dict(OBS_SMOKE_KWARGS)
    golden_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "tests", "data", "table2_golden_test.json")
    with open(golden_path) as f:
        golden = json.load(f)
    assert golden["config"] == kw, "golden drifted from OBS_SMOKE_KWARGS"
    golden_r = np.asarray(golden["r_star_pct"], dtype=np.float64)

    # phase 1: warm-up — compiles the sweep executable AND pays the cost
    # ledger's one-time AOT capture (lower + cache-served compile), so
    # the timed phases below measure steady-state profiling overhead
    t0 = time.perf_counter()
    run_table2_sweep(SweepConfig(), dtype=jnp.float64, **kw)
    print(f"[bench] profile smoke: warm-up in "
          f"{time.perf_counter() - t0:.1f}s", file=sys.stderr)

    with tempfile.TemporaryDirectory() as td:
        trace_path = os.path.join(td, "trace.json")
        journal_path = os.path.join(td, "events.jsonl")
        obs = build_obs(ObsConfig(enabled=True, profile=True,
                                  trace_path=trace_path,
                                  journal_path=journal_path))
        # capture warm-up inside the profiled bundle (the AOT compile
        # lands here, outside the timed interleave below)
        run_table2_sweep(SweepConfig(), dtype=jnp.float64, obs=obs, **kw)

        # phases 2+3: timed plain vs profiled runs, interleaved (off,
        # on, off, on — same drift argument as the obs smoke), best-of
        timed_rounds = 2
        walls_off, walls_on, res_off, res_on = [], [], None, None
        for _ in range(timed_rounds):
            t0 = time.perf_counter()
            res_off = run_table2_sweep(SweepConfig(), dtype=jnp.float64,
                                       **kw)
            walls_off.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            res_on = run_table2_sweep(SweepConfig(), dtype=jnp.float64,
                                      obs=obs, **kw)
            walls_on.append(time.perf_counter() - t0)

        snap = obs.cost_ledger.snapshot()
        dev_stats = obs.sample_devices(where="profile_smoke")
        obs.close()     # journals PROFILE_SNAPSHOT + flushes the trace

        overhead = min(walls_on) / max(min(walls_off), 1e-9) - 1.0
        on_off_identical = bool(
            np.array_equal(res_on.r_star_pct, res_off.r_star_pct)
            and np.array_equal(res_on.saving_rate_pct,
                               res_off.saving_rate_pct)
            and np.array_equal(res_on.status, res_off.status))
        golden_identical = bool(
            np.array_equal(np.asarray(res_on.r_star_pct), golden_r))
        golden_max_diff = float(
            np.max(np.abs(np.asarray(res_on.r_star_pct) - golden_r)))

        # the analytic-vs-measured FLOP cross-check: the hand model from
        # the profiled run's own counters over XLA's static count x
        # launches (>> 1 expected: XLA counts a while body once; the
        # RATIO is the recorded, watchable number).  The ledger
        # accumulated over EVERY profiled run under this bundle (the
        # in-bundle warm-up plus the timed on-runs — identical inputs,
        # so identical per-run counters), so the analytic side must
        # cover the same launches or the ratio becomes a
        # harness-structure artifact.
        n_profiled_runs = 1 + timed_rounds   # in-bundle warm-up + on-runs
        analytic = n_profiled_runs * model_flops(
            float(res_on.egm_iters.sum()), float(res_on.dist_iters.sum()),
            kw["a_count"], LABOR_STATES, kw["dist_count"],
            dense_dist=(res_on.dist_method in ("dense", "pallas")))
        ratio = obs.cost_ledger.flops_model_vs_measured_ratio(analytic)

        snapshots = read_journal(journal_path, run_id=obs.run_id,
                                 event="PROFILE_SNAPSHOT")
        with open(trace_path) as f:
            trace = json.load(f)
        counter_events = [e for e in trace["traceEvents"]
                          if e.get("ph") == "C"]

    # phase 4: the bench-regression sentinel on the committed history
    report = evaluate_history(load_bench_history(_repo_dir()))
    regress_clean = bool(report.worst < REGRESSED)

    record = {
        "metric": "profile_smoke",
        "backend": backend,
        "profile_run_id": obs.run_id,
        "profile_smoke_cells": len(golden_r),
        # measured cost attribution (non-null acceptance; cost-analysis
        # fields may be null only with the reason in cost_sources)
        "profile_executables": snap["executables"],
        "profile_launches": snap["launches"],
        "profile_launch_wall_s": round(snap["launch_wall_s"], 4),
        "profile_lowering_wall_s": round(snap["lowering_wall_s"], 4),
        "profile_compile_wall_s": round(snap["compile_wall_s"], 4),
        "profile_measured_flops_total": snap["measured_flops_total"],
        "profile_bytes_accessed_total": snap["bytes_accessed_total"],
        "profile_achieved_flops_per_sec": snap["achieved_flops_per_sec"],
        "profile_arithmetic_intensity": snap["arithmetic_intensity"],
        "profile_roofline": snap["roofline"],
        "profile_mfu_pct": snap["mfu_pct"],
        "profile_cost_sources": snap["cost_sources"],
        "profile_flops_model_vs_measured_ratio": (
            None if ratio is None else round(ratio, 2)),
        "profile_trace_counter_events": len(counter_events),
        "profile_snapshot_events": len(snapshots),
        # per-device telemetry (CPU: zero devices report stats, by
        # design — the graceful-None contract)
        "profile_device_mem_stats_devices": dev_stats,
        # overhead + bit-identity acceptance
        "profile_wall_off_s": round(min(walls_off), 4),
        "profile_wall_on_s": round(min(walls_on), 4),
        "profile_overhead_frac": round(max(0.0, overhead), 4),
        "profile_overhead_under_2pct": bool(
            overhead < PROFILE_OVERHEAD_BUDGET),
        "profile_on_vs_off_bit_identical": on_off_identical,
        "profile_golden_bit_identical": golden_identical,
        "profile_golden_max_abs_diff": golden_max_diff,
        # bench-regression sentinel acceptance
        "profile_bench_regress_clean": regress_clean,
        "profile_bench_regress_worst": SEVERITY_NAMES[report.worst],
        "profile_bench_regress_findings": len(report.findings),
        "profile_bench_regress_ungraded": len(report.unknown_fields),
    }
    print(f"[bench] profile smoke: {snap['executables']} executable(s), "
          f"{snap['launches']} launches, "
          f"measured {snap['measured_flops_total'] or 0:.3e} FLOPs "
          f"({snap['roofline']}), model/measured "
          f"{ratio if ratio is not None else float('nan'):.1f}x, "
          f"overhead {100 * max(0.0, overhead):.2f}%, golden "
          f"{'OK' if golden_identical else 'DIFF'}, sentinel "
          f"{report.summary()}", file=sys.stderr)
    if not (on_off_identical and golden_identical):
        print("[bench] profile smoke: BIT-IDENTITY FAILED — profiling "
              "changed solver bits", file=sys.stderr)
    return record


# Compaction smoke (ISSUE 12): grid-compaction acceptance on the
# committed-golden 12-cell configuration — the compact policy must keep
# every cell CERTIFIED with r* within 0.1bp of the committed goldens
# while measurably shrinking gridpoints, inner-step work, and wall.
COMPACTION_SMOKE_KWARGS = dict(a_count=24, dist_count=150)
COMPACTION_DRIFT_BUDGET_BP = 0.1


def _compaction_smoke() -> dict:
    """The ``--compaction-smoke`` acceptance run (DESIGN §5b): run the
    12-cell golden CPU sweep under ``grid="compact"`` with certification
    on, assert every cell CERTIFIED and r* within 0.1bp of the committed
    goldens, pin the default ``grid="reference"`` path bit-identical to
    those goldens (and to the explicit-default spelling), and record the
    measured gridpoint / inner-step / effective-gridpoint-step / wall
    reductions as ``grid_*`` fields for the regression sentinel."""
    import numpy as np

    import jax

    # CPU float64, like the integrity/obs smokes: the golden cells are
    # f64 physics and the smoke runs standalone before any backend
    # initializes.
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

    from aiyagari_hark_tpu.models.equilibrium import solve_calibration_lean
    from aiyagari_hark_tpu.ops.grids import (
        build_asset_grids,
        grid_point_counts,
    )
    from aiyagari_hark_tpu.parallel.sweep import run_table2_sweep
    from aiyagari_hark_tpu.utils.config import SweepConfig

    backend = jax.default_backend()
    kw = dict(COMPACTION_SMOKE_KWARGS)
    golden_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "tests", "data", "table2_golden_test.json")
    with open(golden_path) as f:
        golden = json.load(f)
    assert golden["config"] == kw, \
        "golden drifted from COMPACTION_SMOKE_KWARGS"
    golden_r = np.asarray(golden["r_star_pct"], dtype=np.float64)

    a_ref, d_ref = grid_point_counts("reference", **kw)
    a_cmp, d_cmp = grid_point_counts("compact", **kw)
    _, _, knee = build_asset_grids("compact", 0.001, 50.0, kw["a_count"],
                                   2, kw["dist_count"])

    # phase 1: warm-up — compiles the reference and compact sweep
    # executables plus both certifiers, so the timed walls below measure
    # steady-state solve cost, not compiles
    t0 = time.perf_counter()
    run_table2_sweep(SweepConfig(certify=True), **kw)
    run_table2_sweep(SweepConfig(certify=True), grid="compact", **kw)
    print(f"[bench] compaction smoke: warm-up in "
          f"{time.perf_counter() - t0:.1f}s", file=sys.stderr)

    # phase 2: timed reference run — also the golden bit-identity pin
    res_ref = run_table2_sweep(SweepConfig(certify=True), perturb=0.0,
                               **kw)
    golden_identical = bool(
        np.array_equal(np.asarray(res_ref.r_star_pct), golden_r))

    # explicit-default spelling: one cell, bit-identical to the bare call
    # (hashable_kwargs drops grid="reference", so the two spellings share
    # one executable — this asserts the VALUES agree bitwise too)
    lean_bare = solve_calibration_lean(3.0, 0.6, **kw)
    lean_expl = solve_calibration_lean(3.0, 0.6, grid="reference", **kw)
    explicit_identical = bool(
        np.asarray(lean_bare.r_star).tobytes()
        == np.asarray(lean_expl.r_star).tobytes())

    # phase 3: timed compact run — certification is the referee
    res_cmp = run_table2_sweep(SweepConfig(certify=True), perturb=0.0,
                               grid="compact", **kw)
    drift_bp = float(
        np.max(np.abs(np.asarray(res_cmp.r_star_pct) - golden_r)) * 100.0)
    certs = [int(v) for v in res_cmp.cert_level]
    all_certified = bool((res_cmp.cert_level == 0).all())

    # measured work accounting: total inner steps, and EFFECTIVE
    # gridpoint-steps — each EGM step weighted by the policy-grid points
    # it updates, each distribution step by the histogram points it
    # pushes.  The grid ladder's COARSE (descent) steps are POLICY steps
    # on the every-other-point subsample (the distribution loop runs no
    # support ladder — DESIGN §5b), so exactly those steps count at half
    # the policy-grid weight.  This is the number every fixed point,
    # transfer, and flush actually scales with.
    steps_ref = int(res_ref.total_work().sum())
    steps_cmp = int(res_cmp.total_work().sum())
    eff_ref = int((res_ref.egm_iters * a_ref
                   + res_ref.dist_iters * d_ref).sum())
    eff_cmp = int((res_cmp.egm_iters * a_cmp
                   + res_cmp.dist_iters * d_cmp
                   - 0.5 * res_cmp.descent_steps * a_cmp).sum())
    wall_ref = float(res_ref.wall_seconds)
    wall_cmp = float(res_cmp.wall_seconds)

    record = {
        "metric": "compaction_smoke",
        "backend": backend,
        "grid_cells": len(golden_r),
        "grid_knee": round(float(knee), 4),
        "grid_points_reference": a_ref + d_ref,
        "grid_points_compact": a_cmp + d_cmp,
        "grid_point_reduction": round((a_ref + d_ref)
                                      / max(a_cmp + d_cmp, 1), 4),
        "grid_total_inner_steps_reference": steps_ref,
        "grid_total_inner_steps_compact": steps_cmp,
        "grid_step_reduction": round(steps_ref / max(steps_cmp, 1), 4),
        "grid_effective_gridpoint_steps_reference": eff_ref,
        "grid_effective_gridpoint_steps_compact": eff_cmp,
        "grid_effective_reduction": round(eff_ref / max(eff_cmp, 1), 4),
        "grid_reference_wall_s": round(wall_ref, 3),
        "grid_compact_wall_s": round(wall_cmp, 3),
        "grid_wall_reduction": round(wall_ref / max(wall_cmp, 1e-9), 4),
        # acceptance: verdicts + drift + bit-identity
        "grid_cert_levels": certs,
        "grid_cells_certified": int((res_cmp.cert_level == 0).sum()),
        "grid_all_certified": all_certified,
        "grid_r_drift_max_bp": round(drift_bp, 4),
        "grid_drift_under_budget": bool(
            drift_bp < COMPACTION_DRIFT_BUDGET_BP),
        "grid_escalations": int(res_cmp.precision_escalations.sum()),
        "grid_reference_bit_identical": bool(golden_identical
                                             and explicit_identical),
    }
    print(f"[bench] compaction smoke: {a_ref + d_ref} -> "
          f"{a_cmp + d_cmp} gridpoints (knee {knee:.1f}), "
          f"effective work x{record['grid_effective_reduction']:.2f}, "
          f"wall {wall_ref:.1f}s -> {wall_cmp:.1f}s, drift "
          f"{drift_bp:.4f}bp, certs {certs}, reference golden "
          f"{'OK' if golden_identical else 'DIFF'}", file=sys.stderr)
    if not all_certified or drift_bp >= COMPACTION_DRIFT_BUDGET_BP:
        print("[bench] compaction smoke: ACCEPTANCE FAILED — compact "
              "cells must all certify within the drift budget",
              file=sys.stderr)
    return record


# Kernel smoke (ISSUE 13): fused-kernel acceptance on the committed-golden
# 12-cell configuration — the fused path must keep every cell CERTIFIED
# with r* within 0.1bp of the committed goldens while the default
# reference path stays bit-identical; interpret-mode kernels on CPU (the
# correctness leg), real Mosaic kernels on TPU (the roofline leg).
KERNEL_SMOKE_KWARGS = dict(a_count=24, dist_count=150)
KERNEL_DRIFT_BUDGET_BP = 0.1


def _kernel_smoke() -> dict:
    """The ``--kernel-smoke`` acceptance run (ISSUE 13, DESIGN §4c): run
    the 12-cell golden sweep under ``kernel="fused"`` with certification
    on (profiled, so the CostLedger keys the fused executables), assert
    every cell CERTIFIED and r* within 0.1bp of the committed goldens,
    pin the default ``kernel="reference"`` path bit-identical to those
    goldens (and to the explicit-default spelling), run the bf16-rung
    escalation drill (injected descent fault -> escalation journaled in
    the PRECISION_ESCALATED slot, cell recovered), and grade the
    ``kernel_*`` record against the committed history with the
    regression sentinel.  On a TPU backend the profile snapshot is the
    roofline witness: the fused executables' class must move off
    "latency"; on CPU the class is recorded as measured (interpret-mode
    kernels measure nothing about the MXU)."""
    import numpy as np

    import jax

    # CPU float64 like the other golden smokes UNLESS a real accelerator
    # is ambient — the TPU leg is exactly what the roofline acceptance
    # needs, so don't force it away.
    on_tpu = False
    try:
        on_tpu = jax.default_backend() in ("tpu", "axon")
    except Exception:   # noqa: BLE001 — backend init failure = CPU leg
        pass
    if not on_tpu:
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_enable_x64", True)

    import jax.numpy as jnp

    import aiyagari_hark_tpu.models.household as hh
    from aiyagari_hark_tpu.models.equilibrium import solve_calibration_lean
    from aiyagari_hark_tpu.obs import ObsConfig, build_obs
    from aiyagari_hark_tpu.obs.regress import (
        REGRESSED,
        SEVERITY_NAMES,
        evaluate_history,
        load_bench_history,
    )
    from aiyagari_hark_tpu.parallel.sweep import run_table2_sweep
    from aiyagari_hark_tpu.utils.config import SweepConfig

    backend = jax.default_backend()
    n_devices = max(1, len(jax.devices()))
    dtype = jnp.float64 if not on_tpu else None
    kw = dict(KERNEL_SMOKE_KWARGS)
    golden_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "tests", "data", "table2_golden_test.json")
    with open(golden_path) as f:
        golden = json.load(f)
    assert golden["config"] == kw, "golden drifted from KERNEL_SMOKE_KWARGS"
    golden_r = np.asarray(golden["r_star_pct"], dtype=np.float64)

    # phase 1: warm-up — compiles the reference and fused sweep
    # executables plus both certifiers (separate compile-cache entries:
    # kernel="fused" rides kwargs_items into the work fingerprint)
    t0 = time.perf_counter()
    run_table2_sweep(SweepConfig(certify=True), dtype=dtype, **kw)
    run_table2_sweep(SweepConfig(certify=True, kernel="fused"),
                     dtype=dtype, **kw)
    print(f"[bench] kernel smoke: warm-up in "
          f"{time.perf_counter() - t0:.1f}s", file=sys.stderr)

    # phase 2: timed reference run — also the golden bit-identity pin
    t0 = time.perf_counter()
    res_ref = run_table2_sweep(SweepConfig(certify=True), perturb=0.0,
                               dtype=dtype, **kw)
    wall_ref = time.perf_counter() - t0
    golden_identical = bool(
        np.array_equal(np.asarray(res_ref.r_star_pct), golden_r))

    # explicit-default spelling: hashable_kwargs drops kernel="reference",
    # so the two spellings share one executable — assert the VALUES agree
    # bitwise too
    lean_bare = solve_calibration_lean(3.0, 0.6, dtype=dtype, **kw)
    lean_expl = solve_calibration_lean(3.0, 0.6, kernel="reference",
                                       dtype=dtype, **kw)
    explicit_identical = bool(
        np.asarray(lean_bare.r_star).tobytes()
        == np.asarray(lean_expl.r_star).tobytes())

    # phase 3: timed fused run, PROFILED — certification is the numeric
    # referee, the cost ledger the executable-identity/roofline witness
    # (profiling is bit-identical and <2% overhead, pinned by ISSUE 10)
    obs = build_obs(ObsConfig(enabled=True, profile=True))
    t0 = time.perf_counter()
    res_fus = run_table2_sweep(SweepConfig(certify=True, kernel="fused"),
                               perturb=0.0, dtype=dtype, obs=obs, **kw)
    wall_fus = time.perf_counter() - t0
    snap = obs.cost_ledger.snapshot()
    obs.close()
    # Drift baseline: the committed goldens are f64 CPU physics; on an
    # f32 accelerator the measured f32-vs-f64 noise (~0.097bp, BASELINE)
    # would eat the whole budget, so the TPU leg honestly measures the
    # fused engine against the SAME-backend reference sweep instead.
    base_r = golden_r if not on_tpu else np.asarray(res_ref.r_star_pct)
    drift_bp = float(
        np.max(np.abs(np.asarray(res_fus.r_star_pct) - base_r)) * 100.0)
    certs = [int(v) for v in res_fus.cert_level]
    all_certified = bool((res_fus.cert_level == 0).all())

    # phase 4: the bf16-rung escalation drill at the solver seam (the
    # deterministic, accuracy-meaningful level: a whole-bisection stall
    # drill mis-steers the descent-only bracket trips BY DESIGN and a
    # NaN drill routes through quarantine — that leg is pinned in tier-1
    # by test_kernel_policy's fused-sweep quarantine test).  The rung
    # (forced on off-TPU so the drill exercises the NEW rung, not just
    # the f32 descent) is poisoned; it must escalate into the
    # PRECISION_ESCALATED slot and the polish must still certify the
    # caller's tolerance, landing within it of the no-fault solve.
    from aiyagari_hark_tpu.models.household import (
        build_simple_model,
        solve_household,
    )

    drill_model = build_simple_model(labor_ar=0.6, dtype=dtype,
                                     a_count=kw["a_count"],
                                     dist_count=kw["dist_count"])
    saved_backends = hh.BF16_RUNG_BACKENDS
    try:
        hh.BF16_RUNG_BACKENDS = saved_backends + (backend,)
        pol_ok, _, _, st_ok, ph_ok = solve_household(
            1.02, 1.0, drill_model, 0.96, 3.0, precision="mixed",
            kernel="fused", return_phases=True)
        pol_dr, _, _, st_dr, ph_dr = solve_household(
            1.02, 1.0, drill_model, 0.96, 3.0, precision="mixed",
            kernel="fused", return_phases=True, descent_fault_iter=1)
    finally:
        hh.BF16_RUNG_BACKENDS = saved_backends
    drill_esc = int(np.asarray(ph_dr.escalated))
    drill_knot_diff = float(np.max(np.abs(
        np.asarray(pol_dr.c_knots) - np.asarray(pol_ok.c_knots))))
    # both solves certify sup-norm tol 1e-6; distinct certified fixed
    # points can sit ~tol/(1-lambda) apart (lambda ~ disc_fac)
    drill_ok = bool(int(st_dr) == 0 and drill_esc > 0
                    and not bool(np.asarray(ph_ok.escalated))
                    and drill_knot_diff < 1e-4)

    # throughput accounting (launch-wall-inclusive, like the headline
    # metric — see the module docstring's provenance note)
    gp = kw["a_count"] * LABOR_STATES
    gps_ref = float(res_ref.egm_iters.sum()) * gp / wall_ref / n_devices
    gps_fus = float(res_fus.egm_iters.sum()) * gp / wall_fus / n_devices

    record = {
        "metric": "kernel_smoke",
        "backend": backend,
        "kernel_cells": len(golden_r),
        "kernel_reference_wall_s": round(wall_ref, 3),
        "kernel_fused_wall_s": round(wall_fus, 3),
        "kernel_wall_reduction": round(wall_ref / max(wall_fus, 1e-9), 4),
        "kernel_reference_egm_gridpoints_per_sec_per_chip": round(gps_ref),
        "kernel_fused_egm_gridpoints_per_sec_per_chip": round(gps_fus),
        # acceptance: verdicts + drift + bit-identity
        "kernel_cert_levels": certs,
        "kernel_cells_certified": int((res_fus.cert_level == 0).sum()),
        "kernel_all_certified": all_certified,
        "kernel_r_drift_max_bp": round(drift_bp, 4),
        "kernel_drift_baseline": ("golden" if not on_tpu
                                  else "reference_same_backend"),
        "kernel_drift_under_budget": bool(
            drift_bp < KERNEL_DRIFT_BUDGET_BP),
        "kernel_escalations": int(res_fus.precision_escalations.sum()),
        "kernel_reference_bit_identical": bool(
            (golden_identical or on_tpu) and explicit_identical),
        # escalation drill (the reused PRECISION_ESCALATED slot)
        "kernel_drill_escalations": drill_esc,
        "kernel_drill_max_knot_diff": round(drill_knot_diff, 10),
        "kernel_drill_recovered": drill_ok,
        # cost-ledger witness: fused executables keyed apart (their
        # kwargs_items carry kernel="fused"), roofline class as measured
        "kernel_fused_executables": snap["executables"],
        "kernel_fused_launches": snap["launches"],
        "kernel_fused_mfu_pct": snap["mfu_pct"],
        "kernel_roofline": snap["roofline"],
        "kernel_roofline_not_latency": bool(snap["roofline"] != "latency"),
    }

    # phase 5: the regression sentinel on committed history + this record
    history = load_bench_history(_repo_dir()) + [("kernel_smoke", record)]
    report = evaluate_history(history)
    kernel_regressed = [f.metric for f in report.regressed()
                        if f.metric.startswith("kernel_")]
    record["kernel_sentinel_clean"] = not kernel_regressed
    record["kernel_sentinel_worst"] = SEVERITY_NAMES[report.worst]

    print(f"[bench] kernel smoke [{backend}]: reference {wall_ref:.1f}s "
          f"({gps_ref:.3g} gp/s) vs fused {wall_fus:.1f}s "
          f"({gps_fus:.3g} gp/s), drift {drift_bp:.4f}bp, certs {certs}, "
          f"drill esc={drill_esc} ({'OK' if drill_ok else 'FAILED'}), "
          f"roofline {snap['roofline']}, reference golden "
          f"{'OK' if golden_identical else 'DIFF'}", file=sys.stderr)
    if not all_certified or drift_bp >= KERNEL_DRIFT_BUDGET_BP:
        print("[bench] kernel smoke: ACCEPTANCE FAILED — fused cells "
              "must all certify within the drift budget", file=sys.stderr)
    if on_tpu and snap["roofline"] == "latency":
        print("[bench] kernel smoke: TPU ROOFLINE STILL LATENCY — the "
              "fused executables did not move the class", file=sys.stderr)
    return record


# Load smoke (ISSUE 8): the overload acceptance on the Table II lattice
# (both sd panels plus a third, so the cold-key space is wide enough to
# saturate) at serving grid sizes.  Modeled capacity is max_batch /
# batch_service_s = 400 cold queries per clock second; the spec arrives
# at 3x that with a flat-ish Zipf, so admission control, shedding,
# degraded answers, and the deadline machinery all genuinely fire while
# the Zipf head keeps a hot exact-hit stream alive.
LOAD_SMOKE_CELLS = tuple((s, r, sd) for sd in (0.2, 0.3, 0.4)
                         for s in (1.0, 3.0, 5.0)
                         for r in (0.0, 0.3, 0.6, 0.9))


def _load_smoke() -> dict:
    """The ``--load-smoke`` acceptance run (DESIGN §11): replay a seeded
    open-loop Zipf overload scenario at 2.5x modeled capacity on the
    injected clock, twice — the outcome digests must match bit-for-bit;
    zero futures may be left unresolved; exact hits must stay fast under
    saturation (real-wall p50 vs an unsaturated warm baseline); every
    shed/reject/degrade must appear in the typed event journal exactly
    as often as the report counts it (injected == journaled); and a
    breaker drill must walk OPEN -> REJECT -> PROBE -> CLOSE with one
    journal event each.  Emits the ``load_*`` record fields."""
    import tempfile

    import numpy as np

    from aiyagari_hark_tpu.obs import ObsConfig, read_journal
    from aiyagari_hark_tpu.serve import (
        AdmissionPolicy,
        CircuitOpen,
        EquilibriumService,
        EquilibriumSolveFailed,
        LoadSpec,
        make_query,
        run_load,
    )

    kw = dict(SERVE_SMOKE_KWARGS)
    spec = LoadSpec(cells=LOAD_SMOKE_CELLS, model_kwargs=kw,
                    n_queries=300, seed=20260803, rate=1200.0,
                    zipf_s=0.5, priority_mix=(0.5, 0.3, 0.2),
                    deadline_frac=0.2, deadline_s=0.015,
                    degraded_frac=0.3, batch_service_s=0.01,
                    warm_frac=0.2)
    policy = AdmissionPolicy(max_work=2.5, est_batch_s=0.01,
                             degraded_pressure=0.4,
                             degraded_distance=0.6)

    # unsaturated exact-hit baseline (real wall): one warm service, the
    # hottest cell, repeated hit submits
    svc = EquilibriumService(start_worker=False, max_batch=4,
                             ladder=(1, 2, 4))
    hot = LOAD_SMOKE_CELLS[0]
    svc.query(hot[0], hot[1], labor_sd=hot[2], **kw)
    base_walls = []
    for _ in range(64):
        t0 = time.perf_counter()
        fut = svc.submit(make_query(hot[0], hot[1], labor_sd=hot[2],
                                    **kw))
        base_walls.append((time.perf_counter() - t0) * 1e3)
        assert fut.done()
    svc.close()
    hit_p50_baseline_ms = float(np.median(base_walls))

    with tempfile.TemporaryDirectory() as td:
        jp = os.path.join(td, "load.jsonl")
        t0 = time.perf_counter()
        rep = run_load(spec, admission=policy,
                       obs=ObsConfig(enabled=True, journal_path=jp),
                       measure_hit_wall=True)
        load_wall = time.perf_counter() - t0
        rep2 = run_load(spec, admission=policy)

        # injected == journaled, event by event
        snap = rep.snapshot
        pairs = (
            ("OVERLOADED", snap["serve_overloaded"]),
            ("LOAD_SHED", snap["serve_load_sheds"]),
            ("DEGRADED_ANSWER",
             rep.counts.get("served:degraded_neighbor", 0)),
            ("CIRCUIT_REJECT", snap["serve_circuit_rejects"]),
            ("DEADLINE_EXCEEDED",
             snap["serve_deadline_rejects_submit"]
             + snap["serve_deadline_expirations"]),
        )
        journal_ok = all(len(read_journal(jp, event=e)) == n
                         for e, n in pairs)

    # breaker drill: a poisoned region walks the full state machine,
    # one typed journal event per transition
    with tempfile.TemporaryDirectory() as td:
        jb = os.path.join(td, "breaker.jsonl")
        clk = [0.0]
        svc = EquilibriumService(
            start_worker=False, max_batch=4, ladder=(1, 2, 4),
            clock=lambda: clk[0], inject_fault_mode="nan",
            admission=AdmissionPolicy(breaker_failures=1,
                                      breaker_cooldown_s=1.0),
            obs=ObsConfig(enabled=True, journal_path=jb))
        fut = svc.submit(make_query(3.0, 0.6, fault_iter=0, **kw))
        svc.flush()
        try:
            fut.result(0)
            drill_ok = False
        except EquilibriumSolveFailed:
            try:
                svc.submit(make_query(3.0, 0.6, **kw))
                drill_ok = False
            except CircuitOpen:
                clk[0] = 1.0
                probe = svc.submit(make_query(3.0, 0.6, **kw))
                svc.flush()
                drill_ok = probe.exception(0) is None
        svc.close()
        drill_ok = bool(drill_ok) and all(
            len(read_journal(jb, event=e)) == 1
            for e in ("CIRCUIT_OPEN", "CIRCUIT_REJECT",
                      "CIRCUIT_PROBE", "CIRCUIT_CLOSE"))

    hit_p50_sat_ms = (float(np.median(rep.hit_wall_ms))
                      if rep.hit_wall_ms else None)
    hit_ok = (hit_p50_sat_ms is not None
              and hit_p50_sat_ms < max(5.0 * hit_p50_baseline_ms, 2.0))
    served = sum(n for o, n in rep.counts.items()
                 if o.startswith("served:"))
    record = {
        "metric": "load_smoke",
        "backend": __import__("jax").default_backend(),
        "load_cells": len(LOAD_SMOKE_CELLS),
        "load_requests": rep.arrivals,
        "load_rate_over_capacity": round(
            spec.rate * spec.batch_service_s / 4.0, 2),
        "load_wall_s": round(load_wall, 3),
        "load_digest": rep.digest,
        # acceptance: seeded replay is bit-reproducible across two runs
        "load_replay_bit_reproducible": rep.digest == rep2.digest,
        # acceptance: zero unresolved futures
        "load_unresolved": rep.unresolved,
        "load_served": served,
        "load_served_hit": rep.counts.get("served:hit", 0),
        "load_served_near": rep.counts.get("served:near", 0),
        "load_served_cold": rep.counts.get("served:cold", 0),
        "load_degraded": rep.counts.get("served:degraded_neighbor", 0),
        "load_overloaded": snap["serve_overloaded"],
        "load_sheds": snap["serve_load_sheds"],
        "load_circuit_rejects": snap["serve_circuit_rejects"],
        "load_deadline_rejects": snap["serve_deadline_rejects_submit"],
        "load_deadline_expirations": snap["serve_deadline_expirations"],
        "load_failures": snap["serve_failures"],
        "load_p50_clock_ms": rep.p50_ms["all"],
        "load_p99_clock_ms": rep.p99_ms["all"],
        "load_queue_depth_p50": rep.queue_depth_p50,
        "load_queue_depth_p99": rep.queue_depth_p99,
        "load_queue_depth_peak": rep.queue_depth_peak,
        # acceptance: exact hits stay fast under saturation
        "load_hit_p50_baseline_ms": round(hit_p50_baseline_ms, 4),
        "load_hit_p50_saturated_ms": (None if hit_p50_sat_ms is None
                                      else round(hit_p50_sat_ms, 4)),
        "load_hit_p50_ok": hit_ok,
        # acceptance: injected == journaled; breaker walks its machine
        "load_journal_consistent": journal_ok,
        "load_breaker_drill": int(drill_ok),
    }
    n_deadline = (record["load_deadline_rejects"]
                  + record["load_deadline_expirations"])
    print(f"[bench] load smoke: {rep.arrivals} arrivals at "
          f"{record['load_rate_over_capacity']}x capacity -> "
          f"{served} served ({record['load_degraded']} degraded) / "
          f"{record['load_overloaded']} overloaded / "
          f"{record['load_sheds']} shed / "
          f"{n_deadline} deadline; "
          f"depth p99={rep.queue_depth_p99} "
          f"digest={'OK' if record['load_replay_bit_reproducible'] else 'MISMATCH'} "
          f"unresolved={rep.unresolved} "
          f"hit p50 {hit_p50_sat_ms}ms vs {hit_p50_baseline_ms:.3f}ms "
          f"journal={'OK' if journal_ok else 'MISMATCH'} "
          f"breaker_drill={'OK' if drill_ok else 'FAIL'}",
          file=sys.stderr)
    return record


# Fleet smoke (ISSUE 15): the 12-cell golden lattice with the shared
# labor_sd spelled explicitly — the fleet workers' query cells.
FLEET_SMOKE_CELLS = tuple((s, r, 0.2) for s in (1.0, 3.0, 5.0)
                          for r in (0.0, 0.3, 0.6, 0.9))


def _served_vs_reference(served_values: dict, kw: dict):
    """Bit-identity leg shared by the fleet and chaos smokes — the PR
    4/11 contract, replayed through one local single-process service: a
    served result equals a batch-of-1 ``reference_solve`` WITH THE SAME
    SEED, bit for bit.  The harness captured each solved fingerprint's
    ``bracket_init`` from the solving worker's response (the JSON hop is
    bit-exact: floats serialize via repr round-trip), so seeded keys
    compare on EVERY value field including the warm-seed-dependent
    capital; keys whose solving response was lost (a prefetch solve
    nobody queried before hitting, or a killed worker's in-flight reply)
    compare on the seed-independent fields — r* (PR 2's verified-bracket
    contract pins the root bits warm or cold), labor, status.  Returns
    ``(mismatches, seeded_compares)``."""
    from aiyagari_hark_tpu.serve import make_query
    from aiyagari_hark_tpu.serve.service import EquilibriumService

    ref_svc = EquilibriumService(start_worker=False, max_batch=4,
                                 ladder=(1, 2, 4))
    mismatches = 0
    seeded = 0
    try:
        for _key, vals in sorted(served_values.items()):
            c = vals["cell"]
            q = make_query(c[0], c[1], labor_sd=c[2], **kw)
            seed = vals.get("bracket_init")
            if seed is not None:
                seeded += 1
                ref = ref_svc.reference_solve(q, bracket_init=tuple(seed))
                same = (vals["r_star"] == ref.r_star
                        and vals["capital"] == ref.capital
                        and vals["labor"] == ref.labor
                        and vals["status"] == ref.status)
            else:
                ref = ref_svc.reference_solve(q)
                same = (vals["r_star"] == ref.r_star
                        and vals["labor"] == ref.labor
                        and vals["status"] == ref.status)
            if not same:
                mismatches += 1
    finally:
        ref_svc.close()
    return mismatches, seeded


def _fleet_smoke() -> dict:
    """The ``--fleet-smoke`` acceptance run (ISSUE 15, DESIGN §14): 4
    worker PROCESSES over one shared disk store replay deterministic
    per-worker-seeded Zipf mixes of the 12-cell golden lattice over
    HTTP, with worker 3 SIGTERMed mid-load.  Measured acceptance:
    fleet-wide dedup ratio 1.0 (each distinct cold fingerprint solved
    exactly once across the fleet — the claim/lease election), served
    values bit-identical to a single-process ``reference_solve`` (and
    to each other: loser-serves-winner), speculative prefetch
    converting >= 1 would-be cold miss into an exact hit, fleet p50/p99
    per path in the ``fleet_*`` record graded by the regression
    sentinel, and zero hung arrivals / leaked leases after the SIGTERM
    (typed Interrupted journaled, exit 75, lease TTL reclaims)."""
    import tempfile

    import numpy as np

    from aiyagari_hark_tpu.obs.regress import (
        SEVERITY_NAMES,
        evaluate_history,
        load_bench_history,
    )
    from aiyagari_hark_tpu.serve.loadgen import FleetSpec, run_fleet_load

    kw = dict(SERVE_SMOKE_KWARGS)
    spec = FleetSpec(cells=FLEET_SMOKE_CELLS, model_kwargs=kw,
                     n_workers=4, queries_per_worker=30,
                     seed=20260804, zipf_s=0.8, prefetch_k=2,
                     lease_ttl_s=2.0, warm_count=0,
                     sigterm_worker=3, sigterm_after=10)
    t0 = time.perf_counter()
    with tempfile.TemporaryDirectory() as td:
        rep = run_fleet_load(spec, store_dir=os.path.join(td, "store"))
    wall = time.perf_counter() - t0

    mismatches, seeded = _served_vs_reference(rep.served_values, kw)

    served = sum(n for o, n in rep.counts.items()
                 if o.startswith("served:"))
    drill_rc = rep.interrupted_rcs.get(spec.sigterm_worker)
    record = {
        "metric": "fleet_smoke",
        "backend": __import__("jax").default_backend(),
        "fleet_workers": rep.workers,
        "fleet_cells": len(FLEET_SMOKE_CELLS),
        "fleet_requests": rep.arrivals,
        "fleet_wall_s": round(wall, 3),
        "fleet_trace_digest": rep.trace_digest,
        "fleet_served": served,
        "fleet_served_hit": rep.counts.get("served:hit", 0),
        "fleet_served_near": rep.counts.get("served:near", 0),
        "fleet_served_cold": rep.counts.get("served:cold", 0),
        # acceptance: every arrival reached a terminal outcome
        "fleet_unresolved": rep.unresolved,
        # acceptance: exactly-once fleet-wide (claim/lease election)
        "fleet_cold_solves": rep.cold_solves,
        "fleet_distinct_fingerprints": rep.distinct_published,
        "fleet_dedup_ratio": rep.dedup_ratio,
        "fleet_dedup_exact": rep.dedup_ratio == 1.0,
        # acceptance: served values == reference_solve, and every
        # response for one fingerprint agreed (loser-serves-winner)
        "fleet_bit_identical": (mismatches == 0
                                and rep.value_divergence == 0),
        "fleet_value_mismatches": mismatches,
        "fleet_value_divergence": rep.value_divergence,
        "fleet_seeded_compares": seeded,
        # acceptance: prefetch converted >= 1 would-be cold miss
        "fleet_prefetch_issued": rep.prefetch_issued,
        "fleet_prefetch_converted": rep.prefetch_converted,
        "fleet_remote_hits": rep.remote_hits,
        "fleet_claims_won": rep.claims_won,
        "fleet_claims_lost": rep.claims_lost,
        "fleet_lease_reclaims": rep.lease_reclaims,
        # acceptance: SIGTERM drill — typed Interrupted, exit 75, no
        # leaked leases after the TTL sweep
        "fleet_leases_leaked": rep.leases_leaked,
        "fleet_drill_rc": drill_rc,
        "fleet_drill_interrupted_typed": (drill_rc == 75
                                          and rep.interrupted_journaled),
        # fleet-wide latency per path (real wall, HTTP hop included)
        "fleet_hit_p50_ms": rep.p50_ms.get("hit"),
        "fleet_hit_p99_ms": rep.p99_ms.get("hit"),
        "fleet_near_p50_ms": rep.p50_ms.get("near"),
        "fleet_cold_p50_ms": rep.p50_ms.get("cold"),
        "fleet_cold_p99_ms": rep.p99_ms.get("cold"),
    }
    history = load_bench_history(_repo_dir()) + [("fleet_smoke", record)]
    report = evaluate_history(history)
    fleet_regressed = [f.metric for f in report.regressed()
                      if f.metric.startswith("fleet_")]
    record["fleet_sentinel_clean"] = not fleet_regressed
    record["fleet_sentinel_worst"] = SEVERITY_NAMES[report.worst]

    print(f"[bench] fleet smoke: {rep.workers} workers, "
          f"{rep.arrivals} arrivals -> {served} served "
          f"(hit/near/cold {record['fleet_served_hit']}/"
          f"{record['fleet_served_near']}/{record['fleet_served_cold']}),"
          f" dedup {rep.dedup_ratio} ({rep.cold_solves} solves / "
          f"{rep.distinct_published} fingerprints), bit-identical="
          f"{'OK' if record['fleet_bit_identical'] else 'MISMATCH'}, "
          f"prefetch {rep.prefetch_issued} issued / "
          f"{rep.prefetch_converted} converted, hit p50 "
          f"{record['fleet_hit_p50_ms']}ms, drill rc={drill_rc} "
          f"journaled={rep.interrupted_journaled} "
          f"leaked={rep.leases_leaked} unresolved={rep.unresolved}",
          file=sys.stderr)
    ok = (rep.dedup_ratio == 1.0 and record["fleet_bit_identical"]
          and rep.prefetch_converted >= 1 and rep.unresolved == 0
          and rep.leases_leaked == 0
          and record["fleet_drill_interrupted_typed"])
    if not ok:
        print("[bench] fleet smoke: ACCEPTANCE FAILED — see the "
              "fleet_* fields above", file=sys.stderr)
    return record


# Chaos smoke (ISSUE 16): five drill cells DISJOINT from the traffic
# lattice (labor_sd 0.25 vs the lattice's 0.2), one per drill, so the
# drills' expected duplicate publishes never contaminate the clean
# traffic dedup ledger.
CHAOS_DRILL_CELLS = tuple((s, r, 0.25) for (s, r) in
                          ((1.0, 0.0), (3.0, 0.3), (5.0, 0.6),
                           (1.0, 0.9), (3.0, 0.0)))


def _chaos_smoke() -> dict:
    """The ``--chaos-smoke`` acceptance run (ISSUE 16, DESIGN §14): 4
    worker processes (CPU) over one shared store replay the 12-cell
    golden lattice through the RESILIENT client (typed retry + hedged
    reads) while the elasticity schedule churns the pool (one worker
    leaves mid-load, a fresh one joins), then every chaos drill runs
    sequentially — torn publish, store partition, SIGKILL mid-solve,
    heartbeat stall, skewed-clock double election.  Measured
    acceptance: every drill detected from public artifacts
    (detected == injected), the drilled dedup ratio back at 1.0 with
    the drills' EXPECTED duplicates separated out, zero leaked leases
    and zero unresolved arrivals, served values bit-identical to
    same-seed ``reference_solve``, availability and churn-p99 recorded
    as sentinel-graded ``chaos_*`` fields."""
    import tempfile

    from aiyagari_hark_tpu.obs.regress import (
        SEVERITY_NAMES,
        evaluate_history,
        load_bench_history,
    )
    from aiyagari_hark_tpu.serve.chaos import ChaosPlan
    from aiyagari_hark_tpu.serve.loadgen import FleetSpec, run_fleet_load

    kw = dict(SERVE_SMOKE_KWARGS)
    spec = FleetSpec(cells=FLEET_SMOKE_CELLS, model_kwargs=kw,
                     n_workers=4, queries_per_worker=30,
                     seed=20260806, zipf_s=0.8, prefetch_k=0,
                     lease_ttl_s=2.0, warm_count=0)
    plan = ChaosPlan(drill_cells=CHAOS_DRILL_CELLS,
                     churn=((40, "leave", 2), (60, "join", None)),
                     slow_publish_s=8.0, partition_reads=2,
                     recovery_queries=6, settle_timeout_s=60.0)
    t0 = time.perf_counter()
    with tempfile.TemporaryDirectory() as td:
        rep = run_fleet_load(spec, store_dir=os.path.join(td, "store"),
                             chaos=plan)
    wall = time.perf_counter() - t0
    ch = rep.chaos
    assert ch is not None, "run_fleet_load(chaos=...) returned no ledger"

    mismatches, seeded = _served_vs_reference(rep.served_values, kw)
    served = sum(n for o, n in rep.counts.items()
                 if o.startswith("served:"))
    drills_ok = all(r["detected"] == r["injected"]
                    for r in ch["drills"])
    record = {
        "metric": "chaos_smoke",
        "backend": __import__("jax").default_backend(),
        "chaos_workers": rep.workers,
        "chaos_arrivals": rep.arrivals,
        "chaos_wall_s": round(wall, 3),
        "chaos_served": served,
        # acceptance: availability under churn + drills (served /
        # submitted through the retrying client)
        "chaos_availability": ch["availability"],
        "chaos_unresolved": rep.unresolved,
        # acceptance: every drill's fault detected from journals /
        # process state (the ledger counts FIRINGS, not armings)
        "chaos_drills_injected": ch["injected"],
        "chaos_drills_detected": ch["detected"],
        "chaos_detect_all": drills_ok,
        # one flat field per drill (nested dicts flatten into dotted
        # keys the direction table can't resolve; the "detected" affix
        # rule grades these NEUTRAL)
        **{f"chaos_detected_{r['drill']}": int(r["detected"])
           for r in ch["drills"]},
        # acceptance: exactly-once after recovery — expected drill
        # duplicates separated, everything else published once, and the
        # recovery phase re-published NOTHING already published
        "chaos_dedup_ratio": ch["dedup_ratio"],
        "chaos_dedup_exact": ch["dedup_ratio"] == 1.0,
        "chaos_traffic_dedup_exact": rep.dedup_ratio == 1.0,
        "chaos_recovery_dup_publishes": ch["recovery_dup_publishes"],
        "chaos_recovery_served": ch["recovery_served"],
        "chaos_recovery_errors": ch["recovery_errors"],
        # acceptance: no leaked leases after the TTL sweep
        "chaos_leases_leaked": rep.leases_leaked,
        "chaos_reclaims": rep.lease_reclaims,
        # elasticity schedule accounting
        "chaos_joins": ch["joins"],
        "chaos_leaves": ch["leaves"],
        "chaos_kills": ch["kills"],
        # hedged reads (known-published fingerprints only)
        "chaos_hedges_issued": ch["hedges"]["issued"],
        "chaos_hedges_won": ch["hedges"]["won"],
        # acceptance: bit-identity against same-seed reference solves
        "chaos_bit_identical": (mismatches == 0
                                and rep.value_divergence == 0),
        "chaos_value_mismatches": mismatches,
        "chaos_value_divergence": rep.value_divergence,
        "chaos_seeded_compares": seeded,
        # latency under churn (real wall, HTTP hop + retries included)
        "chaos_churn_p99_ms": ch["churn_p99_ms"],
        "chaos_hit_p50_ms": rep.p50_ms.get("hit"),
        "chaos_hit_p99_ms": rep.p99_ms.get("hit"),
    }
    history = load_bench_history(_repo_dir()) + [("chaos_smoke", record)]
    report = evaluate_history(history)
    chaos_regressed = [f.metric for f in report.regressed()
                       if f.metric.startswith("chaos_")]
    record["chaos_sentinel_clean"] = not chaos_regressed
    record["chaos_sentinel_worst"] = SEVERITY_NAMES[report.worst]

    print(f"[bench] chaos smoke: {rep.workers} workers "
          f"(+{ch['joins']} joined, -{ch['leaves']} left, "
          f"{ch['kills']} killed), {rep.arrivals} arrivals -> "
          f"{served} served (availability {ch['availability']}), "
          f"drills {ch['detected']}/{ch['injected']} detected "
          f"{dict((r['drill'], r['detected']) for r in ch['drills'])}, "
          f"dedup drilled={ch['dedup_ratio']} "
          f"traffic={rep.dedup_ratio} recovery_dup="
          f"{ch['recovery_dup_publishes']}, hedges "
          f"{ch['hedges']['issued']} issued / {ch['hedges']['won']} "
          f"won, bit-identical="
          f"{'OK' if record['chaos_bit_identical'] else 'MISMATCH'}, "
          f"leaked={rep.leases_leaked} unresolved={rep.unresolved} "
          f"churn p99={ch['churn_p99_ms']}ms",
          file=sys.stderr)
    ok = (drills_ok and ch["dedup_ratio"] == 1.0
          and rep.dedup_ratio == 1.0
          and ch["recovery_dup_publishes"] == 0
          and rep.leases_leaked == 0 and rep.unresolved == 0
          and record["chaos_bit_identical"]
          and ch["joins"] >= 1 and ch["leaves"] >= 1
          and ch["kills"] >= 1)
    if not ok:
        print("[bench] chaos smoke: ACCEPTANCE FAILED — see the "
              "chaos_* fields above", file=sys.stderr)
    return record


# DR smoke (ISSUE 18): five drill cells disjoint from BOTH the traffic
# lattice (labor_sd 0.2) and the chaos drill cells (0.25), one per
# disaster-recovery drill; the mid-solve kill uses a sixth.
DR_DRILL_CELLS = tuple((s, r, 0.3) for (s, r) in
                       ((1.0, 0.0), (3.0, 0.3), (5.0, 0.6),
                        (1.0, 0.9), (3.0, 0.9)))
DR_KILL_CELL = (5.0, 0.0, 0.3)


def _dr_smoke() -> dict:
    """The ``--dr-smoke`` acceptance run (ISSUE 18, DESIGN §16): 4
    worker processes coordinate through a 3-replica WAL-backed quorum
    CAS (real processes, real sockets) while serving the 12-cell golden
    lattice; the disaster-recovery drills attack the substrate —
    replica SIGKILL, torn WAL tail, ENOSPC at a snapshot write, a
    minority-then-majority client partition, ENOSPC at a store publish
    — and then the WHOLE fleet (workers and replicas, one of them
    holding a live mid-solve lease) is SIGKILLed.  Measured acceptance:
    every replica restarts to a BIT-identical CAS record map (WAL +
    snapshot replay, compared over the public ``dump`` op), the
    orphaned lease TTL-reclaims through the recovered state, the
    restarted fleet re-serves every lattice cell bit-identically with
    dedup 1.0 (drill duplicates separated), zero leaked leases / hung
    arrivals, and every injected fault detected from public artifacts."""
    import signal
    import tempfile
    import threading

    from aiyagari_hark_tpu.obs.journal import read_journal
    from aiyagari_hark_tpu.obs.regress import (
        SEVERITY_NAMES,
        evaluate_history,
        load_bench_history,
    )
    from aiyagari_hark_tpu.serve.chaos import (DRPlan, _poll_until,
                                               run_dr_drills)
    from aiyagari_hark_tpu.serve.lease import (LoopbackCASBackend,
                                               make_backend)
    from aiyagari_hark_tpu.serve.loadgen import (FleetCtl, FleetSpec,
                                                 _spawn_fleet)
    from aiyagari_hark_tpu.serve.replicated import ReplicaSet
    from aiyagari_hark_tpu.serve.store import SolutionStore

    kw = dict(SERVE_SMOKE_KWARGS)
    served_values: dict = {}
    divergence = 0
    served = arrivals = errors = 0

    def _note(cell, res) -> None:
        nonlocal divergence, served
        served += 1
        key = int(res["key"])
        vals = {"cell": tuple(float(c) for c in cell),
                "r_star": res["r_star"], "capital": res["capital"],
                "labor": res["labor"], "status": res["status"],
                "bracket_init": res.get("bracket_init")}
        prior = served_values.get(key)
        if prior is not None and (prior["r_star"], prior["labor"],
                                  prior["status"]) != (
                vals["r_star"], vals["labor"], vals["status"]):
            divergence += 1
        served_values.setdefault(key, vals)

    t0 = time.perf_counter()
    with tempfile.TemporaryDirectory() as td:
        store_dir = os.path.join(td, "store")
        os.makedirs(store_dir)
        with ReplicaSet(os.path.join(td, "replicas"), n=3,
                        snapshot_every=16) as replicas:
            spec = FleetSpec(cells=FLEET_SMOKE_CELLS, model_kwargs=kw,
                             n_workers=4, queries_per_worker=0,
                             seed=20260807, lease_ttl_s=2.0,
                             lease_backend=replicas.spec)
            journals1 = [os.path.join(store_dir, f"journal_w{i}.jsonl")
                         for i in range(spec.n_workers)]
            procs, urls = _spawn_fleet(spec, store_dir, journals1,
                                       ready_timeout_s=180.0, chaos=True)
            ctl = FleetCtl(spec, procs, urls, journals1, store_dir,
                           timeout_s=120.0)
            drill_info = None
            kill_parked = False
            try:
                # phase 1: every lattice cell cold, then again as hits
                # from a different worker (quorum election + remote
                # serves, all through the replicated CAS)
                for rnd in range(2):
                    for j, cell in enumerate(FLEET_SMOKE_CELLS):
                        arrivals += 1
                        try:
                            _note(cell, ctl.query(
                                cell, prefer=(j + rnd) % spec.n_workers))
                        except Exception:
                            errors += 1

                # the DR drill campaign against the live substrate
                plan = DRPlan(drill_cells=DR_DRILL_CELLS,
                              settle_timeout_s=60.0)
                drill_info = run_dr_drills(plan, ctl, replicas)

                # full-fleet SIGKILL with a LIVE lease in flight: worker
                # 0 holds DR_KILL_CELL's lease inside an armed publish
                # delay when everything dies at once
                ctl.post(0, "/chaos", {"slow_publish_s": 12.0,
                                       "slow_cells": [list(DR_KILL_CELL)]})
                parked: dict = {}

                def _park():
                    try:
                        parked["res"] = ctl.query(DR_KILL_CELL, prefer=0)
                    except Exception as e:
                        parked["err"] = e

                pt = threading.Thread(target=_park, name="dr-park")
                pt.start()
                kill_parked = _poll_until(
                    lambda: ctl.fleet_info(0) is not None
                    and len(ctl.fleet_info(0)["held_leases"]) > 0, 10.0)
                for p in procs:
                    p.send_signal(signal.SIGKILL)
                for p in procs:
                    p.wait(30.0)
                pt.join(60.0)
            except BaseException:
                for p in procs:
                    if p.poll() is None:
                        p.kill()
                raise

            # the coordination tier's own disaster: capture each
            # replica's record map over the public wire, SIGKILL all
            # replicas, restart, and demand BIT-identical recovery
            dumps_before = []
            for port in replicas.ports:
                cli = LoopbackCASBackend(f"127.0.0.1:{port}")
                dumps_before.append(sorted(map(tuple, cli.dump())))
                cli.close()
            replicas.kill_all(signal.SIGKILL)
            t_rec0 = time.perf_counter()
            for i in range(replicas.n):
                replicas.restart(i)
            state_mismatches = 0
            recovered_keys = 0
            for i, port in enumerate(replicas.ports):
                cli = LoopbackCASBackend(f"127.0.0.1:{port}")
                after = sorted(map(tuple, cli.dump()))
                cli.close()
                recovered_keys = max(recovered_keys, len(after))
                if after != dumps_before[i]:
                    state_mismatches += 1

            # phase 2: a fresh worker pool over the SAME store + the
            # recovered quorum — the orphaned mid-solve lease must
            # TTL-reclaim through the recovered record, and every
            # lattice cell must re-serve bit-identically
            journals2 = [os.path.join(store_dir, f"journal_r{i}.jsonl")
                         for i in range(spec.n_workers)]
            procs2, urls2 = _spawn_fleet(spec, store_dir, journals2,
                                         ready_timeout_s=180.0)
            ctl2 = FleetCtl(spec, procs2, urls2, journals1 + journals2,
                            store_dir, timeout_s=120.0)
            try:
                arrivals += 1
                res_orphan = ctl2.query(DR_KILL_CELL)
                _note(DR_KILL_CELL, res_orphan)
                orphan_key = int(res_orphan["key"])
                orphan_reclaimed = any(
                    ev.get("key") == orphan_key
                    for jp in journals2 if os.path.exists(jp)
                    for ev in read_journal(jp,
                                           event="FLEET_LEASE_RECLAIM"))
                for j, cell in enumerate(FLEET_SMOKE_CELLS):
                    arrivals += 1
                    try:
                        _note(cell, ctl2.query(
                            cell, prefer=j % spec.n_workers))
                    except Exception:
                        errors += 1
                recovery_wall = time.perf_counter() - t_rec0
            except BaseException:
                for p in procs2:
                    if p.poll() is None:
                        p.kill()
                raise

            # lease-leak audit against the SAME recovered quorum the
            # workers used, then graceful shutdown
            for p in procs2:
                p.send_signal(signal.SIGTERM)
            for p in procs2:
                try:
                    p.wait(60.0)
                except Exception:
                    p.kill()
                    p.wait()
            audit = SolutionStore(
                disk_path=store_dir, shared=True, lease_ttl_s=2.0,
                owner="dr-audit",
                lease_backend=make_backend(replicas.spec,
                                           root=store_dir))
            deadline0 = time.perf_counter()
            while (audit.lease_files()
                   and time.perf_counter() - deadline0 < 12.0):
                audit.gc_stale_leases()
                if audit.lease_files():
                    time.sleep(0.2)
            leaked = len(audit.lease_files())
            reclaims = audit.fleet_counts().get("fleet_lease_reclaims", 0)
            audit.close()

            # substrate event accounting from the replica journals
            wal_replays = snapshot_compacts = 0
            for jp in replicas.journals:
                if os.path.exists(jp):
                    wal_replays += len(read_journal(jp,
                                                    event="WAL_REPLAY"))
                    snapshot_compacts += len(read_journal(
                        jp, event="SNAPSHOT_COMPACT"))

            # publish ledger for the dedup accounting — read BEFORE the
            # temp dir (and the journals in it) evaporates
            pub_counts: dict = {}
            for jp in journals1 + journals2:
                if not os.path.exists(jp):
                    continue
                for ev in read_journal(jp, event="FLEET_PUBLISH"):
                    k = int(ev["key"])
                    pub_counts[k] = pub_counts.get(k, 0) + 1
    wall = time.perf_counter() - t0

    mismatches, seeded = _served_vs_reference(served_values, kw)

    # dedup over the CLEAN ledger: drill keys (whose expected
    # duplicates are the drills' own doing) get their own accounting
    drill_keys = set(drill_info["drill_keys"])
    expected_dup = set(drill_info["expected_dup_keys"])
    clean = {k: n for k, n in pub_counts.items() if k not in drill_keys}
    dedup_ratio = (round(sum(clean.values()) / len(clean), 6)
                   if clean else None)
    drill_violations = sum(
        n - 1 for k, n in pub_counts.items()
        if k in drill_keys and k not in expected_dup and n > 1)

    drills_ok = all(r["detected"] == r["injected"]
                    for r in drill_info["drills"])
    unresolved = errors   # every arrival either returned or raised typed
    record = {
        "metric": "dr_smoke",
        "backend": __import__("jax").default_backend(),
        "dr_replicas": 3,
        "dr_workers": spec.n_workers,
        "dr_arrivals": arrivals,
        "dr_wall_s": round(wall, 3),
        "dr_served": served,
        "dr_unresolved": unresolved,
        # acceptance: every DR fault detected from public artifacts
        "dr_drills_injected": drill_info["injected"],
        "dr_drills_detected": drill_info["detected"],
        "dr_detect_all": drills_ok,
        **{f"dr_detected_{r['drill']}": int(r["detected"])
           for r in drill_info["drills"]},
        # acceptance: the full-fleet kill recovered — every replica's
        # record map BIT-identical over the public dump op, the
        # orphaned mid-solve lease reclaimed through recovered state
        "dr_state_mismatches": state_mismatches,
        "dr_state_reference_equal": state_mismatches == 0,
        "dr_recovered_keys": recovered_keys,
        "dr_kill_lease_observed": kill_parked,
        "dr_orphan_reclaimed": orphan_reclaimed,
        "dr_recovery_wall_s": round(recovery_wall, 3),
        "dr_wal_replays": wal_replays,
        "dr_snapshot_compacts": snapshot_compacts,
        # acceptance: exactly-once across the disaster (clean ledger)
        "dr_dedup_ratio": dedup_ratio,
        "dr_dedup_exact": dedup_ratio == 1.0,
        "dr_drill_dup_violations": drill_violations,
        # acceptance: zero leaked leases against the recovered quorum
        "dr_leases_leaked": leaked,
        "dr_reclaims": reclaims,
        # acceptance: bit-identity against same-seed reference solves
        "dr_bit_identical": mismatches == 0 and divergence == 0,
        "dr_value_mismatches": mismatches,
        "dr_value_divergence": divergence,
        "dr_seeded_compares": seeded,
    }
    history = load_bench_history(_repo_dir()) + [("dr_smoke", record)]
    report = evaluate_history(history)
    dr_regressed = [f.metric for f in report.regressed()
                    if f.metric.startswith("dr_")]
    record["dr_sentinel_clean"] = not dr_regressed
    record["dr_sentinel_worst"] = SEVERITY_NAMES[report.worst]

    print(f"[bench] dr smoke: 3 replicas / {spec.n_workers} workers, "
          f"{arrivals} arrivals -> {served} served, drills "
          f"{drill_info['detected']}/{drill_info['injected']} detected "
          f"{dict((r['drill'], r['detected']) for r in drill_info['drills'])}, "
          f"full-fleet kill: state_equal="
          f"{'OK' if state_mismatches == 0 else 'MISMATCH'} "
          f"({recovered_keys} keys, {wal_replays} replays, "
          f"{snapshot_compacts} compactions), orphan_reclaimed="
          f"{orphan_reclaimed}, recovery {record['dr_recovery_wall_s']}s,"
          f" dedup {dedup_ratio} (violations {drill_violations}), "
          f"bit-identical="
          f"{'OK' if record['dr_bit_identical'] else 'MISMATCH'}, "
          f"leaked={leaked} unresolved={unresolved}",
          file=sys.stderr)
    ok = (drills_ok and state_mismatches == 0 and kill_parked
          and orphan_reclaimed and dedup_ratio == 1.0
          and drill_violations == 0 and leaked == 0 and unresolved == 0
          and record["dr_bit_identical"] and wal_replays >= 3
          and snapshot_compacts >= 1)
    if not ok:
        print("[bench] dr smoke: ACCEPTANCE FAILED — see the dr_* "
              "fields above", file=sys.stderr)
    return record


# Chips-scaling smoke (ISSUE 11): the multi-chip tentpole, measured — the
# same balanced sweep dispatched through the shard_map launcher at mesh
# sizes 1/2/4/8 ('cells' axis), on real chips when an accelerator answers
# the probe and on forced host-platform CPU devices otherwise
# (utils.backend.force_cpu_platform — the committed MULTICHIP dryruns'
# device source).  24 cells (both Table II sd panels) so an 8-way mesh
# still holds 3 real lanes per device.
CHIPS_MESH_SIZES = (1, 2, 4, 8)
CHIPS_SMOKE_KWARGS = dict(a_count=10, dist_count=32, labor_states=3,
                          r_tol=1e-5, max_bisect=24)


def _chips_scaling() -> dict:
    """The ``--chips-scaling`` acceptance run (ISSUE 11): cells/sec for
    the balanced 24-cell sweep at mesh sizes 1/2/4/8, every sharded
    result bit-compared against the 1-device-mesh run (values, statuses,
    counters), per-device predicted-work skew and ``DeviceTelemetry``
    memory gauges recorded, and the scalar ``chips_*`` fields graded by
    the bench-regression sentinel from their first committed record
    (``obs.regress.DIRECTION_EXPLICIT`` knows them)."""
    import numpy as np

    ambient = _probe_default_backend()
    forced_host = ambient is None or ambient == "cpu"
    if forced_host:
        from aiyagari_hark_tpu.utils.backend import force_cpu_platform

        force_cpu_platform(max(CHIPS_MESH_SIZES))

    import jax

    if forced_host:
        jax.config.update("jax_enable_x64", True)

    from aiyagari_hark_tpu.obs import ObsConfig, build_obs
    from aiyagari_hark_tpu.parallel.mesh import make_mesh
    from aiyagari_hark_tpu.parallel.sweep import run_table2_sweep
    from aiyagari_hark_tpu.utils.config import SweepConfig

    backend = jax.default_backend()
    devices = jax.devices()
    sizes = [n for n in CHIPS_MESH_SIZES if n <= len(devices)]
    kw = dict(CHIPS_SMOKE_KWARGS)
    cfg = SweepConfig(labor_sd=(0.2, 0.4), schedule="balanced")
    n_cells = len(cfg.cells())
    print(f"[bench] chips scaling: backend={backend} "
          f"devices={len(devices)} "
          f"({'forced host' if forced_host else 'real chips'}), "
          f"mesh sizes {sizes}, {n_cells} cells", file=sys.stderr)

    entries = []
    results = {}
    skew = {}
    mem_devices = 0
    mem_peak = None
    for n in sizes:
        mesh = make_mesh(("cells",), (n,), devices=devices[:n])
        # profile=True: DeviceTelemetry (the memory-gauge sampler) only
        # exists on the performance tier — without it sample_devices()
        # is a no-op and the leg could never populate its gauges
        obs = build_obs(ObsConfig(enabled=True, profile=True))
        run_table2_sweep(cfg, mesh=mesh, obs=obs, **kw)   # compile+warm
        res = run_table2_sweep(cfg, mesh=mesh, perturb=PERTURB, obs=obs,
                               **kw)
        mem_devices = max(mem_devices,
                          obs.sample_devices(where=f"chips{n}"))
        reg = obs.registry.snapshot()
        skew[n] = reg.get("aiyagari_sweep_bucket_device_work_skew",
                          {}).get("value")
        peaks = [e["value"] for name, e in reg.items()
                 if name.endswith("_mem_peak_bytes_in_use")]
        if peaks:
            mem_peak = max(mem_peak or 0.0, max(peaks))
        obs.close()
        results[n] = res
        cps = n_cells / res.wall_seconds
        entries.append({
            "n_devices": n,
            "wall_s": round(res.wall_seconds, 4),
            "cells_per_sec": round(cps, 3),
            "device_work_skew": (None if skew[n] is None
                                 else round(skew[n], 3)),
            "n_buckets": (0 if res.bucket is None
                          else int(res.bucket.max()) + 1),
        })
        print(f"[bench] chips={n}: wall={res.wall_seconds:.3f}s -> "
              f"{cps:.2f} cells/s (device work skew "
              f"{skew[n] if skew[n] is not None else 'n/a'})",
              file=sys.stderr)

    base = results[sizes[0]]
    # the sharded contract (DESIGN §6b): root/status/counters bitwise vs
    # the 1-device mesh; the aggregate contraction (capital) rides XLA
    # reduction orders that differ across program widths, so it is
    # recorded as a drift, not asserted bitwise
    bit_identical = all(
        np.array_equal(results[n].r_star_pct, base.r_star_pct,
                       equal_nan=True)
        and np.array_equal(results[n].status, base.status)
        and np.array_equal(results[n].egm_iters, base.egm_iters)
        and np.array_equal(results[n].dist_iters, base.dist_iters)
        and np.array_equal(results[n].bisect_iters, base.bisect_iters)
        for n in sizes[1:])
    ok = ~np.isnan(base.capital)        # quarantine-exhausted cells are
    #                                     NaN-masked identically (checked
    #                                     above) and carry no drift
    capital_drift = max(
        (float(np.max(np.abs(results[n].capital[ok] - base.capital[ok])
                      / np.abs(base.capital[ok]), initial=0.0))
         for n in sizes[1:]), default=0.0)

    cps = {e["n_devices"]: e["cells_per_sec"] for e in entries}
    record = {
        "metric": "chips_scaling",
        "backend": backend,
        "chips_forced_host": bool(forced_host),
        "chips_smoke_cells": n_cells,
        "chips_scaling": entries,
        # acceptance: sharded == 1-device-mesh bit-for-bit on the root,
        # statuses, and every counter, at every measured mesh size;
        # capital's relative reduction-order drift recorded alongside
        "chips_bit_identical": bit_identical,
        "chips_capital_drift": capital_drift,
        "chips_device_work_skew": (
            None if skew.get(sizes[-1]) is None
            else round(skew[sizes[-1]], 3)),
        "chips_mem_stats_devices": mem_devices,
        "chips_mem_peak_bytes": mem_peak,
    }
    for n in sizes:
        record[f"chips_cells_per_sec_{n}dev"] = cps[n]
        if n > sizes[0]:
            record[f"chips_speedup_{n}dev"] = round(cps[n] / cps[sizes[0]],
                                                    3)
    top = sizes[-1]
    # the acceptance flag is defined AT 8 devices (>= 3x on the CPU
    # smoke, near-linear on real chips); on a host that cannot reach an
    # 8-way mesh the criterion is unmeasurable, not failed
    record["chips_speedup_ok"] = (
        bool(record.get("chips_speedup_8dev", 0.0) >= 3.0)
        if top == 8 else None)
    print(f"[bench] chips scaling: "
          + " ".join(f"{n}dev={cps[n]:.2f}c/s" for n in sizes)
          + f" speedup_{top}dev="
          f"{record.get(f'chips_speedup_{top}dev', 'n/a')} "
          f"bit_identical={'OK' if bit_identical else 'MISMATCH'} "
          f"mem_stats_devices={mem_devices}", file=sys.stderr)
    if not bit_identical:
        print("[bench] chips scaling: BIT-IDENTITY FAILED — sharded "
              "results differ from the 1-device mesh", file=sys.stderr)
    return record


# State-scaling smoke (ISSUE 20): the state-axis tentpole, measured — the
# same 4-cell sweep at wealth-grid sizes that grow PAST the nominal
# single-device resident budget, solved replicated and with the per-cell
# state partitioned across 2 and 4 devices (DESIGN §6b).  The chips leg's
# protocol (probe -> forced host -> warm-up -> timed perturbed run) at a
# reduced lattice: state sharding is a per-cell memory play, so a big
# cell count only dilutes the signal.  dist_method is pinned to "dense"
# on EVERY leg — the sharded path forces dense internally, and the
# replicated baseline must run the same contraction or the comparison
# would measure scatter-vs-dense, not sharding.
STATE_SHARD_SIZES = (1, 2, 4)
STATE_GRID_SIZES = (128, 256, 512)
STATE_SMOKE_KWARGS = dict(a_count=10, labor_states=3, r_tol=1e-5,
                          max_bisect=24, dist_method="dense")
# Nominal per-device resident budget for the forced-host drill: host CPU
# "devices" share one RAM pool and report no memory_stats(), so the
# grid-exceeds-one-device acceptance is defined against this explicit
# budget applied to the MODEL resident (operator + distribution shards,
# exact arithmetic below); on real chips the measured DeviceTelemetry
# gauges ride alongside.  4 MiB puts the largest grid's replicated
# operator (3*512^2*8 B ~ 6.3 MB) over budget while its 2- and 4-way
# shards fit — the smallest drill that exercises the claim.
STATE_NOMINAL_DEVICE_BUDGET = 4 * 1024 * 1024


def _state_model_resident_bytes(n_labor: int, d: int, shards: int) -> int:
    """Per-device resident of the dense push-forward under ``shards``-way
    state partitioning (f64): the wealth operator's row block
    ``[N, D, D/M]`` plus the distribution and its pushed copy
    ``2 x [D/M, N]`` — the terms the partition-rule table shards; the
    policy iterate (O(N*A)) is replicated by design and negligible."""
    rows = d // shards
    return 8 * (n_labor * d * rows + 2 * rows * n_labor)


def _state_scaling() -> dict:
    """The ``--state-scaling`` acceptance run (ISSUE 20): distribution
    gridpoints/sec for a 4-cell sweep at wealth grids 128/256/512, each
    solved at state shards 1/2/4 on the CPU mesh (real chips when an
    accelerator answers the probe), with (a) r* drift of every sharded
    run vs the replicated run at the same grid (< 0.1 bp acceptance),
    (b) per-device resident accounting — measured ``DeviceTelemetry``
    gauges where the backend reports memory_stats(), the exact model
    resident everywhere — showing the operator shrinking ~1/M, (c) the
    largest grid exceeding the nominal single-device budget yet solving
    under state_shards>1 with its per-device resident back under it, and
    (d) the sharding overhead share from the CostLedger's launch walls
    (an upper bound on collective time: forced-host CPU has no per-op
    collective timer, so the leg records wall overhead vs the replicated
    run of the same grid and says so).  Scalar ``state_*`` fields are
    graded by the bench-regression sentinel
    (``obs.regress.DIRECTION_EXPLICIT`` knows them)."""
    import numpy as np

    ambient = _probe_default_backend()
    forced_host = ambient is None or ambient == "cpu"
    if forced_host:
        from aiyagari_hark_tpu.utils.backend import force_cpu_platform

        force_cpu_platform(max(STATE_SHARD_SIZES))

    import jax

    if forced_host:
        jax.config.update("jax_enable_x64", True)

    from aiyagari_hark_tpu.obs import ObsConfig, build_obs
    from aiyagari_hark_tpu.parallel.sweep import (_batched_solver,
                                                  run_table2_sweep)
    from aiyagari_hark_tpu.utils.config import SweepConfig

    backend = jax.default_backend()
    devices = jax.devices()
    shard_sizes = [m for m in STATE_SHARD_SIZES if m <= len(devices)]
    n_labor = int(STATE_SMOKE_KWARGS["labor_states"])
    cfg = SweepConfig(crra_values=(1.0, 3.0), rho_values=(0.3, 0.6))
    n_cells = len(cfg.cells())
    print(f"[bench] state scaling: backend={backend} "
          f"devices={len(devices)} "
          f"({'forced host' if forced_host else 'real chips'}), "
          f"state shards {shard_sizes}, grids {list(STATE_GRID_SIZES)}, "
          f"{n_cells} cells", file=sys.stderr)

    entries = []
    drift_max_bp = 0.0
    status_equal = True
    mem_devices = 0
    mem_peak = None
    walls = {}          # (d, m) -> ledger launch-wall total
    gps = {}            # (d, m) -> dist gridpoints/sec
    for d in STATE_GRID_SIZES:
        base = None
        for m in shard_sizes:
            run_cfg = cfg.replace(state_shards=m)
            kw = dict(STATE_SMOKE_KWARGS, dist_count=d)
            # fresh executables per (grid, shards): the memoized solver
            # keys on the state-mesh geometry (ISSUE 20), but clearing
            # keeps each leg's ledger from inheriting launch walls
            _batched_solver.cache_clear()
            obs = build_obs(ObsConfig(enabled=True, profile=True))
            run_table2_sweep(run_cfg, obs=obs, **kw)    # compile+warm
            res = run_table2_sweep(run_cfg, perturb=PERTURB, obs=obs,
                                   **kw)
            mem_devices = max(mem_devices,
                              obs.sample_devices(where=f"state{d}x{m}"))
            reg = obs.registry.snapshot()
            peaks = [e["value"] for name, e in reg.items()
                     if name.endswith("_mem_peak_bytes_in_use")]
            if peaks:
                mem_peak = max(mem_peak or 0.0, max(peaks))
            ledger = obs.cost_ledger
            walls[(d, m)] = (sum(e.launch_wall_s for e in ledger.entries())
                             if ledger is not None else res.wall_seconds)
            obs.close()
            # distribution-push throughput: wealth-grid points touched
            # per push step, summed over every distribution iteration of
            # every bisection midpoint — the work state sharding splits
            gps[(d, m)] = (float(res.dist_iters.sum()) * d * n_labor
                           / res.wall_seconds)
            model_bytes = _state_model_resident_bytes(n_labor, d, m)
            entries.append({
                "dist_count": d,
                "state_shards": m,
                "wall_s": round(res.wall_seconds, 4),
                "gridpoints_per_sec": round(gps[(d, m)]),
                "model_resident_bytes_per_dev": model_bytes,
                "over_nominal_budget": bool(
                    model_bytes > STATE_NOMINAL_DEVICE_BUDGET),
            })
            if m == shard_sizes[0]:
                base = res
            else:
                drift_bp = float(np.abs(
                    np.asarray(res.r_star_pct)
                    - np.asarray(base.r_star_pct)).max()) * 100.0
                drift_max_bp = max(drift_max_bp, drift_bp)
                status_equal = status_equal and bool(np.array_equal(
                    np.asarray(res.status), np.asarray(base.status)))
            print(f"[bench] state D={d} M={m}: "
                  f"wall={res.wall_seconds:.3f}s -> "
                  f"{gps[(d, m)]:.0f} gridpoints/s, "
                  f"resident/dev {model_bytes} B", file=sys.stderr)

    d_top = STATE_GRID_SIZES[-1]
    m_top = shard_sizes[-1]
    repl_top = _state_model_resident_bytes(n_labor, d_top, 1)
    shard_top = _state_model_resident_bytes(n_labor, d_top, m_top)
    # the overflow drill: the largest grid's replicated resident exceeds
    # the nominal per-device budget, every sharded solve of it converged
    # with the same statuses, and its per-device shard fits back under
    overflow_solved = bool(
        repl_top > STATE_NOMINAL_DEVICE_BUDGET
        and shard_top <= STATE_NOMINAL_DEVICE_BUDGET
        and status_equal and m_top > 1)
    # sharding overhead share at the top (grid, shards) point, from the
    # ledger's launch walls: wall overhead vs the replicated run — an
    # UPPER bound on collective time (no per-op collective timer here)
    w1, wm = walls.get((d_top, 1)), walls.get((d_top, m_top))
    collective_share = (max(0.0, round((wm - w1) / wm, 4))
                        if w1 and wm and wm > 0 else None)

    record = {
        "metric": "state_scaling",
        "backend": backend,
        "state_forced_host": bool(forced_host),
        "state_smoke_cells": n_cells,
        "state_scaling": entries,
        "state_r_star_drift_bp": round(drift_max_bp, 6),
        "state_drift_ok": bool(drift_max_bp < 0.1),
        "state_status_equal": status_equal,
        "state_budget_bytes": STATE_NOMINAL_DEVICE_BUDGET,
        "state_overflow_grid": d_top,
        "state_overflow_grid_solved": overflow_solved,
        "state_model_resident_replicated_bytes": repl_top,
        "state_model_resident_sharded_bytes": shard_top,
        "state_resident_ratio": round(shard_top / repl_top, 4),
        "state_collective_share_frac": collective_share,
        "state_mem_stats_devices": mem_devices,
        "state_mem_peak_bytes": mem_peak,
    }
    for m in shard_sizes:
        record[f"state_gridpoints_per_sec_{m}shard"] = round(gps[(d_top, m)])
    from aiyagari_hark_tpu.obs.regress import (SEVERITY_NAMES,
                                               evaluate_history,
                                               load_bench_history)

    history = load_bench_history(_repo_dir()) + [("state_smoke", record)]
    report = evaluate_history(history)
    state_regressed = [f.metric for f in report.regressed()
                       if f.metric.startswith("state_")]
    record["state_sentinel_clean"] = not state_regressed
    record["state_sentinel_worst"] = SEVERITY_NAMES[report.worst]
    print(f"[bench] state scaling: "
          + " ".join(f"{m}sh={gps[(d_top, m)]:.0f}gp/s"
                     for m in shard_sizes)
          + f" drift={drift_max_bp:.4f}bp "
          f"overflow_grid_solved={'OK' if overflow_solved else 'FAILED'} "
          f"resident {repl_top}->{shard_top} B/dev "
          f"collective_share={collective_share}", file=sys.stderr)
    if not record["state_drift_ok"] or not overflow_solved:
        print("[bench] state scaling: ACCEPTANCE FAILED — see the "
              "state_* fields above", file=sys.stderr)
    return record


def _index_bench(space) -> dict:
    """Measured ``CellIndex``-vs-linear-scan microbench (ISSUE 17
    acceptance: >= 10x nearest-query speedup at 10^4+ synthetic stored
    entries, answers bitwise identical to the linear scan).  Pure numpy
    — synthetic cells drawn in normalized units and mapped back through
    the scenario's ``CellSpace.normalize`` contract, no solves."""
    import numpy as np

    from aiyagari_hark_tpu.serve import CellIndex, linear_nearest_k

    scale = np.asarray(space.scale, dtype=np.float64)
    out = {}
    for n, tag in ((10_000, "1e4"), (50_000, "5e4")):
        rng = np.random.default_rng(n)
        z = rng.uniform(0.0, 8.0, size=(n, scale.shape[0]))
        cells = z * scale      # entries at ~uniform normalized density
        idx = CellIndex()
        for i, c in enumerate(cells):
            idx.add(i, tuple(c), group=0, r_star=float(i % 97),
                    cert_level=0)
        queries = [tuple(q) for q in
                   rng.uniform(0.0, 8.0, size=(200, scale.shape[0]))
                   * scale]
        seqs = np.arange(n)
        idx.nearest_k(queries[0], 0, 2, scale=space.scale)  # build once
        t0 = time.perf_counter()
        grid = [idx.nearest_k(q, 0, 2, scale=space.scale)
                for q in queries]
        t_grid = time.perf_counter() - t0
        t0 = time.perf_counter()
        lin = [linear_nearest_k(q, cells, seqs, 2, space.scale)
               for q in queries]
        t_lin = time.perf_counter() - t0
        # keys were inserted as row indices in insertion order, so the
        # grid answer must equal the scan's bitwise — keys, distances
        # and tie order included (round-trip sanity: the normalization
        # the grid bucketed by is the CellSpace's own)
        assert space.normalize(queries[0]) == tuple(queries[0] / scale)
        out[f"index_speedup_{tag}"] = round(t_lin / max(t_grid, 1e-12),
                                            2)
        out[f"index_grid_ms_{tag}"] = round(t_grid / len(queries) * 1e3,
                                            4)
        out[f"index_linear_ms_{tag}"] = round(
            t_lin / len(queries) * 1e3, 4)
        out[f"index_bitwise_ok_{tag}"] = bool(grid == lin)
        out["index_entries"] = n
        out["index_rebuilds"] = idx.rebuilds
        print(f"[bench] cell index @ {n}: grid "
              f"{out[f'index_grid_ms_{tag}']}ms vs linear "
              f"{out[f'index_linear_ms_{tag}']}ms per query -> "
              f"{out[f'index_speedup_{tag}']}x, bitwise="
              f"{'OK' if grid == lin else 'MISMATCH'}", file=sys.stderr)
    return out


def _surrogate_smoke() -> dict:
    """The ``--surrogate-smoke`` acceptance run (ISSUE 17, DESIGN §15):
    the 12-cell golden lattice is solved and CERTIFIED into the store
    (``surrogate_ok=False`` forces the real solves that become donors),
    then a seeded off-lattice query wave hits the surrogate tier —
    sub-millisecond local-linear answers tagged ``quality="surrogate"``
    with their model-implied error bound, NEVER cached; far/audited
    queries escalate to real solves that publish as LATTICE_REFINED
    refinement points, and every seeded audit's real r* must land
    inside the surrogate's own reported bound.  The ``CellIndex``
    microbench rides along (>= 10x vs the linear scan at 10^4+
    entries, bitwise identical).  Emits the sentinel-graded
    ``surrogate_*``/``index_*`` record."""
    import tempfile

    import numpy as np

    from aiyagari_hark_tpu.obs import ObsConfig, read_journal
    from aiyagari_hark_tpu.obs.regress import (
        SEVERITY_NAMES,
        evaluate_history,
        load_bench_history,
    )
    from aiyagari_hark_tpu.scenarios import get_scenario
    from aiyagari_hark_tpu.serve import (
        EquilibriumService,
        SurrogatePolicy,
        make_query,
    )

    import jax

    backend = jax.default_backend()
    record = {"metric": "surrogate_smoke", "backend": backend}
    record.update(_index_bench(get_scenario("aiyagari").cells))

    kw = dict(SERVE_SMOKE_KWARGS)
    cells = [(s, r) for s in (1.0, 3.0, 5.0) for r in (0.0, 0.3, 0.6, 0.9)]
    pol = SurrogatePolicy(k=6, max_error_bound=0.1, max_distance=0.6,
                          min_donors=4, audit_fraction=0.25,
                          audit_seed=20260806)
    with tempfile.TemporaryDirectory() as td:
        journal = os.path.join(td, "events.jsonl")
        svc = EquilibriumService(start_worker=False, max_batch=4,
                                 ladder=(1, 2, 4),
                                 certify_before_cache=True,
                                 surrogate=pol,
                                 obs=ObsConfig(enabled=True,
                                               journal_path=journal))
        t0 = time.perf_counter()
        futs = [svc.submit(make_query(s, r, surrogate_ok=False, **kw))
                for s, r in cells]
        svc.flush()
        for f in futs:
            f.result(0)
        warm_wall = time.perf_counter() - t0
        print(f"[bench] surrogate smoke: lattice warmed+certified "
              f"({len(cells)} cells) in {warm_wall:.1f}s",
              file=sys.stderr)

        # seeded off-lattice wave; the far probe guarantees one
        # donor_too_far escalation, the seeded audit draw the rest
        rng = np.random.default_rng(20260806)
        wave = [(float(rng.uniform(1.2, 4.8)),
                 float(rng.uniform(0.05, 0.85))) for _ in range(20)]
        wave.append((8.0, 0.5))
        lat, bounds = [], []
        served = escalated = 0
        tagged = never_cached = escalated_certified = True
        for s, r in wave:
            q = make_query(s, r, **kw)
            t1 = time.perf_counter()
            fut = svc.submit(q)
            if fut.done():
                res = fut.result(0)
                lat.append(time.perf_counter() - t1)
                served += 1
                tagged &= (res.quality == "surrogate"
                           and res.surrogate_error_bound is not None
                           and bool(res.donor_keys))
                never_cached &= not svc.store.contains(q.key())
                bounds.append(float(res.surrogate_error_bound or 0.0))
            else:
                svc.flush()
                res = fut.result(0)
                escalated += 1
                # an escalated solve is a REAL solve: certified and
                # published as a lattice refinement point
                escalated_certified &= (res.quality == "exact"
                                        and res.cert_level in (0, 1)
                                        and svc.store.contains(q.key()))
        p50_ms = (float(np.median(lat)) * 1e3 if lat else None)
        snap = svc.metrics.snapshot()
        store_stats = svc.store.index_stats()
        svc.close()
        events = read_journal(journal)
    n_ev = {t: sum(1 for e in events if e["event"] == t)
            for t in ("SURROGATE_SERVED", "SURROGATE_ESCALATED",
                      "LATTICE_REFINED", "INDEX_REBUILD")}
    audits = [e for e in events
              if e["event"] == "LATTICE_REFINED" and "audit_ok" in e]
    audits_within = all(e["audit_ok"] for e in audits)

    record.update({k: v for k, v in snap.items()
                   if k.startswith("surrogate_")})
    record.update({
        "surrogate_queries": len(wave),
        "surrogate_served": served,
        "surrogate_p50_ms": (None if p50_ms is None
                             else round(p50_ms, 4)),
        "surrogate_sub_ms": bool(p50_ms is not None and p50_ms < 1.0),
        "surrogate_bound_max": (round(max(bounds), 6) if bounds
                                else None),
        "surrogate_tagged": bool(tagged),
        "surrogate_never_cached": bool(never_cached),
        "surrogate_escalated_certified": bool(escalated_certified),
        "surrogate_audits_within_bound": bool(audits_within),
        "surrogate_refined_published": n_ev["LATTICE_REFINED"],
        "surrogate_events_served": n_ev["SURROGATE_SERVED"],
        "surrogate_events_escalated": n_ev["SURROGATE_ESCALATED"],
        "surrogate_index_kind": store_stats["index_kind"],
        "surrogate_warm_wall_s": round(warm_wall, 3),
    })
    history = load_bench_history(_repo_dir()) + [("surrogate_smoke",
                                                  record)]
    report = evaluate_history(history)
    regressed = [f.metric for f in report.regressed()
                 if f.metric.startswith(("surrogate_", "index_"))]
    record["surrogate_sentinel_clean"] = not regressed
    record["surrogate_sentinel_worst"] = SEVERITY_NAMES[report.worst]

    print(f"[bench] surrogate smoke: {served}/{len(wave)} served "
          f"(p50 {record['surrogate_p50_ms']}ms, hit rate "
          f"{snap['surrogate_hit_rate']}), {escalated} escalated "
          f"(rate {snap['surrogate_escalation_rate']}), "
          f"{len(audits)} audits "
          f"{'within' if audits_within else 'OUTSIDE'} bound, "
          f"{n_ev['LATTICE_REFINED']} refinement points, index "
          f"{record['index_speedup_5e4']}x @ 5e4", file=sys.stderr)
    ok = (served >= 1 and escalated >= 1
          and record["surrogate_sub_ms"] and tagged and never_cached
          and escalated_certified and audits_within and len(audits) >= 1
          and record["index_bitwise_ok_1e4"]
          and record["index_bitwise_ok_5e4"]
          and record["index_speedup_5e4"] >= 10.0
          and n_ev["LATTICE_REFINED"] == escalated)
    if not ok:
        print("[bench] surrogate smoke: ACCEPTANCE FAILED — see the "
              "surrogate_*/index_* fields above", file=sys.stderr)
    return record


def main(argv=None):
    """CLI wrapper: the preemption-tolerant run layer (ISSUE 3) around the
    measurement body.  ``--resume PATH`` gives the headline sweep a
    durable ledger — a preempted bench restarted with the same flag skips
    the solved buckets; SIGTERM/SIGINT are honored at safe boundaries
    (bucket seams) with exit code 75 (EX_TEMPFAIL: retry me), the
    convention preemptible-slice supervisors restart on.  ``--serve-smoke``
    runs the (fast) serving acceptance instead of the full bench and
    emits the ``serve_*`` record (ISSUE 4); ``--integrity-smoke`` runs
    the solution-integrity acceptance (certification, recheck, corruption
    drills) and emits the ``integrity_*`` record (ISSUE 6);
    ``--obs-smoke`` runs the observability acceptance (Chrome trace,
    metrics snapshot, event-journal drills, disabled-overhead bound) and
    emits the ``obs_*`` record (ISSUE 7); ``--load-smoke`` runs the
    overload acceptance (deterministic Zipf replay at 2.5x capacity,
    typed outcome accounting, breaker drill) and emits the ``load_*``
    record (ISSUE 8); ``--profile-smoke`` runs the
    performance-observability acceptance (XLA cost-analysis capture,
    roofline classification, model-vs-measured FLOP cross-check,
    bench-regression sentinel on the committed history) and emits the
    ``profile_*`` record (ISSUE 10); ``--chips-scaling`` runs the
    multi-chip scaling acceptance (shard_map-dispatched sweep at mesh
    sizes 1/2/4/8 with bit-identity, work-skew, and memory telemetry)
    and emits the ``chips_*`` record (ISSUE 11); ``--state-scaling``
    runs the state-axis sharding acceptance (ISSUE 20: wealth grids past
    the nominal single-device resident budget solved at state shards
    1/2/4 with sub-0.1bp r* drift, ~1/M per-device residents, and a
    ledger-sourced overhead share) and emits the ``state_*`` record;
    ``--compaction-smoke``
    runs the grid-compaction acceptance (12-cell golden sweep under
    ``grid="compact"``: all cells CERTIFIED, r* within 0.1bp of the
    committed goldens, measured gridpoint/step/wall reductions,
    reference path bit-identical) and emits the ``grid_*`` record
    (ISSUE 12); ``--kernel-smoke`` runs the fused-kernel acceptance
    (ISSUE 13: the 12-cell golden sweep under ``kernel="fused"`` —
    interpret-mode kernels on CPU, real Mosaic on TPU — all cells
    CERTIFIED within 0.1bp, reference path bit-identical, bf16-rung
    escalation drill, CostLedger roofline witness, sentinel-graded
    ``kernel_*`` fields) and emits the ``kernel_*`` record;
    ``--fleet-smoke`` runs the fleet-serving acceptance (ISSUE 15: 4
    worker processes over one shared disk store, per-worker-seeded Zipf
    replay over HTTP, dedup ratio 1.0 via the claim/lease election,
    served values bit-identical to ``reference_solve``, speculative
    prefetch conversion, SIGTERM drill with typed ``Interrupted`` and
    zero leaked leases) and emits the ``fleet_*`` record;
    ``--chaos-smoke`` runs the chaos-hardening acceptance (ISSUE 16: 4
    workers under scripted churn replay the golden lattice through the
    retrying/hedging client while every fault drill fires — SIGKILL
    mid-solve, heartbeat stall, torn publish, store partition, skewed
    double election — asserting detected == injected, dedup back to
    1.0, zero leaked leases, bit-identical served values) and emits
    the ``chaos_*`` record; ``--surrogate-smoke`` runs the surrogate
    serving-tier acceptance (ISSUE 17: the certified 12-cell lattice
    warmed, then a seeded off-lattice query wave answered sub-ms by the
    local-linear surrogate with its model-implied bound, audited
    escalations publishing LATTICE_REFINED refinement points, and the
    CellIndex >= 10x-vs-linear-scan microbench) and emits the
    ``surrogate_*``/``index_*`` record."""
    import argparse

    from aiyagari_hark_tpu.utils.resilience import (
        Interrupted,
        preemption_guard,
    )

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--resume", default=None, metavar="PATH",
                    help="durable resume ledger for the headline sweep "
                         "(utils.resilience): a preempted run restarted "
                         "with the same path skips completed buckets, "
                         "bit-identically")
    ap.add_argument("--serve-smoke", action="store_true",
                    help="run the equilibrium-serving smoke (12-cell "
                         "hit/near/cold replay) and emit the serve_* "
                         "record instead of the full bench")
    ap.add_argument("--integrity-smoke", action="store_true",
                    help="run the solution-integrity smoke (12-cell "
                         "golden certification, SDC recheck, corruption "
                         "drills) and emit the integrity_* record "
                         "instead of the full bench")
    ap.add_argument("--obs-smoke", action="store_true",
                    help="run the observability smoke (12-cell golden "
                         "sweep traced+journaled: Chrome-trace/Perfetto "
                         "export, metrics snapshot round-trip, "
                         "injection-drill event contract, <2%% disabled "
                         "overhead) and emit the obs_* record instead "
                         "of the full bench")
    ap.add_argument("--profile-smoke", action="store_true",
                    help="run the performance-observability smoke "
                         "(12-cell golden sweep with the cost ledger on: "
                         "XLA cost-analysis capture, roofline "
                         "classification, model-vs-measured FLOP "
                         "cross-check, <2%% overhead, bit-identity to "
                         "goldens, bench-regression sentinel on the "
                         "committed history) and emit the profile_* "
                         "record instead of the full bench")
    ap.add_argument("--load-smoke", action="store_true",
                    help="run the overload smoke (seeded open-loop Zipf "
                         "replay at 2.5x modeled capacity on the "
                         "injected clock: bit-reproducible outcome "
                         "digest, zero unresolved futures, typed "
                         "shed/reject/degrade/breaker accounting, "
                         "journal consistency) and emit the load_* "
                         "record instead of the full bench")
    ap.add_argument("--fleet-smoke", action="store_true",
                    help="run the fleet-serving smoke (ISSUE 15: 4 "
                         "worker processes over one shared disk store "
                         "replay per-worker-seeded Zipf mixes of the "
                         "12-cell golden lattice over HTTP — dedup "
                         "ratio 1.0 via the claim/lease election, "
                         "served values bit-identical to "
                         "reference_solve, speculative prefetch "
                         "conversion, SIGTERM drill with typed "
                         "Interrupted and zero leaked leases) and emit "
                         "the fleet_* record instead of the full bench")
    ap.add_argument("--chaos-smoke", action="store_true",
                    help="run the chaos-hardening smoke (ISSUE 16: 4 "
                         "workers under scripted churn replay the "
                         "12-cell golden lattice through the retrying/"
                         "hedging client while every fault drill fires "
                         "— SIGKILL mid-solve, heartbeat stall, torn "
                         "publish, store partition, skewed double "
                         "election — asserting detected == injected, "
                         "dedup ratio back to 1.0, zero leaked leases, "
                         "bit-identical served values) and emit the "
                         "chaos_* record instead of the full bench")
    ap.add_argument("--dr-smoke", action="store_true",
                    help="run the disaster-recovery smoke (ISSUE 18: 4 "
                         "workers coordinate through a 3-replica "
                         "WAL-backed quorum CAS serving the 12-cell "
                         "golden lattice while the DR drills fire — "
                         "replica SIGKILL, torn WAL tail, ENOSPC at a "
                         "snapshot write, minority/majority partition, "
                         "disk-full publish — then the FULL fleet is "
                         "SIGKILLed with a live lease in flight; every "
                         "replica must restart bit-identical from "
                         "WAL+snapshot, the orphaned lease TTL-reclaim, "
                         "every cell re-serve bit-identically with "
                         "dedup 1.0 and zero leaked leases, detected == "
                         "injected from public artifacts) and emit the "
                         "dr_* record instead of the full bench")
    ap.add_argument("--surrogate-smoke", action="store_true",
                    help="run the surrogate serving-tier smoke (ISSUE "
                         "17: certified 12-cell lattice warmed, seeded "
                         "off-lattice query wave answered "
                         "sub-millisecond by the local-linear surrogate "
                         "with model-implied error bounds — never "
                         "cached, never untagged — audited escalations "
                         "published as LATTICE_REFINED refinement "
                         "points, CellIndex bitwise==linear-scan with "
                         ">=10x measured speedup at 10^4+ entries) and "
                         "emit the surrogate_*/index_* record instead "
                         "of the full bench")
    ap.add_argument("--chips-scaling", action="store_true",
                    help="run the multi-chip scaling smoke (ISSUE 11: "
                         "the balanced 24-cell sweep dispatched through "
                         "the shard_map launcher at mesh sizes 1/2/4/8 "
                         "— real chips on an accelerator, forced "
                         "host-platform CPU devices otherwise — with "
                         "bit-identity vs the 1-device mesh, per-device "
                         "work skew, and memory gauges) and emit the "
                         "chips_* record instead of the full bench")
    ap.add_argument("--state-scaling", action="store_true",
                    help="run the state-sharding smoke (ISSUE 20: a "
                         "4-cell sweep at wealth grids 128/256/512 under "
                         "state shards 1/2/4 — the largest grid exceeds "
                         "the nominal single-device resident budget and "
                         "solves sharded with r* within 0.1bp of the "
                         "replicated run, per-device resident ~1/M, "
                         "ledger-sourced overhead share) and emit the "
                         "state_* record instead of the full bench")
    ap.add_argument("--compaction-smoke", action="store_true",
                    help="run the grid-compaction smoke (ISSUE 12: the "
                         "12-cell golden CPU sweep under grid='compact' "
                         "— all cells CERTIFIED, r* within 0.1bp of the "
                         "committed goldens, measured gridpoint/step/"
                         "wall reductions, default reference path "
                         "bit-identical) and emit the grid_* record "
                         "instead of the full bench")
    ap.add_argument("--kernel-smoke", action="store_true",
                    help="run the fused-kernel smoke (ISSUE 13: the "
                         "12-cell golden sweep under kernel='fused' — "
                         "interpret-mode on CPU, real Mosaic kernels on "
                         "TPU — all cells CERTIFIED, r* within 0.1bp of "
                         "the committed goldens, reference path "
                         "bit-identical, bf16-rung escalation drill, "
                         "roofline witness) and emit the kernel_* "
                         "record instead of the full bench")
    ap.add_argument("--scenario-smoke", action="store_true",
                    help="run the scenario-registry smoke (ISSUE 9: "
                         "balanced+certified Huggett sweep with a "
                         "quarantine drill, Huggett serve replay with "
                         "zero-compile exact hits and near-hit warm "
                         "starts, certified Epstein-Zin mini-sweep) and "
                         "emit the scenario_* record instead of the "
                         "full bench")
    args = ap.parse_args(argv)
    if (args.serve_smoke or args.integrity_smoke or args.obs_smoke
            or args.load_smoke or args.scenario_smoke
            or args.profile_smoke or args.chips_scaling
            or args.state_scaling
            or args.compaction_smoke or args.kernel_smoke
            or args.fleet_smoke or args.chaos_smoke
            or args.dr_smoke or args.surrogate_smoke):
        from aiyagari_hark_tpu.utils.backend import (
            enable_compilation_cache,
        )

        enable_compilation_cache()
        smoke = (_surrogate_smoke if args.surrogate_smoke
                 else _dr_smoke if args.dr_smoke
                 else _chaos_smoke if args.chaos_smoke
                 else _fleet_smoke if args.fleet_smoke
                 else _kernel_smoke if args.kernel_smoke
                 else _compaction_smoke if args.compaction_smoke
                 else _chips_scaling if args.chips_scaling
                 else _state_scaling if args.state_scaling
                 else _profile_smoke if args.profile_smoke
                 else _scenario_smoke if args.scenario_smoke
                 else _load_smoke if args.load_smoke
                 else _obs_smoke if args.obs_smoke
                 else _integrity_smoke if args.integrity_smoke
                 else _serve_smoke)
        try:
            with preemption_guard():
                print(json.dumps(smoke()))
        except Interrupted as e:
            print(f"[bench] preempted at a safe boundary: {e}",
                  file=sys.stderr)
            sys.exit(75)
        return
    gc_paths = () if args.resume is None else (args.resume,)
    try:
        with preemption_guard(gc_paths=gc_paths):
            _run_bench(resume_path=args.resume)
    except Interrupted as e:
        print(f"[bench] preempted at a safe boundary: {e}", file=sys.stderr)
        sys.exit(75)


def _run_bench(resume_path=None):
    from aiyagari_hark_tpu.utils.backend import enable_compilation_cache
    from aiyagari_hark_tpu.utils.timing import PhaseTimer, device_trace

    cache_dir = enable_compilation_cache()
    print(f"[bench] persistent compilation cache: {cache_dir}",
          file=sys.stderr)
    timer = PhaseTimer()
    with timer.phase("probe"):
        ambient = _probe_default_backend()
    if ambient is None:
        print("[bench] ambient backend probe hung/failed -> forcing CPU",
              file=sys.stderr)
        _force_cpu()
    else:
        print(f"[bench] ambient backend probe: {ambient}", file=sys.stderr)

    import jax

    from aiyagari_hark_tpu.parallel.sweep import (_batched_solver,
                                                  run_table2_sweep)
    from aiyagari_hark_tpu.utils.config import SweepConfig

    sweep = SweepConfig()   # full Table II: 3 sigmas x 4 rhos
    trace_dir = os.environ.get("AIYAGARI_TRACE_DIR")

    # The axon TPU tunnel intermittently faults on first execution of a
    # freshly compiled program; retry with cleared caches, and fall back to
    # CPU for the final attempt so the round always records a number.
    # Degrade the distribution method down the measured-performance ladder
    # (pallas-grid default -> dense MXU matvecs -> scatter) so a
    # Pallas/Mosaic compile problem costs one retry, not the accelerator
    # number, and a dense-path problem still leaves the portable scatter.
    from aiyagari_hark_tpu.utils.timing import CompileCounter

    attempts = 4
    res = None
    backend = "unknown"
    n_devices = 0
    used_kwargs: dict = dict(SWEEP_KWARGS)
    cold_counter = CompileCounter()   # replaced per attempt; this default
    #                                   only covers the no-attempt edge
    for attempt in range(attempts):
        kwargs = dict(SWEEP_KWARGS)
        if attempt == 1:
            kwargs["dist_method"] = "dense"
        elif attempt == 2:
            kwargs["dist_method"] = "scatter"
        try:
            backend = jax.default_backend()   # inside the loop: init may fail
            n_devices = len(jax.devices())
            print(f"[bench] attempt {attempt + 1}/{attempts}: "
                  f"backend={backend} devices={n_devices} "
                  f"kwargs={kwargs}", file=sys.stderr)
            # compile_s must describe the backend this attempt runs on, not
            # accumulate failed attempts on a different backend
            timer.seconds.pop("compile", None)
            timer.counts.pop("compile", None)
            cold_counter = CompileCounter()
            with cold_counter, timer.phase("compile"):
                # no resume ledger here: the warm-up is a throwaway
                # compile pass, and its perturb=0 inputs fingerprint
                # differently from the timed sweep's — sharing one path
                # would clobber (then delete) the measured sweep's saved
                # buckets on a restart, and resuming the warm-up itself
                # would skip the launches that exist to compile/warm
                run_table2_sweep(sweep, **kwargs)   # compile + warm-up
            with timer.phase("sweep"), device_trace(trace_dir):
                res = run_table2_sweep(sweep, perturb=PERTURB,
                                       resume_path=resume_path, **kwargs)
            used_kwargs = kwargs
            break
        except Exception as e:   # noqa: BLE001 — device faults surface as
            # JaxRuntimeError; anything else is equally fatal for a bench run
            print(f"[bench] attempt {attempt + 1}/{attempts} failed: "
                  f"{type(e).__name__}: {str(e)[:300]}", file=sys.stderr)
            try:
                jax.clear_caches()
                _batched_solver.cache_clear()
            except Exception:   # noqa: BLE001 — cache teardown is best-effort
                pass
            if attempt == attempts - 2:
                print("[bench] falling back to CPU for final attempt",
                      file=sys.stderr)
                _force_cpu()
            time.sleep(5.0 * (attempt + 1))
    if res is None:
        print("[bench] all attempts failed (including CPU fallback)",
              file=sys.stderr)
        sys.exit(1)
    wall = res.wall_seconds
    on_accel = backend in ("tpu", "axon")

    # EGM throughput: knots touched per backward step x total steps summed
    # over all 12 cells' bisection midpoints, per second per chip.
    total_egm_steps = float(res.egm_iters.sum())
    gridpoints_per_sec_per_chip = (
        total_egm_steps * A_COUNT * LABOR_STATES / wall / max(n_devices, 1))

    # FLOP accounting (VERDICT r2 weak-item 1): model FLOPs from the
    # counters, vs the chip's nominal peak.  The result records which
    # distribution method actually executed.
    dist_method = res.dist_method if res.dist_method != "auto" else "scatter"
    sweep_flops = _model_flops(
        total_egm_steps, float(res.dist_iters.sum()), A_COUNT, LABOR_STATES,
        DIST_COUNT, dense_dist=(dist_method in ("dense", "pallas")))
    flops_per_sec = sweep_flops / wall
    peak = _peak_flops_per_chip(backend)
    mfu_pct = (None if peak.value is None
               else 100.0 * flops_per_sec / (peak.value * max(n_devices, 1)))
    print(f"[bench] sweep FLOPs {sweep_flops:.3e} ({dist_method} dist path) "
          f"-> {flops_per_sec:.3e} FLOP/s"
          + (f" = {mfu_pct:.4f}% of peak" if mfu_pct is not None else ""),
          file=sys.stderr)

    baseline = REFERENCE_CELL_SECONDS * N_CELLS
    record = {
        "metric": "table2_sweep_wall_s",
        "value": round(wall, 4),
        "unit": "s",
        "vs_baseline": round(baseline / wall, 1),
        "backend": backend,
        "n_devices": n_devices,
        "egm_gridpoints_per_sec_per_chip": round(gridpoints_per_sec_per_chip),
        "iteration_skew": round(res.iteration_skew(), 3),
        # post-scheduling straggler ratio — the lock-step waste the
        # hardware actually paid (== iteration_skew when the headline ran
        # lock-step, e.g. on the accelerator where auto-scheduling stays
        # off; the worst within-bucket ratio when it ran bucketed, the
        # CPU default — ISSUE 2 acceptance: < 1.6 at 12 cells, from 2.6).
        # The warm_scheduled_iteration_skew field below carries the
        # explicitly-scheduled sweep's number on every backend.
        "scheduled_iteration_skew": round(res.scheduled_iteration_skew(), 3),
        "n_buckets": (0 if res.bucket is None
                      else int(res.bucket.max()) + 1),
        "compile_s": round(timer.seconds.get("compile", float("nan")), 2),
        # cold-side compile attribution (the warm side lands later via
        # _compile_cold_warm): how many programs XLA actually built vs
        # loaded from the persistent compilation cache during the compile
        # phase — distinguishes a true cold compile from a disk-warm one
        "compile_cold_s": round(timer.seconds.get("compile", float("nan")),
                                2),
        "compile_cold_cache_hits": cold_counter.cache_hits,
        "compile_cold_cache_misses": cold_counter.cache_misses,
        "egm_method": res.egm_method,
        "flops_per_sec": round(flops_per_sec),
        "mfu_pct": None if mfu_pct is None else round(mfu_pct, 4),
        # True when the MFU denominator is the unknown-chip class guess
        # (ISSUE 4 satellite): an assumed peak must read as assumed
        "peak_flops_assumed": peak.assumed,
        # Which source produced the FLOP numerator (ISSUE 10 satellite):
        # the headline rides the analytic step-count model; the measured
        # XLA side lives in the --profile-smoke profile_* record
        "flops_provenance": "analytic",
        "dist_method": dist_method,
    }
    if on_accel:
        record["captured_at"] = time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                              time.gmtime())
        _persist_tpu_evidence(record)     # sweep evidence: durable NOW
    else:
        # CPU fallback (wedged tunnel): carry the durable accelerator
        # record INLINE so this JSON line is self-contained evidence —
        # the round-3 failure mode was a driver-captured artifact showing
        # backend=cpu while the TPU measurement existed only in prose.
        try:
            with open(os.path.join(_repo_dir(),
                                   "bench_tpu_last.json")) as f:
                last = json.load(f)
            if isinstance(last, dict):   # a truncated write can yield
                record["last_tpu"] = last   # valid-but-non-object JSON
                print("[bench] CPU fallback: embedded the committed TPU "
                      "record (bench_tpu_last.json, captured_at="
                      f"{last.get('captured_at')})", file=sys.stderr)
        except (OSError, ValueError):
            pass

    # The ISSUE 2 tentpole end-to-end: sidecar-scheduled warm-bracket
    # sweep vs the headline (runs on every backend — the acceptance
    # criteria are CPU numbers too).
    record.update(_warm_scheduled_metrics(timer, used_kwargs, res))
    if on_accel:
        _persist_tpu_evidence(record)

    # The ISSUE 5 tentpole end-to-end: the mixed-precision ladder sweep vs
    # the reference headline (every backend — polish_frac and the bp
    # agreement are CPU acceptance numbers too).
    record.update(_precision_ladder_metrics(timer, used_kwargs, res))
    if on_accel:
        _persist_tpu_evidence(record)

    # Compiled-Mosaic correctness + A/B margin (accelerator, pallas path).
    if on_accel and dist_method == "pallas":
        try:
            record.update(_pallas_dense_ab(timer, used_kwargs,
                                           res.r_star_pct))
        except Exception as e:   # noqa: BLE001
            print(f"[bench] pallas/dense A/B failed: {type(e).__name__}: "
                  f"{str(e)[:200]}", file=sys.stderr)

    # The lanes-scaling thesis (accelerator only — that is the claim).
    if on_accel:
        record["lanes_scaling"] = _lanes_scaling(timer, used_kwargs)
        _persist_tpu_evidence(record)     # scaling evidence: durable NOW

    # Fixed-overhead attribution + the sharded-mesh composition + the
    # welfare compile leg (VERDICT r4 weak-items 5, 2c, 3) — all cheap and
    # sentinel-guarded where hazardous, all persisted before the (long,
    # historically wedging) fine-grid phase can strand them.
    if on_accel:
        record.update(_overhead_decomposition(timer, used_kwargs))
        _persist_tpu_evidence(record)
        # warm-compile attribution AFTER the repeat probes (it drops the
        # in-process executable caches, which would pollute their floors)
        record.update(_compile_cold_warm(timer, used_kwargs))
        _persist_tpu_evidence(record)     # before the sharded phase's
        # fresh GSPMD/Mosaic compile can strand it
        # pin the sharded run to the method the primary actually executed
        shard_kwargs = dict(used_kwargs)
        shard_kwargs.setdefault("dist_method", dist_method)
        record.update(_sharded_sweep_metrics(timer, shard_kwargs,
                                             res.r_star_pct))
        _persist_tpu_evidence(record)
        record.update(_welfare_sweep_metrics(timer))
        _persist_tpu_evidence(record)

    # At-scale configuration (BASELINE config 2): one fine-grid GE cell.
    record.update(_fine_grid_metrics(backend, timer))
    if on_accel:
        _persist_tpu_evidence(record)     # fine-grid evidence: durable
        # before the (long) oracle subprocess can strand it

    with timer.phase("oracle_f64"):
        oracle = _oracle_r_star()
    if oracle is not None:
        # r* is in percent; 1 bp = 0.01 percentage points.
        max_bp = max(abs(a - b) for a, b in
                     zip([float(x) for x in res.r_star_pct], oracle)) * 100.0
    else:
        max_bp = None
    record["r_star_f32_f64_max_bp"] = (None if max_bp is None
                                       else round(max_bp, 3))
    if on_accel:
        _persist_tpu_evidence(record)     # the complete record

    # last line of defense against the stranded-null class (ISSUE 5
    # satellite): a derived field that is null while its wall is present
    # is a record bug — flag it loudly in the artifact and on stderr
    nulls = record_null_violations(record)
    if nulls:
        record["record_null_violations"] = [list(p) for p in nulls]
        print(f"[bench] WARNING: stranded-null record fields: {nulls}",
              file=sys.stderr)

    print(f"[bench] phase breakdown:\n{timer.summary()}", file=sys.stderr)
    print(f"[bench] Table II r* (%):\n{res.table()}", file=sys.stderr)
    print(f"[bench] per-cell work (egm+dist steps): "
          f"{res.total_work().tolist()} skew={res.iteration_skew():.2f}",
          file=sys.stderr)
    print(json.dumps(record))


if __name__ == "__main__":
    main()
