#!/usr/bin/env python
"""Headline benchmark: the full Aiyagari Table II sweep (σ ∈ {1,3,5} ×
ρ ∈ {0, 0.3, 0.6, 0.9} — 12 general-equilibrium solves) as one batched XLA
program on the local device(s).

Baseline: the reference solves ONE calibration cell in 27.12 min
(``economy.solve()``, notebook cell 19 output; BASELINE.md) and runs Table II
by editing the notebook one cell at a time (SURVEY.md §2.4), so the
reference-equivalent work is 12 × 1627.2 s.  ``vs_baseline`` is the speedup
factor (baseline seconds / measured seconds).

Prints ONE JSON line:
  {"metric": "table2_sweep_wall_s", "value": <s>, "unit": "s",
   "vs_baseline": <speedup>}
"""

import json
import sys
import time

REFERENCE_CELL_SECONDS = 27.12 * 60.0   # notebook cell 19 (BASELINE.md)
N_CELLS = 12


def main():
    import jax

    from aiyagari_hark_tpu.parallel.sweep import run_table2_sweep
    from aiyagari_hark_tpu.utils.config import SweepConfig

    sweep = SweepConfig()   # full Table II: 3 sigmas x 4 rhos
    kwargs = dict(a_count=32, dist_count=500)

    print(f"[bench] backend={jax.default_backend()} "
          f"devices={len(jax.devices())}", file=sys.stderr)
    # The axon TPU tunnel intermittently faults on first execution of a
    # freshly compiled program; retry with cleared caches before giving up.
    attempts = 4
    res = None
    compile_s = float("nan")
    for attempt in range(attempts):
        try:
            t0 = time.perf_counter()
            run_table2_sweep(sweep, **kwargs)        # compile + warm-up
            compile_s = time.perf_counter() - t0
            res = run_table2_sweep(sweep, **kwargs)  # timed, cached executable
            break
        except Exception as e:   # noqa: BLE001 — device faults surface as
            # JaxRuntimeError; anything else is equally fatal for a bench run
            print(f"[bench] attempt {attempt + 1}/{attempts} failed: "
                  f"{type(e).__name__}: {str(e)[:200]}", file=sys.stderr)
            jax.clear_caches()
            from aiyagari_hark_tpu.parallel.sweep import _batched_solver
            _batched_solver.cache_clear()
            time.sleep(5.0 * (attempt + 1))
    if res is None:
        print("[bench] all attempts failed", file=sys.stderr)
        sys.exit(1)
    wall = res.wall_seconds

    baseline = REFERENCE_CELL_SECONDS * N_CELLS
    print(f"[bench] compile+first-run {compile_s:.2f}s, "
          f"steady-state sweep {wall:.3f}s", file=sys.stderr)
    print("[bench] Table II r* (%):\n" + res.table(), file=sys.stderr)
    print(json.dumps({
        "metric": "table2_sweep_wall_s",
        "value": round(wall, 4),
        "unit": "s",
        "vs_baseline": round(baseline / wall, 1),
    }))


if __name__ == "__main__":
    main()
