#!/usr/bin/env python
"""Generate (and optionally execute) the driver notebook
``Aiyagari-HARK-tpu.ipynb`` — the framework's analog of the reference's
canonical entry point (``Aiyagari-HARK.ipynb``, SURVEY.md §2.1 C6).

The notebook mirrors the reference's cell flow (build -> solve ->
equilibrium stats -> consumption/saving-rule plots -> wealth stats ->
Lorenz vs SCF -> runtime) through this framework's facade, so a reference
user can follow the same narrative.  ``reproduce.py`` remains the scripted
equivalent; the notebook is the human-readable tour.

Usage: python scripts/make_notebook.py [--execute] [--quick]
"""

import argparse
import os
import sys

import nbformat as nbf

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def build(quick: bool) -> nbf.NotebookNode:
    nb = nbf.v4.new_notebook()
    md = nbf.v4.new_markdown_cell
    code = nbf.v4.new_code_cell
    cfg_quick = ("econ_dict.update(LaborStatesNo=5, act_T=600, "
                 "T_discard=120)\n"
                 "agent_dict.update(LaborStatesNo=5, AgentCount=100, "
                 "aCount=16)\n" if quick else "")
    cells = [
        md("# Aiyagari (1994) on TPU — driver notebook\n\n"
           "TPU-native (JAX/XLA) replication of *Uninsured Idiosyncratic "
           "Risk and Aggregate Saving*, with the capabilities of the "
           "`Aiyagari-HARK` reference replication.  This notebook follows "
           "the reference notebook's flow (its cells 13–30): build the "
           "economy and agents, solve the Krusell–Smith general "
           "equilibrium, then reproduce the equilibrium statistics, "
           "consumption functions, aggregate saving rule, wealth "
           "distribution, and Lorenz comparison.\n\n"
           "Reference golden numbers: equilibrium return **4.178 %**, "
           "saving rate **23.649 %**, `economy.solve()` wall-clock "
           "**27.12 min** (this framework: seconds)."),
        code("import time\n\n"
             "import matplotlib.pyplot as plt\n"
             "import numpy as np\n\n"
             "from aiyagari_hark_tpu import (AiyagariEconomy, AiyagariType,\n"
             "                               init_aiyagari_agents,\n"
             "                               init_aiyagari_economy)\n"
             "from aiyagari_hark_tpu.utils import stats\n"
             "from aiyagari_hark_tpu.utils.backend import select_backend\n\n"
             "info = select_backend('auto')\n"
             "print(f'backend={info.name} x64={info.x64}')"),
        md("## Build the economy and agents\n\n"
           "Parameter dictionaries use the reference's exact spelling and "
           "defaults (`init_Aiyagari_agents`/`init_Aiyagari_economy`, "
           "`Aiyagari_Support.py:752-757,1525-1551`); the notebook "
           "calibration overrides match its cells 16–17."),
        code("econ_dict = init_aiyagari_economy()\n"
             "econ_dict.update(LaborAR=0.3, LaborSD=0.2, CRRA=1.0, "
             "verbose=False)\n"
             "agent_dict = init_aiyagari_agents()\n"
             "agent_dict.update(AgentCount=350)\n"
             + cfg_quick +
             "economy = AiyagariEconomy(seed=0, **econ_dict)\n"
             "agent = AiyagariType(**agent_dict)\n"
             "agent.cycles = 0\n"
             "agent.get_economy_data(economy)\n"
             "economy.agents = [agent]\n"
             "economy.make_Mrkv_history()\n"
             "print(f'KSS={economy.KSS:.4f}  MSS={economy.MSS:.4f}')"),
        md("## Solve for the general equilibrium\n\n"
           "The reference's `economy.solve()` took **27.12 minutes** "
           "(notebook cell 19).  Here the same Krusell–Smith fixed point — "
           "EGM household solve, 11,000-period panel, saving-rule "
           "regression — runs as three jitted XLA programs per outer "
           "iteration."),
        code("t0 = time.time()\n"
             "sol = economy.solve(dtype=info.dtype)\n"
             "mins = (time.time() - t0) / 60\n"
             "print(f'Solving the Aiyagari model took {mins:.3f} minutes '\n"
             "      f'(reference: 27.12).  converged={sol.converged} in '\n"
             "      f'{len(sol.records)} outer iterations')"),
        md("## Equilibrium statistics (reference cell 20)"),
        code("depr = econ_dict['DeprFac']\n"
             "a_mean = float(np.mean(economy.reap_state['aNow']))\n"
             "r_pct = (economy.sow_state['Rnow'] - 1.0) * 100.0\n"
             "s_pct = 100.0 * depr * a_mean / (economy.sow_state['Mnow']\n"
             "                                 - (1 - depr) * a_mean)\n"
             "print(f'Equilibrium Return to Capital: {r_pct:.4f} % "
             "(reference 4.178 %)')\n"
             "print(f'Equilibrium Savings Rate: {s_pct:.4f} % "
             "(reference 23.649 %)')"),
        md("### Solution accuracy (den Haan 2010)\n\n"
           "The reference's only quality signal is the one-step "
           "regression R² — the weak test den Haan's paper is about.  "
           "Here the converged rule is iterated on its own output along "
           "the realized shock path with no feedback; the panel (MC-fit) "
           "rule carries percent-level off-path drift by construction "
           "(its noise-attenuated slope — `models/diagnostics.py`), while "
           "the deterministic pinned-histogram engine meets the "
           "fraction-of-a-percent standard (`results.json` reports both "
           "side by side)."),
        code("from aiyagari_hark_tpu.models.diagnostics import "
             "den_haan_forecast\n"
             "dh = den_haan_forecast(sol, t_start=econ_dict['T_discard'])\n"
             "print(f'den Haan dynamic forecast error (panel rule): '\n"
             "      f'max {float(dh.max_error_pct):.3f} %  '\n"
             "      f'mean {float(dh.mean_error_pct):.3f} %')"),
        md("## Consumption functions by labor-supply state "
           "(reference cell 21)\n\nOne panel per labor state; each line is "
           "one aggregate-resources gridpoint of the two-level policy "
           "`solution[0].cFunc[4j].xInterpolators`."),
        code("n = econ_dict['LaborStatesNo']\n"
             "fig, axes = plt.subplots(1, n, figsize=(2.6 * n, 2.8), "
             "sharey=True)\n"
             "m = np.linspace(0.0, 50.0, 200)\n"
             "for j, ax in enumerate(np.atleast_1d(axes)):\n"
             "    for interp in agent.solution[0].cFunc[4 * j]"
             ".xInterpolators:\n"
             "        ax.plot(m, interp(m), lw=0.8)\n"
             "    ax.set_title(f'labor state {j + 1}/{n}', fontsize=9)\n"
             "plt.tight_layout(); plt.show()"),
        md("## Aggregate saving rule (reference cell 22)"),
        code("x = np.linspace(0.1, 2.0 * economy.KSS, 500)\n"
             "plt.plot(x, economy.AFunc[0](x), label='bad state')\n"
             "plt.plot(x, economy.AFunc[1](x), '--', label='good state')\n"
             "plt.xlabel('Aggregate market resources $M$')\n"
             "plt.ylabel('Aggregate savings $A$')\n"
             "plt.legend(); plt.show()"),
        md("## Simulated wealth distribution (reference cells 24–27)"),
        code("sim_wealth = np.asarray(economy.reap_state['aNow'][0])\n"
             "ws = stats.wealth_stats(sim_wealth)\n"
             "print(f'max={ws.max:.3f} mean={ws.mean:.3f} std={ws.std:.3f} '\n"
             "      f'median={ws.median:.3f}  (reference 22.046 / 5.439 / '\n"
             "      f'3.697 / 4.718)')\n"
             "scf = stats.load_scf_lorenz()   # vendored from the "
             "reference's committed vector figure\n"
             "pct, lor_scf = scf.pctiles, scf.scf_shares\n"
             "lor_sim = stats.get_lorenz_shares(sim_wealth, "
             "percentiles=pct)\n"
             "plt.figure(figsize=(5, 5))\n"
             "plt.plot(pct, lor_scf, '--k', label='SCF')\n"
             "plt.plot(pct, lor_sim, '-b', label='Aiyagari')\n"
             "plt.plot(pct, pct, 'g-.', label='45 degree')\n"
             "plt.legend(loc=2); plt.ylim([0, 1]); plt.show()\n"
             "print(f'Lorenz distance: '\n"
             "      f'{float(np.sqrt(((lor_scf - lor_sim) ** 2).sum())):"
             ".4f}  (reference vs real SCF: 0.9714)')"),
        md("## Beyond the reference\n\n"
           "Capabilities the reference does not have, one call away:\n\n"
           "- **Deterministic equilibrium** — "
           "`economy.solve(sim_method='distribution')` replaces the "
           "Monte-Carlo panel with a histogram push-forward and a "
           "fixed-price pinned secant (cross-validates the bisection "
           "engine to 0.3bp).\n"
           "- **Closing the SCF gap** — the plot above shows this "
           "model's known failure (the reference's Lorenz distance "
           "0.9714: too little inequality); "
           "`calibrate_spread_to_lorenz` fits a beta-dist "
           "discount-factor spread to the real SCF curve and closes it "
           "to ~0.12 (Carroll et al. 2017).\n"
           "- **Fiscal redistribution** — `solve_fiscal_equilibrium` / "
           "`tax_rate_sweep`: revenue-neutral tax/transfer and HSV "
           "progressivity with GE + welfare; the optimal-tax search "
           "runs as one vmapped XLA program (interior optimum, "
           "hump-shaped welfare).\n"
           "- **Table II sweep** — `run_table2_sweep()` solves all 12 "
           "(σ, ρ) calibration cells as one batched XLA program "
           "(1.26 s on one TPU chip via the Pallas lane-grid kernel vs "
           "12 × 27 min of reference-equivalent work).\n"
           "- **Welfare** — `policy_value` / `aggregate_welfare` / "
           "`consumption_equivalent` (models/value.py).\n"
           "- **Life cycle** — `solve_lifecycle` / `simulate_cohort` "
           "(models/lifecycle.py).\n"
           "- **Two-asset portfolio choice** — "
           "`solve_portfolio_equilibrium` (models/portfolio.py).\n"
           "- **Huggett bond economy** — negative borrowing limits + "
           "zero-net-supply credit-market clearing "
           "(`solve_huggett_equilibrium`), and Guerrieri–Lorenzoni-style "
           "**credit-crunch deleveraging transitions** "
           "(`solve_credit_crunch`, models/huggett.py).\n"
           "- **Endogenous labor supply** — consumption-leisure EGM with "
           "equilibrium effective labor (`solve_labor_equilibrium`, "
           "models/labor.py).\n"
           "- **Epstein–Zin preferences** — risk aversion decoupled from "
           "the EIS, exact CRRA reduction at γ = 1/ψ "
           "(`solve_ez_equilibrium`, models/epstein_zin.py).\n"
           "- **Calibration** — invert the equilibrium map "
           "(`calibrate_discount_factor`, `calibrate_labor_weight`, "
           "models/calibrate.py).\n"
           "- **Transition welfare** — the consumption-equivalent value "
           "of a shock path (`transition_welfare`, "
           "models/transition.py).\n"
           "- **MIT-shock transitions** — perfect-foresight impulse "
           "responses (`solve_transition`, models/transition.py).\n"
           "- **Sequence-space Jacobians** — `jax.jacrev` through the "
           "transition path map; linear GE IRFs and business-cycle "
           "moments (`sequence_jacobians`, models/jacobian.py).\n"
           "- **Discount-factor heterogeneity** — beta-dist wealth "
           "concentration (`solve_heterogeneous_equilibrium`, "
           "models/heterogeneity.py).\n"
           "- **Accuracy diagnostics** — den Haan (2010) dynamic-forecast "
           "errors of the aggregate law (`den_haan_forecast`, "
           "models/diagnostics.py).\n\n"
           "Two live examples below."),
        md("### GE impulse response to a 1% TFP shock\n\n"
           "The nonlinear MIT-shock path (damped fixed point on the "
           "capital path) against its sequence-space linearization (one "
           "`jax.jacrev` + one linear solve) — they agree to O(‖shock‖²)."),
        code("import jax.numpy as jnp\n"
             "from aiyagari_hark_tpu.models.household import "
             "build_simple_model\n"
             "from aiyagari_hark_tpu.models.equilibrium import "
             "solve_bisection_equilibrium\n"
             "from aiyagari_hark_tpu.models.jacobian import "
             "(sequence_jacobians,\n"
             "                                             "
             "linear_impulse_response)\n"
             "from aiyagari_hark_tpu.models.transition import "
             "solve_transition\n\n"
             "T = 40\n"
             "m5 = build_simple_model(labor_states=5, labor_ar=0.3, "
             "a_count=32,\n"
             "                        dist_count=150, dtype=info.dtype)\n"
             "eq = solve_bisection_equilibrium(m5, 0.96, 1.0, "
             "econ_dict['CapShare'], depr)\n"
             "dz = 0.01 * 0.8 ** np.arange(T)\n"
             "jac = sequence_jacobians(m5, 0.96, 1.0, "
             "econ_dict['CapShare'], depr, eq, T)\n"
             "lin = linear_impulse_response(jac, jnp.asarray(dz))\n"
             "nl = solve_transition(m5, 0.96, 1.0, econ_dict['CapShare'], "
             "depr,\n"
             "                      init_dist=eq.distribution, "
             "terminal_policy=eq.policy,\n"
             "                      k_terminal=eq.capital, horizon=T, "
             "prod_path=1 + dz)\n"
             "k_ss = float(eq.capital)\n"
             "plt.plot(100 * (np.asarray(nl.k_path) / k_ss - 1), "
             "label='K nonlinear')\n"
             "plt.plot(100 * np.asarray(lin.dk) / k_ss, ':', "
             "label='K linear (Jacobian)')\n"
             "plt.plot(100 * dz, 'k--', label='TFP (%)')\n"
             "plt.xlabel('quarters'); plt.ylabel('% dev from SS'); "
             "plt.legend(); plt.show()"),
        md("### Beta-dist wealth concentration\n\n"
           "A ±0.012 uniform spread of discount factors around the "
           "notebook β: the patient quartile accumulates most of the "
           "wealth and the Gini jumps toward its empirical level — the "
           "Krusell–Smith §3 / Carroll et al. (2017) mechanism."),
        code("from aiyagari_hark_tpu.models.heterogeneity import "
             "(uniform_beta_types,\n"
             "    solve_heterogeneous_equilibrium, "
             "population_distribution)\n\n"
             "betas = uniform_beta_types(0.96, 0.012, 4)\n"
             "het = solve_heterogeneous_equilibrium(m5, betas, "
             "np.ones(4), 1.0,\n"
             "                                      "
             "econ_dict['CapShare'], depr)\n"
             "grid = np.asarray(m5.dist_grid)\n"
             "g_hom = stats.gini(grid, "
             "np.asarray(eq.distribution).sum(1))\n"
             "g_het = stats.gini(grid, "
             "np.asarray(population_distribution(het)).sum(1))\n"
             "print(f'r*: homogeneous {float(eq.r_star):.4%}  beta-dist "
             "{float(het.r_star):.4%}')\n"
             "print(f'wealth Gini: homogeneous {g_hom:.3f}  beta-dist "
             "{g_het:.3f}')\n"
             "print('per-type mean wealth:', "
             "np.round(np.asarray(het.type_capital), 2))"),
    ]
    nb.cells = cells
    nb.metadata.kernelspec = {"display_name": "Python 3",
                              "language": "python", "name": "python3"}
    return nb


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--execute", action="store_true")
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--out", default=os.path.join(
        REPO, "Aiyagari-HARK-tpu.ipynb"))
    args = ap.parse_args()
    nb = build(args.quick)
    if args.execute:
        from nbclient import NotebookClient
        client = NotebookClient(nb, timeout=1200, kernel_name="python3",
                                resources={"metadata": {"path": REPO}})
        client.execute()
    with open(args.out, "w") as f:
        nbf.write(nb, f)
    print(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
