"""A/B the sweep's distribution fixed point: vmapped XLA dense vs the
Pallas lane-grid kernel (VERDICT r2 next-round item 5).

Workload: the 12 Table II cells' dense lottery operators at a common
interest rate (policies solved per cell, so iteration counts carry the
real sweep's skew), then the stationary fixed point batched two ways:

  A. ``jit(vmap(...))`` over the XLA dense push-forward — the sweep's
     current method: every step processes all 12 lanes until the slowest
     converges (lock-step; measured total-work skew ~2.5).
  B. ``stationary_dense_pallas_grid`` — one pallas program instance per
     lane, each lane VMEM-resident and exiting at its own convergence.

Prints wall times and the max difference of the stationary distributions.
Run on the TPU chip: ``python scripts/pallas_ab.py``.
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.normpath(
    os.path.join(os.path.dirname(os.path.abspath(__file__)), os.pardir)))

import jax
import jax.numpy as jnp
import numpy as np


def main():
    from aiyagari_hark_tpu.models.household import (
        accelerated_distribution_fixed_point,
        build_simple_model,
        dense_wealth_operator,
        initial_distribution,
        solve_household,
        wealth_transition,
    )
    from aiyagari_hark_tpu.ops.pallas_kernels import (
        pallas_tpu_available,
        stationary_dense_pallas_grid,
    )
    from aiyagari_hark_tpu.models import firm

    backend = jax.default_backend()
    print(f"backend={backend} devices={jax.devices()}")
    cells = [(s, r) for s in (1.0, 3.0, 5.0) for r in (0.0, 0.3, 0.6, 0.9)]
    D, NS, A = 500, 7, 32
    r = 0.03
    tol = 1e-8

    Ss, Ps, d0s = [], [], []
    for crra, rho in cells:
        m = build_simple_model(labor_states=NS, labor_ar=rho, a_count=A,
                               dist_count=D)
        k_to_l = firm.k_to_l_from_r(r, 0.36, 0.08)
        W = firm.wage_rate(k_to_l, 0.36)
        pol, _, _, _ = solve_household(1.0 + r, W, m, 0.96, crra)
        trans = wealth_transition(pol, 1.0 + r, W, m)
        Ss.append(dense_wealth_operator(trans, D))
        Ps.append(m.transition)             # per-cell: rho varies
        d0s.append(initial_distribution(m))
    S = jnp.stack(Ss)                       # [12, N, D, D]
    Pb = jnp.stack(Ps)                      # [12, N, N]
    d0 = jnp.stack(d0s)                     # [12, D, N]

    # --- A: vmapped XLA dense (the sweep's method)
    def one_dense(S_i, P_i, d0_i):
        def push(dist):
            moved = jnp.einsum("ndk,kn->dn", S_i, dist,
                               precision=jax.lax.Precision.HIGHEST)
            return jnp.matmul(moved, P_i,
                              precision=jax.lax.Precision.HIGHEST)
        return accelerated_distribution_fixed_point(push, d0_i, tol, 20000,
                                                    64)

    f_a = jax.jit(jax.vmap(one_dense))
    # timed calls use a freshly-perturbed initial distribution (same fixed
    # point, ~same step count) so an identical-execution cache anywhere in
    # the stack cannot short-circuit the re-run
    def perturb(d_, eps):
        out = d_ + eps
        return out / out.sum(axis=(1, 2), keepdims=True)

    def timed(f, *args, reps=3):
        """Median over fresh perturbations.  The clock stops only after
        full HOST materialization (np.asarray of every output):
        block_until_ready alone measures ~0 ms for XLA executables through
        the tunneled device — it does not actually block there — and
        identical inputs can be served from a cache, so each rep also
        perturbs the initial distribution."""
        outs, ts = None, []
        for k in range(reps):
            a2 = args[:-1] + (perturb(args[-1], (k + 1) * 1e-7),)
            t0 = time.perf_counter()
            outs = tuple(np.asarray(o) for o in f(*a2))
            ts.append(time.perf_counter() - t0)
        return outs, sorted(ts)[len(ts) // 2], ts

    jax.block_until_ready(f_a(S, Pb, d0))      # compile
    (da, ia, _, _), t_a, ts_a = timed(f_a, S, Pb, d0)
    print(f"   A raw timings: {[f'{t*1e3:.0f}ms' for t in ts_a]}")
    print(f"A vmap(dense):  {t_a*1e3:8.1f} ms   iters={np.asarray(ia)} "
          f"(lock-step: every lane pays max)")

    # --- B: pallas lane grid
    if backend in ("tpu", "axon") and not pallas_tpu_available():
        print("B pallas grid: compiled kernel unavailable on this backend")
        return
    interpret = backend not in ("tpu", "axon")
    f_b = jax.jit(lambda S_, P_, d_: stationary_dense_pallas_grid(
        S_, P_, d_, tol=tol, interpret=interpret))
    jax.block_until_ready(f_b(S, Pb, d0))      # compile
    (db, ib, _), t_b, ts_b = timed(f_b, S, Pb, d0)
    print(f"   B raw timings: {[f'{t*1e3:.0f}ms' for t in ts_b]}")
    print(f"B pallas grid: {t_b*1e3:8.1f} ms   iters={np.asarray(ib)} "
          f"(per-lane exit)")
    gap = float(jnp.abs(da - db).max())
    print(f"max |dist_A - dist_B| = {gap:.3e}")
    print(f"speedup A/B = {t_a / t_b:.2f}x")


if __name__ == "__main__":
    main()
