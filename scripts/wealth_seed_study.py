"""Measure the Monte-Carlo sampling band of the notebook's cell-24 goldens.

The reference reports simulated-wealth max/mean/std/median =
22.046/5.439/3.697/4.718 and a Lorenz-vs-SCF distance of 0.9714 from ONE
350-agent panel draw (``Aiyagari-HARK.ipynb`` cell 24/27, BASELINE.md).
With 350 agents those statistics carry real sampling noise — VERDICT r2
missing-item 2 asks for the band to be quantified so the goldens can be
asserted honestly.

Method: solve the notebook-parity economy once (panel mode, CPU x64
oracle), then hold the converged policy + aggregate chain fixed and re-run
the panel simulator under ``vmap`` over N fresh seeds (fresh initial panel
+ fresh idiosyncratic shock streams per seed — exactly the reference's
pipeline, re-randomized).  Each seed yields the four wealth stats plus the
Lorenz distance against the vendored SCF curve.  A distribution-mode solve
provides the zero-noise deterministic-histogram counterpart.

Output: ``tests/data/wealth_seed_study.json`` with per-statistic
min/max/mean/std over seeds; ``tests/test_wealth_goldens.py`` asserts the
reference goldens sit inside (a modest widening of) the measured band and
pins the band to current code via a seed-0 re-simulation.

Usage::

    python scripts/wealth_seed_study.py [--n-seeds 32] [--out PATH]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.normpath(
    os.path.join(os.path.dirname(os.path.abspath(__file__)), os.pardir)))

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

REPO = os.path.normpath(os.path.join(os.path.dirname(__file__), os.pardir))
DEFAULT_OUT = os.path.join(REPO, "tests", "data", "wealth_seed_study.json")

REFERENCE_GOLDENS = {  # notebook cell 24 / cell 27; BASELINE.md
    "max": 22.046, "mean": 5.439, "std": 3.697, "median": 4.718,
    "lorenz_vs_scf": 0.9714,
}


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--n-seeds", type=int, default=32)
    ap.add_argument("--out", default=DEFAULT_OUT)
    args = ap.parse_args(argv)

    from aiyagari_hark_tpu import (AiyagariEconomy, AiyagariType,
                                   init_aiyagari_agents,
                                   init_aiyagari_economy)
    from aiyagari_hark_tpu.models.simulate import initial_panel, simulate_panel
    from aiyagari_hark_tpu.utils import stats

    t0 = time.time()
    econ_dict = init_aiyagari_economy()
    econ_dict.update(LaborAR=0.3, LaborSD=0.2, CRRA=1.0, verbose=False)
    agent_dict = init_aiyagari_agents()
    agent_dict.update(AgentCount=350)

    economy = AiyagariEconomy(seed=0, **econ_dict)
    agent = AiyagariType(**agent_dict)
    agent.cycles = 0
    agent.get_economy_data(economy)
    economy.agents = [agent]
    economy.make_Mrkv_history()
    sol = economy.solve(sim_method="panel")
    print(f"[study] panel-mode solve done in {time.time() - t0:.1f}s, "
          f"converged={sol.converged}")

    cal = sol.calibration
    mrkv_hist = jnp.asarray(sol.mrkv_hist)
    agent_count = int(agent_dict["AgentCount"])
    mrkv_init = int(econ_dict["MrkvNow_init"])

    def one_seed(key):
        k_init, k_sim = jax.random.split(key)
        init = initial_panel(cal, agent_count, mrkv_init, k_init)
        _, final = simulate_panel(sol.policy, cal, mrkv_hist, init, k_sim)
        return final.assets

    keys = jax.random.split(jax.random.PRNGKey(12345), args.n_seeds)
    t1 = time.time()
    assets = np.asarray(jax.jit(jax.vmap(one_seed))(keys))   # [S, Nag]
    print(f"[study] {args.n_seeds} panel re-simulations in "
          f"{time.time() - t1:.1f}s")

    per_seed = []
    for s in range(args.n_seeds):
        ws = stats.wealth_stats(assets[s])
        per_seed.append({
            "max": ws.max, "mean": ws.mean, "std": ws.std,
            "median": ws.median,
            "lorenz_vs_scf": stats.lorenz_distance_vs_scf(assets[s]),
        })

    # zero-noise deterministic counterpart: histogram simulator
    economy2 = AiyagariEconomy(seed=0, **econ_dict)
    agent2 = AiyagariType(**agent_dict)
    agent2.cycles = 0
    agent2.get_economy_data(economy2)
    economy2.agents = [agent2]
    economy2.make_Mrkv_history()
    economy2.solve(sim_method="distribution")
    grid = economy2.reap_state["aNowGrid"][0]
    w = economy2.reap_state["aNowWeights"][0]
    hs = stats.wealth_stats(grid, w)
    hist_stats = {
        "max": hs.max, "mean": hs.mean, "std": hs.std, "median": hs.median,
        "lorenz_vs_scf": stats.lorenz_distance_vs_scf(grid, w),
    }

    out = {
        "config": {"n_seeds": args.n_seeds, "agent_count": agent_count,
                   "act_T": int(econ_dict["act_T"]),
                   "T_discard": int(econ_dict["T_discard"]),
                   "mrkv_init": mrkv_init,
                   "backend": "cpu-x64"},
        # the COLD-converged saving rule: the layer-3 regression test
        # warm-starts its re-solve from this (initial guess only — its
        # solver re-certifies convergence at the same tolerance)
        "afunc": {"intercept": [float(x)
                                for x in np.asarray(sol.afunc.intercept)],
                  "slope": [float(x) for x in np.asarray(sol.afunc.slope)]},
        # the rule sol.policy was actually SOLVED under (the final
        # iteration's pre-update rule = the penultimate record): the test
        # warm-starts from THIS one, so its first-iteration policy matches
        # the study's policy up to EGM tolerance instead of sitting one
        # outer-update (up to the 0.01 outer tolerance, ~1% in K) away
        # (round-4 review)
        "policy_afunc": (
            {"intercept": sol.records[-2].intercept,
             "slope": sol.records[-2].slope}
            if len(sol.records) >= 2 else
            {"intercept": [float(x)
                           for x in np.asarray(sol.afunc.intercept)],
             "slope": [float(x) for x in np.asarray(sol.afunc.slope)]}),
        "reference_goldens": REFERENCE_GOLDENS,
        "band": {},
        "histogram_stats": hist_stats,
        "per_seed": per_seed,
    }
    for k in REFERENCE_GOLDENS:
        vals = np.array([p[k] for p in per_seed])
        out["band"][k] = {
            "min": float(vals.min()), "max": float(vals.max()),
            "mean": float(vals.mean()), "std": float(vals.std()),
        }
        g = REFERENCE_GOLDENS[k]
        z = (g - vals.mean()) / max(vals.std(), 1e-12)
        print(f"[study] {k:14s} band [{vals.min():7.3f}, {vals.max():7.3f}] "
              f"mean {vals.mean():7.3f} std {vals.std():6.3f}  "
              f"golden {g:7.3f} (z={z:+.2f})")

    with open(args.out, "w") as f:
        json.dump(out, f, indent=2)
    print(f"[study] wrote {args.out} in {time.time() - t0:.1f}s total")


if __name__ == "__main__":
    main()
