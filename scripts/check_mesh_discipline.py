#!/usr/bin/env python
"""Static lint: device meshes and shardings are built through
``parallel/mesh.py``, never constructed raw in the hot paths (ISSUE 20).

The multi-chip layer (DESIGN §6b) only works if every mesh and every
sharding in the solver/serving paths routes through ``parallel.mesh`` —
the ONE seam that owns axis naming (``"cells"``/``"state"``), device
selection, the divisibility contract, the partition-rule table, and the
fingerprinted geometry.  A hot path that calls
``jax.sharding.Mesh``/``NamedSharding``/``PartitionSpec`` directly mints
a parallel geometry the seam never sees: its axis names can drift from
the partition rules, its device order from ``balanced_lane_order``, and
its shape from the geometry every resume fingerprint downstream hashed.
This lint bans direct CONSTRUCTION of (or ``from``-import naming)
``Mesh`` / ``NamedSharding`` / ``PartitionSpec`` in the hot directories
(``models/``, ``parallel/``, ``serve/``, ``scenarios/``, ``verify/``,
``ops/``):

any such call or import there must carry an explicit ``# mesh-ok``
waiver on its line stating why the raw construction is correct.

``parallel/mesh.py`` IS the seam and is exempt, as are tests (pinning
construction behavior is a test's job).  Run standalone (exits 1 on
findings) or via tier-1 (``tests/test_mesh_discipline.py``).
"""

from __future__ import annotations

import ast
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# The hot directories: everywhere a mesh or sharding can sit on a
# sweep/serve/certify path.  ops/ is IN scope here (unlike the grid
# lint, where ops/ is the seam): ops kernels consume shardings through
# ``constrain_state``, they never mint geometry.
SCAN_DIRS = (
    os.path.join("aiyagari_hark_tpu", "models"),
    os.path.join("aiyagari_hark_tpu", "parallel"),
    os.path.join("aiyagari_hark_tpu", "serve"),
    os.path.join("aiyagari_hark_tpu", "scenarios"),
    os.path.join("aiyagari_hark_tpu", "verify"),
    os.path.join("aiyagari_hark_tpu", "ops"),
)

BANNED = {"Mesh", "NamedSharding", "PartitionSpec"}
WAIVER = "# mesh-ok"
# The seam itself (repo-relative): the one file allowed to construct.
EXEMPT = (os.path.join("aiyagari_hark_tpu", "parallel", "mesh.py"),)


def scan_source(src: str, rel: str) -> list:
    """Findings for one file's source text (exposed for fixture tests)."""
    if rel.replace("/", os.sep) in EXEMPT:
        return []
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [(rel, e.lineno or 0, f"unparseable: {e.msg}")]
    lines = src.splitlines()
    findings = []

    def _flag(lineno: int, what: str) -> None:
        line = lines[lineno - 1] if lineno <= len(lines) else ""
        if WAIVER in line:
            return
        findings.append(
            (rel, lineno,
             f"raw {what} in a mesh-consuming hot path — build meshes "
             "and shardings through the parallel.mesh seam (make_mesh / "
             "state_mesh / sharding / state_sharding / "
             "match_partition_rules), or waive with '# mesh-ok'"))

    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            for alias in node.names:
                if alias.name in BANNED:
                    _flag(node.lineno, f"import of {alias.name}")
        elif isinstance(node, ast.Call):
            fn = node.func
            name = (fn.id if isinstance(fn, ast.Name)
                    else fn.attr if isinstance(fn, ast.Attribute)
                    else None)
            if name in BANNED:
                _flag(node.lineno, f"construction of {name}")
    return findings


def scan_targets(repo: str = REPO) -> list:
    """The files the lint covers, absolute paths — exposed so the lint's
    own test can assert coverage instead of trusting the list silently."""
    targets = []
    for root in SCAN_DIRS:
        base = os.path.join(repo, root)
        for dirpath, _, names in os.walk(base):
            if "__pycache__" in dirpath:
                continue
            targets += [os.path.join(dirpath, n) for n in sorted(names)
                        if n.endswith(".py")]
    return targets


def scan(repo: str = REPO) -> list:
    findings = []
    for path in scan_targets(repo):
        if os.path.exists(path):
            with open(path) as fh:
                findings += scan_source(fh.read(),
                                        os.path.relpath(path, repo))
    return findings


def main() -> int:
    findings = scan()
    for rel, lineno, msg in findings:
        print(f"{rel}:{lineno}: {msg}")
    if findings:
        print(f"{len(findings)} mesh-discipline violation(s); see "
              f"scripts/check_mesh_discipline.py docstring")
        return 1
    print("mesh-discipline lint: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
