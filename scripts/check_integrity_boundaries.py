#!/usr/bin/env python
"""Static lint: artifact LOADS must verify content checksums (ISSUE 6).

The integrity layer (DESIGN §9) computes a content checksum at solve time
and verifies it at every boundary a solution later crosses — resume-ledger
restore, scheduler-sidecar load, solution-store tiers.  That chain is only
as strong as its weakest load site: ONE raw ``load_pytree``/``np.load``
that skips verification re-opens the silent-corruption hole the layer
closed (exactly how the store's disk tier degraded silently before this
PR).  This lint keeps the chain closed structurally:

every call to a RAW npz loader (``load_pytree`` / ``np.load``) in the
package or entry points, outside the blessed loader module
(``utils/checkpoint.py``, which hosts the verified wrappers), must either

* sit in a function that also calls a checksum-verification primitive
  (``verify_packed_row`` / ``packed_row_checksum`` / ``content_checksum``
  or a ``_verified``/``_verify_rows`` helper built on them), or
* carry an explicit ``# integrity-ok`` waiver comment stating why
  verification does not apply (e.g. the corruption INJECTOR itself, or a
  legacy artifact class with its own fingerprint guard).

Run standalone (exits 1 on findings) or via tier-1
(``tests/test_integrity_lint.py``), so unverified loads cannot regress in.
"""

from __future__ import annotations

import ast
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Same scope policy as scripts/check_atomic_writes.py: the installable
# package plus the entry points; scripts/ and tests/ are out of scope.
SCAN_ROOTS = ("aiyagari_hark_tpu",)
SCAN_FILES = ("bench.py", "reproduce.py")

# The verified wrappers (load_sweep_sidecar etc.) and the raw-loader
# implementation itself live here.
BLESSED = {os.path.join("aiyagari_hark_tpu", "utils", "checkpoint.py")}

WAIVER = "# integrity-ok"

# Raw loaders whose call sites need verification evidence.
RAW_LOADERS = {"load_pytree"}
RAW_LOADER_ATTRS = {("np", "load"), ("numpy", "load")}

# Names whose call inside the same function counts as verification
# evidence: the checksum primitives (utils.fingerprint) and the local
# helpers built directly on them.
VERIFY_NAMES = {"verify_packed_row", "packed_row_checksum",
                "packed_row_checksums", "content_checksum",
                "_verified", "_verify_rows"}


def _call_name(node: ast.Call):
    """Terminal name of a call target: ``f(...)`` -> "f",
    ``a.b.f(...)`` -> "f"; plus the (base, attr) pair for np.load."""
    fn = node.func
    if isinstance(fn, ast.Name):
        return fn.id, None
    if isinstance(fn, ast.Attribute):
        base = fn.value.id if isinstance(fn.value, ast.Name) else None
        return fn.attr, (base, fn.attr)
    return None, None


def _is_raw_load(node: ast.Call) -> bool:
    name, pair = _call_name(node)
    if name in RAW_LOADERS:
        return True
    return pair in RAW_LOADER_ATTRS


def _function_ranges(tree: ast.AST):
    """(start, end, node) for every function, innermost resolvable by
    smallest span."""
    spans = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            spans.append((node.lineno, node.end_lineno, node))
    return spans


def _enclosing(spans, lineno):
    best = None
    for start, end, node in spans:
        if start <= lineno <= end:
            if best is None or (end - start) < (best[1] - best[0]):
                best = (start, end, node)
    return best[2] if best is not None else None


def _has_verify_call(scope: ast.AST) -> bool:
    for node in ast.walk(scope):
        if isinstance(node, ast.Call):
            name, _ = _call_name(node)
            if name in VERIFY_NAMES:
                return True
    return False


def scan_source(src: str, rel: str) -> list:
    """Findings for one file's source text (exposed for fixture tests)."""
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [(rel, e.lineno or 0, f"unparseable: {e.msg}")]
    lines = src.splitlines()
    spans = _function_ranges(tree)
    findings = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call) and _is_raw_load(node)):
            continue
        line = lines[node.lineno - 1] if node.lineno <= len(lines) else ""
        if WAIVER in line:
            continue
        scope = _enclosing(spans, node.lineno)
        if scope is not None and _has_verify_call(scope):
            continue
        where = scope.name if scope is not None else "<module>"
        findings.append(
            (rel, node.lineno,
             f"raw artifact load in {where}() without checksum "
             "verification — call a utils.fingerprint verification "
             "primitive in this function, use a verified loader "
             "(load_sweep_sidecar / the store), or waive with "
             "'# integrity-ok'"))
    return findings


def scan_file(path: str, rel: str) -> list:
    if rel.replace(os.sep, "/") in {b.replace(os.sep, "/")
                                    for b in BLESSED}:
        return []
    with open(path) as fh:
        return scan_source(fh.read(), rel)


def scan_targets(repo: str = REPO) -> list:
    """Every file the lint covers (absolute paths) — exposed so the
    lint's own test can pin coverage (verify/, serve/, resilience)."""
    targets = []
    for root in SCAN_ROOTS:
        for dirpath, _, names in os.walk(os.path.join(repo, root)):
            if "__pycache__" in dirpath:
                continue
            targets += [os.path.join(dirpath, n) for n in sorted(names)
                        if n.endswith(".py")]
    targets += [os.path.join(repo, f) for f in SCAN_FILES]
    return targets


def scan(repo: str = REPO) -> list:
    findings = []
    for path in scan_targets(repo):
        if os.path.exists(path):
            findings += scan_file(path, os.path.relpath(path, repo))
    return findings


def main() -> int:
    findings = scan()
    for rel, lineno, msg in findings:
        print(f"{rel}:{lineno}: {msg}")
    if findings:
        print(f"{len(findings)} unverified artifact load(s); see "
              f"scripts/check_integrity_boundaries.py docstring")
        return 1
    print("integrity-boundary lint: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
