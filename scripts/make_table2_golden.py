#!/usr/bin/env python
"""Generate the committed Table II golden files from the CPU float64 oracle.

Two configurations:
 - ``table2_golden.json``       — the benchmark configuration (a_count=32,
   dist_count=500), the canonical 12-cell table this framework publishes
   against Aiyagari's Table II (regenerate: ~5 min on one CPU core).
 - ``table2_golden_test.json``  — a reduced configuration solved by
   ``tests/test_table2.py`` on every run (~1 min), so any drift in the
   equilibrium pipeline fails the suite deterministically.

Both runs are deterministic (no Monte Carlo anywhere in the bisection path:
Tauchen discretization + EGM + distribution iteration), so the goldens are
exact to solver tolerance, not statistical.
"""

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

TEST_KWARGS = dict(a_count=24, dist_count=150)
FULL_KWARGS = dict(a_count=32, dist_count=500)


def run(kwargs, labor_sd: float = 0.2):
    import jax.numpy as jnp

    from aiyagari_hark_tpu.parallel.sweep import run_table2_sweep
    from aiyagari_hark_tpu.utils.config import SweepConfig

    res = run_table2_sweep(SweepConfig(labor_sd=labor_sd),
                           dtype=jnp.float64, **kwargs)
    return {
        "config": {k: v for k, v in kwargs.items()},
        "dtype": "float64",
        "crra": [float(x) for x in res.crra],
        "labor_ar": [float(x) for x in res.labor_ar],
        "r_star_pct": [float(x) for x in res.r_star_pct],
        "saving_rate_pct": [float(x) for x in res.saving_rate_pct],
        "capital": [float(x) for x in res.capital],
        "table": res.table(),
    }


def main():
    from aiyagari_hark_tpu.utils.backend import select_backend

    select_backend("cpu")
    out_dir = os.path.join(os.path.dirname(__file__), "..", "tests", "data")
    os.makedirs(out_dir, exist_ok=True)
    # panel A (sigma_stationary = 0.2 — the reference's configuration) in
    # both test and benchmark resolutions, plus Aiyagari's panel B
    # (sigma = 0.4), which the reference never ran
    jobs = (("table2_golden_test.json", TEST_KWARGS, 0.2),
            ("table2_golden.json", FULL_KWARGS, 0.2),
            ("table2_sd04_golden.json", FULL_KWARGS, 0.4))
    for name, kwargs, sd in jobs:
        payload = run(kwargs, labor_sd=sd)
        payload["labor_sd"] = sd
        path = os.path.join(out_dir, name)
        with open(path, "w") as f:
            json.dump(payload, f, indent=2)
        print(f"wrote {path}\n{payload['table']}")


if __name__ == "__main__":
    main()
