"""Regenerate ``tests/data/warm_starts.json`` — the committed initial
saving-rule guesses that cut the suite's Krusell-Smith fixtures from
8-10 cold outer iterations to 1-2 warm ones (VERDICT r3 weak-item 5).

Each entry is the COLD-converged ``(intercept, slope)`` of exactly the
config the owning test solves (the configs live in
``tests/fixture_configs.py``, imported by both sides, so registry and
tests cannot drift apart).  Warm starts are initial guesses only: the
solver re-certifies convergence at the unchanged tolerance, and
``AIYAGARI_COLD_START=1`` bypasses the registry entirely.

Run after any change to solver semantics or to the fixture configs:

    python scripts/refresh_warm_starts.py [--only KEY,KEY,...]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.normpath(
    os.path.join(os.path.dirname(os.path.abspath(__file__)), os.pardir)))

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402

os.environ["AIYAGARI_COLD_START"] = "1"   # the refresh must never warm-start

from tests import fixture_configs as fc  # noqa: E402


def _solve(agent, econ, **kwargs):
    from aiyagari_hark_tpu.models.ks_solver import solve_ks_economy
    return solve_ks_economy(agent, econ, **kwargs)


# key -> config builder; the solve kwargs come from fc.SOLVE_KWARGS so
# registry and tests share ONE definition of the program being solved
# (round-4 review: hand-duplicated kwargs here could silently drift)
CASES = {
    "cross_engine": fc.cross_engine_configs,
    "ks98": fc.ks98_configs,
    "diag_parity": fc.diag_parity_configs,
    "diag_pinned": fc.diag_pinned_configs,
    "diag_true_ks": fc.diag_true_ks_configs,
    "dist_method": fc.dist_method_configs,
}

# Facade fixtures drive the reference dict surface instead
FACADE_CASES = {
    "facade_dist": fc.facade_distribution_updates,
}

# Fixtures whose cost is the carried distribution SETTLING, which a rule
# warm start cannot touch (the pinned solves take ~14 windows from any
# intercept): these additionally commit a near-converged checkpoint —
# the cold trajectory frozen two iterations before convergence — that
# the test resumes, running the final iterations and the certification
# for real (a CONVERGED checkpoint would short-circuit through the
# idempotent reload and the test's reproducibility assertion would go
# vacuous).
CHECKPOINT_CASES = ("dist_method", "diag_pinned")


def _registry_solve_with_rolls(key: str, build, kwargs: dict,
                               scratch_dir: str):
    """The ONE cold registry solve, additionally capturing a rolling copy
    of its checkpoint pair each outer iteration, so the near-converged
    freeze needs no second solve and no mid-run assertion (round-4
    review: the freeze re-ran the entire cold solve, and its assert
    could abort main() before the registry was written).

    Timing: the solver writes the pair tagged ``it+1`` at the END of
    iteration ``it``'s body, AFTER the callback fires — so during
    ``callback(rec.iteration == t)`` the pair on disk is tagged ``t``."""
    import shutil

    path = os.path.join(scratch_dir, key + ".npz")

    def roll(rec):
        t = rec.iteration
        if t >= 1 and os.path.exists(path):
            slot = os.path.join(scratch_dir, f"{key}.roll{t % 3}.npz")
            shutil.copy(path, slot)
            if os.path.exists(path + ".dist.npz"):
                shutil.copy(path + ".dist.npz", slot + ".dist.npz")

    agent, econ = build()
    return _solve(agent, econ, checkpoint_path=path, callback=roll,
                  **kwargs)


def _finalize_freeze(key: str, cold_iters: int, scratch_dir: str):
    """Promote the roll tagged ``cold_iters - 2`` into the committed
    checkpoint location, validating it is genuinely unconverged.  Runs
    AFTER the registry JSON is written; failures only cost this key's
    checkpoint (reported, never raised) and never leave a stale pair."""
    import shutil

    from aiyagari_hark_tpu.utils.checkpoint import load_ks_checkpoint

    target = cold_iters - 2
    src = None
    for s in range(3):
        slot = os.path.join(scratch_dir, f"{key}.roll{s}.npz")
        if (os.path.exists(slot)
                and int(load_ks_checkpoint(slot).iteration) == target):
            src = slot
            break
    dst = os.path.join(fc.CHECKPOINTS, key + ".npz")
    for p in (dst, dst + ".dist.npz"):   # never leave a stale/mismatched pair
        if os.path.exists(p):
            os.remove(p)
    if target < 1 or src is None:
        print(f"[warm] {key:14s} no near-converged roll at tag {target} "
              f"(cold={cold_iters}) — checkpoint not frozen")
        return
    if bool(load_ks_checkpoint(src).converged):
        print(f"[warm] {key:14s} roll at tag {target} is already converged "
              f"— a frozen copy would short-circuit the resume; not frozen")
        return
    os.makedirs(fc.CHECKPOINTS, exist_ok=True)
    shutil.copy(src, dst)
    if os.path.exists(src + ".dist.npz"):
        shutil.copy(src + ".dist.npz", dst + ".dist.npz")
    sizes = {os.path.basename(p): os.path.getsize(p)
             for p in (dst, dst + ".dist.npz") if os.path.exists(p)}
    print(f"[warm] {key:14s} froze checkpoint at iteration "
          f"{target}/{cold_iters}: {sizes}")


def _solve_facade(updates: dict, *, AgentCount, aCount, tolerance,
                  **solve_kwargs):
    from aiyagari_hark_tpu import (AiyagariEconomy, AiyagariType,
                                   init_aiyagari_agents,
                                   init_aiyagari_economy)
    econ_dict = init_aiyagari_economy()
    econ_dict.update(updates)
    agent_dict = init_aiyagari_agents()
    agent_dict.update(LaborStatesNo=updates["LaborStatesNo"],
                      AgentCount=AgentCount, aCount=aCount)
    economy = AiyagariEconomy(tolerance=tolerance, **econ_dict)
    economy.verbose = False
    agent = AiyagariType(**agent_dict)
    agent.cycles = 0
    agent.get_economy_data(economy)
    economy.agents = [agent]
    economy.make_Mrkv_history()
    return economy.solve(**solve_kwargs)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--only", help="comma-separated keys (default: all)")
    ap.add_argument("--out", default=fc.REGISTRY)
    args = ap.parse_args(argv)
    keys = set(args.only.split(",")) if args.only else None

    try:
        with open(args.out) as f:
            registry = json.load(f)
    except (OSError, ValueError):
        registry = {}

    import shutil
    import tempfile

    scratch = tempfile.mkdtemp(prefix="warm_rolls_")
    freezes = []
    try:
        for key, build in {**CASES, **FACADE_CASES}.items():
            if keys is not None and key not in keys:
                continue
            t0 = time.time()
            kwargs = fc.SOLVE_KWARGS[key]
            if key in FACADE_CASES:
                sol = _solve_facade(build(), **kwargs)
            elif key in CHECKPOINT_CASES:
                sol = _registry_solve_with_rolls(key, build, kwargs, scratch)
            else:
                agent, econ = build()
                sol = _solve(agent, econ, **kwargs)
            assert sol.converged, f"{key}: cold solve did not converge"
            registry[key] = {
                "intercept": [float(x)
                              for x in np.asarray(sol.afunc.intercept)],
                "slope": [float(x) for x in np.asarray(sol.afunc.slope)],
                "outer_iterations": len(sol.records),
            }
            print(f"[warm] {key:14s} {time.time() - t0:7.1f}s  "
                  f"intercept {registry[key]['intercept']} "
                  f"slope {registry[key]['slope']} "
                  f"({registry[key]['outer_iterations']} cold iters)")
            if key in CHECKPOINT_CASES:
                freezes.append((key, len(sol.records)))

        # registry first: a freeze problem must not discard the solves
        with open(args.out, "w") as f:
            json.dump(registry, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"[warm] wrote {args.out}")

        for key, cold_iters in freezes:
            try:
                _finalize_freeze(key, cold_iters, scratch)
            except Exception as e:   # noqa: BLE001 — freeze is best-effort
                print(f"[warm] {key}: freeze failed "
                      f"({type(e).__name__}: {e}) — checkpoint not frozen")
    finally:
        shutil.rmtree(scratch, ignore_errors=True)


if __name__ == "__main__":
    main()
