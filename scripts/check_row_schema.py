#!/usr/bin/env python
"""Static lint: packed-row layouts are read through a scenario's
``RowSchema``, never via the module-level Aiyagari constants (ISSUE 9).

Before the scenario registry, ``config.PACKED_ROW_FIELDS`` /
``PACKED_ROW_WIDTH`` were imported directly by the sweep engine, the
resume ledger, the solution store, and the certifier — exactly the
coupling that hard-wired the whole run stack to one model family (and
the coupling a second family would silently misparse: a width-7 Huggett
row read through a width-10 constant is column soup, not an error).  The
registry routes every consumer through ``Scenario.schema``; this lint
keeps fresh direct uses from regressing in:

any NAME USE of ``PACKED_ROW_FIELDS`` / ``PACKED_ROW_WIDTH`` (import or
reference) in the package or entry points must be in

* ``utils/config.py`` — the definition site (the canonical Aiyagari
  layout constant itself), or
* ``scenarios/`` — where the Aiyagari ``RowSchema`` is built FROM the
  constant, or
* a line carrying an explicit ``# row-schema-ok`` waiver stating why a
  direct read is correct (e.g. a docstring-generation helper).

Run standalone (exits 1 on findings) or via tier-1
(``tests/test_scenarios.py``).  tests/ are out of scope — pinning the
constant's literal value IS a test's job.
"""

from __future__ import annotations

import ast
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SCAN_ROOTS = ("aiyagari_hark_tpu",)
SCAN_FILES = ("bench.py", "reproduce.py")

BANNED = {"PACKED_ROW_FIELDS", "PACKED_ROW_WIDTH"}
WAIVER = "# row-schema-ok"

# Definition site + the scenario package that wraps it into a RowSchema.
ALLOWED_FILES = {os.path.join("aiyagari_hark_tpu", "utils", "config.py")}
ALLOWED_DIRS = (os.path.join("aiyagari_hark_tpu", "scenarios"),)


def _allowed(rel: str) -> bool:
    rel = rel.replace(os.sep, "/")
    if rel in {a.replace(os.sep, "/") for a in ALLOWED_FILES}:
        return True
    return any(rel.startswith(d.replace(os.sep, "/") + "/")
               for d in ALLOWED_DIRS)


def scan_source(src: str, rel: str) -> list:
    """Findings for one file's source text (exposed for fixture tests)."""
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [(rel, e.lineno or 0, f"unparseable: {e.msg}")]
    lines = src.splitlines()
    findings = []

    def _flag(lineno: int, what: str) -> None:
        line = lines[lineno - 1] if lineno <= len(lines) else ""
        if WAIVER in line:
            return
        findings.append(
            (rel, lineno,
             f"direct use of {what} outside scenarios/ — read the row "
             "layout through the scenario's RowSchema "
             "(scenarios.get_scenario(...).schema), or waive with "
             "'# row-schema-ok'"))

    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            for alias in node.names:
                if alias.name in BANNED:
                    _flag(node.lineno, alias.name)
        elif isinstance(node, ast.Import):
            continue
        elif isinstance(node, ast.Name) and node.id in BANNED:
            _flag(node.lineno, node.id)
        elif (isinstance(node, ast.Attribute)
              and node.attr in BANNED):
            _flag(node.lineno, node.attr)
    return findings


def scan_file(path: str, rel: str) -> list:
    if _allowed(rel):
        return []
    with open(path) as fh:
        return scan_source(fh.read(), rel)


def scan_targets(repo: str = REPO) -> list:
    targets = []
    for root in SCAN_ROOTS:
        for dirpath, _, names in os.walk(os.path.join(repo, root)):
            if "__pycache__" in dirpath:
                continue
            targets += [os.path.join(dirpath, n) for n in sorted(names)
                        if n.endswith(".py")]
    targets += [os.path.join(repo, f) for f in SCAN_FILES]
    return targets


def scan(repo: str = REPO) -> list:
    findings = []
    for path in scan_targets(repo):
        if os.path.exists(path):
            findings += scan_file(path, os.path.relpath(path, repo))
    return findings


def main() -> int:
    findings = scan()
    for rel, lineno, msg in findings:
        print(f"{rel}:{lineno}: {msg}")
    if findings:
        print(f"{len(findings)} direct row-layout use(s); see "
              f"scripts/check_row_schema.py docstring")
        return 1
    print("row-schema lint: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
