"""Recover the SCF Lorenz curve from the reference's committed vector figure.

The reference compares its simulated wealth distribution against the U.S.
Survey of Consumer Finances via HARK's bundled dataset
(``load_SCF_wealth_weights``, ``Aiyagari-HARK.py:303``) and prints a
Euclidean Lorenz distance of 0.9714 (``Aiyagari-HARK.py:332-333``).  That
dataset is not available in this environment (no network, HARK not
vendored) — but the reference's committed
``Figures/wealth_distribution_1.svg`` is a matplotlib *vector* figure whose
path data encodes all three plotted curves at the exact 15-point percentile
grid ``np.linspace(0.01, 0.999, 15)`` (``Aiyagari-HARK.py:312``):

  - ``line2d_13``: the SCF Lorenz curve   (dashed black, ``'--k'``)
  - ``line2d_14``: the reference's simulated Lorenz curve (solid blue)
  - ``line2d_15``: the 45-degree line     (green dash-dot)

The 45-degree line's data coordinates are known exactly (y = x = pctiles),
so it calibrates the affine SVG->data transform on both axes with no
reliance on tick parsing; the residual of that calibration is ~2e-9 data
units, and matplotlib writes 6-decimal SVG coordinates (~4e-6 data-unit
quantization), so the recovered shares are good to ~1e-5.

Verification built in: the Euclidean distance between the two recovered
curves must reproduce the reference's printed golden 0.9714 (we recover
0.97144) — if the figure or the extraction drifted, this script fails.

Output: ``aiyagari_hark_tpu/data/scf_lorenz.csv`` with columns
``pctile,scf_share,ref_sim_share``.

Usage::

    python scripts/extract_scf_lorenz.py [--svg PATH] [--out PATH]
"""

from __future__ import annotations

import argparse
import os
import re

import numpy as np

DEFAULT_SVG = "/root/reference/Figures/wealth_distribution_1.svg"
DEFAULT_OUT = os.path.join(os.path.dirname(__file__), os.pardir,
                           "aiyagari_hark_tpu", "data", "scf_lorenz.csv")
GOLDEN_DISTANCE = 0.9714        # printed by Aiyagari-HARK.py:333


def path_points(svg_text: str, group_id: str) -> np.ndarray:
    """Vertices of the ``<path>`` inside ``<g id=group_id>`` as [N, 2]."""
    m = re.search(r'<g id="%s">(.*?)</g>' % re.escape(group_id),
                  svg_text, re.S)
    if m is None:
        raise ValueError(f"no group {group_id!r} in SVG")
    pts = re.findall(r"[ML] ([0-9.e+-]+) ([0-9.e+-]+)", m.group(1))
    return np.array([[float(x), float(y)] for x, y in pts])


def extract(svg_path: str):
    svg = open(svg_path).read()
    scf = path_points(svg, "line2d_13")
    sim = path_points(svg, "line2d_14")
    diag = path_points(svg, "line2d_15")
    pct = np.linspace(0.01, 0.999, 15)
    if not (scf.shape == sim.shape == diag.shape == (15, 2)):
        raise ValueError("expected 15-vertex curves; figure layout changed?")

    # Calibrate SVG->data affine from the 45-degree line (exact data coords).
    ax = np.polyfit(diag[:, 0], pct, 1)
    ay = np.polyfit(diag[:, 1], pct, 1)
    resid = max(np.abs(np.polyval(ax, diag[:, 0]) - pct).max(),
                np.abs(np.polyval(ay, diag[:, 1]) - pct).max())
    if resid > 1e-6:
        raise ValueError(f"axis calibration residual {resid:.2e} too large")

    for curve in (scf, sim):   # x-vertices must sit on the percentile grid
        if np.abs(np.polyval(ax, curve[:, 0]) - pct).max() > 1e-6:
            raise ValueError("curve x-vertices off the percentile grid")

    scf_share = np.polyval(ay, scf[:, 1])
    sim_share = np.polyval(ay, sim[:, 1])

    dist = float(np.sqrt(np.sum((scf_share - sim_share) ** 2)))
    if abs(dist - GOLDEN_DISTANCE) > 5e-4:
        raise ValueError(
            f"recovered distance {dist:.6f} does not reproduce the "
            f"reference golden {GOLDEN_DISTANCE}")
    return pct, scf_share, sim_share, dist


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--svg", default=DEFAULT_SVG)
    ap.add_argument("--out", default=os.path.normpath(DEFAULT_OUT))
    args = ap.parse_args(argv)

    pct, scf_share, sim_share, dist = extract(args.svg)
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        f.write("# SCF Lorenz curve recovered from the reference's committed "
                "vector figure\n"
                "# (Figures/wealth_distribution_1.svg; see "
                "scripts/extract_scf_lorenz.py for method).\n"
                f"# Recovered SCF-vs-ref-sim distance {dist:.6f} reproduces "
                f"the printed golden {GOLDEN_DISTANCE}.\n"
                "pctile,scf_share,ref_sim_share\n")
        for p, s, r in zip(pct, scf_share, sim_share):
            f.write(f"{p:.10g},{s:.6f},{r:.6f}\n")
    print(f"wrote {args.out}  (recovered distance {dist:.6f}, "
          f"golden {GOLDEN_DISTANCE})")


if __name__ == "__main__":
    main()
