#!/usr/bin/env python
"""Static lint: solver hot paths build grids through the GridPolicy
resolution seam, never by calling the raw builders directly (ISSUE 12).

The grid-compaction layer (DESIGN §5b) only works if every solver-path
model build routes through ``ops.grids.build_asset_grids`` (the ONE
``GridSpec -> concrete grids`` seam): a hot path that calls
``make_asset_grid``/``make_grid_exp_mult`` directly silently pins the
dense reference layout regardless of the requested grid policy — and
worse, produces a model whose grids disagree with the policy every
fingerprint downstream hashed.  This lint bans direct uses of the raw
builders in the solver hot directories (``models/``, ``parallel/``,
``serve/``, ``scenarios/``, ``verify/``):

any CALL of (or ``from``-import naming) ``make_asset_grid`` /
``make_grid_exp_mult`` there must carry an explicit ``# grid-ok`` waiver
on its line stating why the raw builder is correct — e.g. the
KS/portfolio reference-parity paths that deliberately do not ride the
grid policy, or the credit-crunch experiment's per-date grids that must
stay consistent with a model built elsewhere.

``ops/`` is the seam itself and is out of scope, as are tests (pinning
builder behavior IS a test's job).  Run standalone (exits 1 on findings)
or via tier-1 (``tests/test_grid_discipline.py``).
"""

from __future__ import annotations

import ast
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# The solver hot directories: everywhere a model build can sit on a
# sweep/serve/certify path.
SCAN_DIRS = (
    os.path.join("aiyagari_hark_tpu", "models"),
    os.path.join("aiyagari_hark_tpu", "parallel"),
    os.path.join("aiyagari_hark_tpu", "serve"),
    os.path.join("aiyagari_hark_tpu", "scenarios"),
    os.path.join("aiyagari_hark_tpu", "verify"),
)

BANNED = {"make_asset_grid", "make_grid_exp_mult"}
WAIVER = "# grid-ok"


def scan_source(src: str, rel: str) -> list:
    """Findings for one file's source text (exposed for fixture tests)."""
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [(rel, e.lineno or 0, f"unparseable: {e.msg}")]
    lines = src.splitlines()
    findings = []

    def _flag(lineno: int, what: str) -> None:
        line = lines[lineno - 1] if lineno <= len(lines) else ""
        if WAIVER in line:
            return
        findings.append(
            (rel, lineno,
             f"direct {what} in a solver hot path — build grids through "
             "the GridPolicy seam (ops.grids.build_asset_grids / "
             "build_simple_model(grid=...)), or waive with '# grid-ok'"))

    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            for alias in node.names:
                if alias.name in BANNED:
                    _flag(node.lineno, f"import of {alias.name}")
        elif isinstance(node, ast.Call):
            fn = node.func
            name = (fn.id if isinstance(fn, ast.Name)
                    else fn.attr if isinstance(fn, ast.Attribute)
                    else None)
            if name in BANNED:
                _flag(node.lineno, f"call of {name}")
    return findings


def scan_targets(repo: str = REPO) -> list:
    """The files the lint covers, absolute paths — exposed so the lint's
    own test can assert coverage instead of trusting the list silently."""
    targets = []
    for root in SCAN_DIRS:
        base = os.path.join(repo, root)
        for dirpath, _, names in os.walk(base):
            if "__pycache__" in dirpath:
                continue
            targets += [os.path.join(dirpath, n) for n in sorted(names)
                        if n.endswith(".py")]
    return targets


def scan(repo: str = REPO) -> list:
    findings = []
    for path in scan_targets(repo):
        if os.path.exists(path):
            with open(path) as fh:
                findings += scan_source(fh.read(),
                                        os.path.relpath(path, repo))
    return findings


def main() -> int:
    findings = scan()
    for rel, lineno, msg in findings:
        print(f"{rel}:{lineno}: {msg}")
    if findings:
        print(f"{len(findings)} grid-discipline violation(s); see "
              f"scripts/check_grid_discipline.py docstring")
        return 1
    print("grid-discipline lint: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
