#!/usr/bin/env python
"""Static lint: typed failures must leave a journal trail (ISSUE 7).

The observability layer's event journal is only trustworthy if every
lifecycle seam actually emits: a typed error raised without a journal
event is a failure the machine-readable trail never saw — exactly the
"read three artifacts and grep logs" hole the layer closed.  This lint
keeps the event contract closed structurally:

* every CONSTRUCTION of a typed framework error (``TYPED_ERRORS``:
  ``SolverDivergenceError``/``EquilibriumSolveFailed``,
  ``IntegrityError``, ``Interrupted``, ``CertificationFailed``,
  ``DeadlineExceeded``) in the package or entry points — whether raised
  directly or handed to ``Future.set_exception`` — must sit in a
  function that also emits a journal event (a call named ``emit``,
  ``emit_event``, or ``event``), or carry an explicit ``# obs-ok``
  waiver comment stating why no event applies (e.g. the error CLASS
  definitions themselves, a re-wrap of an already-journaled failure);
* every quarantine/retry/evict seam function (``SEAM_DEFS``: the
  store's ``_evict_corrupt`` eviction path, the resilience layer's
  ``retry_transient``) must contain an emit call — these seams recover
  instead of raising, so the error-construction rule cannot see them.

Exception-class DEFINITIONS are exempt automatically (a ``class
DeadlineExceeded`` body constructs nothing).  Run standalone (exits 1
on findings) or via tier-1 (``tests/test_obs_lint.py``), so a seam
added without its event cannot regress in.
"""

from __future__ import annotations

import ast
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Same scope policy as the sibling lints: the installable package plus
# the entry points; scripts/ and tests/ are out of scope.
SCAN_ROOTS = ("aiyagari_hark_tpu",)
SCAN_FILES = ("bench.py", "reproduce.py")

WAIVER = "# obs-ok"

# Typed framework errors whose construction marks a lifecycle seam.
TYPED_ERRORS = {
    "SolverDivergenceError",
    "EquilibriumSolveFailed",
    "IntegrityError",
    "Interrupted",
    "CertificationFailed",
    "DeadlineExceeded",
    # overload family (ISSUE 8): fail-fast admission rejection,
    # priority displacement, breaker fast-fail
    "Overloaded",
    "LoadShed",
    "CircuitOpen",
    # coordination family (ISSUE 18): quorum loss on the replicated CAS
    # — a raise without its QUORUM_LOST trail would make every
    # partition drill's detection ledger unfalsifiable
    "CoordinationUnavailable",
}

# Calls that count as journal-emission evidence in the enclosing
# function: the module-level hook (``obs.runtime.emit_event``), a
# bundle/journal method (``obs.event`` / ``journal.emit``), the
# store's emission wrapper (``_record_eviction`` — itself in SEAM_DEFS,
# so its own emit cannot silently disappear), and the durable/replicated
# CAS backends' wrapper (``_emit``, ISSUE 18 — routes to the attached
# obs bundle or the module hook; its body calls ``event``/``emit_event``
# directly, so the lint still sees through it).
EMIT_NAMES = {"emit", "emit_event", "event", "_record_eviction", "_emit"}

# Recovering seams (no error escapes, so the construction rule cannot
# see them) that must emit anyway: quarantine/retry/evict sites.  The
# sweep's quarantine ladder is inline in ``_run_sweep_impl`` —
# listed here so stripping its QUARANTINE event is a lint failure too.
# ISSUE 10 additions: the flight-recorder dump site (must journal
# FLIGHT_RECORD_DUMP next to the artifact it writes) and the
# bench-regression sentinel's grading loop (must journal
# REGRESSION_FLAGGED for every REGRESSED finding).
# ISSUE 16 additions: the store's lease-backend degrade path
# (``_backend_fault`` must journal LEASE_BACKEND_FAULT — it recovers
# with a fail-safe default instead of raising) and the chaos agent's
# fault-firing site (``fire`` must journal FLEET_CHAOS_INJECT — the
# detection ledger's injected side is only falsifiable if every actual
# firing leaves a typed trail).
# ISSUE 17 additions: the store's cell-index rebuild site
# (``_index_rebuilt`` must journal INDEX_REBUILD — a rebuild is a
# recovery/maintenance action, nothing raises) and the service's
# surrogate-escalation seam (``_surrogate_escalate`` must journal
# SURROGATE_ESCALATED — the query recovers by falling through to a real
# solve, so the construction rule cannot see it).
# ISSUE 18 additions — durability/DR seams that recover instead of
# raising: the disk-fault injector's firing site (``_fire_disk_fault``
# must journal DISK_FAULT — the drills' injected side), WAL replay and
# snapshot compaction (``_recover_state`` → WAL_REPLAY, ``_compact`` →
# SNAPSHOT_COMPACT), the replicated backend's quorum-loss and
# convergence seams (``_quorum_lost`` → QUORUM_LOST, ``_read_repair`` /
# ``_resync_replica`` → REPLICA_RESYNC), and the store's memory-only
# degrade path (``_degrade_memory_only`` → STORE_DEGRADED).
SEAM_DEFS = {"_evict_corrupt", "_record_eviction", "retry_transient",
             "_run_sweep_impl", "dump_flight", "evaluate_history",
             "_backend_fault", "fire",
             "_index_rebuilt", "_surrogate_escalate",
             "_fire_disk_fault", "_recover_state", "_compact",
             "_quorum_lost", "_read_repair", "_resync_replica",
             "_degrade_memory_only"}


def _call_name(node: ast.Call):
    """Terminal name of a call target: ``f(...)`` -> "f",
    ``a.b.f(...)`` -> "f"."""
    fn = node.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return None


def _function_ranges(tree: ast.AST):
    spans = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            spans.append((node.lineno, node.end_lineno, node))
    return spans


def _enclosing(spans, lineno):
    best = None
    for start, end, node in spans:
        if start <= lineno <= end:
            if best is None or (end - start) < (best[1] - best[0]):
                best = (start, end, node)
    return best[2] if best is not None else None


def _class_def_lines(tree: ast.AST) -> set:
    """Line ranges of class bodies that DEFINE a typed error (or a
    subclass thereof, by base name) — their ``super().__init__`` bodies
    are the error's own plumbing, not an emission seam."""
    lines = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        names = {node.name} | {
            b.id for b in node.bases if isinstance(b, ast.Name)} | {
            b.attr for b in node.bases if isinstance(b, ast.Attribute)}
        if names & TYPED_ERRORS:
            lines.update(range(node.lineno, (node.end_lineno or
                                             node.lineno) + 1))
    return lines


def _has_emit_call(scope: ast.AST) -> bool:
    for node in ast.walk(scope):
        if isinstance(node, ast.Call) and _call_name(node) in EMIT_NAMES:
            return True
    return False


def scan_source(src: str, rel: str) -> list:
    """Findings for one file's source text (exposed for fixture tests)."""
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [(rel, e.lineno or 0, f"unparseable: {e.msg}")]
    lines = src.splitlines()
    spans = _function_ranges(tree)
    exempt = _class_def_lines(tree)
    findings = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and _call_name(node) in TYPED_ERRORS):
            continue
        if node.lineno in exempt:
            continue
        line = lines[node.lineno - 1] if node.lineno <= len(lines) else ""
        if WAIVER in line:
            continue
        scope = _enclosing(spans, node.lineno)
        if scope is not None and _has_emit_call(scope):
            continue
        where = scope.name if scope is not None else "<module>"
        findings.append(
            (rel, node.lineno,
             f"typed error {_call_name(node)} constructed in {where}() "
             "without a journal event — emit an obs event "
             "(obs.event / emit_event) in this function, or waive with "
             "'# obs-ok'"))
    # recovering seams: the named functions must emit
    for start, _end, fnode in spans:
        if fnode.name not in SEAM_DEFS:
            continue
        def_line = lines[start - 1] if start <= len(lines) else ""
        if WAIVER in def_line:
            continue
        if not _has_emit_call(fnode):
            findings.append(
                (rel, start,
                 f"seam function {fnode.name}() (quarantine/retry/evict "
                 "site) emits no journal event — add an obs event, or "
                 "waive the def line with '# obs-ok'"))
    return findings


def scan_file(path: str, rel: str) -> list:
    with open(path) as fh:
        return scan_source(fh.read(), rel)


def scan_targets(repo: str = REPO) -> list:
    """Every file the lint covers (absolute paths) — exposed so the
    lint's own test can pin coverage of the instrumented seams."""
    targets = []
    for root in SCAN_ROOTS:
        for dirpath, _, names in os.walk(os.path.join(repo, root)):
            if "__pycache__" in dirpath:
                continue
            targets += [os.path.join(dirpath, n) for n in sorted(names)
                        if n.endswith(".py")]
    targets += [os.path.join(repo, f) for f in SCAN_FILES]
    return targets


def scan(repo: str = REPO) -> list:
    findings = []
    for path in scan_targets(repo):
        if os.path.exists(path):
            findings += scan_file(path, os.path.relpath(path, repo))
    return findings


def main() -> int:
    findings = scan()
    for rel, lineno, msg in findings:
        print(f"{rel}:{lineno}: {msg}")
    if findings:
        print(f"{len(findings)} unjournaled lifecycle seam(s); see "
              f"scripts/check_obs_events.py docstring")
        return 1
    print("obs-event lint: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
