#!/usr/bin/env python
"""Static lint: artifact writes must be crash-consistent (ISSUE 3).

A bare ``open(path, "w")`` + ``json.dump``/``write`` truncates the target
before writing, so a kill mid-write leaves a corrupt artifact — the failure
mode that can poison ``bench_tpu_last.json`` (a later CPU fallback embeds it
as evidence) or strand a half-written ``results.json``.  The blessed
writers — ``utils.checkpoint.save_pytree`` / ``atomic_write_json`` /
``atomic_write_text`` — all go tmp + ``os.replace``.

This lint greps the package and the entry points (``bench.py``,
``reproduce.py``) for write-mode ``open(...)`` calls (and direct
``np.savez`` to a path) outside ``utils/checkpoint.py``; a hit is a
finding unless the line carries an explicit ``# atomic-ok`` waiver (for
the rare write that is genuinely append-only or otherwise crash-safe).
Run standalone (exits 1 on findings) or via tier-1
(``tests/test_checkpoint_tools.py``), so non-crash-consistent writes
cannot regress in.
"""

from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Scope: the installable package plus the two entry points.  scripts/ and
# tests/ are out of scope — they write developer-local files whose loss is
# a re-run, not a poisoned committed artifact.  The package walk is
# recursive, so every subpackage — ``serve/``, whose on-disk
# solution-store tier MUST go through the blessed atomic writers (a torn
# store entry would be served as a cached equilibrium), and ``verify/``
# (ISSUE 6), whose corruption INJECTORS deliberately write raw bytes and
# therefore carry explicit ``# atomic-ok`` waivers — is in scope
# automatically; ``tests/test_checkpoint_tools.py`` pins that coverage.
SCAN_ROOTS = ("aiyagari_hark_tpu",)
SCAN_FILES = ("bench.py", "reproduce.py")

# The atomic writers themselves (tmp + os.replace) live here.
BLESSED = {os.path.join("aiyagari_hark_tpu", "utils", "checkpoint.py")}

WAIVER = "# atomic-ok"

# open(..., "w"/"a") / open(..., mode=...) in any spelling that truncates
# OR appends: w, wt, wb, w+, a, ab, a+ ... — reads ("r") stay out of
# scope.  Appends joined the ban with ISSUE 7: a buffered append handle
# flushes a long record in chunks, so a SIGTERM between chunks tears
# mid-line — the blessed ``utils.checkpoint.append_jsonl`` (one
# ``os.write`` per complete line on an O_APPEND descriptor) is the
# crash-safe spelling.
# The path expression may contain arbitrary nesting (os.path.join(...),
# self.path(), f-strings), so the lazy skip must admit parens — anchoring
# on the mode LITERAL keeps it precise: a quote, 'w'/'a', optional b/t/+,
# closing quote cannot appear inside a normal path literal ("w.txt"
# fails the closing-quote-after-mode-chars requirement).
_OPEN_W = re.compile(
    r"""\bopen\s*\(                  # open(
        [^#]*?                       # path expression (parens allowed)
        (?:mode\s*=\s*)?             # optional mode=
        (?P<q>['"])[wa][bt+]*(?P=q)  # a truncating/appending mode literal
    """, re.VERBOSE)
# np.savez/savez_compressed called on a PATH (a string/variable, not the
# blessed writers' file-descriptor handle f).
_SAVEZ = re.compile(r"\bnp\.savez(?:_compressed)?\s*\(\s*(?!f\b)")
# Raw writable descriptors (ISSUE 15): ``os.open`` with a write/create
# flag bypasses every blessed writer — exactly how an unblessed lease or
# publish path would sneak in a non-crash-consistent write.  The blessed
# spellings live in utils/checkpoint.py (``append_jsonl``'s O_APPEND
# one-write-per-line, ``acquire_lease``'s O_CREAT|O_EXCL election);
# anything else needs a ``# atomic-ok`` waiver stating why it is safe.
_OS_OPEN_W = re.compile(
    r"\bos\.open\s*\([^)]*\bO_(?:WRONLY|RDWR|CREAT|APPEND|TRUNC)\b")
# Raw fsync (ISSUE 18): durability is the blessed writers' job — their
# ``durable=True`` path fsyncs the file AND its parent directory in the
# one order that survives a crash (data, rename, directory).  A raw
# ``os.fsync`` elsewhere is either redundant or, worse, a half-durable
# write that LOOKS safe in review; route it through the writers or waive
# with '# atomic-ok' stating why the bare sync is correct.
_OS_FSYNC = re.compile(r"\bos\.fsync\s*\(")


def scan_file(path: str, rel: str) -> list:
    findings = []
    if rel.replace(os.sep, "/") in {b.replace(os.sep, "/")
                                    for b in BLESSED}:
        return findings
    with open(path) as fh:
        for lineno, line in enumerate(fh, start=1):
            if WAIVER in line:
                continue
            if _OPEN_W.search(line):
                findings.append(
                    (rel, lineno,
                     "bare write/append-mode open() — use "
                     "utils.checkpoint.atomic_write_json/_text, "
                     "save_pytree, or append_jsonl, or waive with "
                     "'# atomic-ok'"))
            elif _SAVEZ.search(line):
                findings.append(
                    (rel, lineno,
                     "np.savez to a path — use "
                     "utils.checkpoint.save_pytree (atomic), or waive "
                     "with '# atomic-ok'"))
            elif _OS_OPEN_W.search(line):
                findings.append(
                    (rel, lineno,
                     "raw writable os.open — use the blessed "
                     "utils.checkpoint writers (append_jsonl, "
                     "acquire_lease, atomic_write_*), or waive with "
                     "'# atomic-ok'"))
            elif _OS_FSYNC.search(line):
                findings.append(
                    (rel, lineno,
                     "raw os.fsync — pass durable=True to the blessed "
                     "utils.checkpoint writers (they sync file AND "
                     "parent directory in crash-safe order), or waive "
                     "with '# atomic-ok'"))
    return findings


def scan_targets(repo: str = REPO) -> list:
    """Every file the lint covers, as absolute paths — exposed so the
    lint's own test can assert coverage (e.g. that ``serve/`` is in
    scope) instead of trusting the walk silently."""
    targets = []
    for root in SCAN_ROOTS:
        for dirpath, _, names in os.walk(os.path.join(repo, root)):
            if "__pycache__" in dirpath:
                continue
            targets += [os.path.join(dirpath, n) for n in sorted(names)
                        if n.endswith(".py")]
    targets += [os.path.join(repo, f) for f in SCAN_FILES]
    return targets


def scan(repo: str = REPO) -> list:
    """All findings as (relpath, lineno, message) triples."""
    findings = []
    for path in scan_targets(repo):
        if os.path.exists(path):
            findings += scan_file(path, os.path.relpath(path, repo))
    return findings


def main() -> int:
    findings = scan()
    for rel, lineno, msg in findings:
        print(f"{rel}:{lineno}: {msg}")
    if findings:
        print(f"{len(findings)} non-crash-consistent artifact write(s); "
              f"see scripts/check_atomic_writes.py docstring")
        return 1
    print("atomic-write lint: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
