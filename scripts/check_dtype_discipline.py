#!/usr/bin/env python
"""Static lint: hot-loop dtype/precision discipline (ISSUE 5).

The mixed-precision ladder (DESIGN §5) only works if every matmul in the
hot loops states its accumulation dtype explicitly and no hot-loop module
hard-codes a compute dtype.  Two violation classes, scoped to the modules
whose inner loops the ladder runs (``HOT_MODULES``):

1. **Bare matmul** — a ``jnp.matmul``/``jnp.dot``/``jnp.einsum``/
   ``jnp.tensordot`` call (or a ``lax.dot_general`` — the spelling the
   ISSUE 13 tiled contraction and anything else hand-lowered uses)
   without ``preferred_element_type=``, or the infix ``@`` operator
   (which cannot carry one at all).  On TPU a matmul without a pinned
   accumulation dtype silently accumulates at whatever the precision
   mode implies — exactly the drift the descent phase's
   ``precision=DEFAULT`` + ``preferred_element_type`` pairing exists to
   control (and the Pallas guide's standing MXU rule).  The rule covers
   ``ops/pallas_kernels.py`` — matmuls INSIDE kernel bodies accumulate
   on the MXU under exactly the same contract (bare accumulation in a
   kernel is invisible to the XLA-level lint everywhere else).
2. **Hard-coded ``jnp.float64``** — a compute dtype literal in a hot
   module pins work to the reference dtype regardless of the model dtype
   or the ladder policy.  Dtypes must flow from the model/config.
3. **Hard-coded ``jnp.bfloat16``** (ISSUE 13) — the bf16 descent rung
   is opt-in, TPU-gated, and escalation-protected at its definition
   sites (``models.household``: the rung seams carry waivers); a bare
   bf16 literal anywhere else in a hot module would smuggle the narrow
   dtype past the ``KernelPolicy``/``PrecisionPolicy`` ladder contract
   (no coarse-tolerance floor, no escalation, no TPU gate).

A hit is a finding unless its line carries an explicit ``# dtype-ok``
waiver (for dtype *dispatch* like ``dtype == jnp.float64``, which tests a
dtype rather than imposing one, and for the bf16 rung's definition
sites).  Run standalone (exits 1 on findings) or via tier-1
(``tests/test_dtype_discipline.py``), next to ``check_atomic_writes.py``.
"""

from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# The hot-loop modules: the two fixed-point implementations, the kernels,
# and the bisection equilibrium that threads them.
HOT_MODULES = (
    os.path.join("aiyagari_hark_tpu", "models", "household.py"),
    os.path.join("aiyagari_hark_tpu", "models", "equilibrium.py"),
    os.path.join("aiyagari_hark_tpu", "ops", "markov.py"),
    os.path.join("aiyagari_hark_tpu", "ops", "pallas_kernels.py"),
)

WAIVER = "# dtype-ok"

_MATMUL_CALL = re.compile(
    r"\b(?:jnp\.(matmul|dot|einsum|tensordot)|(?:jax\.)?lax\.(dot_general))"
    r"\s*\(")
# infix matrix multiply: ' @ ' between expressions.  Decorators are
# line-initial '@name' with no preceding expression, so requiring a
# non-space character before ' @ ' on the same line excludes them.
_INFIX_AT = re.compile(r"\S\s+@\s+\S")
_F64_LITERAL = re.compile(r"\bjnp\.float64\b")
_BF16_LITERAL = re.compile(r"\bjnp\.bfloat16\b")


_TRIPLE_STRING = re.compile(r"('''|\"\"\")(.*?)(\1)", re.DOTALL)


def _blank_strings(src: str) -> str:
    """Triple-quoted strings (docstrings) blanked out, newlines kept, so
    the line-based scans cannot trip on prose examples like ``S @ d``."""
    def blank(m):
        return m.group(1) + re.sub(r"[^\n]", " ", m.group(2)) + m.group(3)
    return _TRIPLE_STRING.sub(blank, src)


def _call_span(src: str, open_paren: int) -> str:
    """The argument text of a call whose '(' sits at ``open_paren``
    (balanced-paren scan, so multi-line calls are covered)."""
    depth = 0
    for i in range(open_paren, len(src)):
        if src[i] == "(":
            depth += 1
        elif src[i] == ")":
            depth -= 1
            if depth == 0:
                return src[open_paren:i + 1]
    return src[open_paren:]


def scan_source(src: str, rel: str) -> list:
    """All findings in one module's source, as (rel, lineno, message)."""
    findings = []
    src = _blank_strings(src)
    lines = src.splitlines()

    for m in _MATMUL_CALL.finditer(src):
        lineno = src.count("\n", 0, m.start()) + 1
        if WAIVER in lines[lineno - 1]:
            continue
        call = _call_span(src, m.end() - 1)
        if "preferred_element_type" not in call:
            name = (f"jnp.{m.group(1)}" if m.group(1)
                    else f"lax.{m.group(2)}")
            findings.append(
                (rel, lineno,
                 f"{name} without preferred_element_type= — pin "
                 "the accumulation dtype (descent ladder contract, DESIGN "
                 "§5; inside kernel bodies too, DESIGN §4c), or waive "
                 "with '# dtype-ok'"))

    for lineno, line in enumerate(lines, start=1):
        if WAIVER in line:
            continue
        code = line.split("#", 1)[0]
        if _INFIX_AT.search(code):
            findings.append(
                (rel, lineno,
                 "infix '@' matmul cannot carry preferred_element_type — "
                 "use jnp.matmul(..., preferred_element_type=...), or "
                 "waive with '# dtype-ok'"))
        if _F64_LITERAL.search(code):
            findings.append(
                (rel, lineno,
                 "hard-coded jnp.float64 in a hot-loop module — dtypes "
                 "flow from the model/config (precision policy, DESIGN "
                 "§5), or waive with '# dtype-ok'"))
        if _BF16_LITERAL.search(code):
            findings.append(
                (rel, lineno,
                 "hard-coded jnp.bfloat16 outside the bf16 descent "
                 "rung's waived definition sites — the narrow dtype must "
                 "ride the KernelPolicy ladder (coarse tolerance floor, "
                 "escalation, TPU gate — DESIGN §4c), or waive with "
                 "'# dtype-ok'"))
    return findings


def scan_targets(repo: str = REPO) -> list:
    """The files the lint covers, absolute paths — exposed so the lint's
    own test can assert coverage instead of trusting the list silently."""
    return [os.path.join(repo, rel) for rel in HOT_MODULES]


def scan(repo: str = REPO) -> list:
    findings = []
    for path in scan_targets(repo):
        if os.path.exists(path):
            with open(path) as fh:
                findings += scan_source(fh.read(),
                                        os.path.relpath(path, repo))
    return findings


def main() -> int:
    findings = scan()
    for rel, lineno, msg in findings:
        print(f"{rel}:{lineno}: {msg}")
    if findings:
        print(f"{len(findings)} dtype-discipline violation(s); see "
              f"scripts/check_dtype_discipline.py docstring")
        return 1
    print("dtype-discipline lint: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
