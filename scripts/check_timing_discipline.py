#!/usr/bin/env python
"""Static lint: hot-module timing discipline (ISSUE 10).

The performance-observability tier (DESIGN §10b) is only trustworthy if
every measured wall in the hot modules flows through ONE clock and one
exception-safe idiom: a ``Tracer`` span, ``utils.timing.PhaseTimer``, or
``utils.timing.stopwatch()``/``Stopwatch``.  An ad-hoc
``t0 = time.perf_counter(); ...; t1 - t0`` pair is exactly how the
pre-ISSUE-7 wall-clock story fractured into four disconnected encodings
— and a bare ``time.time()`` wall is additionally wrong under clock
adjustment.  One violation class, scoped to the modules whose seams the
obs layer instruments (``HOT_DIRS``):

* a CALL to ``time.perf_counter()``, ``time.time()``, or — since the
  multi-chip launch sites landed (ISSUE 11 satellite) —
  ``time.monotonic()`` (an attribute reference like
  ``clock=time.monotonic`` — injectable-clock plumbing — does not
  match, by design: passing the clock is the pattern we want; a bare
  ``monotonic()`` CALL next to a launch is an ad-hoc wall that belongs
  in a Tracer span or ``stopwatch()``).

A hit is a finding unless its line carries an explicit ``# timing-ok``
waiver stating why a raw clock read is required (e.g. a module that IS
the timing substrate, or the batcher's real-time wait backstops that
exist precisely to bound a stalled injected clock).  Docstrings are blanked before scanning so prose
examples cannot trip it.  Run standalone (exits 1 on findings) or via
tier-1 (``tests/test_timing_lint.py``), next to the sibling
``check_dtype_discipline.py`` / ``check_atomic_writes.py`` lints.
"""

from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# The hot-module scope: every package dir whose seams the obs layer
# instruments.  utils/ is deliberately OUT of scope — utils/timing.py is
# the blessed substrate the rule routes callers through.
HOT_DIRS = (
    os.path.join("aiyagari_hark_tpu", "parallel"),
    os.path.join("aiyagari_hark_tpu", "serve"),
    os.path.join("aiyagari_hark_tpu", "obs"),
    os.path.join("aiyagari_hark_tpu", "models"),
)

WAIVER = "# timing-ok"

_CLOCK_CALL = re.compile(r"\btime\.(perf_counter|time|monotonic)\s*\(")

_TRIPLE_STRING = re.compile(r"('''|\"\"\")(.*?)(\1)", re.DOTALL)


def _blank_strings(src: str) -> str:
    """Triple-quoted strings (docstrings) blanked out, newlines kept, so
    the line scan cannot trip on prose like ``time.time() pairs``."""
    def blank(m):
        return m.group(1) + re.sub(r"[^\n]", " ", m.group(2)) + m.group(3)
    return _TRIPLE_STRING.sub(blank, src)


def scan_source(src: str, rel: str) -> list:
    """All findings in one module's source, as (rel, lineno, message)."""
    findings = []
    src = _blank_strings(src)
    lines = src.splitlines()
    for m in _CLOCK_CALL.finditer(src):
        lineno = src.count("\n", 0, m.start()) + 1
        line = lines[lineno - 1] if lineno <= len(lines) else ""
        if WAIVER in line:
            continue
        if line.split("#", 1)[0].strip() == "":
            continue        # match sits in a line comment
        findings.append(
            (rel, lineno,
             f"ad-hoc time.{m.group(1)}() in a hot module — route the "
             "measurement through a Tracer span, utils.timing.PhaseTimer, "
             "or utils.timing.stopwatch()/Stopwatch (one clock, "
             "exception-safe; DESIGN §10b), or waive with '# timing-ok'"))
    return findings


def scan_targets(repo: str = REPO) -> list:
    """Every file the lint covers (absolute paths) — exposed so the
    lint's own test can pin coverage instead of trusting the walk."""
    targets = []
    for rel_dir in HOT_DIRS:
        for dirpath, _, names in os.walk(os.path.join(repo, rel_dir)):
            if "__pycache__" in dirpath:
                continue
            targets += [os.path.join(dirpath, n) for n in sorted(names)
                        if n.endswith(".py")]
    return targets


def scan(repo: str = REPO) -> list:
    findings = []
    for path in scan_targets(repo):
        if os.path.exists(path):
            with open(path) as fh:
                findings += scan_source(fh.read(),
                                        os.path.relpath(path, repo))
    return findings


def main() -> int:
    findings = scan()
    for rel, lineno, msg in findings:
        print(f"{rel}:{lineno}: {msg}")
    if findings:
        print(f"{len(findings)} timing-discipline violation(s); see "
              f"scripts/check_timing_discipline.py docstring")
        return 1
    print("timing-discipline lint: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
