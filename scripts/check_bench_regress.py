#!/usr/bin/env python
"""Tier-1 bench-regression sentinel: grade the committed BENCH history
(ISSUE 10, ``obs.regress``).

Loads every committed ``BENCH_r*.json``, grades the newest record
against the robust baseline of the earlier rounds (median of the last K
with an IQR noise band, per-metric direction of goodness), and exits:

* 0 — no REGRESSED finding (NOISE findings are printed but do not
  fail: outside the band yet under the 10% actionability line);
* 1 — at least one REGRESSED finding, printed worst-first with its
  baseline, band, and relative move — a committed bench number moved
  >= 10% in the bad direction past everything history contains.

Run standalone or via tier-1 (``tests/test_regress.py`` calls ``scan``
and additionally drills the injected-slowdown path: a synthetic 20%
slowdown appended to the history MUST flag REGRESSED).  ``--json``
prints the full report as one JSON object for tooling.
"""

from __future__ import annotations

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from aiyagari_hark_tpu.obs.regress import (  # noqa: E402
    NOISE,
    REGRESSED,
    evaluate_history,
    load_bench_history,
)


def scan(repo: str = REPO, window: int = 5):
    """The sentinel report for the committed history (exposed so tier-1
    tests pin clean-on-committed and flag-on-injection behavior)."""
    history = load_bench_history(repo)
    return evaluate_history(history, window=window)


def _fmt(f) -> str:
    base = (f"  {f.severity_name:9s} {f.metric} = {f.value:g}"
            if f.value is not None else
            f"  {f.severity_name:9s} {f.metric}")
    if f.baseline is not None and f.band is not None:
        base += (f" (baseline {f.baseline:g} ± {f.band:g}"
                 + (f", moved {100.0 * f.delta_frac:+.1f}% "
                    f"{'worse' if f.delta_frac > 0 else 'better'}"
                    if f.delta_frac is not None else "")
                 + f", direction-of-goodness {f.direction})")
    elif f.note:
        base += f" ({f.note})"
    return base


def main(argv=None) -> int:
    import argparse
    import json

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--json", action="store_true",
                    help="print the full report as JSON")
    ap.add_argument("--window", type=int, default=5,
                    help="baseline window: last K prior rounds "
                         "(default 5)")
    args = ap.parse_args(argv)

    report = scan(window=args.window)
    if args.json:
        import dataclasses

        print(json.dumps({
            "summary": report.summary(),
            "worst": report.worst,
            "latest_round": report.latest_round,
            "baseline_rounds": report.baseline_rounds,
            "unknown_fields": report.unknown_fields,
            "findings": [dataclasses.asdict(f) for f in report.findings],
        }))
    else:
        print(report.summary())
        for f in report.findings:
            if f.severity >= NOISE:
                print(_fmt(f))
        for metric in report.unknown_fields:
            print(f"  UNGRADED  {metric} (no direction of goodness — "
                  "add to obs.regress.DIRECTION_EXPLICIT)")
    return 1 if report.worst >= REGRESSED else 0


if __name__ == "__main__":
    sys.exit(main())
