"""aiyagari_hark_tpu — a TPU-native (JAX/XLA) heterogeneous-agent macro
framework with the capabilities of the Aiyagari-HARK reference replication.

Layers (mirroring SURVEY.md §1, rebuilt TPU-first):
  * ``ops``       — numerics core (grids, Tauchen, CRRA, batched interp, OLS)
  * ``models``    — EGM household solver, simulators, equilibrium loops
  * ``parallel``  — device meshes, calibration sweeps, sharded agent panels
  * ``scenarios`` — pluggable model families riding the whole run stack
  * ``serve``     — micro-batched equilibrium query engine + solution store
  * ``verify``    — a posteriori certification, checksum chain, SDC defense
  * ``obs``       — run-scoped tracing spans, metrics registry, event journal
  * ``utils``     — typed configs, checkpointing, logging, statistics
  * ``facade``    — notebook-compatible AiyagariType / AiyagariEconomy classes
"""

__version__ = "0.1.0"

from .facade import (  # noqa: F401
    AggregateSavingRule,
    AiyagariEconomy,
    AiyagariType,
    init_aiyagari_agents,
    init_aiyagari_economy,
)
from .models.equilibrium import (  # noqa: F401
    solve_bisection_equilibrium,
    solve_calibration,
    solve_calibration_lean,
)
from .models.calibrate import (  # noqa: F401
    CalibrationResult,
    LorenzFit,
    calibrate_beta_spread,
    calibrate_discount_factor,
    calibrate_labor_weight,
    calibrate_spread_to_lorenz,
)
from .models.epstein_zin import (  # noqa: F401
    EZEquilibrium,
    EZPolicy,
    aggregate_ez_welfare,
    solve_ez_equilibrium,
    solve_ez_household,
)
from .models.fiscal import (  # noqa: F401
    FiscalEquilibrium,
    TaxSweepResult,
    build_fiscal_model,
    progressive_labor_levels,
    redistributive_labor_levels,
    solve_fiscal_equilibrium,
    tax_rate_sweep,
)
from .models.heterogeneity import (  # noqa: F401
    HeterogeneousEquilibrium,
    population_distribution,
    solve_heterogeneous_equilibrium,
    uniform_beta_types,
)
from .models.huggett import (  # noqa: F401
    CreditCrunchResult,
    HuggettEquilibrium,
    solve_credit_crunch,
    solve_huggett_equilibrium,
)
from .models.diagnostics import DenHaanStats, den_haan_forecast  # noqa: F401
from .models.labor import (  # noqa: F401
    LaborEquilibrium,
    LaborTransitionResult,
    build_labor_model,
    solve_labor_equilibrium,
    solve_labor_household,
    solve_labor_transition,
)
from .models.lifecycle import (  # noqa: F401
    simulate_cohort,
    solve_lifecycle,
)
from .models.portfolio import (  # noqa: F401
    build_portfolio_model,
    solve_portfolio_equilibrium,
    solve_portfolio_household,
)
from .models.jacobian import (  # noqa: F401
    BusinessCycleMoments,
    HouseholdJacobians,
    LaborSequenceJacobians,
    LinearIRF,
    SequenceJacobians,
    ShockFit,
    business_cycle_moments,
    fit_shock_process,
    household_jacobians,
    innovation_irf,
    labor_business_cycle_moments,
    labor_sequence_jacobians,
    linear_impulse_response,
    sequence_jacobians,
    simulate_linear,
)
from .models.transition import (  # noqa: F401
    TransitionResult,
    TransitionWelfare,
    household_path_response,
    path_policies,
    solve_transition,
    transition_welfare,
)
from .models.value import (  # noqa: F401
    aggregate_welfare,
    consumption_equivalent,
    marginal_value_at,
    policy_value,
    value_at,
)
from .parallel.sweep import SweepResult, run_table2_sweep  # noqa: F401
from .serve import (  # noqa: F401
    EquilibriumQuery,
    EquilibriumService,
    EquilibriumSolveFailed,
    ServedResult,
    SolutionStore,
    make_query,
)
from .solver_health import (  # noqa: F401
    CONVERGED,
    MAX_ITER,
    NONFINITE,
    STALLED,
    SolverDivergenceError,
    combine_status,
    inject_fault,
    is_failure,
    status_name,
)
from .utils.backend import BackendInfo, select_backend  # noqa: F401
from .utils.config import AgentConfig, EconomyConfig, SweepConfig  # noqa: F401
