"""Equilibrium serving subsystem (DESIGN §8): micro-batched query engine
over a content-addressed solution store with nearest-neighbor warm starts.

The batch sweep (``parallel.sweep``) answers "solve this lattice once,
fast"; this package answers "serve equilibrium queries interactively" —
exact hits from the store in microseconds, near hits warm-started from
the nearest cached neighbor through the verified ``dyadic_bracket``
mechanism, cold misses micro-batched onto a fixed ladder of executable
shapes shared with the sweep's compiled cell solver.
"""

from .batcher import (  # noqa: F401
    MicroBatcher,
    ServeQueueFull,
    default_ladder,
    shard_ladder,
)
from .cellindex import (  # noqa: F401
    CellIndex,
    linear_nearest_k,
)
from .chaos import (  # noqa: F401
    ChaosAgent,
    ChaosPlan,
    DrillError,
    run_drills,
)
from .fleet import (  # noqa: F401
    FleetClient,
    FleetFront,
    FleetHTTPError,
    HedgePolicy,
    RetryPolicy,
    error_to_json,
    result_to_json,
    worker_main,
)
from .lease import (  # noqa: F401
    CASServer,
    LeaseBackend,
    LoopbackCASBackend,
    MemoryCASBackend,
    SharedDirBackend,
    make_backend,
)
from .loadgen import (  # noqa: F401
    Arrival,
    FleetCtl,
    FleetReport,
    FleetSpec,
    LoadReport,
    LoadSpec,
    ManualClock,
    generate_arrivals,
    generate_fleet_arrivals,
    run_fleet_load,
    run_load,
)
from .metrics import ServeMetrics  # noqa: F401
from .overload import (  # noqa: F401
    AdmissionPolicy,
    CircuitBreaker,
    Priority,
    predicted_work,
    priority_name,
)
from .service import (  # noqa: F401
    CertificationFailed,
    CircuitOpen,
    DeadlineExceeded,
    EquilibriumQuery,
    EquilibriumService,
    EquilibriumSolveFailed,
    LoadShed,
    Overloaded,
    ServedResult,
    ServeError,
    ServiceClosed,
    make_query,
)
from .store import (  # noqa: F401
    UNCERTIFIED,
    Donation,
    SolutionStore,
    StoredSolution,
    make_solution,
)
from .surrogate import (  # noqa: F401
    SurrogateFit,
    SurrogatePolicy,
    fit_surrogate,
)
