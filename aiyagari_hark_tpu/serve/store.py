"""Content-addressed equilibrium solution store: LRU memory + disk tier.

The serving cache (DESIGN §8).  A solution is addressed by its
``utils.fingerprint.solution_fingerprint`` — the solver configuration
(kwargs + dtype) plus the calibration cell — so two queries collide iff
every input that can move a bit of the answer matches.  Entries within one
*solver group* (``work_fingerprint``: same kwargs + dtype, any cell) also
serve as **warm-start donors**: ``nominate`` picks the nearest solved
neighbor in normalized (σ, ρ, sd) space and proposes a (target, margin)
pair for the service's dyadic bracket descent — the same donor rule the
sweep scheduler applies across buckets (``parallel.sweep._neighbor_seed``),
pointed at the store instead of the in-flight batch.

Tiers:

* **memory** — a bounded LRU of full entries (the hot set; an exact hit
  is a dict lookup, no device, no disk).
* **disk** (optional) — one tiny npz per entry under ``disk_path``,
  written with ``utils.checkpoint.save_pytree`` (tmp + ``os.replace``;
  the atomic-write lint covers this package).  Evicted memory entries
  stay on disk; a process restart reloads the index and serves stored
  calibrations without re-solving.

Failed solutions (``solver_health.is_failure``) are never stored — a
quarantine-grade status must not become a cache hit, and a NaN root must
never be nominated as a donor (the sidecar's NaN-row rule).

Integrity (ISSUE 6, DESIGN §9; residency memoization ISSUE 15): every
entry carries a solve-time ``packed_row_checksum`` verified at every
TIER BOUNDARY — on disk load, and ONCE per in-memory residency (the
first ``get`` after an insert).  Re-hashing on every memory hit (the
PR 6 rule) re-verified bytes that had not crossed any boundary since
the last verification and put a ~µs hash on the hot path's critical
microseconds; the memoized rule keeps the corrupt-eviction semantics at
every boundary a bit can actually go wrong across (disk write/read,
promotion, restart) and accepts that a bit flipped INSIDE a verified
resident Python object is out of the threat model (pinned by the
mutate-after-residency test in ``tests/test_fleet.py`` — disk-tier
corruption is still caught and evicted).  An entry failing verification
is EVICTED: dropped from both tiers, its disk file deleted (a corrupt
file left in place would re-degrade every restart), the eviction
counted (``integrity_counts`` → ``ServeMetrics``
``store_corrupt_evictions``) and logged once with the entry key.  The
store never serves bytes it cannot verify — a miss and a re-solve is
the degrade.

Fleet tier (ISSUE 15, DESIGN §14): ``shared=True`` makes the disk tier
safe for N CONCURRENT WORKER PROCESSES over one directory.  Entry
publication was already atomic (``save_pytree`` = tmp + ``os.replace``;
readers see the old bytes or the new bytes, never a hybrid, and the
checksum chain verifies whichever they got); what sharing adds is
**exactly-once election**: a ``lease_<hex>.lease`` claim file per
solution fingerprint (``utils.checkpoint.acquire_lease``,
O_CREAT|O_EXCL — one process wins the create) so N workers racing the
same cold miss solve it once fleet-wide, the losers blocking-or-polling
on the winner's publish.  A crashed winner cannot wedge its
fingerprint: leases older than ``lease_ttl_s`` are BROKEN by any
claimant (``FLEET_LEASE_RECLAIM`` journaled) and the reclaimer solves.
``get`` under ``shared`` additionally probes the disk directory for
keys the in-memory index has never seen — a peer's publish after this
process's index load must become servable without a restart.

Coordination backend (ISSUE 16, DESIGN §14): the claim/heartbeat/
reclaim mechanics live behind the ``serve.lease.LeaseBackend`` trait —
``SharedDirBackend`` (lease files over this directory; the default,
byte-compatible with pre-trait stores) or any conformant peer (the
in-memory/loopback CAS backend models object-store conditional-put).
Backend substrate faults degrade typed (``LEASE_BACKEND_FAULT``
journaled, the operation fails SAFE); the backend decides who solves,
never what a solve produces — entry bytes and fingerprints are backend-
independent."""

from __future__ import annotations

import glob
import os
import threading
import warnings
from collections import OrderedDict
from typing import NamedTuple, Optional

import numpy as np

from ..obs.runtime import NULL_OBS, active_obs
from ..solver_health import is_failure
from ..utils.checkpoint import (
    CORRUPT_NPZ_ERRORS,
    LEASE_SUFFIX,
    load_pytree,
    save_pytree,
)
from ..utils.fingerprint import fingerprint_hex, packed_row_checksum
from .cellindex import CellIndex
from .lease import LeaseBackend, SharedDirBackend

# verify.certificate.UNCERTIFIED, inlined to keep this module's imports
# host-cheap (the certificate module is imported lazily by the service);
# the equality is pinned by tests/test_verify.py.
UNCERTIFIED = -1


class StoredSolution(NamedTuple):
    """One cached equilibrium, npz-able as a pytree (disk tier).

    ``packed`` is the batched solver's device row in its SCENARIO's
    ``RowSchema`` layout (ISSUE 9: widths differ per family), in float64
    — float64 round-trips npz bit-exactly and holds every narrower
    compute dtype exactly, so a reload serves the original bits.
    ``schema_ck`` is the producing scenario's ``RowSchema.checksum()``;
    ``status``/``root`` lift the schema's status code and warm-start
    target out of the row so the store never hard-codes a column index.
    A pre-scenario disk entry fails the template load and degrades like
    any corrupt entry; a same-key entry with a STALE schema checksum is
    evicted at read time.

    ``checksum`` is the solve-time ``packed_row_checksum`` of ``packed``
    (verified at every boundary, DESIGN §9); ``cert_level`` the
    ``verify`` certificate verdict for this solution (``UNCERTIFIED``
    when the service ran without ``certify_before_cache``)."""

    cell: np.ndarray    # [3] cell coordinates, float64
    packed: np.ndarray  # [W] float64 — scenario row layout
    group: np.ndarray   # scalar int64 — work_fingerprint (solver config)
    key: np.ndarray     # scalar int64 — solution_fingerprint (full address)
    checksum: np.ndarray    # scalar int64 — solve-time row checksum
    cert_level: np.ndarray  # scalar int64 — verify certificate level
    schema_ck: np.ndarray   # scalar int64 — RowSchema.checksum()
    status: np.ndarray      # scalar int64 — solver_health code
    root: np.ndarray        # scalar float64 — donor/warm-start target


def _template() -> StoredSolution:
    # leaf SHAPES come from the file (load_pytree), so one template loads
    # every scenario's row width; structure (leaf count) is what gates
    return StoredSolution(cell=np.zeros(3),
                          packed=np.zeros(1),
                          group=np.zeros((), np.int64),
                          key=np.zeros((), np.int64),
                          checksum=np.zeros((), np.int64),
                          cert_level=np.zeros((), np.int64),
                          schema_ck=np.zeros((), np.int64),
                          status=np.zeros((), np.int64),
                          root=np.zeros(()))


def make_solution(cell, packed, group: int, key: int,
                  cert_level: int = UNCERTIFIED,
                  schema=None) -> StoredSolution:
    """Build one entry from a packed row.  ``schema`` is the producing
    scenario's ``RowSchema`` (None = the Aiyagari layout): it names the
    status and root columns and stamps ``schema_ck`` so stale layouts
    drop instead of misparsing."""
    if schema is None:
        from ..scenarios.aiyagari import AIYAGARI_SCHEMA as schema
    packed = np.asarray(packed, dtype=np.float64)
    return StoredSolution(
        cell=np.asarray(cell, dtype=np.float64),
        packed=packed,
        group=np.asarray(group, np.int64),
        key=np.asarray(key, np.int64),
        checksum=np.asarray(packed_row_checksum(packed), np.int64),
        cert_level=np.asarray(int(cert_level), np.int64),
        schema_ck=np.asarray(schema.checksum(), np.int64),
        status=np.asarray(
            int(np.rint(packed[schema.idx(schema.status)])), np.int64),
        root=np.asarray(float(packed[schema.idx(schema.root)]),
                        np.float64))


class Donation(NamedTuple):
    """A nominated warm-start seed: descend toward ``target`` keeping a
    ``margin`` safety ball (the ``dyadic_bracket`` inputs)."""

    target: float
    margin: float
    donor_key: int


class _Meta(NamedTuple):
    """Host-side index row kept for every known entry (memory or disk):
    what donor nomination (and degraded-answer selection, ISSUE 8)
    needs without touching the entry itself."""

    cell: tuple
    group: int
    r_star: float            # the schema root value (donor target)
    on_disk: bool
    cert_level: int = UNCERTIFIED
    schema_ck: int = 0       # producing scenario's RowSchema.checksum()


class SolutionStore:
    """Bounded LRU of ``StoredSolution`` with an optional disk tier.

    Thread-safe (one lock; every operation is O(small)).  ``capacity``
    bounds the in-memory entries only; with a disk tier, evicted entries
    remain addressable (a ``get`` promotes them back), and the index of
    disk entries — a few dozen bytes each — is kept in memory for donor
    nomination."""

    def __init__(self, capacity: int = 256,
                 disk_path: Optional[str] = None,
                 donor_cutoff: float = float("inf"), obs=None,
                 shared: bool = False, lease_ttl_s: float = 30.0,
                 owner: str = "",
                 lease_backend: Optional[LeaseBackend] = None,
                 index: str = "grid"):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        if index not in ("grid", "linear"):
            raise ValueError(
                f"index must be 'grid' or 'linear', got {index!r}")
        if shared and disk_path is None:
            raise ValueError(
                "SolutionStore(shared=True) requires a disk_path: the "
                "shared tier IS the disk directory")
        self.capacity = int(capacity)
        self.disk_path = disk_path
        # fleet tier (ISSUE 15): shared enables the claim/lease protocol
        # and the unknown-key disk probe; lease_ttl_s is the stale-lease
        # reclaim horizon; owner is a diagnostic worker id stamped into
        # lease payloads (election correctness never reads it).
        # lease_backend (ISSUE 16) is the pluggable coordination
        # authority behind the protocol — default the shared-dir
        # backend over the store directory (the pre-trait behavior,
        # byte-compatible); backend choice never enters solution
        # fingerprints or served bytes.
        self.shared = bool(shared)
        self.lease_ttl_s = float(lease_ttl_s)
        self.owner = str(owner)
        if lease_backend is not None and not shared:
            raise ValueError(
                "lease_backend requires SolutionStore(shared=True): "
                "the claim protocol only exists on the shared tier")
        if isinstance(lease_backend, str):
            # accept the worker-flag spec spelling ("dir"/"cas:host:port"/
            # "memory") directly — a raw string would otherwise fail only
            # at the FIRST claim, deep inside _backend_call
            from .lease import make_backend
            lease_backend = make_backend(lease_backend, root=disk_path)
        self.lease_backend: Optional[LeaseBackend] = (
            (lease_backend if lease_backend is not None
             else SharedDirBackend(disk_path)) if shared else None)
        self._held: set = set()          # keys whose lease WE hold
        self._published_keys: list = []  # keys this store published
        self._fleet = {"fleet_claims_won": 0, "fleet_claims_lost": 0,
                       "fleet_publishes": 0, "fleet_lease_reclaims": 0,
                       "fleet_backend_faults": 0,
                       "fleet_store_degraded": 0}
        # lease HEARTBEAT (ISSUE 15): a lease's liveness stamp is
        # refreshed every ttl/4 while its owner lives, so staleness
        # means "the owner stopped beating" (crashed/killed), never
        # "the solve is slower than the TTL" — without it, a first cold
        # solve's compile wall outlives a short TTL and a LIVE winner
        # gets its claim stolen (a measured double-solve, dedup ratio
        # 1.5, in this PR's drill trials).  The daemon runs only while
        # leases are held, and stops DETERMINISTICALLY (ISSUE 16
        # satellite) on the last release, on ``close``, and on
        # ``__del__`` — ``_hb_wake`` is the wake-now event those paths
        # set so no thread outlives the store.
        self._hb_thread = None
        self._hb_wake = threading.Event()
        self._hb_beats = 0       # completed refresh rounds
        self._hb_lost = 0        # held leases found released/stolen
        self._closed = False
        # chaos seams (ISSUE 16): an armable ``serve.chaos.ChaosAgent``
        # consulted at publish / heartbeat / disk-read / staleness
        # seams; None (the default) costs one attribute check
        self._chaos = None
        # normalized-distance radius beyond which nominate() declines: a
        # donor across the whole lattice proposes a junk target (safe —
        # in-program verification falls back to cold — but an honest
        # "cold" classification beats a doomed descent).  inf = always
        # nominate, the sweep scheduler's behavior.
        self.donor_cutoff = float(donor_cutoff)
        self._lock = threading.RLock()
        self._mem: OrderedDict = OrderedDict()   # key -> StoredSolution
        self._meta: dict = {}                    # key -> _Meta
        # neighbor-lookup acceleration (ISSUE 17): the grid-bucket
        # CellIndex is the default; "linear" keeps the scan as the
        # pinned fallback.  Both tie-break by _meta insertion order, so
        # every mutation of _meta MUST go through _meta_set/_meta_del —
        # the index mirror and the per-group matrix cache stay exact.
        self._index: Optional[CellIndex] = (
            CellIndex(on_rebuild=self._index_rebuilt)
            if index == "grid" else None)
        self.index_kind = index
        self._group_cache: dict = {}    # group -> (rows, cell matrix)
        # keys whose CURRENT in-memory residency has been checksum-
        # verified (ISSUE 15 satellite): membership is dropped whenever
        # the memory copy changes hands (insert, promote, evict), so
        # every residency is verified exactly once — on its first get
        self._verified_mem: set = set()
        self._corrupt_evictions = 0
        # Eviction "log once" state is PER STORE INSTANCE (ISSUE 7
        # satellite): the old pattern leaned on the warnings module's
        # per-process dedup registry, so a second store over the same
        # corrupt path — a restarted service in one process — degraded
        # SILENTLY.  The machine-readable trail (journal event + counter
        # + ``integrity_counts``) fires on EVERY eviction regardless.
        self._evict_warned: set = set()
        # the obs bundle must be adopted BEFORE the disk index loads:
        # restart-time evictions are exactly the ones worth journaling
        self._obs = obs if obs is not None else NULL_OBS
        # the replicated backend journals its own seams (QUORUM_LOST,
        # REPLICA_RESYNC); adopt it into this store's scope (ISSUE 18)
        if (self.lease_backend is not None and self._obs is not NULL_OBS
                and hasattr(self.lease_backend, "attach_obs")):
            self.lease_backend.attach_obs(self._obs)
        if disk_path is not None:
            os.makedirs(disk_path, exist_ok=True)
            self._load_disk_index()

    # -- tiers --------------------------------------------------------------

    def _file(self, key: int) -> str:
        # keys are signed int64; the shared hex spelling
        # (``fingerprint_hex``) keeps entry and lease filenames agreeing
        return os.path.join(self.disk_path,
                            f"sol_{fingerprint_hex(key)}.npz")

    def _lease_file(self, key: int) -> str:
        return os.path.join(self.disk_path,
                            f"lease_{fingerprint_hex(key)}{LEASE_SUFFIX}")

    def attach_obs(self, obs) -> None:
        """Adopt a service's observability bundle (ISSUE 7) so eviction
        events/counters land in ITS journal/registry.  First caller
        wins — a store shared by two services keeps one run's scope —
        and the active-scope fallback still covers a bare store used
        inside someone else's run."""
        if self._obs is NULL_OBS and obs is not None:
            self._obs = obs
            if (self.lease_backend is not None
                    and hasattr(self.lease_backend, "attach_obs")):
                self.lease_backend.attach_obs(obs)

    def _obs_scope(self):
        return self._obs if self._obs is not NULL_OBS else active_obs()

    # -- metadata index maintenance (ISSUE 17) ------------------------------

    def _meta_set(self, key: int, meta: _Meta) -> None:
        """The ONLY writer of ``_meta`` rows (lock held): mirrors every
        insert/refresh into the CellIndex and invalidates the group's
        cached cell matrix, so the neighbor seam can never observe a
        stale view."""
        key = int(key)
        prior = self._meta.get(key)
        if prior is not None and prior.group != meta.group:
            # defensive: a key's group is fingerprint-derived and never
            # changes in practice, but a mismatch must not strand the
            # old group's mirror entry
            self._group_cache.pop(prior.group, None)
            if self._index is not None:
                self._index.remove(key, prior.group)
        self._meta[key] = meta
        self._group_cache.pop(meta.group, None)
        if self._index is not None:
            self._index.add(key, meta.cell, meta.group, meta.r_star,
                            meta.cert_level)

    def _meta_del(self, key: int) -> Optional[_Meta]:
        """The ONLY remover of ``_meta`` rows (lock held)."""
        meta = self._meta.pop(int(key), None)
        if meta is not None:
            self._group_cache.pop(meta.group, None)
            if self._index is not None:
                self._index.remove(int(key), meta.group)
        return meta

    def _index_rebuilt(self, group, entries, reason: str) -> None:
        """The index-rebuild seam (ISSUE 17; covered by
        ``check_obs_events``): every CellIndex (re)build — restart
        reload, scenario scale change, growth re-width — leaves a
        journal trail with its size and cause."""
        self._obs_scope().event(
            "INDEX_REBUILD", group=None if group is None else int(group),
            entries=int(entries), reason=str(reason))

    def _record_eviction(self, reason: str, tier: str, path: str,
                         key=None, message=None,
                         stacklevel: int = 4) -> None:
        """The machine-readable eviction trail (ISSUE 7 satellite; lock
        held): journal event + registry counter on EVERY eviction, a
        human warning once per (tier, key) per store instance.
        ``stacklevel`` counts frames from the warn to the store's
        caller: 4 via ``_evict_corrupt``, 3 for direct callers."""
        self._corrupt_evictions += 1
        obs = self._obs_scope()
        obs.event("STORE_EVICT_CORRUPT", tier=tier, reason=reason,
                  key=None if key is None else int(key),
                  file=os.path.basename(path) if path else None)
        obs.counter("aiyagari_store_corrupt_evictions_total",
                    "store entries evicted on failed verification").inc()
        token = (tier, os.path.basename(path) if key is None
                 else int(key))
        if token in self._evict_warned:
            return
        self._evict_warned.add(token)
        if message is None:
            message = (
                "solution store: evicting corrupt entry "
                + (f"{int(key)} " if key is not None else "")
                + f"({os.path.basename(path) if path else tier}): "
                f"{reason}; the entry is deleted and the query will "
                "re-solve")
        warnings.warn(message, stacklevel=stacklevel)

    def _evict_corrupt(self, path: str, reason: str, key=None) -> None:
        """One shared corrupt-entry eviction (DESIGN §9; lock held):
        journal + count + log (``_record_eviction``), forget the entry
        in both tiers, and DELETE the disk file — a corrupt file left
        behind would re-degrade on every restart, and must never be
        servable."""
        if key is not None:
            self._mem.pop(int(key), None)
            self._meta_del(int(key))
            self._verified_mem.discard(int(key))
        self._record_eviction(reason, "disk", path, key=key)
        try:
            os.remove(path)
        except OSError:
            pass

    def _verified(self, sol: StoredSolution) -> bool:
        """Content-checksum verification of one entry's packed row
        against its solve-time checksum (# integrity-ok: this IS the
        verification site)."""
        return packed_row_checksum(sol.packed) == int(sol.checksum)

    def _load_disk_index(self) -> None:
        """Rebuild the index from the disk tier (process restart).  A
        corrupt entry file is EVICTED — logged with its key, counted,
        deleted — and the store degrades to re-solving: it must never
        refuse to start, and never serve (or keep) bytes it cannot
        verify."""
        for path in sorted(glob.glob(os.path.join(self.disk_path,
                                                  "sol_*.npz"))):
            try:
                sol = load_pytree(path, _template())
            except CORRUPT_NPZ_ERRORS as e:
                # includes pre-scenario entry formats (leaf-count
                # mismatch): stale layouts drop, never misparse
                self._evict_corrupt(path, f"unreadable ({e})")
                continue
            if not self._verified(sol):
                self._evict_corrupt(path, "checksum mismatch",
                                    key=sol.key)
                continue
            self._meta_set(int(sol.key), _Meta(
                cell=tuple(np.asarray(sol.cell, dtype=np.float64)),
                group=int(sol.group),
                r_star=float(sol.root), on_disk=True,
                cert_level=int(sol.cert_level),
                schema_ck=int(sol.schema_ck)))
        if self._index is not None and self._meta:
            # restart rebuild of the neighbor index from the metadata
            # tier (ISSUE 17) — journaled through the rebuild seam
            self._index_rebuilt(None, len(self._meta), "restart")

    # -- core ops -----------------------------------------------------------

    def get(self, key: int,
            schema_ck: Optional[int] = None) -> Optional[StoredSolution]:
        """Exact lookup; promotes to most-recently-used.  A disk-resident
        entry is loaded and promoted into memory (evicting LRU).  Every
        TIER BOUNDARY re-verifies the entry's content checksum — disk
        load, and once per in-memory residency on its first get (the
        memoized rule, ISSUE 15 satellite; module docstring for the
        threat model) — and a failed verification evicts the entry (both
        tiers + disk file) and reports a miss, so the caller re-solves
        instead of serving corruption.  Under ``shared`` a key unknown
        to the index additionally probes the disk directory: a peer
        worker's publish becomes servable without a restart.

        ``schema_ck`` (ISSUE 9): the querying scenario's
        ``RowSchema.checksum()``.  An entry stored under a DIFFERENT row
        layout is evicted as stale (a widened schema must drop old
        entries, never misparse their columns); None skips the check."""
        key = int(key)
        with self._lock:
            sol = self._mem.get(key)
            if (sol is not None and schema_ck is not None
                    and int(sol.schema_ck) != int(schema_ck)):
                self._mem.pop(key, None)
                self._meta_del(key)
                self._verified_mem.discard(key)
                self._record_eviction("stale row schema", "memory", "",
                                      key=key, stacklevel=3)
                if self.disk_path is not None:
                    try:
                        os.remove(self._file(key))
                    except OSError:
                        pass
                return None
            if sol is not None:
                if (key not in self._verified_mem
                        and not self._verified(sol)):
                    # in-RAM corruption caught at the residency's first
                    # verification: drop ONLY the memory copy — the
                    # disk entry is a separate byte store written
                    # atomically with its own verification on load, very
                    # plausibly still healthy; destroying it would turn
                    # one transient memory flip into a permanent cache
                    # loss.  Fall through to the disk path below, which
                    # re-verifies (and evicts the file iff IT is bad).
                    del self._mem[key]
                    meta = self._meta.get(key)
                    on_disk = meta is not None and meta.on_disk
                    self._record_eviction(
                        "checksum mismatch", "memory", "", key=key,
                        message=(
                            f"solution store: entry {key} failed "
                            "checksum verification in the memory tier; "
                            "dropping the in-memory copy"
                            + (" and retrying the disk tier" if on_disk
                               else "")),
                        stacklevel=3)
                    if not on_disk:
                        self._meta_del(key)
                        return None
                else:
                    self._verified_mem.add(key)
                    self._mem.move_to_end(key)
                    return sol
            meta = self._meta.get(key)
            if meta is None or not meta.on_disk:
                # shared tier (ISSUE 15): the index was built at startup
                # (plus our own puts) — a PEER process may have
                # published this key since.  One existence probe per
                # miss keeps cross-process publication visible; the
                # load below verifies the bytes like any disk read.
                if not (self.shared and meta is None
                        and os.path.exists(self._file(key))):
                    return None
            path = self._file(key)
            if self._chaos is not None and self._chaos.read_fault(key):
                # injected store partition (ISSUE 16): a TRANSIENT read
                # failure degrades to a miss WITHOUT evicting — the
                # bytes on disk are healthy, and deleting them would
                # turn a partition window into a permanent cache loss
                self._backend_fault(
                    "disk_read", "injected partition read fault",
                    key=key)
                return None
            try:
                sol = load_pytree(path, _template())
            except CORRUPT_NPZ_ERRORS as e:
                self._evict_corrupt(path, f"unreadable ({e})", key=key)
                return None
            if (schema_ck is not None
                    and int(sol.schema_ck) != int(schema_ck)):
                self._evict_corrupt(path, "stale row schema", key=key)
                return None
            if not self._verified(sol):
                self._evict_corrupt(path, "checksum mismatch", key=key)
                return None
            # a verified disk load begins a verified residency; a
            # probe-discovered peer publish also earns an index row so
            # donor nomination sees it from now on
            self._meta_set(key, _Meta(
                cell=tuple(np.asarray(sol.cell, dtype=np.float64)),
                group=int(sol.group),
                r_star=float(sol.root), on_disk=True,
                cert_level=int(sol.cert_level),
                schema_ck=int(sol.schema_ck)))
            self._insert(key, sol)
            self._verified_mem.add(key)
            return sol

    def put(self, sol: StoredSolution) -> None:
        """Insert (or refresh) one solution.  Failed statuses are refused
        loudly — caching an uncertified result is a caller bug."""
        status = int(sol.status)
        if is_failure(status):
            raise ValueError(
                f"refusing to store a failed solution (status={status}); "
                "failures raise on their future, they are never cached")
        key = int(sol.key)
        with self._lock:
            on_disk = False
            if self.disk_path is not None:
                try:
                    save_pytree(self._file(key), sol)
                    on_disk = True
                except OSError as e:
                    self._degrade_memory_only(key, e)
            prior = self._meta.get(key)
            if prior is not None and prior.on_disk:
                on_disk = True
            self._meta_set(key, _Meta(
                cell=tuple(np.asarray(sol.cell, dtype=np.float64)),
                group=int(sol.group),
                r_star=float(sol.root), on_disk=on_disk,
                cert_level=int(sol.cert_level),
                schema_ck=int(sol.schema_ck)))
            self._insert(key, sol)

    def _degrade_memory_only(self, key: int, error) -> None:
        """The failed-disk-publish seam (ISSUE 18; covered by
        ``check_obs_events``; ``_lock`` held): a full/failing disk
        (ENOSPC/EIO — real or injected via ``utils.checkpoint
        .arm_disk_fault``) must degrade the store to MEMORY-ONLY for
        this entry — journaled ``STORE_DEGRADED``, counted, warned —
        never crash the solve or tear the disk tier.  This process
        keeps serving the solution from memory; peers re-solve (the
        atomic writer guarantees they never read a torn file)."""
        self._fleet["fleet_store_degraded"] += 1
        self._obs_scope().event(
            "STORE_DEGRADED", key=int(key), tier="disk",
            error=f"{type(error).__name__}: {error}")
        warnings.warn(
            f"solution store: could not persist entry {int(key)} "
            f"({error}); serving it memory-only — peers will re-solve "
            "until the disk recovers", stacklevel=3)

    # -- fleet claim / publish (ISSUE 15, DESIGN §14) -----------------------

    def _require_shared(self, what: str) -> None:
        if not self.shared:
            raise ValueError(
                f"{what} requires SolutionStore(shared=True): the "
                "claim/lease protocol only exists on the shared tier")

    def _backend_call(self, op: str, default, *args, **kw):
        """One lease-backend operation with the typed degrade: a
        substrate fault (socket drop, I/O error) journals
        ``LEASE_BACKEND_FAULT`` and returns ``default`` — chosen per
        call site so a transient fault fails SAFE (an acquire fault
        reads as "lost", a heartbeat fault keeps the claim, a reclaim
        fault reclaims nothing)."""
        try:
            return getattr(self.lease_backend, op)(*args, **kw)
        except (OSError, ConnectionError) as e:
            self._backend_fault(op, e)
            return default

    def _backend_fault(self, op: str, detail, key=None) -> None:
        """The lease-backend fault seam (ISSUE 16; covered by
        ``check_obs_events``): journal + count every degraded backend
        operation — partitions and lost leases must leave the same
        machine-readable trail as every other typed failure."""
        with self._lock:
            self._fleet["fleet_backend_faults"] += 1
        if isinstance(detail, BaseException):
            detail = f"{type(detail).__name__}: {detail}"
        self._obs_scope().event(
            "LEASE_BACKEND_FAULT", op=str(op), owner=self.owner,
            key=None if key is None else int(key), detail=str(detail))

    def _chaos_now(self):
        """The staleness clock's ``now`` override: None normally; a
        chaos-armed skew returns a shifted wall (the duplicated-
        election drill's injected fault)."""
        return None if self._chaos is None else self._chaos.skew_now()

    def set_chaos(self, agent) -> None:
        """Attach a ``serve.chaos.ChaosAgent`` (fault-injection drills;
        ``--chaos`` workers only).  None detaches."""
        self._chaos = agent

    def claim(self, key: int) -> str:
        """Elect a solver for ``key`` fleet-wide.  Returns:

        * ``"published"`` — the entry already exists on disk (serve it;
          no solve needed);
        * ``"won"`` — THIS store now holds the key's lease: the caller
          must solve and then ``publish`` (success) or ``release``
          (failure/abandon), or let the TTL reclaim it (crash);
        * ``"lost"`` — a live peer holds the lease: block-or-poll for
          its publish (``get`` probes the disk) or for the lease to go
          stale.

        A lease older than ``lease_ttl_s`` is broken here (journaled
        ``FLEET_LEASE_RECLAIM``) and the claim re-runs — a crashed
        winner never wedges its fingerprint."""
        self._require_shared("claim")
        key = int(key)
        for _ in range(2):      # once, plus once after a stale break
            if os.path.exists(self._file(key)):
                return "published"
            if self._backend_call("try_acquire", False, key, self.owner):
                with self._lock:
                    self._held.add(key)
                    self._fleet["fleet_claims_won"] += 1
                    self._ensure_heartbeat_locked()
                self._obs_scope().event("FLEET_CLAIM", key=key,
                                        owner=self.owner)
                # the entry may have been published between the
                # existence probe and the create: the winner must not
                # re-solve what the fleet already has
                if os.path.exists(self._file(key)):
                    self.release(key)
                    return "published"
                return "won"
            if self._backend_call("break_stale", False, key,
                                  self.lease_ttl_s, now=self._chaos_now()):
                with self._lock:
                    self._fleet["fleet_lease_reclaims"] += 1
                self._obs_scope().event("FLEET_LEASE_RECLAIM", key=key,
                                        owner=self.owner)
                continue
            break
        with self._lock:
            self._fleet["fleet_claims_lost"] += 1
        return "lost"

    def publish(self, sol: StoredSolution, speculative: bool = False,
                seed=None) -> None:
        """Winner's completion: ``put`` (atomic disk write included) then
        release the key's lease, journaled ``FLEET_PUBLISH``.
        ``speculative`` tags a prefetch-driven solve (the fleet load
        harness attributes prefetch conversions from this attr);
        ``seed`` is the solving lane's exact bracket seed ``(lo, hi,
        levels)`` — journaled bit-exactly so the fleet bit-identity
        acceptance can replay ANY published solve through a same-seed
        ``reference_solve``, including solves whose response no client
        ever saw (prefetch, a drilled worker's in-flight reply)."""
        self._require_shared("publish")
        key = int(sol.key)
        if self._chaos is not None:
            # chaos seam: an armed publish delay holds the lease
            # mid-"solve" — the kill/stall drills' deterministic window
            delay = self._chaos.publish_delay_s(sol.cell)
            if delay > 0.0:
                import time

                time.sleep(delay)
        self.put(sol)
        with self._lock:
            self._fleet["fleet_publishes"] += 1
            self._published_keys.append(key)
        self._obs_scope().event(
            "FLEET_PUBLISH", key=key, owner=self.owner,
            speculative=bool(speculative),
            seed=(None if seed is None else
                  [float(seed[0]), float(seed[1]), int(seed[2])]))
        self.release(key)

    def release(self, key: int) -> None:
        """Give up a held lease WITHOUT publishing (failed solve, cert
        failure, abandoned batch): the fingerprint becomes claimable
        again immediately.  Idempotent; a no-op for leases this store
        never held.  OWNER-CHECKED at the backend (ISSUE 16): a release
        landing after a TTL reclaim + peer re-acquire must not delete
        the peer's fresh lease.  The LAST release wakes the heartbeat
        daemon so it exits deterministically instead of on its next
        tick."""
        key = int(key)
        with self._lock:
            held = key in self._held
            self._held.discard(key)
            if held and not self._held:
                self._hb_wake.set()
        if held:
            self._backend_call("release", False, key, owner=self.owner)

    def _ensure_heartbeat_locked(self) -> None:
        """Start the lease-heartbeat daemon if it is not running
        (``_lock`` held).  It exits on its own once nothing is held (or
        the store closed), so a store that stops claiming stops
        threading."""
        if self._closed:
            return
        if self._hb_thread is not None and self._hb_thread.is_alive():
            return
        self._hb_wake.clear()
        self._hb_thread = threading.Thread(
            target=self._heartbeat_loop, name="lease-heartbeat",
            daemon=True)
        self._hb_thread.start()

    def _heartbeat_loop(self) -> None:
        interval = max(0.05, self.lease_ttl_s / 4.0)
        while True:
            self._hb_wake.wait(interval)
            with self._lock:
                self._hb_wake.clear()
                if self._closed or not self._held:
                    self._hb_thread = None
                    return
                held = list(self._held)
            chaos = self._chaos
            if chaos is not None and chaos.heartbeat_stalled():
                continue     # the zombie-winner drill: alive, not beating
            lost = []
            for key in held:
                # default True: a TRANSIENT backend fault must not drop
                # the claim (the fault itself is journaled); only a
                # definitive "you no longer hold this" does
                if not self._backend_call("heartbeat", True, key,
                                          self.owner):
                    lost.append(key)
            with self._lock:
                self._hb_beats += 1
                for key in lost:
                    self._held.discard(key)
                    self._hb_lost += 1
            for key in lost:
                self._backend_fault(
                    "heartbeat",
                    "held lease no longer ours (released, TTL-reclaimed,"
                    " or re-acquired by a peer) — claim dropped",
                    key=key)

    def close(self, release_leases: bool = False,
              timeout_s: float = 5.0) -> None:
        """Deterministically stop the heartbeat daemon (ISSUE 16
        satellite): after ``close`` returns no store thread is running.
        Held leases are left for TTL reclaim by default — the crashed-
        winner protocol, and the right semantics for a dying worker —
        or released first with ``release_leases=True`` (an orderly
        shutdown that will not publish).  Idempotent; entries and the
        disk tier are untouched.

        The release pass is BOUNDED by ``timeout_s`` (ISSUE 18
        satellite): against an unreachable/partitioned backend each
        release already degrades typed (``_backend_call``), but N keys
        x a dial timeout could wedge a dying worker for minutes — once
        the budget is spent the remaining leases are LEFT FOR TTL
        RECLAIM with one more typed journal line, and ``close`` keeps
        its promise to return."""
        if release_leases:
            import time as _time

            deadline = _time.monotonic() + max(0.0, float(timeout_s))
            held = self.held_leases()
            for idx, key in enumerate(held):
                if _time.monotonic() >= deadline:
                    self._backend_fault(
                        "close_release",
                        f"close(release_leases=True) exceeded its "
                        f"{float(timeout_s):.1f}s budget with "
                        f"{len(held) - idx} lease(s) unreleased — "
                        "left for TTL reclaim")
                    break
                self.release(key)
        with self._lock:
            self._closed = True
            t = self._hb_thread
            self._hb_wake.set()
        if t is not None and t is not threading.current_thread():
            t.join(max(1.0, self.lease_ttl_s))
        if self.lease_backend is not None:
            try:
                self.lease_backend.close()
            except (OSError, ConnectionError) as e:
                self._backend_fault("close", e)

    def __del__(self):   # pragma: no cover - GC timing
        try:
            self.close()
        except Exception:
            pass

    def heartbeat_health(self) -> dict:
        """Heartbeat/lease liveness for ``/healthz`` and ``/fleet``
        (ISSUE 16): completed refresh rounds, held-lease count, leases
        found lost/stolen by the beat, backend identity, and whether
        the daemon is currently running."""
        with self._lock:
            return {
                "thread_alive": (self._hb_thread is not None
                                 and self._hb_thread.is_alive()),
                "held": len(self._held),
                "beats": self._hb_beats,
                "lost_leases": self._hb_lost,
                "backend": (None if self.lease_backend is None
                            else self.lease_backend.name),
                "closed": self._closed,
            }

    def lease_present(self, key: int) -> bool:
        self._require_shared("lease_present")
        return self._backend_call("age_s", None, int(key)) is not None

    def lease_stale(self, key: int) -> bool:
        """True iff the key's lease exists and is past the TTL."""
        self._require_shared("lease_stale")
        age = self._backend_call("age_s", None, int(key))
        return age is not None and age > self.lease_ttl_s

    def reclaim_if_stale(self, key: int) -> bool:
        """Break one stale lease (TTL reclaim outside the claim loop —
        the waiter path); True iff this call removed it."""
        self._require_shared("reclaim_if_stale")
        key = int(key)
        if self._backend_call("break_stale", False, key,
                              self.lease_ttl_s, now=self._chaos_now()):
            with self._lock:
                self._fleet["fleet_lease_reclaims"] += 1
            self._obs_scope().event("FLEET_LEASE_RECLAIM", key=key,
                                    owner=self.owner)
            return True
        return False

    def held_leases(self) -> list:
        """Keys whose lease THIS store instance currently holds."""
        with self._lock:
            return sorted(self._held)

    def lease_files(self) -> list:
        """Every live lease, all owners — the leak audit.  The
        shared-dir backend returns real file paths (the pre-trait
        spelling); other backends synthesize the same naming."""
        self._require_shared("lease_files")
        return self._backend_call("lease_names", [])

    def gc_stale_leases(self) -> int:
        """Sweep every stale lease the backend knows (end-of-run leak
        reclaim; counts + journals each).  Returns how many were
        removed."""
        self._require_shared("gc_stale_leases")
        removed = 0
        for key in self._backend_call("list_keys", []):
            if self._backend_call("break_stale", False, key,
                                  self.lease_ttl_s):
                removed += 1
                with self._lock:
                    self._fleet["fleet_lease_reclaims"] += 1
                self._obs_scope().event(
                    "FLEET_LEASE_RECLAIM", key=int(key),
                    owner=self.owner, swept=True)
        return removed

    def contains(self, key: int) -> bool:
        """Key addressable without loading it: indexed in either tier,
        or (shared) published on disk by a peer."""
        key = int(key)
        with self._lock:
            if key in self._meta:
                return True
        return self.shared and os.path.exists(self._file(key))

    def published_keys(self) -> list:
        """Keys THIS store published (fleet dedup accounting)."""
        with self._lock:
            return list(self._published_keys)

    def fleet_counts(self) -> dict:
        """Fleet protocol counters (``ServeMetrics`` merge)."""
        with self._lock:
            return dict(self._fleet)

    def _insert(self, key: int, sol: StoredSolution) -> None:
        # a (re)insert starts a FRESH residency: verification membership
        # is per-residency, so the new bytes verify on their first get
        # unless the caller (a just-verified disk load) marks them
        self._verified_mem.discard(key)
        self._mem[key] = sol
        self._mem.move_to_end(key)
        while len(self._mem) > self.capacity:
            old_key, _ = self._mem.popitem(last=False)
            self._verified_mem.discard(old_key)
            meta = self._meta.get(old_key)
            if meta is not None and not meta.on_disk:
                # memory-only tier: eviction forgets the entry entirely
                # (bounded memory is the contract); with a disk tier the
                # index row stays so the entry remains addressable
                self._meta_del(old_key)

    # -- donor nomination ---------------------------------------------------

    def _group_rows_locked(self, group: int):
        """Cached per-group donor rows for the LINEAR path (ISSUE 17
        satellite; lock held): the finite-r* rows of ``group`` in
        metadata insertion order plus their prebuilt cell matrix —
        ``nominate``/``nearest`` previously re-materialized the matrix
        on EVERY call.  Invalidated by ``_meta_set``/``_meta_del``."""
        cached = self._group_cache.get(group)
        if cached is None:
            rows = [(k, m) for k, m in self._meta.items()
                    if m.group == group and np.isfinite(m.r_star)]
            mat = (np.asarray([m.cell for _, m in rows])
                   if rows else None)
            cached = (rows, mat)
            self._group_cache[group] = cached
        return cached

    def neighbors(self, cell, group: int, k: Optional[int],
                  require_certified: bool = False, scale=None):
        """THE neighbor-selection seam (ISSUE 17, DESIGN §15): donor
        nomination, degraded-answer selection and the surrogate tier's
        k-NN all route through here.  Returns up to ``k`` entries
        ``[(key, _Meta, distance), ...]`` ordered by (normalized-L1
        distance, metadata insertion order) — ``k=None`` ranks the whole
        group.  The grid-bucket ``CellIndex`` answers by default; the
        linear scan (over the cached per-group cell matrix) is the
        pinned fallback, and the two are property-tested bitwise
        identical, ties included."""
        from ..parallel.sweep import NEIGHBOR_CELL_SCALE, neighbor_distance

        if scale is None:
            scale = NEIGHBOR_CELL_SCALE
        group = int(group)
        with self._lock:
            if self._index is not None:
                hits = self._index.nearest_k(
                    cell, group, k, scale=scale,
                    require_certified=require_certified)
                return [(kk, self._meta[kk], dd) for kk, dd in hits]
            rows, mat = self._group_rows_locked(group)
        if require_certified and rows:
            sel = [i for i, (_, m) in enumerate(rows)
                   if m.cert_level >= 0]
            rows = [rows[i] for i in sel]
            mat = mat[sel] if sel else None
        if not rows:
            return []
        d = neighbor_distance(cell, mat, scale=scale)
        if k == 1:
            # first-minimum == stable-argsort[0]; O(n) beats the sort
            i = int(np.argmin(d))
            return [(int(rows[i][0]), rows[i][1], float(d[i]))]
        order = np.argsort(d, kind="stable")
        if k is not None:
            order = order[:k]
        return [(int(rows[int(i)][0]), rows[int(i)][1], float(d[int(i)]))
                for i in order]

    def nominate(self, cell, group: int, width: float,
                 r_tol: float, scale=None) -> Optional[Donation]:
        """Warm-start donor for ``cell`` within solver group ``group``:
        target = nearest stored root in normalized (σ, ρ, sd) space,
        margin = the r*-spread between the two nearest donors (how far the
        root plausibly moved), floored defensively — LITERALLY the sweep
        scheduler's neighbor rule (``parallel.sweep.neighbor_distance`` /
        ``donor_margin``, one shared implementation) pointed at the store.
        ``width`` is the economic bracket width and ``r_tol`` the
        bisection tolerance of the *querying* configuration; ``scale``
        the querying scenario's ``CellSpace.scale`` (None = the Aiyagari
        lattice normalization).  None when the group holds no donors (or
        none inside ``donor_cutoff``)."""
        from ..parallel.sweep import donor_margin

        near = self.neighbors(cell, group, k=2, scale=scale)
        if not near:
            return None
        k0, m0, d0 = near[0]
        if d0 > self.donor_cutoff:
            return None
        target = float(m0.r_star)
        spread = (abs(target - float(near[1][1].r_star))
                  if len(near) > 1 else None)
        return Donation(target=target,
                        margin=donor_margin(spread, width, r_tol),
                        donor_key=int(k0))

    def nearest(self, cell, group: int,
                require_certified: bool = False, scale=None):
        """Nearest stored neighbor of ``cell`` within solver group
        ``group`` in normalized (σ, ρ, sd) space — the degraded-answer
        donor (ISSUE 8, DESIGN §11).  Returns ``(key, distance)`` or
        None.

        Unlike ``nominate`` this proposes no bracket: the caller serves
        the donor's OWN row, tagged degraded, so the donor must be a
        real addressable entry — fetch it with ``get(key)``, which
        re-verifies the content checksum (a corrupt donor degrades to a
        miss, never to a served wrong answer).  With
        ``require_certified`` only donors carrying a CERTIFIED/MARGINAL
        ``verify`` certificate qualify (an UNCERTIFIED entry from a
        service running without ``certify_before_cache`` is skipped)."""
        near = self.neighbors(cell, group, k=1,
                              require_certified=require_certified,
                              scale=scale)
        if not near:
            return None
        return int(near[0][0]), float(near[0][2])

    # -- introspection ------------------------------------------------------

    def __len__(self) -> int:
        """In-memory (LRU-bounded) entry count."""
        with self._lock:
            return len(self._mem)

    def known(self) -> int:
        """Addressable entries across both tiers."""
        with self._lock:
            return len(self._meta)

    def mem_keys(self) -> list:
        """Memory-tier keys in LRU order (oldest first) — test hook for
        the eviction-order contract."""
        with self._lock:
            return list(self._mem.keys())

    def integrity_counts(self) -> dict:
        """Integrity counters for ``ServeMetrics`` (DESIGN §9):
        ``store_corrupt_evictions`` is the number of entries that failed
        checksum/format verification and were evicted (+ file deleted)."""
        with self._lock:
            return {"store_corrupt_evictions": self._corrupt_evictions}

    def index_stats(self) -> dict:
        """Neighbor-index introspection (ISSUE 17): which path answers
        ``neighbors`` and how often the grid index was (re)built."""
        with self._lock:
            return {
                "index_kind": self.index_kind,
                "index_entries": (len(self._index)
                                  if self._index is not None
                                  else len(self._meta)),
                "index_rebuilds": (self._index.rebuilds
                                   if self._index is not None else 0),
            }
