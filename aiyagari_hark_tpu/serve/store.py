"""Content-addressed equilibrium solution store: LRU memory + disk tier.

The serving cache (DESIGN §8).  A solution is addressed by its
``utils.fingerprint.solution_fingerprint`` — the solver configuration
(kwargs + dtype) plus the calibration cell — so two queries collide iff
every input that can move a bit of the answer matches.  Entries within one
*solver group* (``work_fingerprint``: same kwargs + dtype, any cell) also
serve as **warm-start donors**: ``nominate`` picks the nearest solved
neighbor in normalized (σ, ρ, sd) space and proposes a (target, margin)
pair for the service's dyadic bracket descent — the same donor rule the
sweep scheduler applies across buckets (``parallel.sweep._neighbor_seed``),
pointed at the store instead of the in-flight batch.

Tiers:

* **memory** — a bounded LRU of full entries (the hot set; an exact hit
  is a dict lookup, no device, no disk).
* **disk** (optional) — one tiny npz per entry under ``disk_path``,
  written with ``utils.checkpoint.save_pytree`` (tmp + ``os.replace``;
  the atomic-write lint covers this package).  Evicted memory entries
  stay on disk; a process restart reloads the index and serves stored
  calibrations without re-solving.

Failed solutions (``solver_health.is_failure``) are never stored — a
quarantine-grade status must not become a cache hit, and a NaN root must
never be nominated as a donor (the sidecar's NaN-row rule).

Integrity (ISSUE 6, DESIGN §9): every entry carries a solve-time
``packed_row_checksum`` verified on EVERY read — memory-tier hits
included (hashing 80 bytes costs ~a microsecond against the sub-ms hit
budget) — and a ``cert_level`` (``verify`` certificate verdict;
``UNCERTIFIED`` when certification was off).  An entry failing
verification is EVICTED: dropped from both tiers, its disk file deleted
(a corrupt file left in place would re-degrade every restart), the
eviction counted (``integrity_counts`` → ``ServeMetrics``
``store_corrupt_evictions``) and logged once with the entry key.  The
store never serves bytes it cannot verify — a miss and a re-solve is the
degrade."""

from __future__ import annotations

import glob
import os
import threading
import warnings
from collections import OrderedDict
from typing import NamedTuple, Optional

import numpy as np

from ..obs.runtime import NULL_OBS, active_obs
from ..solver_health import is_failure
from ..utils.checkpoint import CORRUPT_NPZ_ERRORS, load_pytree, save_pytree
from ..utils.fingerprint import packed_row_checksum

# verify.certificate.UNCERTIFIED, inlined to keep this module's imports
# host-cheap (the certificate module is imported lazily by the service);
# the equality is pinned by tests/test_verify.py.
UNCERTIFIED = -1


class StoredSolution(NamedTuple):
    """One cached equilibrium, npz-able as a pytree (disk tier).

    ``packed`` is the batched solver's device row in its SCENARIO's
    ``RowSchema`` layout (ISSUE 9: widths differ per family), in float64
    — float64 round-trips npz bit-exactly and holds every narrower
    compute dtype exactly, so a reload serves the original bits.
    ``schema_ck`` is the producing scenario's ``RowSchema.checksum()``;
    ``status``/``root`` lift the schema's status code and warm-start
    target out of the row so the store never hard-codes a column index.
    A pre-scenario disk entry fails the template load and degrades like
    any corrupt entry; a same-key entry with a STALE schema checksum is
    evicted at read time.

    ``checksum`` is the solve-time ``packed_row_checksum`` of ``packed``
    (verified at every boundary, DESIGN §9); ``cert_level`` the
    ``verify`` certificate verdict for this solution (``UNCERTIFIED``
    when the service ran without ``certify_before_cache``)."""

    cell: np.ndarray    # [3] cell coordinates, float64
    packed: np.ndarray  # [W] float64 — scenario row layout
    group: np.ndarray   # scalar int64 — work_fingerprint (solver config)
    key: np.ndarray     # scalar int64 — solution_fingerprint (full address)
    checksum: np.ndarray    # scalar int64 — solve-time row checksum
    cert_level: np.ndarray  # scalar int64 — verify certificate level
    schema_ck: np.ndarray   # scalar int64 — RowSchema.checksum()
    status: np.ndarray      # scalar int64 — solver_health code
    root: np.ndarray        # scalar float64 — donor/warm-start target


def _template() -> StoredSolution:
    # leaf SHAPES come from the file (load_pytree), so one template loads
    # every scenario's row width; structure (leaf count) is what gates
    return StoredSolution(cell=np.zeros(3),
                          packed=np.zeros(1),
                          group=np.zeros((), np.int64),
                          key=np.zeros((), np.int64),
                          checksum=np.zeros((), np.int64),
                          cert_level=np.zeros((), np.int64),
                          schema_ck=np.zeros((), np.int64),
                          status=np.zeros((), np.int64),
                          root=np.zeros(()))


def make_solution(cell, packed, group: int, key: int,
                  cert_level: int = UNCERTIFIED,
                  schema=None) -> StoredSolution:
    """Build one entry from a packed row.  ``schema`` is the producing
    scenario's ``RowSchema`` (None = the Aiyagari layout): it names the
    status and root columns and stamps ``schema_ck`` so stale layouts
    drop instead of misparsing."""
    if schema is None:
        from ..scenarios.aiyagari import AIYAGARI_SCHEMA as schema
    packed = np.asarray(packed, dtype=np.float64)
    return StoredSolution(
        cell=np.asarray(cell, dtype=np.float64),
        packed=packed,
        group=np.asarray(group, np.int64),
        key=np.asarray(key, np.int64),
        checksum=np.asarray(packed_row_checksum(packed), np.int64),
        cert_level=np.asarray(int(cert_level), np.int64),
        schema_ck=np.asarray(schema.checksum(), np.int64),
        status=np.asarray(
            int(np.rint(packed[schema.idx(schema.status)])), np.int64),
        root=np.asarray(float(packed[schema.idx(schema.root)]),
                        np.float64))


class Donation(NamedTuple):
    """A nominated warm-start seed: descend toward ``target`` keeping a
    ``margin`` safety ball (the ``dyadic_bracket`` inputs)."""

    target: float
    margin: float
    donor_key: int


class _Meta(NamedTuple):
    """Host-side index row kept for every known entry (memory or disk):
    what donor nomination (and degraded-answer selection, ISSUE 8)
    needs without touching the entry itself."""

    cell: tuple
    group: int
    r_star: float            # the schema root value (donor target)
    on_disk: bool
    cert_level: int = UNCERTIFIED
    schema_ck: int = 0       # producing scenario's RowSchema.checksum()


class SolutionStore:
    """Bounded LRU of ``StoredSolution`` with an optional disk tier.

    Thread-safe (one lock; every operation is O(small)).  ``capacity``
    bounds the in-memory entries only; with a disk tier, evicted entries
    remain addressable (a ``get`` promotes them back), and the index of
    disk entries — a few dozen bytes each — is kept in memory for donor
    nomination."""

    def __init__(self, capacity: int = 256,
                 disk_path: Optional[str] = None,
                 donor_cutoff: float = float("inf"), obs=None):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self.disk_path = disk_path
        # normalized-distance radius beyond which nominate() declines: a
        # donor across the whole lattice proposes a junk target (safe —
        # in-program verification falls back to cold — but an honest
        # "cold" classification beats a doomed descent).  inf = always
        # nominate, the sweep scheduler's behavior.
        self.donor_cutoff = float(donor_cutoff)
        self._lock = threading.RLock()
        self._mem: OrderedDict = OrderedDict()   # key -> StoredSolution
        self._meta: dict = {}                    # key -> _Meta
        self._corrupt_evictions = 0
        # Eviction "log once" state is PER STORE INSTANCE (ISSUE 7
        # satellite): the old pattern leaned on the warnings module's
        # per-process dedup registry, so a second store over the same
        # corrupt path — a restarted service in one process — degraded
        # SILENTLY.  The machine-readable trail (journal event + counter
        # + ``integrity_counts``) fires on EVERY eviction regardless.
        self._evict_warned: set = set()
        # the obs bundle must be adopted BEFORE the disk index loads:
        # restart-time evictions are exactly the ones worth journaling
        self._obs = obs if obs is not None else NULL_OBS
        if disk_path is not None:
            os.makedirs(disk_path, exist_ok=True)
            self._load_disk_index()

    # -- tiers --------------------------------------------------------------

    def _file(self, key: int) -> str:
        # keys are signed int64; hex-encode the two's-complement bits so
        # the filename is stable and glob-able
        return os.path.join(self.disk_path,
                            f"sol_{int(key) & 0xFFFFFFFFFFFFFFFF:016x}.npz")

    def attach_obs(self, obs) -> None:
        """Adopt a service's observability bundle (ISSUE 7) so eviction
        events/counters land in ITS journal/registry.  First caller
        wins — a store shared by two services keeps one run's scope —
        and the active-scope fallback still covers a bare store used
        inside someone else's run."""
        if self._obs is NULL_OBS and obs is not None:
            self._obs = obs

    def _obs_scope(self):
        return self._obs if self._obs is not NULL_OBS else active_obs()

    def _record_eviction(self, reason: str, tier: str, path: str,
                         key=None, message=None,
                         stacklevel: int = 4) -> None:
        """The machine-readable eviction trail (ISSUE 7 satellite; lock
        held): journal event + registry counter on EVERY eviction, a
        human warning once per (tier, key) per store instance.
        ``stacklevel`` counts frames from the warn to the store's
        caller: 4 via ``_evict_corrupt``, 3 for direct callers."""
        self._corrupt_evictions += 1
        obs = self._obs_scope()
        obs.event("STORE_EVICT_CORRUPT", tier=tier, reason=reason,
                  key=None if key is None else int(key),
                  file=os.path.basename(path) if path else None)
        obs.counter("aiyagari_store_corrupt_evictions_total",
                    "store entries evicted on failed verification").inc()
        token = (tier, os.path.basename(path) if key is None
                 else int(key))
        if token in self._evict_warned:
            return
        self._evict_warned.add(token)
        if message is None:
            message = (
                "solution store: evicting corrupt entry "
                + (f"{int(key)} " if key is not None else "")
                + f"({os.path.basename(path) if path else tier}): "
                f"{reason}; the entry is deleted and the query will "
                "re-solve")
        warnings.warn(message, stacklevel=stacklevel)

    def _evict_corrupt(self, path: str, reason: str, key=None) -> None:
        """One shared corrupt-entry eviction (DESIGN §9; lock held):
        journal + count + log (``_record_eviction``), forget the entry
        in both tiers, and DELETE the disk file — a corrupt file left
        behind would re-degrade on every restart, and must never be
        servable."""
        if key is not None:
            self._mem.pop(int(key), None)
            self._meta.pop(int(key), None)
        self._record_eviction(reason, "disk", path, key=key)
        try:
            os.remove(path)
        except OSError:
            pass

    def _verified(self, sol: StoredSolution) -> bool:
        """Content-checksum verification of one entry's packed row
        against its solve-time checksum (# integrity-ok: this IS the
        verification site)."""
        return packed_row_checksum(sol.packed) == int(sol.checksum)

    def _load_disk_index(self) -> None:
        """Rebuild the index from the disk tier (process restart).  A
        corrupt entry file is EVICTED — logged with its key, counted,
        deleted — and the store degrades to re-solving: it must never
        refuse to start, and never serve (or keep) bytes it cannot
        verify."""
        for path in sorted(glob.glob(os.path.join(self.disk_path,
                                                  "sol_*.npz"))):
            try:
                sol = load_pytree(path, _template())
            except CORRUPT_NPZ_ERRORS as e:
                # includes pre-scenario entry formats (leaf-count
                # mismatch): stale layouts drop, never misparse
                self._evict_corrupt(path, f"unreadable ({e})")
                continue
            if not self._verified(sol):
                self._evict_corrupt(path, "checksum mismatch",
                                    key=sol.key)
                continue
            self._meta[int(sol.key)] = _Meta(
                cell=tuple(np.asarray(sol.cell, dtype=np.float64)),
                group=int(sol.group),
                r_star=float(sol.root), on_disk=True,
                cert_level=int(sol.cert_level),
                schema_ck=int(sol.schema_ck))

    # -- core ops -----------------------------------------------------------

    def get(self, key: int,
            schema_ck: Optional[int] = None) -> Optional[StoredSolution]:
        """Exact lookup; promotes to most-recently-used.  A disk-resident
        entry is loaded and promoted into memory (evicting LRU).  EVERY
        return path re-verifies the entry's content checksum — a
        memory-tier bit flip is as silent as a disk one — and a failed
        verification evicts the entry (both tiers + disk file) and
        reports a miss, so the caller re-solves instead of serving
        corruption.

        ``schema_ck`` (ISSUE 9): the querying scenario's
        ``RowSchema.checksum()``.  An entry stored under a DIFFERENT row
        layout is evicted as stale (a widened schema must drop old
        entries, never misparse their columns); None skips the check."""
        key = int(key)
        with self._lock:
            sol = self._mem.get(key)
            if (sol is not None and schema_ck is not None
                    and int(sol.schema_ck) != int(schema_ck)):
                self._mem.pop(key, None)
                self._meta.pop(key, None)
                self._record_eviction("stale row schema", "memory", "",
                                      key=key, stacklevel=3)
                if self.disk_path is not None:
                    try:
                        os.remove(self._file(key))
                    except OSError:
                        pass
                return None
            if sol is not None:
                if not self._verified(sol):
                    # in-RAM corruption: drop ONLY the memory copy — the
                    # disk entry is a separate byte store written
                    # atomically with its own verification on load, very
                    # plausibly still healthy; destroying it would turn
                    # one transient memory flip into a permanent cache
                    # loss.  Fall through to the disk path below, which
                    # re-verifies (and evicts the file iff IT is bad).
                    del self._mem[key]
                    meta = self._meta.get(key)
                    on_disk = meta is not None and meta.on_disk
                    self._record_eviction(
                        "checksum mismatch", "memory", "", key=key,
                        message=(
                            f"solution store: entry {key} failed "
                            "checksum verification in the memory tier; "
                            "dropping the in-memory copy"
                            + (" and retrying the disk tier" if on_disk
                               else "")),
                        stacklevel=3)
                    if not on_disk:
                        self._meta.pop(key, None)
                        return None
                else:
                    self._mem.move_to_end(key)
                    return sol
            meta = self._meta.get(key)
            if meta is None or not meta.on_disk:
                return None
            path = self._file(key)
            try:
                sol = load_pytree(path, _template())
            except CORRUPT_NPZ_ERRORS as e:
                self._evict_corrupt(path, f"unreadable ({e})", key=key)
                return None
            if (schema_ck is not None
                    and int(sol.schema_ck) != int(schema_ck)):
                self._evict_corrupt(path, "stale row schema", key=key)
                return None
            if not self._verified(sol):
                self._evict_corrupt(path, "checksum mismatch", key=key)
                return None
            self._insert(key, sol)
            return sol

    def put(self, sol: StoredSolution) -> None:
        """Insert (or refresh) one solution.  Failed statuses are refused
        loudly — caching an uncertified result is a caller bug."""
        status = int(sol.status)
        if is_failure(status):
            raise ValueError(
                f"refusing to store a failed solution (status={status}); "
                "failures raise on their future, they are never cached")
        key = int(sol.key)
        with self._lock:
            on_disk = False
            if self.disk_path is not None:
                try:
                    save_pytree(self._file(key), sol)
                    on_disk = True
                except OSError as e:
                    warnings.warn(f"solution store: could not persist entry "
                                  f"{key}: {e}", stacklevel=2)
            prior = self._meta.get(key)
            if prior is not None and prior.on_disk:
                on_disk = True
            self._meta[key] = _Meta(
                cell=tuple(np.asarray(sol.cell, dtype=np.float64)),
                group=int(sol.group),
                r_star=float(sol.root), on_disk=on_disk,
                cert_level=int(sol.cert_level),
                schema_ck=int(sol.schema_ck))
            self._insert(key, sol)

    def _insert(self, key: int, sol: StoredSolution) -> None:
        self._mem[key] = sol
        self._mem.move_to_end(key)
        while len(self._mem) > self.capacity:
            old_key, _ = self._mem.popitem(last=False)
            meta = self._meta.get(old_key)
            if meta is not None and not meta.on_disk:
                # memory-only tier: eviction forgets the entry entirely
                # (bounded memory is the contract); with a disk tier the
                # index row stays so the entry remains addressable
                del self._meta[old_key]

    # -- donor nomination ---------------------------------------------------

    def nominate(self, cell, group: int, width: float,
                 r_tol: float, scale=None) -> Optional[Donation]:
        """Warm-start donor for ``cell`` within solver group ``group``:
        target = nearest stored root in normalized (σ, ρ, sd) space,
        margin = the r*-spread between the two nearest donors (how far the
        root plausibly moved), floored defensively — LITERALLY the sweep
        scheduler's neighbor rule (``parallel.sweep.neighbor_distance`` /
        ``donor_margin``, one shared implementation) pointed at the store.
        ``width`` is the economic bracket width and ``r_tol`` the
        bisection tolerance of the *querying* configuration; ``scale``
        the querying scenario's ``CellSpace.scale`` (None = the Aiyagari
        lattice normalization).  None when the group holds no donors (or
        none inside ``donor_cutoff``)."""
        from ..parallel.sweep import (
            NEIGHBOR_CELL_SCALE,
            donor_margin,
            neighbor_distance,
        )

        if scale is None:
            scale = NEIGHBOR_CELL_SCALE
        with self._lock:
            rows = [(k, m) for k, m in self._meta.items()
                    if m.group == int(group) and np.isfinite(m.r_star)]
        if not rows:
            return None
        d = neighbor_distance(cell, np.asarray([m.cell for _, m in rows]),
                              scale=scale)
        order = np.argsort(d, kind="stable")
        if float(d[order[0]]) > self.donor_cutoff:
            return None
        k0, m0 = rows[int(order[0])]
        target = float(m0.r_star)
        spread = (abs(target - float(rows[int(order[1])][1].r_star))
                  if len(rows) > 1 else None)
        return Donation(target=target,
                        margin=donor_margin(spread, width, r_tol),
                        donor_key=int(k0))

    def nearest(self, cell, group: int,
                require_certified: bool = False, scale=None):
        """Nearest stored neighbor of ``cell`` within solver group
        ``group`` in normalized (σ, ρ, sd) space — the degraded-answer
        donor (ISSUE 8, DESIGN §11).  Returns ``(key, distance)`` or
        None.

        Unlike ``nominate`` this proposes no bracket: the caller serves
        the donor's OWN row, tagged degraded, so the donor must be a
        real addressable entry — fetch it with ``get(key)``, which
        re-verifies the content checksum (a corrupt donor degrades to a
        miss, never to a served wrong answer).  With
        ``require_certified`` only donors carrying a CERTIFIED/MARGINAL
        ``verify`` certificate qualify (an UNCERTIFIED entry from a
        service running without ``certify_before_cache`` is skipped)."""
        from ..parallel.sweep import (
            NEIGHBOR_CELL_SCALE,
            neighbor_distance,
        )

        if scale is None:
            scale = NEIGHBOR_CELL_SCALE
        with self._lock:
            rows = [(k, m) for k, m in self._meta.items()
                    if m.group == int(group) and np.isfinite(m.r_star)
                    and (not require_certified or m.cert_level >= 0)]
        if not rows:
            return None
        d = neighbor_distance(cell, np.asarray([m.cell for _, m in rows]),
                              scale=scale)
        i = int(np.argmin(d))
        return int(rows[i][0]), float(d[i])

    # -- introspection ------------------------------------------------------

    def __len__(self) -> int:
        """In-memory (LRU-bounded) entry count."""
        with self._lock:
            return len(self._mem)

    def known(self) -> int:
        """Addressable entries across both tiers."""
        with self._lock:
            return len(self._meta)

    def mem_keys(self) -> list:
        """Memory-tier keys in LRU order (oldest first) — test hook for
        the eviction-order contract."""
        with self._lock:
            return list(self._mem.keys())

    def integrity_counts(self) -> dict:
        """Integrity counters for ``ServeMetrics`` (DESIGN §9):
        ``store_corrupt_evictions`` is the number of entries that failed
        checksum/format verification and were evicted (+ file deleted)."""
        with self._lock:
            return {"store_corrupt_evictions": self._corrupt_evictions}
