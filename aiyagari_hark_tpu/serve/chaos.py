"""Deterministic fleet fault injection (ISSUE 16, DESIGN §14).

The single-process engine already injects every failure it types
(``solver_health.inject_fault`` for NaN/stall, the loadgen's overload
regimes, corrupt-entry tests for the checksum chain).  The fleet tier's
failure domain — processes dying mid-election, heartbeats stalling,
partitions, skewed clocks — had no injector: the SIGTERM drill was the
only scripted fault.  This module closes that gap in two halves:

* **worker side** — ``ChaosAgent``, an armable fault surface the shared
  ``SolutionStore`` consults at exactly four seams: publish delay (hold
  a lease mid-"solve" so a kill/stall drill has a deterministic window),
  heartbeat stall (owner alive but not refreshing — the zombie-winner
  regime), transient disk-read partition (reads fail N times, the entry
  is NOT evicted — transient is not corrupt), and wall-clock skew
  applied to staleness judgments (the duplicated-election regime).
  Faults are armed over HTTP (``POST /chaos``, gated by the worker's
  ``--chaos`` flag) and every actual firing is journaled
  ``FLEET_CHAOS_INJECT`` — the harness counts *fired* injections, not
  armed intentions, so detected==injected is a real ledger.

* **harness side** — ``ChaosPlan`` + ``run_drills``: scripted drills
  against a LIVE worker pool (real processes, real HTTP, real store),
  each drill asserting the invariant the fleet claims: the query is
  still answered, the answer is bit-identical across every server that
  ever serves that fingerprint, leases do not leak, and the fault left
  a journal trail.  Expected duplicate publishes (a stalled winner's
  late publish, a skew-forced double election) are *accounted*, not
  hidden: the drill ledger separates them from protocol violations so
  the dedup invariant stays falsifiable.

Drill taxonomy (DESIGN §14): ``torn_publish`` (reader-side corrupt
entry: evict + re-solve), ``partition`` (transient read faults degrade
to a miss, never an eviction), ``worker_kill`` (SIGKILL mid-solve; TTL
reclaim re-elects), ``heartbeat_stall`` (live-but-silent winner loses
its claim; its late publish is bit-identical and its late release is
owner-checked away), ``clock_skew`` (a reclaimer running ``ttl×4``
ahead steals a fresh lease; both solves publish the same bits).
"""

from __future__ import annotations

import os
import signal
import threading
import time
from typing import NamedTuple, Optional, Tuple

from ..obs.runtime import NULL_OBS

# one spelling for "the same lattice cell" across HTTP/JSON hops
def _cell_token(cell) -> tuple:
    return tuple(round(float(c), 9) for c in cell)


class ChaosAgent:
    """Worker-side armable fault surface.  Thread-safe; consulted by the
    shared store at its chaos seams (``SolutionStore.set_chaos``).  Every
    fault that actually FIRES is journaled ``FLEET_CHAOS_INJECT`` by
    ``fire`` — arming alone journals nothing."""

    def __init__(self, obs=None, owner: str = ""):
        # reentrant: ``arm`` reports the armed state back via ``armed``
        # while still holding the lock
        self._lock = threading.RLock()
        self._obs = obs if obs is not None else NULL_OBS
        self.owner = str(owner)
        self._slow_publish_s = 0.0
        self._slow_cells: set = set()
        self._heartbeat_stall = False
        self._stall_fired = False
        self._partition_reads = 0
        self._lease_skew_s = 0.0
        self._skew_fired = False
        self._fired = 0

    def fire(self, drill: str, **fields) -> None:
        """Journal one actual fault firing (the detection ledger's
        injected side).  Seam-covered by ``check_obs_events``."""
        with self._lock:
            self._fired += 1
        self._obs.event("FLEET_CHAOS_INJECT", drill=str(drill),
                        owner=self.owner, **fields)

    def arm(self, cfg: dict) -> dict:
        """Adopt a fault configuration (the ``POST /chaos`` body); keys
        absent from ``cfg`` are left armed as-is, explicit zeros/False
        disarm.  Returns the armed state."""
        with self._lock:
            if "slow_publish_s" in cfg:
                self._slow_publish_s = float(cfg["slow_publish_s"])
            if "slow_cells" in cfg:
                self._slow_cells = {_cell_token(c)
                                    for c in cfg["slow_cells"]}
            if "heartbeat_stall" in cfg:
                self._heartbeat_stall = bool(cfg["heartbeat_stall"])
                self._stall_fired = False
            if "partition_reads" in cfg:
                self._partition_reads = int(cfg["partition_reads"])
            if "lease_skew_s" in cfg:
                self._lease_skew_s = float(cfg["lease_skew_s"])
                self._skew_fired = False
            if "disk_fault" in cfg:
                # ISSUE 18: arm the checkpoint writers' deterministic
                # ENOSPC/EIO seam inside THIS worker process (the
                # disk-full-publish drill); falsy disarms.
                from ..utils.checkpoint import (arm_disk_fault,
                                                disarm_disk_faults)

                df = cfg["disk_fault"]
                if df:
                    arm_disk_fault(df["op"],
                                   kind=df.get("kind", "ENOSPC"),
                                   count=int(df.get("count", 1)),
                                   match=df.get("match", ""))
                else:
                    disarm_disk_faults()
            return self.armed()

    def armed(self) -> dict:
        with self._lock:
            return {"slow_publish_s": self._slow_publish_s,
                    "slow_cells": [list(c) for c in
                                   sorted(self._slow_cells)],
                    "heartbeat_stall": self._heartbeat_stall,
                    "partition_reads": self._partition_reads,
                    "lease_skew_s": self._lease_skew_s,
                    "fired": self._fired}

    # -- the store's seams --------------------------------------------------

    def publish_delay_s(self, cell) -> float:
        """Seconds to hold the lease before a publish of ``cell`` (the
        kill/stall drills' deterministic mid-solve window); 0 when the
        cell is not armed."""
        with self._lock:
            if (self._slow_publish_s <= 0.0
                    or _cell_token(cell) not in self._slow_cells):
                return 0.0
            d = self._slow_publish_s
        self.fire("slow_publish", cell=list(_cell_token(cell)),
                  delay_s=d)
        return d

    def heartbeat_stalled(self) -> bool:
        """True while the heartbeat-stall fault is armed: the store's
        refresh loop skips its beats (owner alive, lease aging)."""
        with self._lock:
            stalled = self._heartbeat_stall
            first = stalled and not self._stall_fired
            if first:
                self._stall_fired = True
        if first:
            self.fire("heartbeat_stall")
        return stalled

    def read_fault(self, key: int) -> bool:
        """Consume one transient disk-read fault (the partition window);
        True = this read must fail WITHOUT evicting anything."""
        with self._lock:
            if self._partition_reads <= 0:
                return False
            self._partition_reads -= 1
        self.fire("partition", key=int(key))
        return True

    def skew_now(self) -> Optional[float]:
        """A skewed wall-clock ``now`` for staleness judgments, or None
        when no skew is armed.  The skewed clock IS the injected fault —
        a reclaimer running ahead by more than ttl + tolerance steals a
        live lease (the duplicated-election drill)."""
        with self._lock:
            skew = self._lease_skew_s
            first = skew != 0.0 and not self._skew_fired
            if first:
                self._skew_fired = True
        if skew == 0.0:
            return None
        if first:
            self.fire("clock_skew", skew_s=skew)
        return time.time() + skew  # timing-ok: the skewed wall IS the injected fault


# -- harness side -----------------------------------------------------------

DRILLS = ("torn_publish", "partition", "worker_kill",
          "heartbeat_stall", "clock_skew")


class ChaosPlan(NamedTuple):
    """One scripted chaos campaign over a live fleet.

    ``drills`` run SEQUENTIALLY after the main traffic replay, each on
    its own dedicated cell from ``drill_cells`` (disjoint from the
    traffic lattice, so drill duplicates never contaminate the clean
    dedup ledger); ``churn`` is the elasticity schedule applied DURING
    the replay: ``(after_total_dispatches, "leave"|"join",
    worker_index_or_None)`` — leave SIGTERMs, join spawns a fresh worker
    into the pool.  ``slow_publish_s`` must comfortably exceed the
    harness's observe-then-act window (poll /fleet, send the signal);
    ``settle_timeout_s`` bounds every wait-for-recovery loop."""

    drills: Tuple[str, ...] = DRILLS
    drill_cells: Tuple[Tuple[float, float, float], ...] = ()
    churn: Tuple[Tuple[int, str, Optional[int]], ...] = ()
    slow_publish_s: float = 8.0
    partition_reads: int = 2
    recovery_queries: int = 6
    settle_timeout_s: float = 60.0


class DrillError(RuntimeError):
    """A drill could not even run (no live victim, arming failed) —
    distinct from a drill that ran and was not detected."""


def _poll_until(pred, timeout_s: float, interval_s: float = 0.02) -> bool:
    from ..utils.timing import Stopwatch

    watch = Stopwatch()
    while watch.elapsed() < timeout_s:
        if pred():
            return True
        time.sleep(interval_s)
    return pred()


def run_drills(plan: ChaosPlan, ctl) -> dict:
    """Execute every drill in ``plan`` against the live fleet behind
    ``ctl`` (the loadgen's ``FleetCtl``) and return the chaos ledger:

    ``{"drills": [per-drill records], "injected": n, "detected": n,
    "expected_dup_keys": [...], "drill_keys": [...]}``

    Each drill record carries ``injected``/``detected`` (0/1), the
    drill key, and which evidence fired.  Detection is read from the
    workers' journals and process states — the same artifacts a
    postmortem would use — never from harness-private flags."""
    if len(plan.drill_cells) < len(plan.drills):
        raise ValueError(
            f"ChaosPlan needs one drill cell per drill "
            f"({len(plan.drills)} drills, {len(plan.drill_cells)} cells)")
    records = []
    expected_dup: list = []
    drill_keys: list = []
    runners = {"torn_publish": _drill_torn_publish,
               "partition": _drill_partition,
               "worker_kill": _drill_worker_kill,
               "heartbeat_stall": _drill_heartbeat_stall,
               "clock_skew": _drill_clock_skew}
    for i, name in enumerate(plan.drills):
        if name not in runners:
            raise ValueError(f"unknown drill {name!r} "
                             f"(known: {', '.join(DRILLS)})")
        rec = runners[name](plan, ctl, plan.drill_cells[i])
        rec["drill"] = name
        records.append(rec)
        if rec.get("key") is not None:
            drill_keys.append(int(rec["key"]))
        if rec.get("expected_dup"):
            expected_dup.append(int(rec["key"]))
    return {"drills": records,
            "injected": sum(r["injected"] for r in records),
            "detected": sum(r["detected"] for r in records),
            "expected_dup_keys": expected_dup,
            "drill_keys": drill_keys}


def _journal_events(ctl, event: str, key: Optional[int] = None) -> list:
    from ..obs.journal import read_journal

    out = []
    for jp in list(ctl.journal_paths):
        if not os.path.exists(jp):
            continue
        for ev in read_journal(jp, event=event):
            if key is None or ev.get("key") == int(key):
                out.append(ev)
    return out


def _value_fields(res: dict) -> tuple:
    return (res["r_star"], res["capital"], res["labor"], res["status"])


def _arm(ctl, worker: int, cfg: dict) -> None:
    resp = ctl.post(worker, "/chaos", cfg)
    if not resp.get("ok"):
        raise DrillError(f"arming worker {worker} failed: {resp}")


def _disarm(ctl, worker: int) -> None:
    if ctl.alive(worker):
        ctl.post(worker, "/chaos", {
            "slow_publish_s": 0.0, "slow_cells": [],
            "heartbeat_stall": False, "partition_reads": 0,
            "lease_skew_s": 0.0, "disk_fault": None})


def _drill_torn_publish(plan: ChaosPlan, ctl, cell) -> dict:
    """Reader-side torn entry: publish, corrupt the bytes on disk, and
    make a DIFFERENT worker serve the key — it must evict the garbage
    (``STORE_EVICT_CORRUPT``), re-solve, and re-publish the exact same
    bits."""
    first, second = ctl.two_live_workers()
    res0 = ctl.query(cell, prefer=first)
    key = int(res0["key"])
    path = os.path.join(ctl.store_dir,
                        f"sol_{_hex(key)}.npz")
    with open(path, "wb") as f:   # atomic-ok: the drill WRITES a torn entry
        f.write(b"torn-publish-drill: not an npz")
    res1 = ctl.query(cell, prefer=second)
    evicted = bool(_journal_events(ctl, "STORE_EVICT_CORRUPT", key=key))
    bits_equal = _value_fields(res0) == _value_fields(res1)
    republished = len(_journal_events(ctl, "FLEET_PUBLISH", key=key)) >= 2
    return {"injected": 1,
            "detected": int(evicted and bits_equal and republished),
            "key": key, "evicted": evicted, "bits_equal": bits_equal,
            "republished": republished, "expected_dup": True}


def _drill_partition(plan: ChaosPlan, ctl, cell) -> dict:
    """Transient store partition: the victim's next N disk reads fail.
    A read fault degrades to a MISS (journaled ``LEASE_BACKEND_FAULT``)
    — never an eviction: transient is not corrupt, and the entry must
    survive the window untouched."""
    first, victim = ctl.two_live_workers()
    res0 = ctl.query(cell, prefer=first)      # published, not in victim's RAM
    key = int(res0["key"])
    _arm(ctl, victim, {"partition_reads": int(plan.partition_reads)})
    try:
        res1 = ctl.query(cell, prefer=victim)
    finally:
        _disarm(ctl, victim)
    faults = [ev for ev in _journal_events(ctl, "LEASE_BACKEND_FAULT",
                                           key=key)
              if ev.get("op") == "disk_read"]
    survived = os.path.exists(os.path.join(ctl.store_dir,
                                           f"sol_{_hex(key)}.npz"))
    bits_equal = _value_fields(res0) == _value_fields(res1)
    return {"injected": 1,
            "detected": int(bool(faults) and survived and bits_equal),
            "key": key, "read_faults": len(faults),
            "entry_survived": survived, "bits_equal": bits_equal,
            "expected_dup": False}


def _drill_worker_kill(plan: ChaosPlan, ctl, cell) -> dict:
    """SIGKILL mid-solve: the victim wins the election, holds the lease
    inside an armed publish delay, and dies ungracefully.  The client's
    connection-level failover re-submits to a survivor, whose waiter
    path TTL-reclaims the orphaned lease and re-solves — the query is
    still answered, exactly once fleet-wide AFTER the reclaim."""
    victim, _ = ctl.two_live_workers()
    _arm(ctl, victim, {"slow_publish_s": float(plan.slow_publish_s),
                       "slow_cells": [list(cell)]})
    result: dict = {}

    def _ask():
        result["res"] = ctl.query(cell, prefer=victim)

    t = threading.Thread(target=_ask, name="chaos-kill-client")
    t.start()
    # observe the held lease through /fleet (the public surface), then kill
    held = _poll_until(lambda: ctl.fleet_info(victim) is not None
                       and len(ctl.fleet_info(victim)["held_leases"]) > 0,
                       plan.slow_publish_s * 0.75)
    ctl.kill(victim, signal.SIGKILL)
    t.join(plan.settle_timeout_s)
    res = result.get("res")
    key = None if res is None else int(res["key"])
    rc = ctl.returncode(victim)
    reclaimed = (key is not None
                 and bool(_journal_events(ctl, "FLEET_LEASE_RECLAIM",
                                          key=key)))
    return {"injected": 1,
            "detected": int(held and rc == -int(signal.SIGKILL)
                            and reclaimed and res is not None),
            "key": key, "lease_observed_held": held, "victim_rc": rc,
            "reclaimed": reclaimed, "answered": res is not None,
            "expected_dup": False}


def _drill_heartbeat_stall(plan: ChaosPlan, ctl, cell) -> dict:
    """Zombie winner: alive, holding the lease, not beating.  A peer
    TTL-reclaims and re-solves; the stalled winner's LATE publish lands
    the same bits (deterministic solve) and its late release is
    owner-checked into a no-op — the peer's claim is never deleted out
    from under it."""
    victim, peer = ctl.two_live_workers()
    _arm(ctl, victim, {"heartbeat_stall": True,
                       "slow_publish_s": float(plan.slow_publish_s),
                       "slow_cells": [list(cell)]})
    result: dict = {}

    def _ask():
        result["res"] = ctl.query(cell, prefer=victim)

    t = threading.Thread(target=_ask, name="chaos-stall-client")
    t.start()
    try:
        _poll_until(lambda: ctl.fleet_info(victim) is not None
                    and len(ctl.fleet_info(victim)["held_leases"]) > 0,
                    plan.slow_publish_s * 0.75)
        # the peer's claim loses to the stalled-but-unbeating lease and
        # its waiter path TTL-reclaims once the missing beats age it out
        res_peer = ctl.query(cell, prefer=peer)
        key = int(res_peer["key"])
        t.join(plan.settle_timeout_s)
    finally:
        _disarm(ctl, victim)
    res_victim = result.get("res")
    reclaimed = bool(_journal_events(ctl, "FLEET_LEASE_RECLAIM",
                                     key=key))
    victim_alive = ctl.alive(victim)
    bits_equal = (res_victim is not None
                  and _value_fields(res_victim)
                  == _value_fields(res_peer))
    return {"injected": 1,
            "detected": int(reclaimed and victim_alive and bits_equal),
            "key": key, "reclaimed": reclaimed,
            "victim_alive": victim_alive, "bits_equal": bits_equal,
            "expected_dup": True}


def _drill_clock_skew(plan: ChaosPlan, ctl, cell) -> dict:
    """Duplicated election under skew: the victim holds a FRESH lease;
    a peer whose staleness clock runs ``ttl×4`` ahead judges it stale,
    reclaims, and solves in parallel.  The election invariant is
    violated by construction — the drill verifies the violation is
    SAFE: both publishes carry identical bits and no lease leaks."""
    victim, skewed = ctl.two_live_workers()
    _arm(ctl, victim, {"slow_publish_s": float(plan.slow_publish_s),
                       "slow_cells": [list(cell)]})
    _arm(ctl, skewed, {"lease_skew_s": 4.0 * ctl.lease_ttl_s})
    result: dict = {}

    def _ask():
        result["res"] = ctl.query(cell, prefer=victim)

    t = threading.Thread(target=_ask, name="chaos-skew-client")
    t.start()
    try:
        _poll_until(lambda: ctl.fleet_info(victim) is not None
                    and len(ctl.fleet_info(victim)["held_leases"]) > 0,
                    plan.slow_publish_s * 0.75)
        res_skewed = ctl.query(cell, prefer=skewed)
        key = int(res_skewed["key"])
        t.join(plan.settle_timeout_s)
    finally:
        _disarm(ctl, victim)
        _disarm(ctl, skewed)
    res_victim = result.get("res")
    reclaims = _journal_events(ctl, "FLEET_LEASE_RECLAIM", key=key)
    injects = [ev for ev in _journal_events(ctl, "FLEET_CHAOS_INJECT")
               if ev.get("drill") == "clock_skew"]
    bits_equal = (res_victim is not None
                  and _value_fields(res_victim)
                  == _value_fields(res_skewed))
    return {"injected": 1,
            "detected": int(bool(reclaims) and bool(injects)
                            and bits_equal),
            "key": key, "reclaimed": bool(reclaims),
            "skew_fired": bool(injects), "bits_equal": bits_equal,
            "expected_dup": True}


# -- disaster-recovery drills (ISSUE 18, DESIGN §16) ------------------------
#
# The ISSUE 16 drills above attack the WORKERS; these attack the
# COORDINATION SUBSTRATE itself — the replicated CAS quorum the fleet's
# exactly-once election now rides on.  Same contract: injection through
# a public surface (signals, the replica wire protocol, the /chaos
# endpoint), detection ONLY from public artifacts (journals, /fleet,
# process return codes, served bits).

DR_DRILLS = ("replica_kill", "torn_wal_tail", "snapshot_mid_write",
             "minority_partition", "disk_full_publish")


class DRPlan(NamedTuple):
    """One disaster-recovery campaign over a live fleet + its replica
    set.  ``drill_cells`` must be disjoint from the traffic lattice
    (drill re-publishes carry their own accounting); each drill that
    needs a SECOND fresh fingerprint derives it by perturbing its cell's
    labor-sd, staying off-lattice.  ``mutation_budget`` bounds the
    synthetic lease traffic the snapshot drill drives to force a
    compaction."""

    drills: Tuple[str, ...] = DR_DRILLS
    drill_cells: Tuple[Tuple[float, float, float], ...] = ()
    settle_timeout_s: float = 60.0
    mutation_budget: int = 160


def run_dr_drills(plan: DRPlan, ctl, replicas) -> dict:
    """Execute every DR drill against the live fleet behind ``ctl``
    (loadgen ``FleetCtl``) coordinated by ``replicas`` (a
    ``serve.replicated.ReplicaSet``); returns the same ledger shape as
    ``run_drills``."""
    if len(plan.drill_cells) < len(plan.drills):
        raise ValueError(
            f"DRPlan needs one drill cell per drill "
            f"({len(plan.drills)} drills, {len(plan.drill_cells)} cells)")
    runners = {"replica_kill": _dr_replica_kill,
               "torn_wal_tail": _dr_torn_wal_tail,
               "snapshot_mid_write": _dr_snapshot_mid_write,
               "minority_partition": _dr_minority_partition,
               "disk_full_publish": _dr_disk_full_publish}
    records = []
    expected_dup: list = []
    drill_keys: list = []
    for i, name in enumerate(plan.drills):
        if name not in runners:
            raise ValueError(f"unknown DR drill {name!r} "
                             f"(known: {', '.join(DR_DRILLS)})")
        rec = runners[name](plan, ctl, replicas, plan.drill_cells[i])
        rec["drill"] = name
        records.append(rec)
        for k in rec.get("keys", ()):
            drill_keys.append(int(k))
            if rec.get("expected_dup"):
                expected_dup.append(int(k))
    return {"drills": records,
            "injected": sum(r["injected"] for r in records),
            "detected": sum(r["detected"] for r in records),
            "expected_dup_keys": expected_dup,
            "drill_keys": drill_keys}


def _replica_events(replicas, event: str, i: Optional[int] = None) -> list:
    """Journal events from the replica processes' own journals (the
    coordination substrate's public artifact trail)."""
    from ..obs.journal import read_journal

    paths = (replicas.journals if i is None else [replicas.journals[i]])
    out = []
    for jp in paths:
        if os.path.exists(jp):
            out.extend(read_journal(jp, event=event))
    return out


def _second_cell(cell) -> tuple:
    """A fresh off-lattice fingerprint adjacent to ``cell`` (drills that
    need two never-queried cells)."""
    return (float(cell[0]), float(cell[1]), float(cell[2]) + 1e-3)


def _dr_replica_kill(plan: DRPlan, ctl, replicas, cell) -> dict:
    """SIGKILL one replica: a 3-replica quorum keeps electing with 2,
    and the restarted replica recovers its exact map from WAL+snapshot
    (``WAL_REPLAY`` in its own journal)."""
    victim = replicas.n - 1
    replays_before = len(_replica_events(replicas, "WAL_REPLAY",
                                         i=victim))
    replicas.kill(victim, signal.SIGKILL)
    rc_ok = _poll_until(
        lambda: replicas.returncode(victim) == -int(signal.SIGKILL),
        plan.settle_timeout_s)
    res = ctl.query(cell)            # cold election on a 2/3 quorum
    key = int(res["key"])
    replicas.restart(victim)
    replayed = len(_replica_events(replicas, "WAL_REPLAY",
                                   i=victim)) > replays_before
    return {"injected": 1,
            "detected": int(rc_ok and replayed),
            "keys": [key], "victim_rc": replicas.returncode(victim),
            "answered": True, "replayed": replayed,
            "expected_dup": False}


def _dr_torn_wal_tail(plan: DRPlan, ctl, replicas, cell) -> dict:
    """Hard-kill a replica and tear its WAL tail (the partial final
    record a crash mid-append leaves): recovery must skip EXACTLY that
    record, loudly (``WAL_REPLAY`` with ``torn_skipped >= 1``), and
    serve every earlier acknowledged mutation."""
    victim = replicas.n - 1
    res0 = ctl.query(cell)           # ensure there is real CAS history
    key = int(res0["key"])
    replicas.kill(victim, signal.SIGKILL)
    _poll_until(lambda: replicas.returncode(victim) is not None,
                plan.settle_timeout_s)
    wal = os.path.join(replicas.data_dirs[victim], "cas.wal")
    with open(wal, "ab") as f:   # atomic-ok: the drill WRITES a torn tail
        f.write(b'{"seq":999999999,"k":1,"o":"torn')
    replicas.restart(victim)
    torn = [ev for ev in _replica_events(replicas, "WAL_REPLAY",
                                         i=victim)
            if ev.get("torn_skipped", 0) >= 1]
    res1 = ctl.query(_second_cell(cell))   # quorum still elects
    return {"injected": 1,
            "detected": int(bool(torn)),
            "keys": [key, int(res1["key"])],
            "torn_detected": bool(torn), "answered": True,
            "expected_dup": False}


def _dr_snapshot_mid_write(plan: DRPlan, ctl, replicas, cell) -> dict:
    """ENOSPC exactly at a replica's snapshot write (armed over the
    wire through the replica's own ``inject_fault`` op, fired by real
    compaction pressure): the replica journals ``DISK_FAULT``, keeps
    serving from memory + WAL, and the next compaction window retries."""
    from .lease import LoopbackCASBackend

    victim = 0
    cli = LoopbackCASBackend(f"127.0.0.1:{replicas.ports[victim]}")
    base = 0x5D15C000_00000000   # synthetic drill keys, off any lattice
    try:
        cli.inject_fault("atomic_write_json", kind="ENOSPC", count=1,
                         match="cas.snapshot")
        fired = False
        for i in range(int(plan.mutation_budget)):
            cli.try_acquire(base + i, "dr-snapshot-drill")
            cli.release(base + i, owner="dr-snapshot-drill")
            fired = bool(_replica_events(replicas, "DISK_FAULT",
                                         i=victim))
            if fired:
                break
        still_serving = cli.try_acquire(base + 999_999,
                                        "dr-snapshot-drill")
        cli.release(base + 999_999, owner="dr-snapshot-drill")
    finally:
        cli.close()
    res = ctl.query(cell)
    return {"injected": 1,
            "detected": int(fired and still_serving),
            "keys": [int(res["key"])], "fault_journaled": fired,
            "replica_served_after": bool(still_serving),
            "answered": True, "expected_dup": False}


def _dr_minority_partition(plan: DRPlan, ctl, replicas, cell) -> dict:
    """Client-side partition in two acts.  Minority unreachable: the
    worker keeps electing (quorum holds).  Majority unreachable: the
    worker's claims degrade TYPED (``QUORUM_LOST`` +
    ``LEASE_BACKEND_FAULT`` journaled, query parked); healing the
    partition lets the parked election win, and first contact with the
    returning replicas anti-entropy-resyncs them
    (``REPLICA_RESYNC``)."""
    worker, _ = ctl.two_live_workers()
    n = replicas.n
    # act 1: minority gone — still serves
    _arm(ctl, worker, {"partition_replicas": [n - 1]})
    res1 = ctl.query(cell, prefer=worker)
    key1 = int(res1["key"])
    # act 2: majority gone — typed degrade, then heal.  Counts are
    # taken before/after: earlier drills' replica restarts already left
    # resync events, and detection must be THIS drill's evidence.
    lost0 = len(_journal_events(ctl, "QUORUM_LOST"))
    resync0 = len(_journal_events(ctl, "REPLICA_RESYNC"))
    _arm(ctl, worker, {"partition_replicas": list(range(1, n))})
    cell2 = _second_cell(cell)
    result: dict = {}

    def _ask():
        try:
            result["res"] = ctl.query(cell2, prefer=worker)
        except Exception as e:
            result["err"] = e

    t = threading.Thread(target=_ask, name="dr-partition-client")
    t.start()
    try:
        lost = _poll_until(
            lambda: len(_journal_events(ctl, "QUORUM_LOST")) > lost0,
            plan.settle_timeout_s)
    finally:
        _arm(ctl, worker, {"partition_replicas": []})
    t.join(plan.settle_timeout_s)
    res2 = result.get("res")
    faults = [ev for ev in _journal_events(ctl, "LEASE_BACKEND_FAULT")
              if "CoordinationUnavailable" in str(ev.get("detail", ""))]
    resynced = len(_journal_events(ctl, "REPLICA_RESYNC")) > resync0
    keys = [key1] + ([] if res2 is None else [int(res2["key"])])
    return {"injected": 1,
            "detected": int(lost and bool(faults) and res2 is not None
                            and resynced),
            "keys": keys, "answered_minority": True,
            "quorum_lost_journaled": lost,
            "typed_degrades": len(faults),
            "answered_after_heal": res2 is not None,
            "resynced": resynced, "expected_dup": False}


def _dr_disk_full_publish(plan: DRPlan, ctl, replicas, cell) -> dict:
    """ENOSPC at a worker's store publish: the entry degrades to
    memory-only (``STORE_DEGRADED`` journaled), the query is still
    answered, and a peer re-solves the key onto healthy disk with
    bit-identical values."""
    victim, peer = ctl.two_live_workers()
    _arm(ctl, victim, {"disk_fault": {"op": "save_pytree",
                                      "kind": "ENOSPC", "count": 1,
                                      "match": "sol_"}})
    try:
        res0 = ctl.query(cell, prefer=victim)
    finally:
        _disarm(ctl, victim)
    key = int(res0["key"])
    degraded = bool(_journal_events(ctl, "STORE_DEGRADED", key=key))
    res1 = ctl.query(cell, prefer=peer)    # peer re-solves onto disk
    bits_equal = _value_fields(res0) == _value_fields(res1)
    republished = len(_journal_events(ctl, "FLEET_PUBLISH",
                                      key=key)) >= 2
    survives = os.path.exists(os.path.join(
        ctl.store_dir, f"sol_{_hex(key)}.npz"))
    return {"injected": 1,
            "detected": int(degraded and bits_equal and republished
                            and survives),
            "keys": [key], "degraded_journaled": degraded,
            "bits_equal": bits_equal, "republished": republished,
            "entry_on_disk_after": survives, "expected_dup": True}


def _hex(key: int) -> str:
    from ..utils.fingerprint import fingerprint_hex

    return fingerprint_hex(key)
