"""Micro-batching for equilibrium queries: bounded queue, shape ladder.

The device economics (DESIGN §8): one vmapped launch amortizes dispatch
overhead across lanes, but XLA compiles one executable **per input
shape** — so admitting arbitrary batch sizes would compile an executable
per arrival pattern.  The batcher therefore pads every flush up to a small
**ladder** of fixed shapes (default: powers of two up to ``max_batch``),
so a warmed service owns exactly ``len(ladder)`` executables per solver
group and every later launch is a pure executable-cache hit.  Padded lanes
duplicate a real lane's inputs (identical bits, masked out at scatter) —
the sweep's padding rule.

Flush policy: a group flushes when it holds ``max_batch`` requests
(occupancy-bound) or when its oldest request has waited ``max_wait_s``
(latency-bound).  The clock is injectable, so the deadline machinery is
property-testable with a deterministic fake clock; the bounded queue
(``max_queue`` across groups) sheds load by blocking or raising
``ServeQueueFull``.

This module is deliberately generic: items are opaque (the service's
pending-request records), groups are opaque hashable keys (the service
uses (dtype, kwargs) — only same-configuration queries can share an
executable).  No jax imports."""

from __future__ import annotations

import threading
import time
from typing import Hashable, List, Optional, Tuple


class ServeQueueFull(RuntimeError):
    """The bounded request queue is at capacity and the caller asked not
    to block (or timed out blocking)."""


def default_ladder(max_batch: int) -> Tuple[int, ...]:
    """Powers of two up to and including ``max_batch``: the shape set a
    warmed service compiles, e.g. ``max_batch=8 -> (1, 2, 4, 8)``,
    ``max_batch=12 -> (1, 2, 4, 8, 12)``."""
    if max_batch < 1:
        raise ValueError(f"max_batch must be >= 1, got {max_batch}")
    ladder = []
    s = 1
    while s < max_batch:
        ladder.append(s)
        s *= 2
    ladder.append(max_batch)
    return tuple(ladder)


class MicroBatcher:
    """Collects requests per group behind a bounded queue and releases
    them as ladder-shaped batches on size or deadline."""

    def __init__(self, max_batch: int = 8, max_wait_s: float = 0.002,
                 max_queue: int = 1024,
                 ladder: Optional[Tuple[int, ...]] = None,
                 clock=time.monotonic):
        self.ladder = (default_ladder(max_batch) if ladder is None
                       else tuple(sorted(set(int(s) for s in ladder))))
        if not self.ladder or self.ladder[0] < 1:
            raise ValueError(f"invalid ladder {self.ladder}")
        self.max_batch = self.ladder[-1]
        if max_batch > self.max_batch:
            raise ValueError(
                f"max_batch={max_batch} exceeds the ladder's largest "
                f"shape {self.max_batch}; every flush must pad to a "
                "ladder shape")
        self.max_wait_s = float(max_wait_s)
        self.max_queue = int(max_queue)
        self.clock = clock
        self._cond = threading.Condition()
        self._groups: dict = {}     # group -> list of (item, t_enqueued)
        self._depth = 0

    def pad_to(self, n: int) -> int:
        """Smallest ladder shape >= n (the launch shape for n real lanes)."""
        for s in self.ladder:
            if s >= n:
                return s
        return self.max_batch

    def depth(self) -> int:
        with self._cond:
            return self._depth

    def offer(self, group: Hashable, item, block: bool = True,
              timeout: Optional[float] = None) -> None:
        """Enqueue one request.  At capacity: block (optionally up to
        ``timeout`` seconds of real time) or raise ``ServeQueueFull``."""
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        with self._cond:
            while self._depth >= self.max_queue:
                if not block:
                    raise ServeQueueFull(
                        f"serving queue at capacity ({self.max_queue})")
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    raise ServeQueueFull(
                        f"serving queue still at capacity "
                        f"({self.max_queue}) after {timeout:g}s")
                self._cond.wait(remaining)
            self._groups.setdefault(group, []).append((item, self.clock()))
            self._depth += 1
            self._cond.notify_all()

    def _pop_from(self, group: Hashable, n: int) -> list:
        entries = self._groups[group]
        taken = [item for item, _ in entries[:n]]
        rest = entries[n:]
        if rest:
            self._groups[group] = rest
        else:
            del self._groups[group]
        self._depth -= len(taken)
        self._cond.notify_all()
        return taken

    def pop_ready(self, now: Optional[float] = None) -> List[tuple]:
        """Batches due at ``now`` (default: the injected clock), as
        ``(group, [items...])`` — full groups first (oldest requests),
        then deadline-expired groups.  Non-blocking."""
        if now is None:
            now = self.clock()
        out = []
        with self._cond:
            for group in list(self._groups):
                while len(self._groups.get(group, ())) >= self.max_batch:
                    out.append((group, self._pop_from(group,
                                                      self.max_batch)))
                entries = self._groups.get(group)
                if entries and now - entries[0][1] >= self.max_wait_s:
                    out.append((group, self._pop_from(group,
                                                      self.max_batch)))
        return out

    def pop_all(self) -> List[tuple]:
        """Everything still queued, chunked at ``max_batch`` — the drain
        path (service shutdown)."""
        out = []
        with self._cond:
            for group in list(self._groups):
                while group in self._groups:
                    out.append((group, self._pop_from(group,
                                                      self.max_batch)))
        return out

    def next_deadline(self) -> Optional[float]:
        """Earliest instant (in clock units) a queued group becomes due,
        or None when the queue is empty."""
        with self._cond:
            oldest = [entries[0][1] for entries in self._groups.values()
                      if entries]
        if not oldest:
            return None
        return min(oldest) + self.max_wait_s

    def wait_ready(self, timeout: Optional[float] = None) -> List[tuple]:
        """Block (on real time) until at least one batch is due, then
        return the due batches; ``[]`` on timeout.  The worker thread's
        wait primitive — uses the injected clock only for deadlines, real
        time for the condition wait."""
        end = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while True:
                ready = self.pop_ready()
                if ready:
                    return ready
                nd = self.next_deadline()
                wait = None
                if nd is not None:
                    wait = max(0.0, nd - self.clock())
                if end is not None:
                    remaining = end - time.monotonic()
                    if remaining <= 0:
                        return []
                    wait = remaining if wait is None else min(wait,
                                                              remaining)
                self._cond.wait(wait)
