"""Micro-batching for equilibrium queries: bounded queue, shape ladder.

The device economics (DESIGN §8): one vmapped launch amortizes dispatch
overhead across lanes, but XLA compiles one executable **per input
shape** — so admitting arbitrary batch sizes would compile an executable
per arrival pattern.  The batcher therefore pads every flush up to a small
**ladder** of fixed shapes (default: powers of two up to ``max_batch``),
so a warmed service owns exactly ``len(ladder)`` executables per solver
group and every later launch is a pure executable-cache hit.  Padded lanes
duplicate a real lane's inputs (identical bits, masked out at scatter) —
the sweep's padding rule.

Flush policy: a group flushes when it holds ``max_batch`` requests
(occupancy-bound) or when its oldest request has waited ``max_wait_s``
(latency-bound).  The clock is injectable, so the deadline machinery is
property-testable with a deterministic fake clock; the bounded queue
(``max_queue`` across groups) sheds load by blocking or raising
``ServeQueueFull``.

This module is deliberately generic: items are opaque (the service's
pending-request records), groups are opaque hashable keys (the service
uses (dtype, kwargs) — only same-configuration queries can share an
executable).  No jax imports."""

from __future__ import annotations

import threading
import time
from typing import Hashable, List, Optional, Tuple


class ServeQueueFull(RuntimeError):
    """The bounded request queue is at capacity and the caller asked not
    to block (or timed out blocking).

    Carries the queue state at rejection time so callers can implement
    retry-after (ISSUE 8 satellite): ``depth`` (queued requests),
    ``max_queue`` (the bound), and ``oldest_wait_s`` (how long the
    oldest queued request has waited, in injected-clock units — a proxy
    for drain speed; None on an empty queue)."""

    def __init__(self, message: str, depth: Optional[int] = None,
                 max_queue: Optional[int] = None,
                 oldest_wait_s: Optional[float] = None):
        super().__init__(message)
        self.depth = depth
        self.max_queue = max_queue
        self.oldest_wait_s = oldest_wait_s


def default_ladder(max_batch: int) -> Tuple[int, ...]:
    """Powers of two up to and including ``max_batch``: the shape set a
    warmed service compiles, e.g. ``max_batch=8 -> (1, 2, 4, 8)``,
    ``max_batch=12 -> (1, 2, 4, 8, 12)``."""
    if max_batch < 1:
        raise ValueError(f"max_batch must be >= 1, got {max_batch}")
    ladder = []
    s = 1
    while s < max_batch:
        ladder.append(s)
        s *= 2
    ladder.append(max_batch)
    return tuple(ladder)


def shard_ladder(ladder: Tuple[int, ...], multiple: int) -> Tuple[int, ...]:
    """Round every ladder shape up to a multiple of ``multiple`` (the
    lane-axis device count) and dedupe, preserving order by size — the
    multi-chip ladder (ISSUE 11): every flush shape divides evenly
    across the mesh, so a sharded launch never needs a second padding
    pass and a warmed multi-chip service still owns ONE executable per
    (rounded) ladder shape per solver group.  ``multiple=1`` is the
    identity."""
    if multiple < 1:
        raise ValueError(f"shard multiple must be >= 1, got {multiple}")
    if multiple == 1:
        return tuple(ladder)
    return tuple(sorted({-(-int(s) // multiple) * multiple
                         for s in ladder}))


class MicroBatcher:
    """Collects requests per group behind a bounded queue and releases
    them as ladder-shaped batches on size or deadline."""

    def __init__(self, max_batch: int = 8, max_wait_s: float = 0.002,
                 max_queue: int = 1024,
                 ladder: Optional[Tuple[int, ...]] = None,
                 clock=time.monotonic, priority_of=None,
                 shard_multiple: int = 1):
        # shard_multiple (ISSUE 11): the lane-axis device count — every
        # ladder shape rounds UP to a multiple so flushes dispatch
        # evenly across a mesh (1 = unsharded, the identity)
        self.shard_multiple = int(shard_multiple)
        self.ladder = shard_ladder(
            default_ladder(max_batch) if ladder is None
            else tuple(sorted(set(int(s) for s in ladder))),
            self.shard_multiple)
        if not self.ladder or self.ladder[0] < 1:
            raise ValueError(f"invalid ladder {self.ladder}")
        self.max_batch = self.ladder[-1]
        if max_batch > self.max_batch:
            raise ValueError(
                f"max_batch={max_batch} exceeds the ladder's largest "
                f"shape {self.max_batch}; every flush must pad to a "
                "ladder shape")
        self.max_wait_s = float(max_wait_s)
        self.max_queue = int(max_queue)
        self.clock = clock
        # item -> priority class (int; LOWER = more important) for
        # ``shed_lowest``; None disables shedding (items stay opaque)
        self._priority_of = priority_of
        self._cond = threading.Condition()
        self._groups: dict = {}     # group -> list of (item, t_enqueued)
        self._depth = 0

    def pad_to(self, n: int) -> int:
        """Smallest ladder shape >= n (the launch shape for n real lanes)."""
        for s in self.ladder:
            if s >= n:
                return s
        return self.max_batch

    def depth(self) -> int:
        with self._cond:
            return self._depth

    def _oldest_wait(self, now: float) -> Optional[float]:
        """Wait of the oldest queued request in clock units (lock held)."""
        oldest = [entries[0][1] for entries in self._groups.values()
                  if entries]
        if not oldest:
            return None
        return now - min(oldest)

    def _full_error(self, message: str) -> ServeQueueFull:
        """A payload-carrying ``ServeQueueFull`` (lock held)."""
        now = self.clock()
        return ServeQueueFull(message, depth=self._depth,
                              max_queue=self.max_queue,
                              oldest_wait_s=self._oldest_wait(now))

    def offer(self, group: Hashable, item, block: bool = True,
              timeout: Optional[float] = None) -> None:
        """Enqueue one request.  At capacity: block (optionally up to
        ``timeout``) or raise ``ServeQueueFull`` (carrying depth /
        max_queue / oldest-wait so callers can retry-after).

        The block timeout is measured on the INJECTED clock (ISSUE 8
        satellite) so backpressure is property-testable with a fake
        clock — advance the clock past the timeout and ``kick()`` to
        wake the blocked caller deterministically.  An equal real-time
        backstop still bounds the wait when the injected clock is the
        real one (they coincide) or has stalled (a fake clock nobody
        advances must not block a caller forever)."""
        t0 = self.clock()
        real_deadline = (None if timeout is None
                         else time.monotonic() + timeout)  # timing-ok: real-time backstop, not a measured wall
        with self._cond:
            while self._depth >= self.max_queue:
                if not block:
                    raise self._full_error(
                        f"serving queue at capacity ({self.max_queue})")
                if timeout is not None:
                    clock_left = timeout - (self.clock() - t0)
                    real_left = real_deadline - time.monotonic()  # timing-ok: backstop deadline check
                    if clock_left <= 0 or real_left <= 0:
                        raise self._full_error(
                            f"serving queue still at capacity "
                            f"({self.max_queue}) after {timeout:g}s")
                    self._cond.wait(min(clock_left, real_left))
                else:
                    self._cond.wait(None)
            self._groups.setdefault(group, []).append((item, self.clock()))
            self._depth += 1
            self._cond.notify_all()

    def kick(self) -> None:
        """Wake every blocked ``offer``/``wait_ready`` so it re-reads the
        injected clock — pair with fake-clock advances in tests and the
        load harness."""
        with self._cond:
            self._cond.notify_all()

    def shed_lowest(self, max_class: Optional[int] = None):
        """Remove and return the single most-sheddable queued request as
        ``(group, item)``: the one in the numerically-HIGHEST (least
        important) priority class, youngest within the class — shedding
        the youngest wastes the least accumulated waiting (ISSUE 8 shed
        ordering).  Only items whose class is STRICTLY greater than
        ``max_class`` (the displacing arrival's class) are eligible.
        None when nothing is sheddable or no ``priority_of`` was given."""
        if self._priority_of is None:
            return None
        with self._cond:
            best = None          # ((class, t_enqueued), group, index)
            for group, entries in self._groups.items():
                for idx, (item, t) in enumerate(entries):
                    c = int(self._priority_of(item))
                    if max_class is not None and c <= int(max_class):
                        continue
                    key = (c, t)
                    if best is None or key > best[0]:
                        best = (key, group, idx)
            if best is None:
                return None
            _, group, idx = best
            item, _t = self._groups[group].pop(idx)
            if not self._groups[group]:
                del self._groups[group]
            self._depth -= 1
            self._cond.notify_all()
            return group, item

    def ready(self, now: Optional[float] = None) -> bool:
        """True iff ``pop_ready(now)`` would release at least one batch
        (a full group, or an oldest request past ``max_wait_s``) —
        non-destructive, for harnesses scheduling around the batcher."""
        if now is None:
            now = self.clock()
        with self._cond:
            for entries in self._groups.values():
                if len(entries) >= self.max_batch:
                    return True
                # same boundary arithmetic as pop_ready/next_deadline
                if entries and now >= entries[0][1] + self.max_wait_s:
                    return True
        return False

    def _pop_from(self, group: Hashable, n: int) -> list:
        entries = self._groups[group]
        taken = [item for item, _ in entries[:n]]
        rest = entries[n:]
        if rest:
            self._groups[group] = rest
        else:
            del self._groups[group]
        self._depth -= len(taken)
        self._cond.notify_all()
        return taken

    def pop_ready(self, now: Optional[float] = None) -> List[tuple]:
        """Batches due at ``now`` (default: the injected clock), as
        ``(group, [items...])`` — full groups first (oldest requests),
        then deadline-expired groups.  Non-blocking."""
        if now is None:
            now = self.clock()
        out = []
        with self._cond:
            for group in list(self._groups):
                while len(self._groups.get(group, ())) >= self.max_batch:
                    out.append((group, self._pop_from(group,
                                                      self.max_batch)))
                entries = self._groups.get(group)
                # due test in the SAME arithmetic next_deadline()
                # reports (oldest + max_wait_s): ``now - oldest >=
                # max_wait_s`` can round the other way at the boundary,
                # leaving a caller who advanced exactly to the reported
                # deadline spinning on a never-due batch
                if entries and now >= entries[0][1] + self.max_wait_s:
                    out.append((group, self._pop_from(group,
                                                      self.max_batch)))
        return out

    def pop_all(self) -> List[tuple]:
        """Everything still queued, chunked at ``max_batch`` — the drain
        path (service shutdown)."""
        out = []
        with self._cond:
            for group in list(self._groups):
                while group in self._groups:
                    out.append((group, self._pop_from(group,
                                                      self.max_batch)))
        return out

    def next_deadline(self) -> Optional[float]:
        """Earliest instant (in clock units) a queued group becomes due,
        or None when the queue is empty."""
        with self._cond:
            oldest = [entries[0][1] for entries in self._groups.values()
                      if entries]
        if not oldest:
            return None
        return min(oldest) + self.max_wait_s

    def wait_ready(self, timeout: Optional[float] = None) -> List[tuple]:
        """Block (on real time) until at least one batch is due, then
        return the due batches; ``[]`` on timeout.  The worker thread's
        wait primitive — uses the injected clock only for deadlines, real
        time for the condition wait."""
        end = (None if timeout is None
               else time.monotonic() + timeout)  # timing-ok: worker real-time wait bound
        with self._cond:
            while True:
                ready = self.pop_ready()
                if ready:
                    return ready
                nd = self.next_deadline()
                wait = None
                if nd is not None:
                    wait = max(0.0, nd - self.clock())
                if end is not None:
                    remaining = end - time.monotonic()  # timing-ok: wait bound, not a wall
                    if remaining <= 0:
                        return []
                    wait = remaining if wait is None else min(wait,
                                                              remaining)
                self._cond.wait(wait)
