"""Fleet serving: a thin HTTP front over ``EquilibriumService`` plus the
out-of-process worker entry point (ISSUE 15, DESIGN §14).

One process was the throughput ceiling: the PR 4/8 engine answers exact
hits in ~0.07 ms and survives overload, but every query funnels through
one Python process.  The fleet tier scales OUT instead of up:

* **N worker processes**, each running the UNCHANGED in-process
  ``EquilibriumService`` (priorities, deadlines, admission, breakers all
  ride through verbatim) behind a stdlib ``ThreadingHTTPServer`` — no
  new dependencies, JSON over HTTP;
* **one shared disk store** (``SolutionStore(shared=True)``): the
  content-addressed fingerprints plus the PR 6 checksum and PR 9
  ``schema_ck`` contracts make cross-process sharing verifiably safe,
  and the claim/lease election makes cold solves exactly-once
  fleet-wide (``serve.store`` docstring for the protocol);
* **speculative neighbor prefetch** around misses
  (``EquilibriumService(prefetch_k=..., prefetch_cells=...)``) riding
  ``Priority.SPECULATIVE`` — sheddable by construction, so prefetch can
  never displace interactive work.

Endpoints (JSON in, JSON out):

* ``POST /query`` — ``{"cell": [σ, ρ, sd], "kwargs": {...},
  "scenario", "priority", "deadline", "degraded_ok", "timeout"}`` →
  the served result, or a typed error payload (``{"error":
  "<TypeName>", "message", "retry_after_s"?, "status"?}``) with the
  HTTP status mapped from the serving layer's typed errors (503 +
  ``Retry-After`` for ``Overloaded``/``CircuitOpen``, 504 for
  deadlines/timeouts, 500 for solve/certification failures).
* ``GET /metrics`` — the ``ServeMetrics`` snapshot (fleet counters
  included).
* ``GET /fleet`` — fleet introspection: owner id, published keys,
  prefetch-issued keys, held leases (the load harness's attribution
  and leak-audit hook).
* ``GET /healthz`` — liveness.

Worker lifecycle: ``python -m aiyagari_hark_tpu.serve.fleet --store DIR
--kwargs '{"a_count": 10}' ...`` prints ``FLEET_READY port=<p>
pid=<pid>`` once the server is listening and then idles under
``resilience.preemption_guard``: SIGTERM turns into the typed
``Interrupted`` (journaled; pending futures fail at the batch seam, the
PR 3 protocol), the front stops, and the process exits 75 — the
driver-facing "interrupted, not failed" code.  Leases the dying worker
still holds are deliberately NOT released on the signal path: the lease
TTL is the designed reclaim (survivors break stale leases and re-solve),
and the interrupt path must not add disk I/O between the signal and
exit.

Scope, honestly: this is a single-host-N-process fleet by default (the
shared-dir lease backend trusts one filesystem's O_EXCL and one wall
clock).  ``--lease-backend cas:host:port`` swaps the directory for the
loopback CAS authority (``serve.lease``) behind the same claim/publish
API; nothing above the store changes, and the backend choice never
enters solution fingerprints.

ISSUE 16 additions (DESIGN §14): the client grows TYPED resilience —
bounded deterministic exponential backoff honoring the server's 503
``Retry-After`` (``RetryPolicy``), per-request deadlines on an
injectable clock, and optional hedged reads for known-published
fingerprints (``HedgePolicy``; a hedge is never issued for a cold miss
— see the module docstring of ``serve.chaos`` for why).  Workers grow a
``POST /chaos`` arm endpoint (only when started with ``--chaos``) and
surface per-worker lease/heartbeat health in ``/healthz`` and
``/fleet``.
"""

from __future__ import annotations

import json
import os
import queue
import threading
import time
from concurrent.futures import TimeoutError as FutureTimeout
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, NamedTuple, Optional
from urllib import error as urlerror
from urllib import request as urlrequest

from .batcher import ServeQueueFull
from .service import (
    CertificationFailed,
    CircuitOpen,
    DeadlineExceeded,
    EquilibriumService,
    EquilibriumSolveFailed,
    LoadShed,
    Overloaded,
    ServeError,
    ServiceClosed,
    make_query,
)

# typed serving error -> (HTTP status, should carry Retry-After).  503
# means "the fleet is saturated or this region is breaking — back off
# and retry"; 504 "your deadline/timeout passed"; 500 "the solve itself
# failed typed".  Unknown scenarios and malformed bodies are 400s.
# Keyed by CLASS (exact type): a rename upstream breaks this table
# loudly at import, not silently at serve time.
_ERROR_STATUS = {
    Overloaded: (503, True),
    CircuitOpen: (503, True),
    ServeQueueFull: (503, False),
    LoadShed: (503, False),
    ServiceClosed: (503, False),
    DeadlineExceeded: (504, False),
    EquilibriumSolveFailed: (500, False),
    CertificationFailed: (500, False),
}
# Interrupted is resolved lazily (importing the resilience layer here
# would be needless at module scope for a transport table).
# CoordinationUnavailable (ISSUE 18): the worker's replicated CAS lost
# its quorum — retryable 503, the client backs off and the majority
# side of the partition keeps serving.
_EXTRA_STATUS = {"Interrupted": (503, False),
                 "CoordinationUnavailable": (503, True)}


def result_to_json(res) -> dict:
    """A ``ServedResult`` as a JSON-safe dict.  Floats serialize via
    ``repr`` (shortest round-trip), so every float64 crosses the wire
    BIT-EXACTLY — the fleet bit-identity acceptance compares served
    values against a local ``reference_solve`` after one JSON hop."""
    return {
        "r_star": float(res.r_star),
        "capital": float(res.capital),
        "labor": float(res.labor),
        "bisect_iters": int(res.bisect_iters),
        "egm_iters": int(res.egm_iters),
        "dist_iters": int(res.dist_iters),
        "status": int(res.status),
        "path": str(res.path),
        "quality": str(res.quality),
        "key": int(res.key),
        "cert_level": (None if res.cert_level is None
                       else int(res.cert_level)),
        "scenario": str(res.scenario),
        "fields": list(res.fields),
        "values": [float(v) for v in res.values],
        "bracket_init": (None if res.bracket_init is None
                         else [float(res.bracket_init[0]),
                               float(res.bracket_init[1]),
                               int(res.bracket_init[2])]),
        # surrogate tier (ISSUE 17): the tag travels with the answer —
        # a surrogate response is never mistakable for an exact one
        "surrogate_error_bound": (
            None if res.surrogate_error_bound is None
            else float(res.surrogate_error_bound)),
        "donor_keys": (None if res.donor_keys is None
                       else [int(k) for k in res.donor_keys]),
    }


def error_to_json(exc: BaseException) -> dict:
    """A typed serving error as a JSON payload the client can re-type:
    the class name, message, and whichever retry-after / status / key
    attributes the error carries."""
    payload = {"error": type(exc).__name__, "message": str(exc)}
    for attr in ("retry_after_s", "est_wait_s", "status", "key",
                 "waited_s", "depth", "max_queue", "reason"):
        v = getattr(exc, attr, None)
        if v is not None and isinstance(v, (int, float, str)):
            payload[attr] = v
    return payload


class _FleetHandler(BaseHTTPRequestHandler):
    """One request: decode JSON, run the service call, encode JSON.
    The SERVICE is the authority on every serving decision — this layer
    only transports."""

    # the service is attached per-server (``FleetFront`` subclasses the
    # server class with a ``service`` attribute)
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):   # quiet: the journal is the log
        pass

    def _send(self, code: int, payload: dict,
              retry_after: Optional[float] = None) -> None:
        body = json.dumps(payload).encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        if retry_after is not None:
            # repr(), not a fixed-width format: the header must equal
            # the JSON payload's ``retry_after_s`` BIT-EXACTLY after one
            # float round-trip (json.dumps also serializes floats via
            # repr), so a client honoring either sees the same wait —
            # pinned by tests/test_fleet_client.py.
            self.send_header("Retry-After", repr(max(0.0, float(retry_after))))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        svc: EquilibriumService = self.server.service
        store = svc.store
        if self.path == "/healthz":
            hb = (store.heartbeat_health()
                  if hasattr(store, "heartbeat_health") else {})
            self._send(200, {"ok": True,
                             "owner": getattr(store, "owner", ""),
                             "heartbeat": hb})
        elif self.path == "/metrics":
            self._send(200, svc.metrics.snapshot())
        elif self.path == "/fleet":
            hb = (store.heartbeat_health()
                  if hasattr(store, "heartbeat_health") else {})
            self._send(200, {
                "owner": getattr(store, "owner", ""),
                "shared": bool(getattr(store, "shared", False)),
                "published_keys": store.published_keys(),
                "prefetch_keys": svc.prefetch_keys(),
                "held_leases": store.held_leases(),
                "store_known": store.known(),
                "fleet_counts": store.fleet_counts(),
                "heartbeat": hb,
                "lease_backend": hb.get("backend", "shared-dir"),
            })
        else:
            self._send(404, {"error": "NotFound", "message": self.path})

    def do_POST(self):
        if self.path == "/chaos":
            self._do_chaos()
            return
        if self.path != "/query":
            self._send(404, {"error": "NotFound", "message": self.path})
            return
        svc: EquilibriumService = self.server.service
        try:
            n = int(self.headers.get("Content-Length", "0"))
            req = json.loads(self.rfile.read(n).decode("utf-8"))
            cell = [float(x) for x in req["cell"]]
            q = make_query(
                cell[0], cell[1], labor_sd=cell[2],
                priority=int(req.get("priority", 0)),
                degraded_ok=bool(req.get("degraded_ok", False)),
                scenario=str(req.get("scenario", "aiyagari")),
                surrogate_ok=bool(req.get("surrogate_ok", True)),
                **req.get("kwargs", {}))
        except Exception as e:   # malformed request: client error
            self._send(400, {"error": "BadRequest", "message": str(e)})
            return
        deadline = req.get("deadline")
        timeout = float(req.get("timeout", 300.0))
        try:
            fut = svc.submit(
                q, deadline=None if deadline is None else float(deadline))
            res = fut.result(timeout)
        except FutureTimeout:
            self._send(504, {"error": "Timeout",
                             "message": f"no result in {timeout:g}s"})
            return
        except BaseException as e:
            code, with_retry = _ERROR_STATUS.get(
                type(e), _EXTRA_STATUS.get(type(e).__name__,
                                           (500, False)))
            self._send(code, error_to_json(e),
                       retry_after=(getattr(e, "retry_after_s", None)
                                    if with_retry else None))
            return
        self._send(200, result_to_json(res))

    def _do_chaos(self):
        """Arm/disarm the worker's chaos agent (ISSUE 16 drills).  Only
        live on workers started with ``--chaos`` — a production worker
        404s, so the fault surface cannot be armed by accident."""
        agent = getattr(self.server, "chaos", None)
        if agent is None:
            self._send(404, {"error": "ChaosDisabled",
                             "message": "worker not started with --chaos"})
            return
        try:
            n = int(self.headers.get("Content-Length", "0"))
            cfg = (json.loads(self.rfile.read(n).decode("utf-8"))
                   if n else {})
            # ISSUE 18: ``partition_replicas`` is a COORDINATION fault,
            # not a solve fault — it routes to the replicated lease
            # backend (which replicas this worker may reach), not to the
            # ChaosAgent's solve-path seams.  [] heals the partition.
            part = cfg.pop("partition_replicas", None)
            if part is not None:
                backend = self.server.service.store.lease_backend
                if not hasattr(backend, "set_partition"):
                    raise ValueError(
                        "partition_replicas needs a replicated lease "
                        f"backend (got {type(backend).__name__})")
                backend.set_partition(part)
            armed = agent.arm(cfg)
        except Exception as e:
            self._send(400, {"error": "BadRequest", "message": str(e)})
            return
        self._send(200, {"ok": True, "armed": armed})


class _FleetServer(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True
    chaos = None   # a ChaosAgent when the worker was started with --chaos


class FleetFront:
    """The HTTP front for ONE worker's service: bind, serve on a daemon
    thread, stop.  ``port=0`` binds an ephemeral port (read ``.port``
    after construction — the worker prints it for its spawner)."""

    def __init__(self, service: EquilibriumService,
                 host: str = "127.0.0.1", port: int = 0, chaos=None):
        self._httpd = _FleetServer((host, int(port)), _FleetHandler)
        self._httpd.service = service
        self._httpd.chaos = chaos
        self.host = host
        self.port = int(self._httpd.server_address[1])
        self._thread: Optional[threading.Thread] = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "FleetFront":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="fleet-front", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None

    def __enter__(self) -> "FleetFront":
        return self.start()

    def __exit__(self, exc_type, exc, tb) -> None:
        self.stop()


class FleetHTTPError(ServeError):
    """A worker answered with a typed error payload: ``payload`` is the
    decoded JSON (``payload["error"]`` names the serving-layer type),
    ``code`` the HTTP status, ``retry_after_s`` the parsed
    ``Retry-After`` header when the worker sent one (float seconds —
    equal to the payload's ``retry_after_s``/``est_wait_s`` field by the
    ``_send`` repr pin)."""

    def __init__(self, code: int, payload: dict,
                 retry_after_s: Optional[float] = None):
        super().__init__(
            f"fleet worker returned {code}: "
            f"{payload.get('error')} ({payload.get('message')})")
        self.code = int(code)
        self.payload = dict(payload)
        self.retry_after_s = (None if retry_after_s is None
                              else float(retry_after_s))


class RetryPolicy(NamedTuple):
    """Bounded DETERMINISTIC exponential backoff for ``FleetClient``
    (ISSUE 16).  Attempt k waits ``base_s * multiplier**k``, raised to
    the server's 503 ``Retry-After`` when one was sent (the worker's
    estimate is better than the client's schedule), capped at
    ``max_backoff_s``.  No jitter by design: the chaos drills replay
    byte-identically only if every client wait is a pure function of
    (policy, attempt index, server answer)."""

    max_attempts: int = 4
    base_s: float = 0.05
    multiplier: float = 2.0
    max_backoff_s: float = 2.0

    def backoff_s(self, attempt: int,
                  retry_after_s: Optional[float] = None) -> float:
        wait = float(self.base_s) * float(self.multiplier) ** int(attempt)
        if retry_after_s is not None:
            wait = max(wait, float(retry_after_s))
        return min(wait, float(self.max_backoff_s))


class HedgePolicy(NamedTuple):
    """Hedged reads for KNOWN-PUBLISHED fingerprints (ISSUE 16, DESIGN
    §14).  If the primary worker hasn't answered within the hedge delay,
    a second identical request goes to the next worker and the first
    answer wins.  ``delay_s=None`` derives the delay from the client's
    own p99 success latency (an exact hit answering slower than p99 is
    evidence the worker is sick, not that the query is hard); the floor
    ``min_delay_s`` also serves as the delay before any latency history
    exists.  A hedge is only LEGAL for a fingerprint this client has
    already seen answered — a cold miss would trigger a second
    fleet-wide solve election and waste a worker on duplicated work, so
    cold misses never hedge."""

    delay_s: Optional[float] = None
    min_delay_s: float = 0.01


class FleetClient:
    """Stdlib client for a worker pool: submit one query to a worker,
    failing over to the next URL on a CONNECTION-level error (a dead
    worker).  Typed serving errors do NOT fail over — an ``Overloaded``
    from a live worker is an answer, not an outage.

    ISSUE 16 resilience (all OPT-IN so existing callers' outcome
    accounting is unchanged):

    * ``retry=RetryPolicy(...)``: 503 answers (``Overloaded`` /
      ``CircuitOpen`` / queue-full / shed) and full-pool connection
      failures are retried under bounded deterministic exponential
      backoff honoring the server's ``Retry-After``.
    * ``deadline_s=`` per query: the whole retry/backoff schedule lives
      inside one budget on the injectable ``clock``; when the budget
      cannot cover the next wait the client raises typed
      ``DeadlineExceeded`` instead of sleeping past it.
    * ``hedge=HedgePolicy(...)``: hedged reads for known-published
      fingerprints only (see ``HedgePolicy``); journaled as
      ``FLEET_HEDGE_ISSUED`` / ``FLEET_HEDGE_WON`` when ``obs`` is
      attached.

    ``clock`` (monotonic seconds) and ``sleep`` are injectable so every
    retry test runs on a fake clock in zero wall time."""

    def __init__(self, urls: List[str], timeout: float = 300.0,
                 retry: Optional[RetryPolicy] = None,
                 hedge: Optional[HedgePolicy] = None,
                 clock=None, sleep=None, obs=None):
        if not urls:
            raise ValueError("FleetClient needs at least one worker URL")
        self.urls = list(urls)
        self.timeout = float(timeout)
        self.retry = retry
        self.hedge = hedge
        self._clock = clock if clock is not None else time.monotonic
        self._sleep = sleep if sleep is not None else time.sleep
        self._obs = obs
        # (scenario, rounded-cell) tokens this client has SEEN answered:
        # the hedge-legality set.  Client-observed only — the client
        # cannot compute fingerprints, and a worker-side "published"
        # answer is exactly the evidence a hedge needs.
        self._published = set()
        self._lat_s: List[float] = []   # success latencies, hedge p99
        self._hedge_counts = {"issued": 0, "won": 0}

    @staticmethod
    def _token(scenario: str, cell) -> tuple:
        return (str(scenario), tuple(round(float(c), 9) for c in cell))

    def hedge_counts(self) -> dict:
        return dict(self._hedge_counts)

    def note_published(self, scenario: str, cell) -> None:
        """Mark a cell hedge-legal without a prior query (e.g. the
        harness pre-warmed it through a different client)."""
        self._published.add(self._token(scenario, cell))

    def _hedge_delay_s(self) -> float:
        assert self.hedge is not None
        if self.hedge.delay_s is not None:
            return max(float(self.hedge.delay_s),
                       float(self.hedge.min_delay_s))
        if not self._lat_s:
            return float(self.hedge.min_delay_s)
        ordered = sorted(self._lat_s)
        p99 = ordered[min(len(ordered) - 1,
                          int(0.99 * (len(ordered) - 1) + 0.5))]
        return max(p99, float(self.hedge.min_delay_s))

    def _post(self, url: str, path: str, payload: dict) -> dict:
        data = json.dumps(payload).encode("utf-8")
        req = urlrequest.Request(url + path, data=data,
                                 headers={"Content-Type":
                                          "application/json"})
        try:
            with urlrequest.urlopen(req, timeout=self.timeout) as resp:
                return json.loads(resp.read().decode("utf-8"))
        except urlerror.HTTPError as e:
            try:
                payload = json.loads(e.read().decode("utf-8"))
            except Exception:
                payload = {"error": "HTTPError", "message": str(e)}
            ra = e.headers.get("Retry-After") if e.headers else None
            try:
                ra = None if ra is None else float(ra)
            except ValueError:
                ra = None
            raise FleetHTTPError(e.code, payload,
                                 retry_after_s=ra) from None

    def get(self, url: str, path: str) -> dict:
        with urlrequest.urlopen(url + path,
                                timeout=self.timeout) as resp:
            return json.loads(resp.read().decode("utf-8"))

    def _query_once(self, payload: dict, start: int) -> dict:
        """One failover sweep over the pool (the pre-ISSUE-16 behavior):
        connection errors and dying workers' typed refusals move on to
        the next URL; any other typed answer raises immediately."""
        last = None
        for i in range(len(self.urls)):
            url = self.urls[(start + i) % len(self.urls)]
            try:
                return self._post(url, "/query", payload)
            except FleetHTTPError as e:
                # a DYING worker's typed refusal is an outage, not an
                # answer — the query is valid, a peer can serve it
                if e.payload.get("error") in ("ServiceClosed",
                                              "Interrupted"):
                    last = e
                    continue
                raise
            except (urlerror.URLError, ConnectionError, OSError,
                    TimeoutError) as e:
                last = e
                continue
        raise ConnectionError(
            f"no fleet worker reachable ({len(self.urls)} tried): "
            f"{last}")

    def _query_hedged(self, payload: dict, start: int,
                      token: tuple) -> dict:
        """Primary request plus, after the hedge delay, one hedge to the
        next worker; first SUCCESS wins.  If the first arrival is an
        error the race waits for the straggler; only when both requests
        fail does the primary's error propagate."""
        results: "queue.Queue" = queue.Queue()

        def _run(tag: str, offset: int) -> None:
            try:
                results.put((tag, None,
                             self._query_once(payload, start + offset)))
            except BaseException as e:   # reported through the queue
                results.put((tag, e, None))

        threading.Thread(target=_run, args=("primary", 0),
                         daemon=True, name="fleet-hedge-primary").start()
        delay = self._hedge_delay_s()
        try:
            first = results.get(timeout=delay)
        except queue.Empty:
            first = None
        if first is not None and first[1] is None:
            return first[2]               # primary answered in time
        self._hedge_counts["issued"] += 1
        if self._obs is not None:
            self._obs.event("FLEET_HEDGE_ISSUED", scenario=token[0],
                            cell=list(token[1]),
                            delay_s=round(delay, 6))
        threading.Thread(target=_run, args=("hedge", 1),
                         daemon=True, name="fleet-hedge-second").start()
        outcomes = [] if first is None else [first]
        while len(outcomes) < 2:
            outcomes.append(results.get())
            tag, err, res = outcomes[-1]
            if err is None:
                if tag == "hedge":
                    self._hedge_counts["won"] += 1
                    if self._obs is not None:
                        self._obs.event("FLEET_HEDGE_WON",
                                        scenario=token[0],
                                        cell=list(token[1]))
                return res
        for tag, err, _res in outcomes:   # both failed: primary's error
            if tag == "primary":
                raise err
        raise outcomes[0][1]

    def query(self, cell, kwargs: dict, scenario: str = "aiyagari",
              priority: int = 0, deadline: Optional[float] = None,
              degraded_ok: bool = False,
              prefer: Optional[int] = None,
              deadline_s: Optional[float] = None) -> dict:
        """POST one query, starting at ``urls[prefer]`` and failing over
        on connection errors.  Returns the result payload; raises
        ``FleetHTTPError`` on a typed error answer, ``ConnectionError``
        when EVERY worker is unreachable (after the retry schedule, when
        one is attached), typed ``DeadlineExceeded`` when ``deadline_s``
        cannot cover the next backoff wait."""
        payload = {"cell": [float(c) for c in cell], "kwargs": kwargs,
                   "scenario": scenario, "priority": int(priority),
                   "deadline": deadline,
                   "degraded_ok": bool(degraded_ok),
                   "timeout": self.timeout}
        start = 0 if prefer is None else int(prefer) % len(self.urls)
        token = self._token(scenario, cell)
        hedge_ok = (self.hedge is not None and len(self.urls) >= 2
                    and token in self._published)
        attempts = (1 if self.retry is None
                    else max(1, int(self.retry.max_attempts)))
        t0 = self._clock()
        limit = None if deadline_s is None else t0 + float(deadline_s)
        for attempt in range(attempts):
            t_req = self._clock()
            try:
                res = (self._query_hedged(payload, start, token)
                       if hedge_ok
                       else self._query_once(payload, start))
                self._lat_s.append(max(0.0, self._clock() - t_req))
                if len(self._lat_s) > 512:
                    del self._lat_s[:-256]
                self._published.add(token)
                return res
            except FleetHTTPError as e:
                if (self.retry is None or attempt + 1 >= attempts
                        or e.code != 503):
                    raise
                wait = self.retry.backoff_s(attempt, e.retry_after_s)
            except ConnectionError:
                if self.retry is None or attempt + 1 >= attempts:
                    raise
                wait = self.retry.backoff_s(attempt)
            if limit is not None and self._clock() + wait > limit:
                raise DeadlineExceeded(   # obs-ok: client-side budget, journaled server-side if at all
                    tuple(float(c) for c in cell), key=-1,
                    waited_s=self._clock() - t0)
            self._sleep(wait)
        raise AssertionError("unreachable: loop raises or returns")


# -- the out-of-process worker ----------------------------------------------

def worker_main(argv=None) -> int:
    """One fleet worker process: shared store + service + HTTP front,
    idling under ``preemption_guard`` until SIGTERM (exit 75, the PR 3
    interrupted-not-failed convention) or ``--max-seconds`` (exit 0)."""
    import argparse
    import sys
    import time

    ap = argparse.ArgumentParser(
        description="aiyagari fleet worker (ISSUE 15)")
    ap.add_argument("--store", required=True,
                    help="shared disk store directory")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0,
                    help="0 binds an ephemeral port (printed)")
    ap.add_argument("--owner", default=f"worker-{os.getpid()}")
    ap.add_argument("--kwargs", default="{}",
                    help="solver model kwargs, JSON")
    ap.add_argument("--scenario", default="aiyagari")
    ap.add_argument("--lease-ttl", type=float, default=30.0)
    ap.add_argument("--max-batch", type=int, default=4)
    ap.add_argument("--ladder", default="1,2,4")
    ap.add_argument("--capacity", type=int, default=256)
    ap.add_argument("--prefetch-k", type=int, default=0)
    ap.add_argument("--prefetch-cells", default=None,
                    help="JSON list of [σ, ρ, sd] lattice cells")
    ap.add_argument("--admission", default=None,
                    help="AdmissionPolicy fields, JSON (omit: no "
                         "admission layer)")
    ap.add_argument("--journal", default=None,
                    help="worker event-journal JSONL path")
    ap.add_argument("--certify", action="store_true",
                    help="certify_before_cache on cold misses")
    ap.add_argument("--max-seconds", type=float, default=None,
                    help="safety exit after this long (tests)")
    ap.add_argument("--lease-backend", default="dir",
                    help="coordination backend spec: 'dir' (shared-dir "
                         "leases, the default), 'cas:HOST:PORT' (the "
                         "loopback CAS authority, serve.lease), or "
                         "'replicated:H:P,H:P,...' (quorum over an odd "
                         "replica set, serve.replicated)")
    ap.add_argument("--chaos", action="store_true",
                    help="enable the POST /chaos fault-injection "
                         "endpoint (ISSUE 16 drills; never on by "
                         "default)")
    ap.add_argument("--surrogate", default=None,
                    help="SurrogatePolicy fields, JSON (ISSUE 17; "
                         "omit: no surrogate tier)")
    args = ap.parse_args(argv)

    from ..obs.runtime import NULL_OBS, ObsConfig, build_obs
    from ..utils.config import AdmissionPolicy
    from ..utils.resilience import interrupt_requested, preemption_guard
    from .lease import make_backend
    from .store import SolutionStore

    obs = (build_obs(ObsConfig(enabled=True, journal_path=args.journal))
           if args.journal else NULL_OBS)
    admission = (AdmissionPolicy(**json.loads(args.admission))
                 if args.admission else None)
    prefetch_cells = (json.loads(args.prefetch_cells)
                      if args.prefetch_cells else None)
    backend = (None if args.lease_backend == "dir"
               else make_backend(args.lease_backend, root=args.store))
    store = SolutionStore(capacity=args.capacity, disk_path=args.store,
                          shared=True, lease_ttl_s=args.lease_ttl,
                          owner=args.owner, obs=obs,
                          lease_backend=backend)
    chaos = None
    if args.chaos:
        from .chaos import ChaosAgent

        chaos = ChaosAgent(obs=obs, owner=args.owner)
        store.set_chaos(chaos)
    surrogate = None
    if args.surrogate:
        from .surrogate import SurrogatePolicy

        surrogate = SurrogatePolicy(**json.loads(args.surrogate))
    svc = EquilibriumService(
        store=store, max_batch=args.max_batch,
        ladder=tuple(int(s) for s in args.ladder.split(",")),
        admission=admission, obs=obs,
        certify_before_cache=bool(args.certify),
        prefetch_k=args.prefetch_k, prefetch_cells=prefetch_cells,
        surrogate=surrogate)
    front = FleetFront(svc, host=args.host, port=args.port,
                       chaos=chaos).start()
    print(f"FLEET_READY port={front.port} pid={os.getpid()} "
          f"owner={args.owner}", flush=True)

    interrupted = False
    deadline = (None if args.max_seconds is None
                else time.monotonic() + args.max_seconds)  # timing-ok: test-only safety exit
    # cleanup stays INSIDE the guard: a second SIGTERM mid-cleanup must
    # escalate through the guard's typed path (KeyboardInterrupt), not
    # hit a restored default handler and kill the worker untyped
    with preemption_guard():
        try:
            while not interrupt_requested():
                if (deadline is not None
                        and time.monotonic() >= deadline):  # timing-ok: safety exit check
                    break
                time.sleep(0.02)
            interrupted = interrupt_requested()
            if interrupted and obs is not NULL_OBS:
                obs.event("INTERRUPTED", what="fleet worker",
                          owner=args.owner)
            front.stop()
            try:
                svc.close(drain=not interrupted)
            except BaseException:
                pass
            if obs is not NULL_OBS:
                obs.close()
        except KeyboardInterrupt:
            interrupted = True
    print(f"FLEET_EXIT interrupted={int(interrupted)}", flush=True)
    return 75 if interrupted else 0


if __name__ == "__main__":   # pragma: no cover - exercised via subprocess
    import sys

    sys.exit(worker_main())
