"""Certified surrogate serving tier: interpolate answers OFF the lattice.

Production traffic is a continuous distribution over (σ, ρ, sd); the
store only has solved lattice points.  The solution manifold over
parameters is smooth and asymptotically linear (PAPERS 2002.09108's
consumption-function linearity, 1905.13045's wealth-evolution structure
— the same facts the analytic tail exploits pointwise in asset space),
so an off-lattice query can be answered in microseconds by a LOCAL
WEIGHTED-LINEAR FIT over the k nearest CERTIFIED stored solutions in
normalized CellSpace coordinates, with a model-implied error bound —
the ``donor_margin`` two-donor machinery generalized to k donors
(DESIGN §15; ISSUE 17).

``SurrogatePolicy`` rides ``EquilibriumService(surrogate=...)`` exactly
like ``AdmissionPolicy``/``PrecisionPolicy``/``GridPolicy``: ``None``
(the default) disables the tier and every served bit is identical to
the pre-surrogate engine.  A surrogate answer is served as
``ServedResult(quality="surrogate", surrogate_error_bound=...,
donor_keys=...)`` — NEVER cached, never untagged; when its bound
exceeds ``max_error_bound`` (or the donors are too few / too far, or a
seeded ``audit_fraction`` draw selects it for a posteriori
certification) the query ESCALATES to a genuine cold solve whose
published result densifies the lattice exactly where the surrogate
failed (``LATTICE_REFINED``).

The fit: donors at normalized offsets ``dz_j`` with distances ``d_j``
get weights ``w_j = 1/(d_j + eps)``; a weighted least-squares plane
``r ≈ β₀ + β·dz`` is solved and evaluated AT the query point (``β₀``).
Because WLS is linear in the observations, the prediction is an
equivalent-kernel row ``a`` with ``r̂ = a·r`` — the same kernel applied
to every packed-row column interpolates the full served row (affine
weights reproduce constant columns exactly, so schema/status columns
survive).  Fewer than ``dim+2`` donors, or an ill-conditioned plane
(coplanar donors), fall back to the distance-weighted mean — the same
kernel contract, zero slope.  Offset columns the donor set does not
actually span (zero peak-to-peak — e.g. donors from a 2-D (σ, ρ)
lattice slice at a single sd) are dropped before the fit: the plane
lives in the spanned subspace, where its β are identifiable, instead
of tripping the condition gate into the mean fallback.

The bound: ``max(inflation * max-fit-residual, spread-term, floor)``
— the residual term measures observed local curvature over the donor
neighborhood (zero iff the donors are exactly coplanar, so an exactly
linear manifold certifies down to the floor); the spread term
(``donor_margin``'s donor-disagreement ball ``max-min donor r*``,
scaled by ``d₁/d̄``, how close the query sits relative to the
neighborhood radius) applies only to the WEIGHTED-MEAN fallback,
whose constant model leaves the whole local variation unexplained —
charging it to the plane fit would bill the plane's own slope as
error; ``floor`` is the caller's solver-tolerance floor (the service
passes ``64·r_tol``, ``donor_margin``'s own floor rung)."""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import NamedTuple, Optional

import numpy as np


@dataclass(frozen=True)
class SurrogatePolicy:
    """Continuous-parameter surrogate serving (ISSUE 17, DESIGN §15).

    * ``k`` — donors in the local fit (the ``donor_margin`` pair,
      generalized).
    * ``max_error_bound`` — r*-units budget: a fit whose model-implied
      bound exceeds this escalates to a real solve.
    * ``max_distance`` — normalized (``neighbor_distance`` units)
      budget on the NEAREST donor: past it the local fit is an
      extrapolation, not an interpolation, and the query escalates.
    * ``min_donors`` — fewer usable donors than this escalates (the
      self-densifying case: sparse regions earn lattice points).
    * ``require_certified`` — only CERTIFIED/MARGINAL donors may enter
      the fit (the PR 6 certifier is the tier's foundation; disable
      only in uncertified-store tests).
    * ``audit_fraction`` / ``audit_seed`` — seeded fraction of
      surrogate-eligible answers escalated to a REAL solve and
      certified a posteriori: the solve is served and published, and
      the surrogate's prediction is checked against it (within its own
      reported bound or the audit fails loudly in metrics/journal).
    * ``bound_inflation`` — conservatism multiplier on the residual
      term of the bound model.
    * ``refine`` — journal escalated publishes as ``LATTICE_REFINED``
      parameter-space refinement points."""

    k: int = 6
    max_error_bound: float = 2e-4
    max_distance: float = 0.5
    min_donors: int = 4
    require_certified: bool = True
    audit_fraction: float = 0.0
    audit_seed: int = 0
    bound_inflation: float = 2.0
    refine: bool = True

    def replace(self, **kwargs) -> "SurrogatePolicy":
        return dataclasses.replace(self, **kwargs)


class SurrogateFit(NamedTuple):
    """One local fit: the prediction, its model-implied error bound,
    and the equivalent kernel that produced it (apply ``kernel`` to any
    donor column to interpolate it consistently)."""

    r_star: float       # fitted r* at the query point
    bound: float        # model-implied |error| bound (r* units)
    kernel: np.ndarray  # [k] equivalent-kernel weights, sum == 1
    resid: float        # max |fit - donor| over the donor set
    spread: float       # max - min donor r*
    linear: bool        # True = plane fit, False = weighted-mean fallback


def fit_surrogate(cell, donor_cells, donor_r, distances, scale,
                  floor: float = 0.0,
                  inflation: float = 2.0) -> Optional[SurrogateFit]:
    """Distance-weighted local-linear fit of r* at ``cell`` over the
    donors (rows of ``donor_cells``), in normalized coordinates
    (``cell[i]/scale[i]``).  Returns None only for an empty donor set;
    degenerate geometries fall back to the weighted mean."""
    donor_cells = np.asarray(donor_cells, dtype=np.float64)
    donor_r = np.asarray(donor_r, dtype=np.float64)
    d = np.asarray(distances, dtype=np.float64)
    n = donor_cells.shape[0]
    if n == 0:
        return None
    scale_a = np.asarray(scale, dtype=np.float64)
    dz = donor_cells / scale_a - np.asarray(
        cell, dtype=np.float64) / scale_a
    w = 1.0 / (d + 1e-6)
    w = w / w.max()
    # fit only the offset columns the donors actually span: a column
    # with zero peak-to-peak (a lattice slice, or a constant query
    # offset along an unswept axis) is collinear with the intercept,
    # and keeping it would push cond(A) to infinity and needlessly
    # degrade the whole fit to the weighted mean
    live = np.ptp(dz, axis=0) > 1e-12
    dz_fit = dz[:, live]
    dim_eff = int(live.sum())
    kernel = None
    linear = False
    if dim_eff and n >= dim_eff + 2:
        X = np.concatenate([np.ones((n, 1)), dz_fit], axis=1)
        XtW = X.T * w
        A = XtW @ X
        # equivalent kernel: r_hat = e0' A^{-1} X'W r = K[0] . r.  ONE
        # SVD of the tiny normal matrix yields both the condition check
        # and the inverse (the serve path is latency-critical: a
        # cond()+solve()+solve() chain triples the LAPACK dispatches)
        try:
            U, s, Vt = np.linalg.svd(A, hermitian=True)
            if s[-1] > 0.0 and s[0] / s[-1] < 1e10:
                K = (Vt.T / s) @ (U.T @ XtW)
                kernel = K[0]
                linear = True
        except np.linalg.LinAlgError:
            kernel = None
    if kernel is None:
        kernel = w / w.sum()
    r_hat = float(kernel @ donor_r)
    if linear:
        fitted = X @ (K @ donor_r)
    else:
        fitted = np.full(n, r_hat)
    resid = float(np.max(np.abs(fitted - donor_r)))
    spread = float(donor_r.max() - donor_r.min())
    d_near = float(d.min())
    d_bar = float(d.mean())
    # the spread term bills the mean fallback for the variation its
    # constant model cannot explain; the plane fit's unexplained part
    # IS its residual (billing raw spread would charge the plane's own
    # slope as error and no smooth region could ever certify)
    bound = float(max(inflation * resid,
                      (0.0 if linear
                       else spread * d_near / max(d_bar, 1e-12)),
                      floor))
    return SurrogateFit(r_star=r_hat, bound=bound, kernel=kernel,
                        resid=resid, spread=spread, linear=linear)
