"""Pluggable lease-coordination backends for the fleet tier (ISSUE 16,
DESIGN §14).

The claim/lease/publish protocol the shared ``SolutionStore`` runs
(exactly-once election per solution fingerprint, heartbeat-refreshed
liveness, TTL reclaim of a crashed winner) was born fused to ONE
implementation: lease files on one filesystem, ``O_CREAT | O_EXCL`` for
the election and mtime for staleness.  ROADMAP item 2's multi-host tier
needs the same protocol over an object store or coordination service —
so the protocol is now a trait, ``LeaseBackend``, with the election
semantics specified by one shared conformance suite
(``tests/test_lease_backend.py``) instead of by whatever the filesystem
happens to do:

* ``SharedDirBackend`` — the existing shared-directory implementation,
  verbatim semantics (``lease_<hex>.lease`` files via
  ``utils.checkpoint``); the fleet default.  Byte-compatible with
  pre-ISSUE-16 stores: same filenames, same payloads.
* ``MemoryCASBackend`` — an in-memory backend modeling OBJECT-STORE
  conditional-put semantics: a lease is a versioned record, acquisition
  is put-if-absent, heartbeat is read-check-owner-bump, and reclaim is
  delete-if-version-unchanged — the compare-and-swap shape an
  S3/GCS/etcd backend would use, so the reclaim-vs-heartbeat race is
  closed by VERSION, not by filesystem atomicity.  Single-process by
  construction (it is a dict); its job is to pin the conformance
  contract a real remote backend must meet.
* ``CASServer`` + ``LoopbackCASBackend`` — the memory backend served
  over a line-JSON TCP loopback, so REAL separate processes can run the
  conformance races (two interpreters' concurrent claims) against the
  CAS semantics, and a fleet worker can be pointed at a shared CAS
  authority with ``--lease-backend cas:<host>:<port>``.

Contract notes shared by every backend:

* ``release``/``heartbeat`` are OWNER-CHECKED: a stalled winner whose
  lease was TTL-reclaimed and re-acquired by a peer must not delete the
  peer's fresh lease when it finally wakes and releases (the unchecked
  ``os.remove`` release had exactly this bug), and its heartbeat must
  return False — "you no longer hold this" — instead of resurrecting a
  stolen claim.
* ages are CLAMPED at zero and staleness honors a ``skew_tolerance_s``
  window (ISSUE 16 satellite): a backward wall-clock step never makes a
  fresh lease stale, and a reclaimer's forward skew must exceed
  ``ttl + tolerance`` before it can steal from a live owner.
* backend choice NEVER enters solution fingerprints or served bytes —
  it decides who solves, not what a solve produces.

No jax imports; everything here is host-side coordination.
"""

from __future__ import annotations

import glob
import json
import os
import socket
import socketserver
import threading
import time
from typing import Dict, List, Optional

from ..utils.checkpoint import (
    LEASE_SUFFIX,
    acquire_lease,
    break_stale_lease,
    lease_age_s,
    read_lease,
    release_lease,
)
from ..utils.fingerprint import fingerprint_hex


def key_from_hex(hex_str: str) -> int:
    """Inverse of ``utils.fingerprint.fingerprint_hex``: the signed
    int64 back from its two's-complement hex spelling."""
    v = int(hex_str, 16)
    return v - (1 << 64) if v >= (1 << 63) else v


class CoordinationUnavailable(ConnectionError):
    """The replicated coordination tier cannot reach a MAJORITY of its
    replicas (ISSUE 18): conditional writes and quorum reads are
    refused rather than answered from a minority view.  A subclass of
    ``ConnectionError`` on purpose — the store's ``_backend_call``
    degrade path (typed ``LEASE_BACKEND_FAULT``, fail-safe defaults,
    PR 15 partition semantics) owns it without a new catch site, and
    the fleet HTTP tier maps it to a retryable 503."""


class LeaseBackend:
    """The coordination trait: per-fingerprint lease election with
    heartbeat liveness and TTL reclaim.  Keys are signed int64 solution
    fingerprints; owners are diagnostic worker ids (election correctness
    never depends on reading them, but release/heartbeat verify them).

    Every method is non-blocking and exception-free under normal
    operation; a backend whose substrate can fail transiently (network
    CAS) raises ``OSError``/``ConnectionError`` and the store degrades
    through its typed ``LEASE_BACKEND_FAULT`` path."""

    name = "abstract"

    def try_acquire(self, key: int, owner: str) -> bool:
        """Atomically create the key's lease.  True iff THIS caller now
        owns it; False when any lease for the key already exists."""
        raise NotImplementedError

    def release(self, key: int, owner: Optional[str] = None) -> bool:
        """Remove the key's lease; True iff this call removed it.  With
        ``owner`` given, remove ONLY a lease that owner holds (a lease
        re-acquired by a peer after a reclaim survives the original
        owner's late release).  An unreadable/ownerless lease record
        yields to the release — existence is the contract, the payload
        is diagnostic."""
        raise NotImplementedError

    def heartbeat(self, key: int, owner: str) -> bool:
        """Refresh the lease's liveness stamp.  True iff the lease still
        exists AND is owned by ``owner``; False means the claim was
        released, reclaimed, or stolen — the caller must stop treating
        the key as held."""
        raise NotImplementedError

    def age_s(self, key: int, now=None) -> Optional[float]:
        """Seconds since the last acquire/heartbeat stamp, clamped >= 0;
        None when no lease exists."""
        raise NotImplementedError

    def break_stale(self, key: int, ttl_s: float, now=None) -> bool:
        """Reclaim the key's lease iff its age exceeds ``ttl_s +
        skew_tolerance_s``; True iff this call removed it."""
        raise NotImplementedError

    def owner_of(self, key: int) -> Optional[str]:
        """The lease's recorded owner, None when no lease exists (or
        the payload is unreadable — the lease itself may still exist;
        probe with ``age_s``)."""
        raise NotImplementedError

    def list_keys(self) -> List[int]:
        """Every key with a live lease record, any owner (leak audit)."""
        raise NotImplementedError

    def lease_names(self) -> List[str]:
        """Audit spelling of every live lease (the shared-dir backend
        returns real file paths; others synthesize the same naming)."""
        return [f"lease_{fingerprint_hex(k)}{LEASE_SUFFIX}"
                for k in sorted(self.list_keys())]

    def close(self) -> None:
        """Release backend resources (sockets); leases are NOT touched —
        a closing process's held leases reclaim through the TTL."""


class SharedDirBackend(LeaseBackend):
    """Lease files in one shared directory — the pre-ISSUE-16 protocol
    behind the trait, byte-compatible (``lease_<hex>.lease``, O_EXCL
    create, mtime staleness).  Single-host-N-process scope: it trusts
    one filesystem's atomic create and one wall clock.

    ``release``/``heartbeat`` owner checks are read-then-act (the
    filesystem has no conditional delete); the TOCTOU window is
    microseconds against a reclaim that already took the TTL to open,
    honest for this backend's scope — the CAS backend closes the same
    race by version."""

    name = "shared-dir"

    def __init__(self, root: str, skew_tolerance_s: float = 0.0):
        self.root = str(root)
        self.skew_tolerance_s = float(skew_tolerance_s)

    def _path(self, key: int) -> str:
        return os.path.join(self.root,
                            f"lease_{fingerprint_hex(key)}{LEASE_SUFFIX}")

    def _resolve(self, key: int) -> str:
        """The canonical (zero-padded) path, or an EXISTING alternate
        hex spelling of the same key — pre-trait sweeps globbed the
        directory and acted on whatever file was there, so the sweep
        path must still find e.g. ``lease_feedbeef.lease`` even though
        new claims always write the padded form."""
        path = self._path(key)
        if os.path.exists(path):
            return path
        for cand in glob.glob(os.path.join(
                self.root, f"lease_*{LEASE_SUFFIX}")):
            stem = os.path.basename(cand)[len("lease_"):-len(LEASE_SUFFIX)]
            try:
                if key_from_hex(stem) == int(key):
                    return cand
            except ValueError:
                continue
        return path

    def try_acquire(self, key: int, owner: str) -> bool:
        return acquire_lease(self._path(key), owner=owner)

    def release(self, key: int, owner: Optional[str] = None) -> bool:
        path = self._path(key)
        if owner is not None:
            rec = read_lease(path)
            if rec is None:
                return False
            holder = rec.get("owner")
            if holder is not None and holder != str(owner):
                return False     # a peer re-acquired it: not ours to drop
        return release_lease(path)

    def heartbeat(self, key: int, owner: str) -> bool:
        path = self._path(key)
        rec = read_lease(path)
        if rec is None:
            return False         # released/reclaimed: we no longer hold it
        holder = rec.get("owner")
        if holder is not None and holder != str(owner):
            return False         # reclaimed AND re-acquired by a peer
        try:
            os.utime(path)
        except OSError:
            return False         # vanished between read and touch
        return True

    def age_s(self, key: int, now=None) -> Optional[float]:
        return lease_age_s(self._resolve(key), now=now)

    def break_stale(self, key: int, ttl_s: float, now=None) -> bool:
        return break_stale_lease(self._resolve(key), ttl_s, now=now,
                                 tolerance_s=self.skew_tolerance_s)

    def owner_of(self, key: int) -> Optional[str]:
        rec = read_lease(self._resolve(key))
        return None if rec is None else rec.get("owner")

    def list_keys(self) -> List[int]:
        out = []
        for path in glob.glob(os.path.join(
                self.root, f"lease_*{LEASE_SUFFIX}")):
            stem = os.path.basename(path)[len("lease_"):-len(LEASE_SUFFIX)]
            try:
                out.append(key_from_hex(stem))
            except ValueError:
                continue         # foreign file matching the glob: not ours
        return sorted(out)

    def lease_names(self) -> List[str]:
        # real paths, sorted — the pre-trait ``lease_files()`` spelling
        return sorted(glob.glob(os.path.join(
            self.root, f"lease_*{LEASE_SUFFIX}")))


class _Rec:
    """One CAS lease record: owner + liveness stamp + version (the
    conditional-put token).  ``owner is None`` is a TOMBSTONE (ISSUE
    18): a released/reclaimed lease keeps its record with the version
    bumped, so per-key versions are MONOTONIC forever — the property
    quorum replication and read-repair need to order a deletion against
    a re-acquire ("highest version wins" is only sound when a delete
    carries a version instead of erasing one)."""

    __slots__ = ("owner", "stamp", "version")

    def __init__(self, owner: Optional[str], stamp: float,
                 version: int = 1):
        self.owner = owner
        self.stamp = stamp
        self.version = version


class MemoryCASBackend(LeaseBackend):
    """Object-store conditional-put semantics over an in-memory dict:

    * acquire  = put-if-absent (one writer wins, the CAS primitive);
    * heartbeat = read; if owner matches, bump stamp AND version;
    * reclaim  = read (stamp, version); if stale, tombstone-if-version —
      a heartbeat that lands between the read and the delete bumps the
      version and the delete is REFUSED, so a live owner can never lose
      its lease to a reclaimer that raced its beat (the race the
      shared-dir backend can only shrink, closed exactly here).

    Deletions are tombstones (see ``_Rec``): invisible through the
    trait (``age_s``/``owner_of`` read None, ``list_keys`` skips them,
    acquire treats them as absent) but version-ordered for the
    replication tier's ``get``/``put_rec``/``dump`` primitives.

    ``clock`` is injectable for deterministic staleness tests; the
    default is the wall clock (leases coordinate processes)."""

    name = "memory-cas"

    def __init__(self, clock=None, skew_tolerance_s: float = 0.0):
        self._recs: Dict[int, _Rec] = {}
        # reentrant: the durable subclass logs WAL records from inside
        # the mutators' critical sections (serve.wal)
        self._lock = threading.RLock()
        self._clock = clock if clock is not None else time.time
        self.skew_tolerance_s = float(skew_tolerance_s)

    def try_acquire(self, key: int, owner: str) -> bool:
        key = int(key)
        with self._lock:
            rec = self._recs.get(key)
            if rec is not None and rec.owner is not None:
                return False
            version = 1 if rec is None else rec.version + 1
            self._recs[key] = _Rec(str(owner), float(self._clock()),
                                   version)
            self._mutated(key)
            return True

    def release(self, key: int, owner: Optional[str] = None) -> bool:
        key = int(key)
        with self._lock:
            rec = self._recs.get(key)
            if rec is None or rec.owner is None:
                return False
            if owner is not None and rec.owner != str(owner):
                return False
            rec.owner = None
            rec.stamp = float(self._clock())
            rec.version += 1
            self._mutated(key)
            return True

    def heartbeat(self, key: int, owner: str) -> bool:
        key = int(key)
        with self._lock:
            rec = self._recs.get(key)
            if rec is None or rec.owner != str(owner):
                return False
            rec.stamp = float(self._clock())
            rec.version += 1
            self._mutated(key)
            return True

    def age_s(self, key: int, now=None) -> Optional[float]:
        key = int(key)
        with self._lock:
            rec = self._recs.get(key)
            if rec is None or rec.owner is None:
                return None
            now = float(self._clock()) if now is None else float(now)
            return max(0.0, now - rec.stamp)

    def break_stale(self, key: int, ttl_s: float, now=None) -> bool:
        key = int(key)
        with self._lock:
            rec = self._recs.get(key)
            if rec is None or rec.owner is None:
                return False
            now_v = float(self._clock()) if now is None else float(now)
            age = max(0.0, now_v - rec.stamp)
            if age <= float(ttl_s) + self.skew_tolerance_s:
                return False
            version = rec.version
            # tombstone-if-version: under this lock the re-read is
            # trivially current, but the shape is the remote-CAS
            # contract — a beat between the staleness read and the
            # delete MUST refuse it
            cur = self._recs.get(key)
            if cur is None or cur.version != version:
                return False
            cur.owner = None
            cur.stamp = float(self._clock())
            cur.version += 1
            self._mutated(key)
            return True

    def owner_of(self, key: int) -> Optional[str]:
        with self._lock:
            rec = self._recs.get(int(key))
            return None if rec is None else rec.owner

    def list_keys(self) -> List[int]:
        with self._lock:
            return sorted(k for k, rec in self._recs.items()
                          if rec.owner is not None)

    # -- replication primitives (ISSUE 18, serve.replicated) ----------------

    def get(self, key: int, now=None) -> Optional[dict]:
        """The versioned read: the key's full record — tombstones
        included — or None when the key was never seen.  ``age`` is
        computed HERE, against this replica's clock (stamps never cross
        clocks) unless the caller supplies its own ``now`` (the trait's
        single-clock affordance, forwarded by the quorum client so
        ``age_s(key, now=...)`` means the same thing on every backend);
        None for a tombstone."""
        with self._lock:
            rec = self._recs.get(int(key))
            if rec is None:
                return None
            now_v = float(self._clock()) if now is None else float(now)
            age = (None if rec.owner is None
                   else max(0.0, now_v - rec.stamp))
            return {"owner": rec.owner, "stamp": rec.stamp,
                    "version": rec.version, "age": age}

    def put_rec(self, key: int, owner: Optional[str], stamp: float,
                version: int) -> bool:
        """Conditional versioned write — the quorum-CAS primitive:
        apply iff ``version`` is STRICTLY newer than the stored one
        (absent = 0).  Each replica therefore acks at most one writer
        per version number, which is what makes a majority of acks an
        election.  Also the anti-entropy repair op (push a winner to a
        stale replica)."""
        key, version = int(key), int(version)
        with self._lock:
            cur = self._recs.get(key)
            if cur is not None and cur.version >= version:
                return False
            self._recs[key] = _Rec(
                None if owner is None else str(owner),
                float(stamp), version)
            self._mutated(key)
            return True

    def dump(self) -> list:
        """Every record (tombstones included) as ``[key, owner, stamp,
        version]`` rows — the anti-entropy transfer format."""
        with self._lock:
            return [[k, rec.owner, rec.stamp, rec.version]
                    for k, rec in sorted(self._recs.items())]

    def _mutated(self, key: int) -> None:
        """Post-mutation hook (lock held); the durable subclass appends
        the key's new record to its WAL here.  A no-op in memory."""

    # -- test hooks ---------------------------------------------------------

    def backdate(self, key: int, dt_s: float) -> None:
        """Age one lease by ``dt_s`` (conformance-suite staleness hook —
        the dict analogue of ``os.utime`` backdating a lease file)."""
        with self._lock:
            rec = self._recs.get(int(key))
            if rec is not None:
                rec.stamp -= float(dt_s)
                self._mutated(int(key))

    def inject_fault(self, writer: str, kind: str = "ENOSPC",
                     count: int = 1, match: str = "") -> bool:
        """Arm a deterministic disk fault in THIS process (drill hook —
        reaching a replica's ``utils.checkpoint`` injector over the
        wire is how the snapshot-mid-write drill works).  ``writer`` is
        the blessed-writer name (``op`` is taken by the wire dispatch)."""
        from ..utils.checkpoint import arm_disk_fault

        arm_disk_fault(writer, kind=kind, count=count, match=match)
        return True


# -- the loopback CAS: same semantics, across real processes ----------------

_CAS_OPS = {"try_acquire", "release", "heartbeat", "age_s",
            "break_stale", "owner_of", "list_keys", "backdate", "ping",
            # replication / durability tier (ISSUE 18): versioned read,
            # conditional versioned write, anti-entropy transfer, and
            # the drill hook arming a disk fault inside the replica
            "get", "put_rec", "dump", "inject_fault"}


class _CASHandler(socketserver.StreamRequestHandler):
    """One connection, many line-JSON requests: ``{"op": ..., ...}`` in,
    ``{"r": <result>}`` (or ``{"err": ...}``) out.  Every op executes
    under the wrapped backend's lock, so each request is atomic — the
    server IS the serialization point, exactly the role an object
    store's conditional-put API plays."""

    def handle(self):
        backend: MemoryCASBackend = self.server.backend
        for line in self.rfile:
            try:
                req = json.loads(line.decode("utf-8"))
                op = req.pop("op")
                if op not in _CAS_OPS:
                    raise ValueError(f"unknown CAS op {op!r}")
                r = (True if op == "ping"
                     else getattr(backend, op)(**req))
                resp = {"r": r}
            except Exception as e:   # a bad request must not kill the server
                resp = {"err": f"{type(e).__name__}: {e}"}
            try:
                self.wfile.write((json.dumps(resp) + "\n").encode("utf-8"))
            except OSError:
                return


class _CASTCPServer(socketserver.ThreadingTCPServer):
    daemon_threads = True
    allow_reuse_address = True


class CASServer:
    """A ``MemoryCASBackend`` served over loopback TCP so separate
    processes share one CAS authority.  ``address`` is ``host:port``
    (ephemeral port when constructed with ``port=0``).

    ``data_dir`` (ISSUE 18) makes the server CRASH-DURABLE: the backend
    becomes a ``serve.wal.DurableCASBackend`` that write-ahead-logs
    every mutation (checksummed, fsynced) and compacts to an atomic
    snapshot every ``snapshot_every`` mutations, so a SIGKILLed replica
    restarted over the same directory recovers its exact version map."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 clock=None, skew_tolerance_s: float = 0.0,
                 data_dir: Optional[str] = None,
                 snapshot_every: int = 256, obs=None):
        if data_dir is not None:
            from .wal import DurableCASBackend

            self.backend: MemoryCASBackend = DurableCASBackend(
                data_dir, clock=clock,
                skew_tolerance_s=skew_tolerance_s,
                snapshot_every=snapshot_every, obs=obs)
        else:
            self.backend = MemoryCASBackend(
                clock=clock, skew_tolerance_s=skew_tolerance_s)
        self._srv = _CASTCPServer((host, int(port)), _CASHandler)
        self._srv.backend = self.backend
        self.host, self.port = self._srv.server_address[:2]
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def start(self) -> "CASServer":
        # poll_interval bounds how long ``shutdown()`` blocks (the
        # default 0.5 s charges every short-lived server a teardown tax)
        self._thread = threading.Thread(
            target=lambda: self._srv.serve_forever(poll_interval=0.05),
            name="cas-server", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None

    def __enter__(self) -> "CASServer":
        return self.start()

    def __exit__(self, exc_type, exc, tb) -> None:
        self.stop()


class LoopbackCASBackend(LeaseBackend):
    """Client half of ``CASServer``: every trait op is one line-JSON
    round trip on a persistent per-backend connection (re-dialed on
    failure).  Substrate failures surface as ``ConnectionError`` — the
    store's ``LEASE_BACKEND_FAULT`` degrade path owns them."""

    name = "loopback-cas"

    def __init__(self, address: str, timeout_s: float = 10.0):
        host, _, port = str(address).rpartition(":")
        self.address = str(address)
        self._host, self._port = host or "127.0.0.1", int(port)
        self._timeout_s = float(timeout_s)
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None
        self._rfile = None

    def _call(self, op: str, **kw):
        with self._lock:
            for attempt in (0, 1):   # one re-dial on a dropped connection
                try:
                    if self._sock is None:
                        self._sock = socket.create_connection(
                            (self._host, self._port),
                            timeout=self._timeout_s)
                        self._rfile = self._sock.makefile("rb")
                    self._sock.sendall(
                        (json.dumps(dict(kw, op=op)) + "\n").encode())
                    line = self._rfile.readline()
                    if line:
                        break
                    raise ConnectionError("CAS server closed connection")
                except (OSError, ConnectionError):
                    self._close_locked()
                    if attempt:
                        raise
            resp = json.loads(line.decode("utf-8"))
        if "err" in resp:
            raise ConnectionError(f"CAS backend error: {resp['err']}")
        return resp["r"]

    def _close_locked(self) -> None:
        if self._rfile is not None:
            try:
                self._rfile.close()
            except OSError:
                pass
            self._rfile = None
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def try_acquire(self, key: int, owner: str) -> bool:
        return bool(self._call("try_acquire", key=int(key),
                               owner=str(owner)))

    def release(self, key: int, owner: Optional[str] = None) -> bool:
        return bool(self._call("release", key=int(key), owner=owner))

    def heartbeat(self, key: int, owner: str) -> bool:
        return bool(self._call("heartbeat", key=int(key),
                               owner=str(owner)))

    def age_s(self, key: int, now=None) -> Optional[float]:
        return self._call("age_s", key=int(key), now=now)

    def break_stale(self, key: int, ttl_s: float, now=None) -> bool:
        return bool(self._call("break_stale", key=int(key),
                               ttl_s=float(ttl_s), now=now))

    def owner_of(self, key: int) -> Optional[str]:
        return self._call("owner_of", key=int(key))

    def list_keys(self) -> List[int]:
        return [int(k) for k in self._call("list_keys")]

    def backdate(self, key: int, dt_s: float) -> None:
        self._call("backdate", key=int(key), dt_s=float(dt_s))

    # replication / durability primitives (ISSUE 18)

    def get(self, key: int, now=None) -> Optional[dict]:
        return self._call("get", key=int(key), now=now)

    def put_rec(self, key: int, owner: Optional[str], stamp: float,
                version: int) -> bool:
        return bool(self._call("put_rec", key=int(key), owner=owner,
                               stamp=float(stamp), version=int(version)))

    def dump(self) -> list:
        return self._call("dump")

    def inject_fault(self, writer: str, kind: str = "ENOSPC",
                     count: int = 1, match: str = "") -> bool:
        return bool(self._call("inject_fault", writer=str(writer),
                               kind=str(kind), count=int(count),
                               match=str(match)))

    def ping(self) -> bool:
        return bool(self._call("ping"))

    def close(self) -> None:
        with self._lock:
            self._close_locked()


def make_backend(spec: str, root: Optional[str] = None,
                 skew_tolerance_s: float = 0.0) -> LeaseBackend:
    """Backend from a CLI spelling: ``dir`` (shared-directory default;
    needs ``root``), ``cas:<host>:<port>`` (loopback CAS client),
    ``replicated:<host>:<port>,...`` (quorum client over 2f+1 CAS
    replicas, ISSUE 18), or ``memory`` (single-process CAS, tests)."""
    spec = str(spec)
    if spec == "dir":
        if root is None:
            raise ValueError("lease backend 'dir' requires a store root")
        return SharedDirBackend(root, skew_tolerance_s=skew_tolerance_s)
    if spec.startswith("replicated:"):
        from .replicated import ReplicatedCASBackend

        addrs = [a.strip() for a in spec[len("replicated:"):].split(",")
                 if a.strip()]
        return ReplicatedCASBackend(addrs,
                                    skew_tolerance_s=skew_tolerance_s)
    if spec.startswith("cas:"):
        return LoopbackCASBackend(spec[len("cas:"):])
    if spec == "memory":
        return MemoryCASBackend(skew_tolerance_s=skew_tolerance_s)
    raise ValueError(
        f"unknown lease backend {spec!r} (expected 'dir', 'memory', "
        "'cas:<host>:<port>', or 'replicated:<h>:<p>,<h>:<p>,...')")


# -- replica process entry point (ISSUE 18) ----------------------------------


def replica_main(argv=None) -> int:
    """Run one CAS replica as a standalone process:

        python -m aiyagari_hark_tpu.serve.lease \\
            --port 0 --data-dir /path/to/replica0 --journal j.jsonl

    Prints ``CAS_READY port=<p> pid=<pid>`` once serving (the spawn
    harness parses it), recovers the version map from WAL+snapshot when
    ``--data-dir`` holds a prior life's state, and exits 0 on
    SIGTERM/SIGINT.  SIGKILL is the drill case: the WAL is the
    contract."""
    import argparse
    import signal
    import sys

    p = argparse.ArgumentParser(prog="aiyagari-cas-replica")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--data-dir", default=None,
                   help="WAL+snapshot directory (durable mode)")
    p.add_argument("--journal", default=None,
                   help="append lifecycle events (WAL_REPLAY, "
                        "SNAPSHOT_COMPACT, DISK_FAULT) to this JSONL")
    p.add_argument("--snapshot-every", type=int, default=256)
    p.add_argument("--skew-tolerance-s", type=float, default=0.0)
    args = p.parse_args(argv)

    obs = None
    if args.journal is not None:
        from ..obs.runtime import ObsConfig, build_obs

        obs = build_obs(ObsConfig(enabled=True,
                                  journal_path=args.journal))
    srv = CASServer(host=args.host, port=args.port,
                    skew_tolerance_s=args.skew_tolerance_s,
                    data_dir=args.data_dir,
                    snapshot_every=args.snapshot_every, obs=obs)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    srv.start()
    print(f"CAS_READY port={srv.port} pid={os.getpid()}", flush=True)
    sys.stdout.flush()
    try:
        while not stop.is_set():
            stop.wait(0.2)
    finally:
        srv.stop()
        if obs is not None:
            obs.close()
    return 0


if __name__ == "__main__":   # pragma: no cover - subprocess entry
    import sys

    sys.exit(replica_main())
