"""EquilibriumService: the in-process inference engine for equilibrium
queries — the serving analogue of the batch sweep (DESIGN §8).

Request path per query (``submit`` / ``query``):

1. **exact hit** — the calibration's ``solution_fingerprint`` is in the
   ``SolutionStore``: the future resolves immediately from the cached
   device row.  No device launch, no jax call — microseconds.
2. **near hit** — the store nominates the nearest solved neighbor in the
   same solver group; the service descends the economic bracket toward
   the donor's root (``dyadic_bracket``) and the lane launches with that
   verified-on-device seed (``solve_equilibrium_lean(bracket_init=)``) —
   a wrong donor costs two cheap-end evaluations and falls back to the
   exact cold trajectory in-program.
3. **cold miss** — the lane launches with the pseudo-cold seed
   ``(r_lo, r_hi, 0)``, which the in-program verifier rejects by
   construction (``it0 = 0``), replaying the exact cold midpoint
   sequence.

Misses are micro-batched (``MicroBatcher``): flush on ``max_batch`` or
the ``max_wait_s`` deadline, padded to a fixed shape ladder so a warmed
service owns ONE executable per ladder shape per solver group — the
sweep's shared-executable discipline (``parallel.sweep._batched_solver``
IS the executable: serving and the batch sweep share the compile cache).

Correctness contract (property-tested in ``tests/test_serve.py``): lane
results are bit-identical across batch packing, padding, and batchmates —
a served result equals a batch-of-1 launch of the same executable with
the same seed, bit for bit (and equals the un-vmapped eager
``solve_equilibrium_lean`` on every field except ``capital``, whose
cross-lane reduction order differs at ~1e-11 — see DESIGN §8).  A failed
(NONFINITE/MAX_ITER) cell raises a typed ``EquilibriumSolveFailed`` on
its own future and is never cached; its batchmates' bits are untouched
(PR 1's quarantine isolation, per launch).

Resilience: every launch runs under ``retry_transient`` (transient
device/RPC faults retried on the deterministic backoff schedule; numeric
failure never retried here), and the worker polls
``resilience.interrupt_requested`` at batch seams — inside a
``preemption_guard`` a SIGTERM drains by *failing* pending futures with
the typed ``Interrupted`` instead of leaving callers hung.
"""

from __future__ import annotations

import functools
import threading
import time
from concurrent.futures import Future
from typing import NamedTuple, Optional, Tuple

import numpy as np

from ..obs.runtime import resolve_obs
from ..solver_health import (
    CIRCUIT_OPEN,
    DEADLINE_EXCEEDED,
    LOAD_SHED,
    OVERLOADED,
    SolverDivergenceError,
    is_failure,
    status_name,
)
from ..utils.fingerprint import (
    hashable_kwargs,
    solution_fingerprint,
    work_fingerprint,
)
from ..utils.resilience import (
    Interrupted,
    RetryPolicy,
    interrupt_requested,
    retry_transient,
)
from .batcher import MicroBatcher, ServeQueueFull  # noqa: F401  (re-export)
from .metrics import ServeMetrics
from .overload import CircuitBreaker, Priority, predicted_work
from .store import UNCERTIFIED, SolutionStore, make_solution
from .surrogate import SurrogatePolicy, fit_surrogate

# Queue-depth histogram buckets for the obs registry (ISSUE 8 satellite):
# powers of two spanning "empty" to the default max_queue.
_DEPTH_BUCKETS = (0.0, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0,
                  256.0, 512.0, 1024.0)


def _scenario_of(name: str):
    """Resolve a query's scenario bundle (registry dict lookup — cheap
    enough for the submit hot path; ``make_query`` already validated)."""
    from ..scenarios.registry import get_scenario

    return get_scenario(name)


class ServeError(RuntimeError):
    """Base of the serving layer's typed errors."""


class ServiceClosed(ServeError):
    """submit() after close(): the service no longer accepts queries."""


class EquilibriumSolveFailed(SolverDivergenceError):
    """One query's solve exited with a failure status (NONFINITE /
    MAX_ITER).  Raised on that query's future only — batchmates are
    unaffected.  Subclasses ``SolverDivergenceError`` so the resilience
    layer's never-retry-numeric-failure rule applies to it by type."""

    def __init__(self, cell, status: int, key: int):
        super().__init__(
            f"equilibrium query (σ={cell[0]:g}, ρ={cell[1]:g}, "
            f"sd={cell[2]:g}) failed with status "
            f"{status_name(status)}", status=status)
        self.cell = tuple(cell)
        self.key = int(key)


class DeadlineExceeded(ServeError):
    """A query's deadline expired before its batch launched: the pending
    future fails typed at the next batch seam instead of waiting
    indefinitely (ISSUE 6 SLO satellite).  ``status`` is the
    process-level ``solver_health.DEADLINE_EXCEEDED`` code; counted in
    ``ServeMetrics`` as ``serve_deadline_expirations``."""

    def __init__(self, cell, key: int, waited_s: float):
        super().__init__(
            f"equilibrium query (σ={cell[0]:g}, ρ={cell[1]:g}, "
            f"sd={cell[2]:g}) missed its deadline after waiting "
            f"{waited_s:.3f}s")
        self.status = DEADLINE_EXCEEDED
        self.cell = tuple(cell)
        self.key = int(key)
        self.waited_s = float(waited_s)


class Overloaded(ServeError):
    """Admission control rejected this query FAIL-FAST at submit
    (ISSUE 8, DESIGN §11): the weighted queue occupancy exceeded the
    ``AdmissionPolicy`` budget for its priority class
    (``reason="class_budget"``), the query's deadline could not be met
    given the estimated wait (``reason="deadline_unmeetable"``), or the
    bounded queue itself was full (``reason="queue_full"``).

    Carries the retry-after payload: ``depth`` (queued requests),
    ``max_queue``, and ``est_wait_s`` (queued batches ahead x recent
    batch latency — also aliased ``retry_after_s``).  ``status`` is the
    process-level ``solver_health.OVERLOADED`` code."""

    def __init__(self, cell, key: int, depth: int, max_queue: int,
                 est_wait_s: float, reason: str, priority: int = 0):
        super().__init__(
            f"equilibrium query (σ={cell[0]:g}, ρ={cell[1]:g}, "
            f"sd={cell[2]:g}) rejected: service overloaded ({reason}; "
            f"depth {depth}/{max_queue}, estimated wait "
            f"{est_wait_s:.3f}s)")
        self.status = OVERLOADED
        self.cell = tuple(cell)
        self.key = int(key)
        self.depth = int(depth)
        self.max_queue = int(max_queue)
        self.est_wait_s = float(est_wait_s)
        self.retry_after_s = float(est_wait_s)
        self.reason = str(reason)
        self.priority = int(priority)


class LoadShed(ServeError):
    """A queued pending was displaced by a higher-priority arrival under
    pressure (ISSUE 8): its future fails with this typed error instead
    of silently losing its slot.  ``priority`` is the shed query's own
    class, ``waited_s`` how long it sat queued, ``displaced_by`` the
    displacing query's solution fingerprint.  ``status`` is the
    process-level ``solver_health.LOAD_SHED`` code."""

    def __init__(self, cell, key: int, priority: int, waited_s: float,
                 displaced_by: Optional[int] = None):
        super().__init__(
            f"equilibrium query (σ={cell[0]:g}, ρ={cell[1]:g}, "
            f"sd={cell[2]:g}) shed from the queue after {waited_s:.3f}s "
            f"by a higher-priority arrival")
        self.status = LOAD_SHED
        self.cell = tuple(cell)
        self.key = int(key)
        self.priority = int(priority)
        self.waited_s = float(waited_s)
        self.displaced_by = displaced_by


class CircuitOpen(ServeError):
    """This query's (σ, ρ, sd) region has an OPEN circuit breaker after
    repeated solve/certification failures (ISSUE 8): fast-failed at
    submit without occupying a queue slot or burning a solve.
    ``region`` is the quantized breaker key, ``retry_after_s`` the clock
    time until the region's next half-open probe window.  ``status`` is
    the process-level ``solver_health.CIRCUIT_OPEN`` code."""

    def __init__(self, cell, key: int, region: tuple,
                 retry_after_s: float):
        super().__init__(
            f"equilibrium query (σ={cell[0]:g}, ρ={cell[1]:g}, "
            f"sd={cell[2]:g}) fast-failed: circuit open for region "
            f"{region} (probe in {retry_after_s:.3f}s)")
        self.status = CIRCUIT_OPEN
        self.cell = tuple(cell)
        self.key = int(key)
        self.region = tuple(region)
        self.retry_after_s = float(retry_after_s)


class CertificationFailed(ServeError):
    """A cold-miss solution FAILED a posteriori certification under
    ``certify_before_cache`` (DESIGN §9): the future fails typed with the
    full ``verify.Certificate`` attached, and the solution is NEVER
    written to the store — an uncertifiable answer must not become a
    cache hit."""

    def __init__(self, cell, key: int, certificate):
        super().__init__(
            f"equilibrium query (σ={cell[0]:g}, ρ={cell[1]:g}, "
            f"sd={cell[2]:g}) failed certification: "
            f"{certificate.summary()}")
        self.cell = tuple(cell)
        self.key = int(key)
        self.certificate = certificate


class EquilibriumQuery(NamedTuple):
    """One canonicalized equilibrium request.

    Build with ``make_query`` (which canonicalizes dtype and kwargs);
    equality of two queries' ``key()`` is exactly "every input that can
    move a bit of the answer matches" — the SCENARIO (model family,
    ISSUE 9) included: a huggett query can never address an aiyagari
    entry at numerically identical parameters.  ``fault_iter`` is the
    deterministic fault-injection hook (tests only; requires the service
    to be constructed with ``inject_fault_mode``): faulted queries bypass
    the cache on both read and write."""

    crra: float
    labor_ar: float
    labor_sd: float
    dtype: np.dtype
    kwargs: tuple
    fault_iter: Optional[int] = None
    # overload layer (ISSUE 8): the priority class (serve.Priority —
    # INTERACTIVE=0 > BATCH=1 > SPECULATIVE=2) admission budgets and
    # shedding key on, and the opt-in degraded-answer consent.  Neither
    # enters key()/group(): the same calibration at any priority
    # addresses the same cached solution.
    priority: int = Priority.INTERACTIVE
    degraded_ok: bool = False
    # the registered model family (ISSUE 9): part of key() AND group(),
    # so executables, store entries, donor groups, and breaker regions
    # are all per-scenario.  The field names above keep their historical
    # Aiyagari spellings; for another family read them as the scenario's
    # first/second/third cell coordinates.
    scenario: str = "aiyagari"
    # surrogate opt-out (ISSUE 17): ``False`` forces a genuine solve on
    # a service running with a SurrogatePolicy — lattice warmup and
    # golden replays must not be answered by interpolation over the
    # cells they are trying to solve.  Never enters key()/group().
    surrogate_ok: bool = True

    def cell(self) -> Tuple[float, float, float]:
        return (self.crra, self.labor_ar, self.labor_sd)

    def key(self) -> int:
        return _query_key(self.crra, self.labor_ar, self.labor_sd,
                          self.kwargs, self.dtype, self.scenario)

    def group(self) -> int:
        return _query_group(self.kwargs, self.dtype, self.scenario)


@functools.lru_cache(maxsize=65536)
def _query_key(crra, labor_ar, labor_sd, kwargs, dtype, scenario) -> int:
    """Memoized ``EquilibriumQuery.key()``: the fingerprint is a pure
    function of hashable fields, and the serve path asks for it several
    times per submit (store probe, surrogate tag, journal attrs) — on
    the sub-millisecond surrogate tier the recomputes are measurable."""
    return solution_fingerprint(crra, labor_ar, labor_sd, kwargs, dtype,
                                scenario=scenario)


@functools.lru_cache(maxsize=4096)
def _query_group(kwargs, dtype, scenario) -> int:
    return work_fingerprint(kwargs, dtype, scenario=scenario)


def make_query(crra: float, labor_ar: float, labor_sd: float = 0.2,
               dtype=None, fault_iter: Optional[int] = None,
               priority: int = Priority.INTERACTIVE,
               degraded_ok: bool = False, scenario: str = "aiyagari",
               surrogate_ok: bool = True,
               **model_kwargs) -> EquilibriumQuery:
    """Canonicalize one request: dtype to the concrete compute dtype
    (``dtype=None`` and the explicit default address the same solution),
    kwargs to the sorted hashable items every fingerprint hashes.
    ``priority``/``degraded_ok`` are the overload-layer knobs (ISSUE 8);
    they shape admission, never the answer's bits.  ``scenario`` names
    the registered model family (ISSUE 9) — validated HERE, so a typo
    raises the typed ``scenarios.UnknownScenarioError`` at build time
    instead of silently addressing a fresh cache namespace.

    ``precision`` and ``grid`` policy kwargs ride ``model_kwargs`` and
    are validated/canonicalized by ``hashable_kwargs`` (explicit
    defaults dropped — the no-drift pin; unknown policies raise here at
    build time): a ``grid="compact"`` query therefore keys its OWN
    store entries, donor groups, and executables — a compacted solution
    can never be served for a reference query or vice versa (DESIGN
    §5b)."""
    from ..parallel.sweep import _canonical_dtype
    from ..scenarios.registry import get_scenario

    priority = int(priority)
    if not 0 <= priority <= Priority.SPECULATIVE:
        raise ValueError(
            f"priority must be one of serve.Priority "
            f"(0..{Priority.SPECULATIVE}), got {priority}")
    scn = get_scenario(scenario)
    return EquilibriumQuery(
        crra=float(crra), labor_ar=float(labor_ar),
        labor_sd=float(labor_sd), dtype=_canonical_dtype(dtype),
        kwargs=hashable_kwargs(model_kwargs),
        fault_iter=None if fault_iter is None else int(fault_iter),
        priority=priority, degraded_ok=bool(degraded_ok),
        scenario=scn.name, surrogate_ok=bool(surrogate_ok))


class ServedResult(NamedTuple):
    """One resolved query.  Scalars are host Python numbers (float64
    holds every compute dtype exactly; counters exact — values ≪ 2^24).

    ``bracket_init`` is the exact ``(lo, hi, levels)`` seed the lane
    launched with (``None`` for a cache hit) — passing it to a direct
    ``solve_equilibrium_lean(bracket_init=)`` call reproduces the served
    bits; ``path`` records which serving path produced the numbers."""

    r_star: float
    capital: float
    labor: float
    bisect_iters: int
    egm_iters: int
    dist_iters: int
    status: int
    path: str                       # "hit" | "near" | "cold"
    bracket_init: Optional[tuple]   # (lo, hi, levels) launched with
    key: int                        # solution_fingerprint
    descent_steps: int = 0          # precision-ladder cheap-phase steps
    polish_steps: int = 0           # reference-phase steps (== the total
    #                                 under precision="reference")
    precision_escalations: int = 0  # ladder descent→reference fallbacks
    #                                 (solver_health.PRECISION_ESCALATED)
    cert_level: Optional[int] = None  # verify certificate verdict
    #   (CERTIFIED/MARGINAL; None = this solution was never certified —
    #   FAILED certificates raise CertificationFailed instead)
    quality: str = "exact"          # "exact" | "degraded_neighbor"
    #   (ISSUE 8): a degraded answer is ALWAYS tagged — the numbers are
    #   a nearby calibration's, served under pressure, never cached as
    #   this query's exact solution
    degraded_distance: Optional[float] = None  # normalized (σ,ρ,sd)
    #   distance to the donor (degraded answers only)
    donor_key: Optional[int] = None  # the donor's solution fingerprint
    #   (degraded answers only)
    # scenario layer (ISSUE 9): which model family answered, plus the
    # FULL packed row under its named fields — the Aiyagari-shaped
    # accessors above stay (NaN/0 where a family lacks the field), and
    # ``value("net_demand")`` reads any scenario-specific column.
    scenario: str = "aiyagari"
    fields: tuple = ()
    values: tuple = ()
    # surrogate tier (ISSUE 17, DESIGN §15): an off-lattice answer
    # interpolated over the k nearest certified stored solutions is
    # ALWAYS tagged ``quality="surrogate"`` with its model-implied
    # |error| bound (r* units) and the donor fingerprints — never
    # cached, never served untagged
    surrogate_error_bound: Optional[float] = None
    donor_keys: Optional[tuple] = None

    def value(self, name: str) -> float:
        """One named packed-row field of the answering scenario."""
        return self.values[self.fields.index(name)]


def _result_from_row(schema, row: np.ndarray, path: str, bracket_init,
                     key: int, cert_level=None,
                     scenario: str = "aiyagari") -> ServedResult:
    def g(name):
        return (float(row[schema.idx(name)]) if schema.has(name)
                else float("nan"))

    def gi(name):
        return (int(np.rint(row[schema.idx(name)])) if schema.has(name)
                else 0)

    c_bisect, c_egm, c_dist = schema.counters
    ph = schema.phases
    return ServedResult(
        r_star=float(row[schema.idx(schema.root)]),
        capital=g("capital"), labor=g("labor"),
        bisect_iters=gi(c_bisect), egm_iters=gi(c_egm),
        dist_iters=gi(c_dist),
        status=int(np.rint(row[schema.idx(schema.status)])),
        path=path, bracket_init=bracket_init, key=int(key),
        descent_steps=gi(ph[0]) if ph else 0,
        polish_steps=gi(ph[1]) if ph else 0,
        precision_escalations=gi(ph[2]) if ph else 0,
        cert_level=cert_level, scenario=scenario,
        fields=tuple(schema.fields),
        values=tuple(float(v) for v in np.asarray(row)))


class _Pending(NamedTuple):
    query: EquilibriumQuery
    future: Future
    t_submit: float
    deadline: Optional[float] = None   # absolute clock-units expiry
    weight: float = 0.0                # predicted-work occupancy units
    region: Optional[tuple] = None     # breaker region (admission on)
    probe: bool = False                # this pending IS a half-open probe
    refine: str = ""                   # surrogate-escalation reason
    #   (ISSUE 17): non-empty marks this cold solve as a parameter-space
    #   refinement point — journaled LATTICE_REFINED after publish


class EquilibriumService:
    """Micro-batched equilibrium query engine over a content-addressed
    solution store (module docstring for the architecture).

    ``start_worker=True`` (default) runs a daemon worker thread draining
    the batcher — production mode; ``submit`` returns immediately and
    futures resolve asynchronously.  ``start_worker=False`` is the
    deterministic test mode: nothing launches until ``pump()`` (due
    batches at the injected clock) or ``flush()`` (everything, now).

    ``inject_fault_mode`` ("nan"/"stall") compiles the deterministic
    fault-injection hook into the service's executables (tests only);
    per-query ``fault_iter`` then selects the poisoned lanes, exactly as
    ``run_table2_sweep(inject_fault=)`` does for the batch path.

    Integrity (ISSUE 6, DESIGN §9): ``certify_before_cache=True`` runs a
    posteriori certification (``verify.certify_equilibrium`` recompute
    path, thresholds from ``cert_thresholds`` or the configuration-scaled
    defaults) on every solved cold miss BEFORE the store sees it — a
    FAILED certificate raises ``CertificationFailed`` on that future and
    the solution is never cached; CERTIFIED/MARGINAL verdicts ride
    ``ServedResult.cert_level`` and the store entry.
    ``inject_corrupt_lane={"at_launch": k, "lane": j, "field": f,
    "amplitude": a}`` deterministically corrupts one solved lane of the
    k-th launch post-solve, pre-certification (tests only) — the serve
    path's silent-data-corruption drill.

    Multi-chip (ISSUE 11): ``mesh`` (a ``jax.sharding.Mesh``, or
    ``"auto"`` for one ``cells`` mesh over all local devices) shards
    every cold-miss flush over ``mesh_axis`` — the ladder rounds up to
    per-device multiples (``shard_ladder``) and launches ride the same
    memoized ``parallel.mesh.sharded_launcher`` shard_map wrapper as
    sweep buckets, so served answers match the 1-device path (bitwise on
    root/status/counters; the aggregate contraction to reduction-order
    noise, DESIGN §6b) and exact replay still performs zero new XLA
    compiles.

    State-axis sharding (ISSUE 20): ``state_shards > 1`` activates a 2-D
    state mesh around every cold-miss flush, so queries whose kwargs
    carry ``state="sharded"`` solve with the per-cell wealth state
    partitioned across devices (DESIGN §6b).  Mutually exclusive with a
    multi-lane ``mesh`` — the two dispatch mechanisms cannot nest, and
    an explicit argument is refused rather than silently ignored."""

    def __init__(self, store: Optional[SolutionStore] = None,
                 capacity: int = 256, disk_path: Optional[str] = None,
                 donor_cutoff: float = float("inf"),
                 max_batch: int = 8, max_wait_s: float = 0.002,
                 max_queue: int = 1024,
                 ladder: Optional[Tuple[int, ...]] = None,
                 retry: Optional[RetryPolicy] = None,
                 inject_fault_mode: Optional[str] = None,
                 clock=time.monotonic, start_worker: bool = True,
                 metrics: Optional[ServeMetrics] = None,
                 certify_before_cache: bool = False,
                 cert_thresholds=None,
                 inject_corrupt_lane: Optional[dict] = None,
                 obs=None, admission=None,
                 mesh=None, mesh_axis: str = "cells",
                 state_shards: int = 1,
                 prefetch_k: int = 0, prefetch_cells=None,
                 fleet_poll_s: float = 0.005,
                 surrogate=None):
        # Multi-chip mesh contract FIRST (ISSUE 11): resolve_mesh raises
        # typed on a mesh without the lane axis, and that must happen
        # before this constructor acquires anything that needs closing
        # (an owned obs bundle, the store's disk handle) — a rejected
        # misconfiguration must not leak resources.
        from ..parallel.mesh import mesh_axis_size, resolve_mesh, state_mesh

        self._mesh = resolve_mesh(mesh, str(mesh_axis))
        self._mesh_axis = str(mesh_axis)
        self._mesh_shards = mesh_axis_size(self._mesh, self._mesh_axis)
        # State-axis sharding (ISSUE 20, DESIGN §6b): with
        # ``state_shards > 1`` every cold-miss solve partitions the
        # per-cell wealth state across devices (queries should carry
        # ``state="sharded"`` in their kwargs to route the push-forward
        # through the sharded contraction).  Lane shard_map dispatch and
        # GSPMD state constraints cannot nest, and ``state_shards`` is an
        # EXPLICIT argument — silently ignoring one of the two would hide
        # a misconfiguration, so the combination is refused up front
        # (same pre-resource placement as the lane-mesh contract above).
        if int(state_shards) > 1 and self._mesh_shards > 1:
            raise ValueError(
                f"state_shards={int(state_shards)} cannot combine with a "
                f"multi-lane mesh ({self._mesh_shards} '{self._mesh_axis}' "
                f"shards): shard_map lane dispatch and state-axis GSPMD "
                f"constraints cannot nest — drop the lane mesh (mesh=None) "
                f"or serve with state_shards=1")
        self._state_mesh = (state_mesh(int(state_shards))
                            if int(state_shards) > 1 else None)
        # Observability (ISSUE 7, DESIGN §10): an ObsConfig builds a
        # bundle owned (and closed) by this service; a shared Obs
        # correlates serving with a caller's wider run.  The store
        # adopts the same scope so eviction events land in one journal.
        # NOTE: resolve BEFORE the store so a store built here sees it.
        self._obs, self._obs_owned = resolve_obs(obs)
        self.store = (store if store is not None
                      else SolutionStore(capacity=capacity,
                                         disk_path=disk_path,
                                         donor_cutoff=donor_cutoff,
                                         obs=self._obs))
        self.store.attach_obs(self._obs)
        self.metrics = metrics if metrics is not None else ServeMetrics()
        self.metrics.attach_store(self.store.integrity_counts)
        self.metrics.attach_fleet(self.store.fleet_counts)
        # Fleet tier (ISSUE 15, DESIGN §14): a SHARED store turns every
        # cold-miss launch into a claim/publish election — N worker
        # processes over one disk directory solve each distinct
        # fingerprint exactly once; claim losers poll for the winner's
        # publish (``fleet_poll_s`` real-time cadence — the peer is
        # another PROCESS, no injected clock crosses that boundary).
        self._fleet = bool(getattr(self.store, "shared", False))
        self._fleet_poll_s = float(fleet_poll_s)
        # Speculative neighbor prefetch (ISSUE 15): on a miss, enqueue
        # up to ``prefetch_k`` nearest UNSOLVED lattice neighbors (from
        # ``prefetch_cells``, normalized CellSpace distance, same solver
        # group) at Priority.SPECULATIVE — sheddable by construction
        # under load (PR 8), so prefetch can never displace interactive
        # work.  Conversion accounting: keys whose stored solution came
        # from a speculative solve convert to "prefetch hits" when a
        # later exact hit addresses them.
        self._prefetch_k = int(prefetch_k)
        self._prefetch_cells = (None if prefetch_cells is None else
                                [tuple(float(x) for x in c)
                                 for c in prefetch_cells])
        if self._prefetch_k > 0 and not self._prefetch_cells:
            raise ValueError(
                "prefetch_k > 0 requires prefetch_cells: the prefetcher "
                "needs a lattice to pick neighbors from")
        self._prefetch_lock = threading.Lock()
        self._prefetch_issued_keys: set = set()
        self._prefetch_stored: set = set()
        # Lattice-neighbor enumeration rides the SAME CellIndex seam the
        # store's donor search uses (ISSUE 17): the prefetch lattice is
        # indexed once here, and _maybe_prefetch asks it for the nearest
        # ring instead of re-ranking the whole lattice per miss.
        self._prefetch_index = None
        if self._prefetch_cells:
            from .cellindex import CellIndex

            self._prefetch_index = CellIndex()
            for i, c in enumerate(self._prefetch_cells):
                self._prefetch_index.add(i, c, group=0, r_star=0.0,
                                         cert_level=UNCERTIFIED)
        # Surrogate tier (ISSUE 17, DESIGN §15): a SurrogatePolicy
        # answers off-lattice misses by local interpolation over the k
        # nearest CERTIFIED stored solutions; None (default) disables
        # the tier — behavior and served bits identical to the
        # pre-surrogate engine.  The audit rng is the policy's seeded
        # escalation sampler; _audit_pending maps an escalated key to
        # the surrogate prediction the real solve must be checked
        # against (resolved in _launch_impl, a-posteriori).
        if surrogate is not None and not isinstance(surrogate,
                                                    SurrogatePolicy):
            raise TypeError(
                f"surrogate must be a serve.SurrogatePolicy or None, "
                f"got {type(surrogate).__name__}")
        self._surrogate = surrogate
        self._audit_lock = threading.Lock()
        self._audit_rng = (np.random.default_rng(surrogate.audit_seed)
                           if surrogate is not None else None)
        self._audit_pending: dict = {}
        self._certify = bool(certify_before_cache)
        self._cert_thresholds = cert_thresholds
        self._corrupt_lane = (dict(inject_corrupt_lane)
                              if inject_corrupt_lane is not None else None)
        self._launch_count = 0
        # Multi-chip serving (ISSUE 11): with a mesh, cold-miss flushes
        # pad to per-device multiples (the batcher's ladder rounds up to
        # shard multiples) and dispatch through the same memoized
        # jit(shard_map) wrapper the sweep launches ride
        # (``parallel.mesh.sharded_launcher``) — a warmed multi-chip
        # service still owns ONE executable per ladder shape per solver
        # group, and exact replay performs zero new XLA compiles.
        self.batcher = MicroBatcher(max_batch=max_batch,
                                    max_wait_s=max_wait_s,
                                    max_queue=max_queue, ladder=ladder,
                                    clock=clock,
                                    priority_of=lambda p: p.query.priority,
                                    shard_multiple=self._mesh_shards)
        # Overload layer (ISSUE 8, DESIGN §11): an AdmissionPolicy turns
        # (the mesh was resolved at the top of __init__, pre-resources)
        # saturation into typed, observable behavior — weighted
        # per-class occupancy with fail-fast Overloaded rejection,
        # priority shedding, degraded neighbor answers past the pressure
        # threshold, and per-region circuit breakers.  None (default)
        # disables the whole layer: behavior and served bits are
        # identical to the pre-overload engine.
        self._admission = admission
        self.breaker = (CircuitBreaker.from_policy(admission)
                        if admission is not None else None)
        self._occ_lock = threading.Lock()
        self._occupancy: dict = {}       # priority class -> queued work
        self._batch_ewma_s: Optional[float] = None   # recent batch wall
        self._retry = retry if retry is not None else RetryPolicy()
        self._fault_mode = inject_fault_mode
        self._clock = clock
        self._closed = False
        self._drain_on_close = True
        self._launch_lock = threading.Lock()
        # serializes submit's closed-check+enqueue against close's
        # closed-set+drain, so a request can never slip into the batcher
        # after the final drain (its future would hang forever)
        self._gate = threading.Lock()
        self._worker: Optional[threading.Thread] = None
        if start_worker:
            self._worker = threading.Thread(target=self._worker_loop,
                                            name="equilibrium-serve",
                                            daemon=True)
            self._worker.start()

    # -- client surface -----------------------------------------------------

    def submit(self, q: EquilibriumQuery,
               deadline: Optional[float] = None,
               _prefetch: bool = False) -> Future:
        """Enqueue one query; returns a future resolving to a
        ``ServedResult`` (or raising ``EquilibriumSolveFailed`` /
        ``DeadlineExceeded`` / ``LoadShed`` / ``Interrupted``).  Exact
        cache hits resolve before returning — and BYPASS the overload
        layer entirely: a hit is a dict lookup, it must stay
        microseconds even at 100% cold-miss saturation.

        ``deadline`` (seconds from now, clock units): an
        already-expired deadline (<= 0) rejects IMMEDIATELY with the
        typed ``DeadlineExceeded`` (ISSUE 8 satellite — it never wastes
        a queue slot; counted apart from seam expirations); a pending
        whose deadline expires before its batch launches fails at the
        next batch seam — the SLO primitive.

        With an ``AdmissionPolicy`` (ISSUE 8) a miss additionally runs
        the overload gauntlet fail-fast, in order: regional circuit
        breaker (``CircuitOpen``), degraded answer for opted-in queries
        past the pressure threshold, deadline-aware admission and
        per-class weighted occupancy (``Overloaded`` with retry-after,
        possibly displacing a lower-priority pending with ``LoadShed``)."""
        if self._closed:
            raise ServiceClosed("EquilibriumService is closed")
        if q.fault_iter is not None and self._fault_mode is None:
            raise ValueError(
                "query carries fault_iter but the service was built "
                "without inject_fault_mode")
        t0 = self._clock()
        fut: Future = Future()
        scn = _scenario_of(q.scenario)
        if q.fault_iter is None:
            sol = self.store.get(q.key(),
                                 schema_ck=scn.schema.checksum())
            if sol is not None:
                lvl = int(sol.cert_level)
                res = _result_from_row(
                    scn.schema, np.asarray(sol.packed), "hit", None,
                    q.key(), cert_level=None if lvl == UNCERTIFIED
                    else lvl, scenario=scn.name)
                latency = self._clock() - t0
                self.metrics.record_served("hit", latency,
                                           scenario=scn.name)
                self._note_prefetch_hit(q.key())
                self._obs.record_span("serve/query", latency,
                                      path="hit", cell=q.cell(),
                                      scenario=scn.name)
                fut.set_result(res)
                return fut
        if deadline is not None and float(deadline) <= 0.0:
            self.metrics.record_deadline_reject()
            self._obs.event("DEADLINE_EXCEEDED", cell=q.cell(),
                            scenario=q.scenario,
                            key=q.key(), waited_s=0.0, where="submit")
            self._obs.counter(
                "aiyagari_serve_deadline_rejects_total",
                "queries rejected at submit on an expired or "
                "unmeetable deadline").inc()
            raise DeadlineExceeded(q.cell(), q.key(), 0.0)
        # Surrogate tier (ISSUE 17): a miss with a SurrogatePolicy is
        # answered by local interpolation over stored certified
        # neighbors — microseconds, before the overload gauntlet, like
        # the exact hit above.  A None return with a reason ESCALATES:
        # the query falls through to a genuine cold solve whose publish
        # is journaled as a lattice refinement point.
        esc_reason = ""
        if (self._surrogate is not None and q.surrogate_ok
                and not _prefetch and q.fault_iter is None
                and q.priority != Priority.SPECULATIVE):
            res, esc_reason = self._surrogate_answer(q, t0)
            if res is not None:
                fut.set_result(res)
                return fut
        adm = self._admission
        region = None
        probe = False
        weight = 0.0
        if adm is not None:
            region = self.breaker.region_key(q.cell(), q.group())
            verdict = self.breaker.admit(region, t0)
            if verdict == "open":
                retry_after = self.breaker.retry_after(region, t0)
                self.metrics.record_circuit_reject()
                self._obs.event("CIRCUIT_REJECT", cell=q.cell(),
                                scenario=q.scenario,
                                key=q.key(), region=list(region),
                                retry_after_s=round(retry_after, 6))
                self._obs.counter(
                    "aiyagari_serve_circuit_rejects_total",
                    "queries fast-failed on an open regional "
                    "breaker").inc()
                raise CircuitOpen(q.cell(), q.key(), region, retry_after)
            if verdict == "probe":
                probe = True
                self.metrics.record_breaker("probe")
                self._obs.event("CIRCUIT_PROBE", cell=q.cell(),
                                scenario=q.scenario,
                                key=q.key(), region=list(region))
        acquired = False
        try:
            if adm is not None:
                if (q.degraded_ok and not probe
                        and self._pressure() >= adm.degraded_pressure):
                    res = self._degraded_answer(q, t0)
                    if res is not None:
                        fut.set_result(res)
                        return fut
                weight = predicted_work(q.cell(), scenario=q.scenario)
                # EWMA cold start (ISSUE 15 satellite): before the first
                # flush there is no measured batch latency, so seed from
                # this query's own predicted wall — the first rejection's
                # retry-after is finite and solve-scaled instead of
                # collapsing to the batcher's millisecond max_wait_s
                if adm.est_batch_s is None and self._batch_ewma_s is None:
                    self._batch_ewma_s = max(self.batcher.max_wait_s,
                                             weight * adm.work_unit_s)
                est_wait = self._estimate_wait()
                if (adm.deadline_aware and deadline is not None
                        and float(deadline) < est_wait):
                    self._reject_overloaded(q, "deadline_unmeetable",
                                            est_wait)
                # Check + acquire under ONE lock hold, and BEFORE the
                # offer makes the pending visible to the worker:
                # concurrent submits cannot jointly overshoot the
                # budget, and a fast worker pop cannot release (clamped
                # at zero) ahead of the acquire and leak the weight.
                if not self._try_acquire(q.priority, weight):
                    if adm.shed:
                        self._shed_for(q, t0, weight)
                    if not self._try_acquire(q.priority, weight):
                        self._reject_overloaded(q, "class_budget",
                                                est_wait)
                acquired = True
            expiry = None if deadline is None else t0 + float(deadline)
            pending = _Pending(q, fut, t0, expiry, weight=weight,
                               region=region, probe=probe,
                               refine=esc_reason)
            # Enqueue under the gate: without it a close() between the
            # closed-check above and the offer could run its final drain
            # first, stranding this future.  The worker drains the
            # batcher without taking the gate, so a blocking offer (full
            # queue) cannot deadlock close().  Admission mode never
            # blocks: the bounded queue translates to the typed
            # fail-fast Overloaded.
            with self._gate:
                if self._closed:
                    raise ServiceClosed("EquilibriumService is closed")
                try:
                    # batch groups are per (scenario, dtype, kwargs):
                    # one executable family per model family (ISSUE 9);
                    # a prefetch submit never blocks — best-effort by
                    # construction, a full queue suppresses it
                    self.batcher.offer(
                        (q.scenario, q.dtype, q.kwargs), pending,
                        block=(self._worker is not None and adm is None
                               and not _prefetch))
                except ServeQueueFull:
                    if adm is None:
                        raise
                    self._reject_overloaded(q, "queue_full",
                                            self._estimate_wait())
        except BaseException:
            # No rejection path may leak overload state: acquired weight
            # is returned, and a half-open probe's region goes back to
            # OPEN — a stuck probing flag would pin the breaker open
            # forever (every admit short-circuits on it).
            if acquired:
                self._release(q.priority, weight)
            if probe:
                self.breaker.abort_probe(region)
            raise
        self._observe_depth(self.batcher.depth())
        if (self._prefetch_k > 0 and not _prefetch
                and q.priority != Priority.SPECULATIVE
                and q.fault_iter is None):
            self._maybe_prefetch(q)
        return fut

    # -- speculative neighbor prefetch (ISSUE 15) ---------------------------

    def _maybe_prefetch(self, q: EquilibriumQuery) -> None:
        """Enqueue the K nearest UNSOLVED lattice neighbors of a missed
        query as Priority.SPECULATIVE submits (asymptotic linearity in
        (σ, ρ, sd)-space makes neighbor locality real — PAPERS
        2002.09108): a hot region's surroundings get solved before they
        are asked for, converting future cold misses into exact hits.
        Best-effort by construction: an overloaded/full-queue rejection
        suppresses the issue (counted) and NEVER surfaces to the
        triggering caller — and SPECULATIVE pendings are the first shed
        under pressure, so prefetch cannot displace interactive work."""
        scn = _scenario_of(q.scenario)
        if self._prefetch_index is None:
            return
        # nearest ring from the CellIndex (ISSUE 17) — the same seam
        # the store's donor search answers through, so prefetch stops
        # re-ranking the whole lattice per miss; keys/fingerprints stay
        # LAZY and only for the nearest few (hashing a key per lattice
        # cell per miss would make prefetch O(lattice) on the serving
        # path).  Ties and ordering are bitwise the old linear scan's:
        # (normalized distance, lattice insertion order), with the
        # query's own cell skipped post-hoc.
        attempts = 0
        scanned = 0
        scan_cap = max(4 * self._prefetch_k, 16)
        with self._prefetch_lock:
            near = self._prefetch_index.nearest_k(
                q.cell(), 0, scan_cap + 1, scale=scn.cells.scale)
        for idx, dist in near:
            # K bounds ATTEMPTS, not successes: under pressure the
            # admission layer rejects the speculative class wholesale,
            # and probing the entire lattice about it helps nobody.
            # The scan cap bounds the already-solved skips the same way
            # — past the nearest handful, cells are not "neighbors".
            if attempts >= self._prefetch_k or scanned >= scan_cap:
                break
            cell = self._prefetch_cells[int(idx)]
            if cell == q.cell():
                continue
            scanned += 1
            nq = q._replace(crra=cell[0], labor_ar=cell[1],
                            labor_sd=cell[2],
                            priority=Priority.SPECULATIVE,
                            degraded_ok=False)
            key = nq.key()
            with self._prefetch_lock:
                if key in self._prefetch_issued_keys:
                    continue
                already = self.store.contains(key)
                if not already:
                    self._prefetch_issued_keys.add(key)
            if already:
                continue
            attempts += 1
            try:
                self.submit(nq, _prefetch=True)
            except (ServeError, ServeQueueFull):
                # best-effort: under pressure the speculative class is
                # exactly what admission exists to reject — allow a
                # later retrigger for this key
                with self._prefetch_lock:
                    self._prefetch_issued_keys.discard(key)
                self.metrics.record_prefetch_suppressed()
                continue
            self.metrics.record_prefetch_issued()
            self._obs.event("PREFETCH_ISSUED", cell=list(cell),
                            scenario=q.scenario, key=key,
                            parent_cell=list(q.cell()),
                            distance=round(float(dist), 6))
            self._obs.counter(
                "aiyagari_serve_prefetch_issued_total",
                "speculative neighbor queries issued around "
                "misses").inc()

    def _note_prefetch_stored(self, key: int) -> None:
        with self._prefetch_lock:
            self._prefetch_stored.add(int(key))

    def _note_prefetch_hit(self, key: int) -> None:
        """An exact hit addressed a key a prefetch solve stored: one
        would-be cold miss converted (counted once per stored key)."""
        with self._prefetch_lock:
            if int(key) not in self._prefetch_stored:
                return
            self._prefetch_stored.discard(int(key))
        self.metrics.record_prefetch_converted()

    def prefetch_keys(self) -> list:
        """Keys this service has issued speculative queries for (the
        fleet harness's attribution hook)."""
        with self._prefetch_lock:
            return sorted(self._prefetch_issued_keys)

    def _reject_overloaded(self, q: EquilibriumQuery, reason: str,
                           est_wait: float) -> None:
        """Fail-fast admission rejection: count, journal, raise typed."""
        depth = self.batcher.depth()
        self.metrics.record_overloaded()
        self._obs.event("OVERLOADED", cell=q.cell(), key=q.key(),
                        scenario=q.scenario,
                        reason=reason, depth=depth,
                        est_wait_s=round(est_wait, 6),
                        priority=q.priority)
        self._obs.counter(
            "aiyagari_serve_overloaded_total",
            "queries rejected fail-fast by admission control").inc()
        raise Overloaded(q.cell(), q.key(), depth,
                         self.batcher.max_queue, est_wait, reason,
                         priority=q.priority)

    def _shed_for(self, q: EquilibriumQuery, now: float,
                  weight: float) -> None:
        """Priority load shedding (ISSUE 8): displace queued pendings of
        STRICTLY lower classes — least important first, youngest within
        a class — until the arrival fits its class budget or nothing
        sheddable remains.  Each displaced future fails with the typed
        ``LoadShed``; an in-flight probe among them is aborted so its
        region can probe again.  Sheds nothing when even a FULL shed of
        every lower class could not admit the arrival — a victim must
        never be killed for a query that gets rejected anyway."""
        if not self._fits_after_full_shed(q.priority, weight):
            return
        while not self._admit_class(q.priority, weight):
            shed = self.batcher.shed_lowest(max_class=q.priority)
            if shed is None:
                return
            _, p = shed
            self._release_pending(p)
            if p.probe and p.region is not None:
                self.breaker.abort_probe(p.region)
            waited = now - p.t_submit
            if not p.future.done():
                p.future.set_exception(LoadShed(
                    p.query.cell(), p.query.key(), p.query.priority,
                    waited, displaced_by=q.key()))
            self.metrics.record_shed(waited)
            self._obs.event("LOAD_SHED", cell=p.query.cell(),
                            scenario=p.query.scenario,
                            key=p.query.key(),
                            priority=p.query.priority,
                            waited_s=round(waited, 6),
                            displaced_by=q.key())
            self._obs.counter(
                "aiyagari_serve_load_sheds_total",
                "queued pendings displaced by higher-priority "
                "arrivals").inc()

    def _degraded_answer(self, q: EquilibriumQuery,
                         t0: float) -> Optional[ServedResult]:
        """The brown-out path (ISSUE 8, DESIGN §11): past the pressure
        threshold an opt-in ``degraded_ok`` query is answered from the
        store's nearest neighbor within the normalized-distance budget —
        principled because policy/aggregate objects vary smoothly-to-
        linearly in the far field (PAPERS 2002.09108), and honest
        because the result is ALWAYS tagged ``degraded_neighbor`` with
        the distance and donor fingerprint, and is never cached as this
        query's exact answer.  None when no acceptable donor exists (the
        query falls through to normal admission)."""
        adm = self._admission
        scn = _scenario_of(q.scenario)
        near = self.store.nearest(
            q.cell(), q.group(),
            require_certified=adm.degraded_require_certified,
            scale=scn.cells.scale)
        if near is None:
            return None
        donor_key, dist = near
        if dist > adm.degraded_distance:
            return None
        sol = self.store.get(donor_key, schema_ck=scn.schema.checksum())
        if sol is None:     # evicted (LRU or corrupt) since indexing
            return None
        lvl = int(sol.cert_level)
        res = _result_from_row(
            scn.schema, np.asarray(sol.packed), "degraded", None,
            q.key(), cert_level=None if lvl == UNCERTIFIED else lvl,
            scenario=scn.name)
        res = res._replace(quality="degraded_neighbor",
                           degraded_distance=float(dist),
                           donor_key=int(donor_key))
        latency = self._clock() - t0
        self.metrics.record_served("degraded", latency,
                                   scenario=scn.name)
        self._obs.event("DEGRADED_ANSWER", cell=q.cell(), key=q.key(),
                        scenario=scn.name, donor_key=int(donor_key),
                        distance=round(float(dist), 6))
        self._obs.counter(
            "aiyagari_serve_degraded_answers_total",
            "queries answered by a tagged nearest-neighbor under "
            "pressure").inc()
        self._obs.record_span("serve/query", latency, path="degraded",
                              cell=q.cell(), scenario=scn.name)
        return res

    # -- surrogate tier (ISSUE 17, DESIGN §15) ------------------------------

    def _surrogate_escalate(self, q: EquilibriumQuery, reason: str,
                            **attrs) -> str:
        """The surrogate-escalation seam (covered by
        ``check_obs_events``): a surrogate-eligible query falls through
        to a genuine cold solve — too few / too distant donors, an
        error bound over budget, or the seeded audit draw.  Returns the
        reason so ``submit`` can mark the pending as a refinement
        point."""
        self.metrics.record_surrogate_escalated(reason)
        self._obs.event("SURROGATE_ESCALATED", cell=q.cell(),
                        key=q.key(), scenario=q.scenario,
                        reason=reason, **attrs)
        self._obs.counter(
            "aiyagari_serve_surrogate_escalations_total",
            "surrogate-eligible queries escalated to a real "
            "solve").inc()
        return reason

    def _surrogate_answer(self, q: EquilibriumQuery, t0: float):
        """Answer a miss by a distance-weighted local-linear fit over
        the k nearest CERTIFIED stored solutions in normalized CellSpace
        coordinates (``surrogate.fit_surrogate`` — the ``donor_margin``
        machinery generalized to k donors).  Returns ``(result, "")``
        on a served surrogate, ``(None, reason)`` on an escalation, and
        ``(None, "")`` when the group holds nothing interpolable (a
        plain cold miss, not an escalation).

        The answer is ALWAYS tagged ``quality="surrogate"`` with its
        model-implied error bound and donor fingerprints, and is NEVER
        cached: the store continues to hold only genuinely solved
        rows.  Solver-effort counters are zeroed (no solve ran) and the
        status column is the nearest donor's — only value columns are
        interpolated through the equivalent kernel."""
        pol = self._surrogate
        scn = _scenario_of(q.scenario)
        neigh = self.store.neighbors(
            q.cell(), q.group(), k=pol.k,
            require_certified=pol.require_certified,
            scale=scn.cells.scale)
        if not neigh:
            return None, ""
        if len(neigh) < pol.min_donors:
            return None, self._surrogate_escalate(
                q, "too_few_donors", donors=len(neigh))
        d0 = float(neigh[0][2])
        if d0 > pol.max_distance:
            return None, self._surrogate_escalate(
                q, "donor_too_far", distance=round(d0, 6))
        # fetch donor rows through get() — the checksum chain re-runs,
        # so a corrupt donor drops out (and may demote this answer to
        # an escalation) instead of poisoning the fit
        schema_ck = scn.schema.checksum()
        donors = []
        for key, meta, dist in neigh:
            sol = self.store.get(key, schema_ck=schema_ck)
            if sol is not None:
                donors.append((int(key),
                               np.asarray(sol.packed, dtype=np.float64),
                               float(dist), tuple(meta.cell)))
        if len(donors) < pol.min_donors:
            return None, self._surrogate_escalate(
                q, "too_few_donors", donors=len(donors))
        floor = 0.0
        if scn.warm is not None:
            floor = 64.0 * float(
                scn.warm.host_r_tol(dict(q.kwargs), q.dtype))
        schema = scn.schema
        rows = np.stack([r for _, r, _, _ in donors])
        fit = fit_surrogate(
            q.cell(), [c for _, _, _, c in donors],
            rows[:, schema.idx(schema.root)],
            [d for _, _, d, _ in donors],
            scn.cells.scale, floor=floor,
            inflation=pol.bound_inflation)
        donor_keys = tuple(k for k, _, _, _ in donors)
        if fit.bound > pol.max_error_bound:
            return None, self._surrogate_escalate(
                q, "bound_exceeded", bound=float(fit.bound),
                budget=float(pol.max_error_bound))
        if pol.audit_fraction > 0.0:
            # seeded a-posteriori audit: escalate to a REAL solve and
            # remember the prediction; _launch_impl checks the solved
            # r* against the surrogate's own reported bound
            with self._audit_lock:
                audited = (float(self._audit_rng.random())
                           < pol.audit_fraction)
                if audited:
                    self._audit_pending[q.key()] = (
                        float(fit.r_star), float(fit.bound), donor_keys)
            if audited:
                return None, self._surrogate_escalate(
                    q, "audit", bound=float(fit.bound))
        row = fit.kernel @ rows
        # interpolated solver-effort counters are fiction — no solve
        # ran; status is taken from the nearest donor (donors are all
        # healthy stored rows, so ties in status are the norm)
        for name in tuple(schema.counters) + tuple(schema.phases or ()):
            if schema.has(name):
                row[schema.idx(name)] = 0.0
        row[schema.idx(schema.status)] = donors[0][1][
            schema.idx(schema.status)]
        res = _result_from_row(schema, row, "surrogate", None, q.key(),
                               cert_level=None, scenario=scn.name)
        res = res._replace(quality="surrogate",
                           surrogate_error_bound=float(fit.bound),
                           donor_keys=donor_keys)
        latency = self._clock() - t0
        self.metrics.record_served("surrogate", latency,
                                   scenario=scn.name)
        self.metrics.record_surrogate_bound(fit.bound)
        self._obs.event("SURROGATE_SERVED", cell=q.cell(), key=q.key(),
                        scenario=scn.name,
                        bound=float(fit.bound), donors=len(donors),
                        distance=round(d0, 6),
                        linear=bool(fit.linear))
        self._obs.counter(
            "aiyagari_serve_surrogate_total",
            "off-lattice queries answered by the certified "
            "surrogate tier").inc()
        self._obs.record_span("serve/query", latency, path="surrogate",
                              cell=q.cell(), scenario=scn.name)
        return res, ""

    # -- occupancy accounting (admission enabled) ---------------------------

    def _fits_after_full_shed(self, pclass: int, weight: float) -> bool:
        """Could the arrival fit its nested budgets if EVERY
        strictly-lower-class pending were shed?  Shedding only removes
        classes > pclass, so the hypothetical keeps just the occupancy
        of classes c..pclass in each aggregate."""
        adm = self._admission
        shares = adm.class_shares
        with self._occ_lock:
            for c in range(0, min(pclass, len(shares) - 1) + 1):
                agg = sum(w for k, w in self._occupancy.items()
                          if c <= k <= pclass)
                if agg + weight > adm.max_work * shares[c]:
                    return False
        return True

    def _try_acquire(self, pclass: int, weight: float) -> bool:
        """Atomic admit-and-acquire: the nested budget check plus the
        occupancy increment under ONE lock hold, so concurrent submits
        cannot both pass the check and jointly overshoot the budget."""
        with self._occ_lock:
            if not self._admit_class_locked(pclass, weight):
                return False
            self._occupancy[pclass] = (self._occupancy.get(pclass, 0.0)
                                       + weight)
            return True

    def _release(self, pclass: int, weight: float) -> None:
        with self._occ_lock:
            self._occupancy[pclass] = max(0.0,
                                          self._occupancy.get(pclass, 0.0)
                                          - weight)

    def _release_pending(self, p: _Pending) -> None:
        if self._admission is None:
            return
        self._release(p.query.priority, p.weight)

    def _admit_class(self, pclass: int, weight: float) -> bool:
        with self._occ_lock:
            return self._admit_class_locked(pclass, weight)

    def _admit_class_locked(self, pclass: int, weight: float) -> bool:
        """Nested per-class budgets (``_occ_lock`` held): admitting
        ``weight`` at class ``pclass`` must keep, for every class
        c <= pclass, the total occupancy of classes >= c within
        ``max_work * class_shares[c]`` — so less-important classes can
        never consume the headroom reserved for more-important ones."""
        adm = self._admission
        shares = adm.class_shares
        for c in range(0, min(pclass, len(shares) - 1) + 1):
            agg = sum(w for k, w in self._occupancy.items()
                      if k >= c)
            if agg + weight > adm.max_work * shares[c]:
                return False
        return True

    def _pressure(self) -> float:
        """Total weighted queue occupancy as a fraction of the admission
        budget — the shed/degraded trigger."""
        with self._occ_lock:
            total = sum(self._occupancy.values())
        return total / max(self._admission.max_work, 1e-12)

    def _estimate_wait(self) -> float:
        """Estimated queueing delay for a new arrival: queued batches
        ahead x recent batch latency (policy ``est_batch_s`` when
        pinned — the load harness's deterministic mode — else a
        measured EWMA, else ``max_wait_s`` before any batch ran).  The
        ``Overloaded`` retry-after and the deadline-aware admission
        bound."""
        depth = self.batcher.depth()
        if depth == 0:
            return 0.0
        adm = self._admission
        batch_s = adm.est_batch_s if adm is not None else None
        if batch_s is None:
            batch_s = (self._batch_ewma_s
                       if self._batch_ewma_s is not None
                       else self.batcher.max_wait_s)
        batches_ahead = -(-depth // self.batcher.max_batch)
        return batches_ahead * float(batch_s)

    def _observe_depth(self, depth: int) -> None:
        """Queue-depth sample (submit and pre-pop): metrics histogram +
        peak, mirrored into the obs registry histogram when enabled."""
        self.metrics.note_queue_depth(depth)
        if self._obs.enabled:
            self._obs.histogram(
                "aiyagari_serve_queue_depth",
                "queued queries sampled at submit and at batch pop",
                buckets=_DEPTH_BUCKETS).observe(float(depth))

    def query(self, crra: float, labor_ar: float, labor_sd: float = 0.2,
              dtype=None, timeout: Optional[float] = None,
              deadline: Optional[float] = None,
              scenario: str = "aiyagari",
              **model_kwargs) -> ServedResult:
        """Synchronous convenience: build the query, submit, wait.  In
        manual (no-worker) mode pending batches are flushed immediately —
        a lone synchronous caller must not wait out ``max_wait_s``."""
        fut = self.submit(make_query(crra, labor_ar, labor_sd=labor_sd,
                                     dtype=dtype, scenario=scenario,
                                     **model_kwargs),
                          deadline=deadline)
        if self._worker is None and not fut.done():
            self.flush()
        return fut.result(timeout)

    # -- launch machinery ---------------------------------------------------

    def _plan_seed(self, scn, q: EquilibriumQuery,
                   host) -> Tuple[tuple, str]:
        """The lane's bracket seed and serving path: donor descent when
        the store nominates one, the pseudo-cold seed otherwise.  A
        cold-only scenario (``scn.warm is None``) has no seed at all —
        ``host`` is None and every miss is an honest "cold"."""
        from ..parallel.sweep import dyadic_bracket

        if host is None:
            return None, "cold"
        r_lo, r_hi, r_tol, max_levels = host
        nom = self.store.nominate(q.cell(), q.group(),
                                  float(r_hi) - float(r_lo), r_tol,
                                  scale=scn.cells.scale)
        if nom is not None:
            lo, hi, lev = dyadic_bracket(r_lo, r_hi, nom.target,
                                         nom.margin, max_levels, q.dtype)
            if lev > 0:
                return (lo, hi, lev), "near"
        return (r_lo, r_hi, 0), "cold"

    def _expire_due(self, pendings) -> list:
        """The batch-seam deadline gate (ISSUE 6 SLO satellite): fail
        every pending whose deadline has passed with the typed
        ``DeadlineExceeded`` and return the still-live remainder.  Runs
        BEFORE the launch, so an expired query never pays for (or waits
        on) a solve its caller has already abandoned."""
        now = self._clock()
        live = []
        for p in pendings:
            if p.deadline is not None and now >= p.deadline:
                if p.probe and p.region is not None:
                    # the expired pending was a half-open probe: return
                    # its region to OPEN so the next due admit re-probes
                    self.breaker.abort_probe(p.region)
                if not p.future.done():
                    p.future.set_exception(DeadlineExceeded(
                        p.query.cell(), p.query.key(), now - p.t_submit))
                self.metrics.record_expired(now - p.t_submit)
                self._obs.event("DEADLINE_EXCEEDED",
                                cell=p.query.cell(),
                                scenario=p.query.scenario,
                                key=p.query.key(),
                                waited_s=now - p.t_submit)
                self._obs.counter(
                    "aiyagari_serve_deadline_expirations_total",
                    "queries expired at a batch seam").inc()
            else:
                live.append(p)
        return live

    # -- fleet claim / await (ISSUE 15, DESIGN §14) -------------------------

    def _serve_stored(self, p: _Pending, sol, scn,
                      remote: bool = False) -> None:
        """Resolve one pending from a stored entry at a launch seam (the
        fleet gate's re-probe or a peer's awaited publish): an exact hit
        in every respect — the PR 6 checksum and PR 9 ``schema_ck``
        contracts made these bytes verifiably safe to share across
        processes."""
        lvl = int(sol.cert_level)
        res = _result_from_row(
            scn.schema, np.asarray(sol.packed), "hit", None,
            p.query.key(),
            cert_level=None if lvl == UNCERTIFIED else lvl,
            scenario=scn.name)
        now = self._clock()
        if not p.future.done():
            p.future.set_result(res)
        self.metrics.record_served("hit", now - p.t_submit,
                                   scenario=scn.name)
        if remote:
            self.metrics.record_remote_hit()
        self._note_prefetch_hit(p.query.key())
        self._obs.record_span("serve/query", now - p.t_submit,
                              path="hit", cell=p.query.cell(),
                              scenario=scn.name)

    def _fleet_gate(self, group, pendings):
        """Partition one popped batch under the claim protocol: returns
        ``(winners, waiters, dups)`` — claim winners this process
        solves, claim losers that poll for a peer's publish, and
        same-fingerprint in-batch duplicates riding their winner's lane
        (``dups[id(winner)]``).  Pendings whose fingerprint turns out
        already published (a peer solved it since submit) are served
        here and appear in neither list."""
        scenario_name, _, _ = group
        scn = _scenario_of(scenario_name)
        winners, waiters, dups = [], [], {}
        owner_by_key = {}
        for p in pendings:
            if p.query.fault_iter is not None:
                # injection bypasses the cache on read AND write, so it
                # must bypass the election too (it never publishes)
                winners.append(p)
                continue
            key = p.query.key()
            if key in owner_by_key:
                dups.setdefault(id(owner_by_key[key]), []).append(p)
                continue
            sol = self.store.get(key, schema_ck=scn.schema.checksum())
            if sol is not None:
                self._serve_stored(p, sol, scn, remote=True)
                continue
            verdict = self.store.claim(key)
            if verdict == "published":
                sol = self.store.get(key,
                                     schema_ck=scn.schema.checksum())
                if sol is not None:
                    self._serve_stored(p, sol, scn, remote=True)
                    continue
                # published-but-unreadable (evicted as corrupt between
                # probe and load): solve it ourselves — claim again,
                # falling through to winner/waiter on the outcome
                verdict = self.store.claim(key)
            if verdict == "won":
                owner_by_key[key] = p
                winners.append(p)
            else:
                waiters.append(p)
        return winners, waiters, dups

    def _fleet_release_claims(self, pendings) -> None:
        """Return every claim a failed batch holds (launch error, drain,
        interrupt): an unpublishable fingerprint must become claimable
        again immediately, not after the TTL."""
        if not self._fleet:
            return
        for p in pendings:
            if p.query.fault_iter is None:
                self.store.release(p.query.key())

    def _fleet_await(self, group, waiters) -> None:
        """Block-or-poll for claim losers (ISSUE 15): each waiter's
        fingerprint is being solved by a PEER process — poll the shared
        disk for its publish (served as an exact hit, bit-identical to
        the winner's solve by the atomic-publish + checksum chain).  A
        lease that disappears without a publish (the winner's solve
        failed, or crashed and was TTL-reclaimed) re-enqueues the waiter
        for the next flush, where the claim gate re-runs the election —
        this process may win it and solve.  Polls the preemption flag
        (typed ``Interrupted`` at this seam, the PR 3 protocol) and each
        waiter's deadline; real-time polling, because the peer is
        another process no injected clock reaches."""
        from ..utils.timing import Stopwatch

        scenario_name, _, _ = group
        scn = _scenario_of(scenario_name)
        pending = list(waiters)
        budget_s = 5.0 * self.store.lease_ttl_s + 30.0
        watch = Stopwatch()
        while pending:
            if interrupt_requested():
                self._obs.event("INTERRUPTED",
                                what="fleet publish wait",
                                waiters=len(pending))
                exc = Interrupted(
                    "equilibrium service interrupted while awaiting "
                    "peer publishes; waiting queries failed at the "
                    "fleet seam")
                self._fail_futures(pending, exc)
                raise exc
            now = self._clock()
            still = []
            for p in pending:
                key = p.query.key()
                if p.deadline is not None and now >= p.deadline:
                    if not p.future.done():
                        p.future.set_exception(DeadlineExceeded(
                            p.query.cell(), key, now - p.t_submit))
                    self.metrics.record_expired(now - p.t_submit)
                    self._obs.event("DEADLINE_EXCEEDED",
                                    cell=p.query.cell(),
                                    scenario=p.query.scenario,
                                    key=key, waited_s=now - p.t_submit,
                                    where="fleet_await")
                    continue
                sol = self.store.get(key,
                                     schema_ck=scn.schema.checksum())
                if sol is not None:
                    self._serve_stored(p, sol, scn, remote=True)
                    continue
                if (not self.store.lease_present(key)
                        or self.store.reclaim_if_stale(key)):
                    # winner abandoned (failure) or crashed (stale):
                    # take over — the next flush re-runs the election
                    try:
                        self.batcher.offer(
                            (p.query.scenario, p.query.dtype,
                             p.query.kwargs), p, block=False)
                    except ServeQueueFull:
                        if not p.future.done():
                            p.future.set_exception(ServeError(
                                "fleet re-election found the queue "
                                "full; retry the query"))
                        self.metrics.record_failure(now - p.t_submit)
                    continue
                still.append(p)
            pending = still
            if pending:
                if watch.elapsed() > budget_s:
                    # backstop against a pathological lease ping-pong:
                    # fail typed rather than wedge the worker thread
                    exc = ServeError(
                        f"fleet publish wait exceeded {budget_s:.0f}s; "
                        "retry the query")
                    self._fail_futures(pending, exc)
                    return
                time.sleep(self._fleet_poll_s)

    def _launch(self, group, pendings) -> None:
        # the batch worker is a different thread from whichever run
        # built the obs bundle, and the active-scope stack is
        # per-thread: re-activate this service's bundle here so deep
        # seams without a threaded handle (``retry_transient`` backoffs)
        # journal into THIS service's run, not the worker thread's
        # (empty) scope
        with self._obs.activate():
            pendings = self._expire_due(pendings)
            if not pendings:
                return
            waiters, dups = [], {}
            if self._fleet:
                # fleet claim gate (ISSUE 15): re-probe the shared disk
                # (a peer may have published since submit), elect one
                # solver per distinct fingerprint, and split the batch
                # into claim winners (solve here), in-batch duplicates
                # (ride the winner's lane), and claim losers (poll for
                # the peer's publish after the launch)
                pendings, waiters, dups = self._fleet_gate(group,
                                                           pendings)
            try:
                if pendings:
                    self._launch_impl(group, pendings, dups)
            except BaseException as e:
                # only Interrupted escapes _launch_impl (every other
                # failure is scattered onto the batch's own futures):
                # the waiters must fail typed too before the seam
                # protocol unwinds, or their callers hang
                self._fail_futures(waiters, e)
                raise
            if waiters:
                self._fleet_await(group, waiters)

    def _launch_impl(self, group, pendings, dups=None) -> None:
        """Solve one flushed batch: plan seeds, pad to the ladder shape,
        launch the shared executable, certify
        (``certify_before_cache``), scatter rows to futures (deadline
        expiry and the fleet claim gate already ran in ``_launch``).
        ``dups`` maps a pending's id to same-fingerprint batchmates that
        ride its lane (fleet dedup).  Any launch-level failure fails
        this batch's futures (typed), never the service; ``Interrupted``
        re-raises after failing them so the worker can drain."""
        import jax.numpy as jnp

        if not pendings:
            return
        dups = dups if dups is not None else {}
        scenario_name, dtype, kwargs_items = group
        scn = _scenario_of(scenario_name)
        schema = scn.schema
        model_kwargs = dict(kwargs_items)
        host = None
        if scn.warm is not None:
            r_lo, r_hi = scn.warm.host_bracket(model_kwargs, dtype)
            host = (r_lo, r_hi,
                    scn.warm.host_r_tol(model_kwargs, dtype),
                    scn.warm.max_levels(model_kwargs))

        plans = [self._plan_seed(scn, p.query, host) for p in pendings]
        n = len(pendings)
        shape = self.batcher.pad_to(n)
        lanes = list(range(n)) + [n - 1] * (shape - n)
        cells = [pendings[i].query.cell() for i in lanes]
        args = [jnp.asarray(np.asarray([c[0] for c in cells]), dtype=dtype),
                jnp.asarray(np.asarray([c[1] for c in cells]), dtype=dtype),
                jnp.asarray(np.asarray([c[2] for c in cells]), dtype=dtype)]
        if host is not None:
            seeds = [plans[i][0] for i in lanes]
            args += [
                jnp.asarray(np.asarray([s[0] for s in seeds]),
                            dtype=dtype),
                jnp.asarray(np.asarray([s[1] for s in seeds]),
                            dtype=dtype),
                jnp.asarray(np.asarray([s[2] for s in seeds],
                                       dtype=np.int32))]
        if self._fault_mode is not None:
            fault = [(-1 if pendings[i].query.fault_iter is None
                      else pendings[i].query.fault_iter) for i in lanes]
            args.append(jnp.asarray(np.asarray(fault, dtype=np.int32)))
        # State-axis sharding (ISSUE 20): the state mesh rides a
        # thread-local read at solver-factory AND trace time, and this
        # runs on the worker thread — the context must wrap the factory
        # call (memo-key geometry token), the ledger's lowering capture,
        # and the launch (cold-call tracing).  ``None`` deactivates: the
        # replicated path is untouched.
        from ..parallel.mesh import active_state_mesh

        with active_state_mesh(self._state_mesh):
            fn = scn.batched_solver(dtype, kwargs_items, self._fault_mode,
                                    host is not None)
            if self._mesh_shards > 1:
                # multi-chip flush (ISSUE 11): the ladder shape divides
                # the mesh (shard_ladder rounding), so one
                # shard_map-wrapped launch of the shared executable
                # dispatches the batch across every device — same
                # wrapper, same memoization, as the sweep's bucket
                # launches
                import jax

                from ..parallel.mesh import sharded_launcher, sharding

                fn = sharded_launcher(fn, self._mesh, self._mesh_axis)
                shard = sharding(self._mesh, self._mesh_axis)
                args = [jax.device_put(a, shard) for a in args]

            # measured cost attribution (ISSUE 10): same compile-cache
            # keying as the sweep's ledger — a warmed service owns one
            # executable per (scenario, flavor, ladder shape), so the
            # ledger's entry count IS the executable-ladder audit
            prof = self._obs.cost_ledger
            prof_key = None
            if prof is not None:
                flavor = "warm" if host is not None else "cold"
                prof_key = ("serve", scn.name,
                            work_fingerprint(kwargs_items, dtype,
                                             scenario=scn.name),
                            flavor, shape, self._fault_mode,
                            self._mesh_shards)
                prof.capture(prof_key, fn, args,
                             label=f"serve/{scn.name}/{flavor}{shape}"
                                   + (f"x{self._mesh_shards}"
                                      if self._mesh_shards > 1 else ""))

            t_launch = self._clock()
            try:
                with self._launch_lock, self.metrics.compile, \
                        self._obs.span("serve/batch_flush", lanes=n,
                                       shape=shape, scenario=scn.name,
                                       device_profile=True) as bsp:
                    packed = retry_transient(
                        lambda: np.asarray(fn(*args)), self._retry,
                        label=f"serve batch [{shape}]")
                    # phase split from the returned counters (no tracing
                    # inside jit): real lanes only — padding duplicates
                    # would double-count
                    if schema.phases is not None:
                        bsp.subdivide(
                            {"descent": float(
                                packed[:n, schema.idx(schema.phases[0])]
                                .sum()),
                             "polish": float(
                                 packed[:n, schema.idx(schema.phases[1])]
                                 .sum())},
                            prefix="serve/phase/")
            except BaseException as e:
                self._fleet_release_claims(pendings)
                pendings = pendings + [d for ps in dups.values()
                                       for d in ps]
                self._abort_probes(pendings)
                for p in pendings:
                    self._audit_forget(p)
                    if not p.future.done():
                        p.future.set_exception(e)
                    self.metrics.record_failure(self._clock() - p.t_submit)
                if isinstance(e, Interrupted):
                    raise
                return
        # recent-batch-latency EWMA (clock units): the estimated-wait
        # model behind Overloaded retry-after and deadline-aware
        # admission (policy est_batch_s, when set, takes precedence)
        wall = self._clock() - t_launch
        self._batch_ewma_s = (wall if self._batch_ewma_s is None
                              else 0.25 * wall + 0.75 * self._batch_ewma_s)
        if prof is not None:
            prof.record_launch(prof_key, wall, tracer=self._obs.tracer)
        if self._obs.enabled:
            # per-flush lane telemetry (ISSUE 10): padding efficiency of
            # the ladder shape, plus the per-device memory sample
            self._obs.gauge("aiyagari_serve_batch_lane_occupancy",
                            "real lanes / ladder shape of the last "
                            "flush").set(n / float(shape))
            self._obs.sample_devices(where="serve/batch_flush")

        self.metrics.record_batch(n, shape)
        rows = np.array(np.asarray(packed), dtype=np.float64)
        launch_id = self._launch_count
        self._launch_count += 1
        if (self._corrupt_lane is not None
                and int(self._corrupt_lane.get("at_launch", 0))
                == launch_id):
            # deterministic post-solve lane corruption (tests): the bits
            # are wrong from here on — certification (or the store's
            # checksum chain) must stop them, not serve them
            lane = int(self._corrupt_lane.get("lane", 0))
            rows[lane, int(self._corrupt_lane.get("field", 0))] += float(
                self._corrupt_lane.get("amplitude", 1e-3))

        # certify_before_cache (DESIGN §9): one vmapped certification
        # launch over this batch's healthy, cacheable lanes — the store
        # never persists (and the futures never see) an uncertified
        # FAILED solution
        certs = [None] * len(pendings)
        if self._certify:
            status_col = schema.idx(schema.status)
            idx = [i for i, p in enumerate(pendings)
                   if p.query.fault_iter is None
                   and not is_failure(int(np.rint(rows[i][status_col])))]
            if idx:
                # padded to the ladder shape (last lane duplicated) like
                # the solve launch, so a warmed service owns ONE
                # certifier executable per ladder shape — unpadded, every
                # distinct healthy-lane count would compile its own
                pad = self.batcher.pad_to(len(idx))
                pidx = idx + [idx[-1]] * (pad - len(idx))
                cells = np.asarray([pendings[i].query.cell()
                                    for i in pidx])
                try:
                    if scn.certify_rows is None:
                        raise ValueError(
                            f"scenario {scn.name!r} has no certify_rows "
                            "hook; run the service without "
                            "certify_before_cache")
                    with self._launch_lock, self.metrics.compile:
                        graded = retry_transient(
                            lambda: scn.certify_rows(
                                rows[pidx], cells, dtype, kwargs_items,
                                thresholds=self._cert_thresholds),
                            self._retry, label=f"serve certify [{pad}]")
                except BaseException as e:
                    # certification is a device launch too: a failure
                    # there fails THIS batch's futures typed — it must
                    # never escape _launch and kill the worker with the
                    # futures stranded unresolved
                    self._fleet_release_claims(pendings)
                    pendings = pendings + [d for ps in dups.values()
                                           for d in ps]
                    self._abort_probes(pendings)
                    for p in pendings:
                        self._audit_forget(p)
                        if not p.future.done():
                            p.future.set_exception(e)
                        self.metrics.record_failure(
                            self._clock() - p.t_submit)
                    if isinstance(e, Interrupted):
                        raise
                    return
                for i, cert in zip(idx, graded[:len(idx)]):
                    certs[i] = cert

        now = self._clock()
        status_col = schema.idx(schema.status)
        for i, p in enumerate(pendings):
            row = rows[i]
            status = int(np.rint(row[status_col]))
            seed, path = plans[i]
            lane_dups = dups.get(id(p), ())
            if is_failure(status):
                # a failed solve abandons the fleet claim (failures are
                # never cached/published): the fingerprint becomes
                # claimable again, and remote waiters re-elect
                if self._fleet and p.query.fault_iter is None:
                    self.store.release(p.query.key())
                exc = EquilibriumSolveFailed(
                    p.query.cell(), status, p.query.key())
                self._audit_forget(p)
                for pp in (p,) + tuple(lane_dups):
                    self._breaker_note(pp, ok=False, now=now)
                    pp.future.set_exception(exc)
                    self.metrics.record_failure(now - pp.t_submit)
                self._obs.event("SOLVER_DIVERGED",
                                cell=p.query.cell(),
                                scenario=scn.name,
                                status=status_name(status),
                                where="serve")
                continue
            cert = certs[i]
            if cert is not None:
                self.metrics.record_certificate(cert.level)
                if cert.failed:
                    if self._fleet and p.query.fault_iter is None:
                        self.store.release(p.query.key())
                    exc = CertificationFailed(
                        p.query.cell(), p.query.key(), cert)
                    self._audit_forget(p)
                    for pp in (p,) + tuple(lane_dups):
                        self._breaker_note(pp, ok=False, now=now)
                        pp.future.set_exception(exc)
                        self.metrics.record_failure(now - pp.t_submit)
                    self._obs.event("CERT_FAILED",
                                    cell=p.query.cell(),
                                    scenario=scn.name,
                                    key=p.query.key(),
                                    summary=cert.summary(),
                                    where="serve")
                    continue
            self._breaker_note(p, ok=True, now=now)
            lvl = None if cert is None else cert.level
            res = _result_from_row(schema, row, path, seed,
                                   p.query.key(), cert_level=lvl,
                                   scenario=scn.name)
            if p.query.fault_iter is None:
                entry = make_solution(
                    p.query.cell(), row, p.query.group(), p.query.key(),
                    cert_level=UNCERTIFIED if lvl is None else lvl,
                    schema=schema)
                if self._fleet:
                    # exactly-once completion: atomic publish + lease
                    # release (journaled FLEET_PUBLISH) — remote waiters
                    # polling this fingerprint serve these bits
                    self.store.publish(
                        entry, speculative=(p.query.priority
                                            == Priority.SPECULATIVE),
                        seed=seed)
                else:
                    self.store.put(entry)
                if p.query.priority == Priority.SPECULATIVE:
                    self._note_prefetch_stored(p.query.key())
                if p.refine:
                    self._note_refinement(p, res, lvl, now)
            for pp in (p,) + tuple(lane_dups):
                pp.future.set_result(res)
                self.metrics.record_served(path, now - pp.t_submit,
                                           scenario=scn.name)
                self._obs.record_span("serve/query", now - pp.t_submit,
                                      path=path, cell=pp.query.cell(),
                                      scenario=scn.name)
            self.metrics.record_phases(res.descent_steps, res.polish_steps,
                                       res.precision_escalations)

    def _note_refinement(self, p: _Pending, res: ServedResult, lvl,
                         now: float) -> None:
        """An escalated surrogate query's real solve was published
        (ISSUE 17): journal the parameter-space refinement point —
        the lattice densified exactly where the surrogate failed — and
        resolve a pending seeded audit: the solved r* must land inside
        the surrogate's own reported error bound, or the audit fails
        loudly in metrics and on the LATTICE_REFINED event."""
        pol = self._surrogate
        attrs: dict = {"reason": p.refine}
        with self._audit_lock:
            audit = self._audit_pending.pop(p.query.key(), None)
        if audit is not None:
            r_hat, bound, donor_keys = audit
            err = abs(float(res.r_star) - r_hat)
            ok = bool(err <= bound)
            self.metrics.record_audit(ok)
            attrs.update(audit_ok=ok, surrogate_err=err,
                         surrogate_bound=bound,
                         donors=[int(k) for k in donor_keys])
        if pol is not None and pol.refine:
            self.metrics.record_lattice_refined()
            self._obs.event("LATTICE_REFINED", cell=p.query.cell(),
                            key=p.query.key(),
                            scenario=p.query.scenario,
                            cert_level=lvl, **attrs)
            self._obs.counter(
                "aiyagari_serve_lattice_refinements_total",
                "escalated solves published as parameter-space "
                "refinement points").inc()

    def _audit_forget(self, p: _Pending) -> None:
        """A pending marked for a surrogate audit left the system
        without a published solve (solver failure, launch error): drop
        the stashed prediction so a LATER same-key solve cannot resolve
        a stale audit."""
        if self._surrogate is None or not p.refine:
            return
        with self._audit_lock:
            self._audit_pending.pop(p.query.key(), None)

    # -- pumping / lifecycle ------------------------------------------------

    def pump(self, now: Optional[float] = None) -> int:
        """Manual mode: launch the batches due at ``now`` (injected-clock
        units).  Returns the number of batches launched.  Polls the
        preemption flag first — at a requested shutdown, pending futures
        fail with the typed ``Interrupted`` and it re-raises (the sweep's
        seam protocol: callers see the typed exit, waiters are never left
        hung)."""
        return self._run_batches(self.batcher.pop_ready(now))

    def flush(self) -> int:
        """Launch everything queued regardless of deadlines."""
        return self._run_batches(self.batcher.pop_all())

    def _breaker_note(self, p: _Pending, ok: bool, now: float) -> None:
        """Feed one solved lane's outcome to its region breaker and
        journal/count any transition (open on K failures, close on a
        certified success — including a successful half-open probe)."""
        if self.breaker is None or p.region is None:
            return
        if ok:
            tr = self.breaker.record_success(p.region, now)
        else:
            tr = self.breaker.record_failure(p.region, now)
        if tr in ("opened", "reopened"):
            self.metrics.record_breaker(tr)
            self._obs.event("CIRCUIT_OPEN", region=list(p.region),
                            cell=p.query.cell(),
                            scenario=p.query.scenario, transition=tr)
            self._obs.counter(
                "aiyagari_serve_breaker_opens_total",
                "regional circuit breakers opened (incl. reopens)").inc()
        elif tr == "closed":
            self.metrics.record_breaker("closed")
            self._obs.event("CIRCUIT_CLOSE", region=list(p.region),
                            cell=p.query.cell(),
                            scenario=p.query.scenario)
            self._obs.counter(
                "aiyagari_serve_breaker_closes_total",
                "regional circuit breakers closed on certified "
                "success").inc()

    def _abort_probes(self, pendings) -> None:
        """Pendings leaving the system without a solve outcome (launch
        error, drain, interrupt): any half-open probe among them returns
        its region to OPEN so the next due admit can re-probe."""
        if self.breaker is None:
            return
        for p in pendings:
            if p.probe and p.region is not None:
                self.breaker.abort_probe(p.region)

    def _run_batches(self, batches) -> int:
        """Launch a popped batch list under the seam protocol.  On a
        shutdown request — the flag set before any launch, or an
        ``Interrupted`` escaping a launch — EVERY popped-but-unlaunched
        batch's futures AND everything still queued fail with the typed
        exception before it re-raises: a batch popped out of the batcher
        must never be silently abandoned (its waiters would hang)."""
        remaining = list(batches)
        if remaining:
            # queue-depth sample at the POP side (ISSUE 8 satellite):
            # the pre-pop depth, so drain-heavy loads don't understate
            # the peak; popped pendings release their admission
            # occupancy here — they no longer hold queue slots
            lanes = sum(len(p) for _, p in remaining)
            self._observe_depth(self.batcher.depth() + lanes)
            for _, pendings in remaining:
                for p in pendings:
                    self._release_pending(p)
        count = 0
        try:
            if interrupt_requested():
                self._obs.event("INTERRUPTED", what="equilibrium service",
                                pending_batches=len(remaining))
                raise Interrupted(
                    "equilibrium service interrupted; pending queries "
                    "failed at the batch seam")
            while remaining:
                group, pendings = remaining.pop(0)
                self._launch(group, pendings)
                count += 1
        except Interrupted as e:
            # _launch already failed its own batch's futures before
            # re-raising; fail the popped-but-unlaunched ones, then the
            # still-queued ones, and stop accepting queries
            for _, pendings in remaining:
                self._fail_futures(pendings, e)
            self._fail_pending(e)
            self._closed = True
            raise
        return count

    def _fail_futures(self, pendings, exc: BaseException) -> None:
        self._abort_probes(pendings)
        for p in pendings:
            if not p.future.done():
                p.future.set_exception(exc)
            self.metrics.record_failure(self._clock() - p.t_submit)

    def _fail_pending(self, exc: BaseException) -> None:
        for _, pendings in self.batcher.pop_all():
            for p in pendings:
                self._release_pending(p)
            self._fail_futures(pendings, exc)

    def _worker_loop(self) -> None:
        while True:
            try:
                self._run_batches(self.batcher.wait_ready(timeout=0.05))
                if self._closed:
                    if self._drain_on_close:
                        self._run_batches(self.batcher.pop_all())
                    else:
                        self._fail_pending(
                            ServiceClosed("service closed without drain"))
                    return
            except Interrupted:
                # _run_batches already failed every pending future and
                # closed the service at the seam
                return

    def close(self, drain: bool = True,
              timeout: Optional[float] = None) -> None:
        """Stop accepting queries; by default drain what is queued (every
        pending future resolves), else fail pending with
        ``ServiceClosed``.  Idempotent.  A ``submit`` racing ``close`` is
        serialized by the gate: it either enqueues before the final drain
        (and resolves) or observes the closed flag and raises."""
        with self._gate:
            self._drain_on_close = drain
            self._closed = True
        if self._worker is not None:
            with self.batcher._cond:
                self.batcher._cond.notify_all()
            self._worker.join(timeout)
            self._worker = None
        elif drain and not interrupt_requested():
            self.flush()
        # belt-and-braces: nothing can be queued past the gate-serialized
        # close, but a stray entry must fail typed, never hang
        self._fail_pending(ServiceClosed("service closed"))
        # fleet hygiene: a CLEAN close returns any stray held leases (a
        # batch that errored between claim and release).  An INTERRUPTED
        # close deliberately does not — the preemption path must not add
        # disk I/O between the signal and exit, and the lease TTL is the
        # designed reclaim for a worker that stopped mid-claim
        if self._fleet and not interrupt_requested():
            for key in self.store.held_leases():
                self.store.release(key)
        # heartbeat hygiene (ISSUE 16): stop the store's lease-heartbeat
        # thread deterministically — no thread may outlive the service
        # that owns the store.  Leases were returned above on the clean
        # path; on the interrupted path close(release_leases=False)
        # leaves them for the TTL reclaim, by design.
        if self._fleet and hasattr(self.store, "close"):
            self.store.close(release_leases=False)
        # observability run-end (ISSUE 7): mirror the metrics snapshot
        # into the registry, then flush trace/journal iff this service
        # owns the bundle (an ObsConfig was passed; a shared Obs belongs
        # to the caller's wider run)
        if self._obs.enabled:
            self.metrics.publish(self._obs.registry)
        if self._obs_owned:
            self._obs.close()

    def __enter__(self) -> "EquilibriumService":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close(drain=exc_type is None)

    # -- verification helper ------------------------------------------------

    def reference_solve(self, q: EquilibriumQuery,
                        bracket_init: Optional[tuple] = None):
        """A direct single-cell solve through the SAME executable family
        serving uses (batch-of-1 launch, no store, no batching): the
        bit-identity contract's reference.  ``bracket_init=None`` solves
        cold (the un-seeded executable — exactly
        ``solve_equilibrium_lean`` with no ``bracket_init``); passing a
        served result's ``bracket_init`` reproduces its bits."""
        import jax.numpy as jnp

        from ..parallel.mesh import active_state_mesh

        scn = _scenario_of(q.scenario)
        warm = bracket_init is not None
        # same state-mesh context as the flush path (ISSUE 20): the
        # reference must trace against the SAME geometry serving used,
        # or its bits would come from a differently-placed contraction
        with active_state_mesh(self._state_mesh):
            fn = scn.batched_solver(q.dtype, q.kwargs, None, warm)
            args = [jnp.asarray([q.crra], dtype=q.dtype),
                    jnp.asarray([q.labor_ar], dtype=q.dtype),
                    jnp.asarray([q.labor_sd], dtype=q.dtype)]
            if warm:
                args += [jnp.asarray([bracket_init[0]], dtype=q.dtype),
                         jnp.asarray([bracket_init[1]], dtype=q.dtype),
                         jnp.asarray([bracket_init[2]], dtype=np.int32)]
            row = np.asarray(fn(*args), dtype=np.float64)[0]
        return _result_from_row(scn.schema, row, "reference",
                                bracket_init, q.key(), scenario=scn.name)
