"""Quorum-replicated CAS coordination: 2f+1 replicas, majority-ack
conditional writes, versioned quorum reads with read-repair, and
anti-entropy resync on replica rejoin (ISSUE 18, DESIGN §16).

``ReplicatedCASBackend`` is a CLIENT: it holds no lease state of its
own, only connections to ``2f+1`` ``CASServer`` replicas (usually
durable ones — ``serve.wal``).  Every trait op reduces to two
primitives the replicas expose:

* **versioned quorum read** — ``get`` from every reachable replica;
  fewer than a majority reachable raises the typed
  ``CoordinationUnavailable`` (minority side of a partition: refuse,
  don't guess).  The winner is the highest version; among same-version
  variants (two writers' racing conditional puts can both land on
  DISJOINT minorities) the variant on MORE replicas wins, so every
  reader picks the same record the election actually produced.
  Replicas holding older versions are READ-REPAIRED in passing.
* **majority-ack conditional write** — ``put_rec(key, rec, version =
  winner + 1)``; each replica acks at most ONE writer per version
  number, so at most one writer can collect a majority: the
  exactly-once election property, preserved across replication and
  proven by the same two-real-process conformance races that pin the
  single-server backends (``tests/test_lease_backend.py``).

Partition semantics follow PR 15's fleet contract: the minority side's
``CoordinationUnavailable`` flows into the store's typed
``LEASE_BACKEND_FAULT`` degrade (fail-safe defaults, keep serving
published bits), while the majority side never notices.  A replica that
failed an op is marked SUSPECT; the first successful contact after that
triggers an anti-entropy resync (merge the quorum's dumps, push every
newer record) journaled ``REPLICA_RESYNC`` — so a rejoining replica
(restart, healed partition) converges without waiting for per-key
read-repair traffic.

Clock notes: record stamps are written with the CLIENT's wall clock and
ages are computed by each REPLICA against its own clock; the
``skew_tolerance_s`` staleness window (ISSUE 16) absorbs the spread,
and the winner's age is the MINIMUM over the replicas agreeing on the
winning version — a live owner's lease can only look fresher, never
staler, from aggregation.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional

from .lease import (
    CoordinationUnavailable,
    LeaseBackend,
    LoopbackCASBackend,
)


class ReplicatedCASBackend(LeaseBackend):
    """The quorum client over ``2f+1`` CAS replica addresses (see the
    module docstring for the protocol)."""

    name = "replicated-cas"

    def __init__(self, addresses: List[str],
                 skew_tolerance_s: float = 0.0,
                 timeout_s: float = 5.0, clock=None):
        addresses = [str(a) for a in addresses]
        if not addresses:
            raise ValueError("replicated backend needs >= 1 address")
        if len(addresses) % 2 == 0:
            raise ValueError(
                f"replicated backend wants an odd replica count (2f+1); "
                f"got {len(addresses)} — an even quorum tolerates no "
                "more faults and splits evenly")
        self.addresses = addresses
        self._clients = [LoopbackCASBackend(a, timeout_s=timeout_s)
                         for a in addresses]
        self.n = len(addresses)
        self.majority = self.n // 2 + 1
        self.skew_tolerance_s = float(skew_tolerance_s)
        self._clock = clock if clock is not None else time.time
        self._lock = threading.Lock()
        self._obs = None
        self._partitioned: set = set()   # injected unreachable indices
        self._suspect: set = set()       # failed an op; resync on rejoin
        self._in_resync = False
        self._quorum_ok = True           # QUORUM_LOST edge trigger
        self.resyncs = 0
        self.read_repairs = 0

    def attach_obs(self, obs) -> None:
        """Adopt an observability bundle so QUORUM_LOST/REPLICA_RESYNC
        land in the owning run's journal (first caller wins)."""
        if self._obs is None and obs is not None:
            self._obs = obs

    def _emit(self, etype: str, **attrs) -> None:
        if self._obs is not None:
            self._obs.event(etype, **attrs)
            return
        from ..obs.runtime import emit_event

        emit_event(etype, **attrs)

    # -- chaos hook ---------------------------------------------------------

    def set_partition(self, indices) -> None:
        """Injected client-side partition (drills): ops to these replica
        indices fail as if the network dropped them.  ``()`` heals."""
        with self._lock:
            self._partitioned = {int(i) for i in indices}

    # -- replica fan-out ----------------------------------------------------

    def _call_replica(self, i: int, op: str, **kw):
        with self._lock:
            if i in self._partitioned:
                raise ConnectionError(
                    f"injected partition from replica {self.addresses[i]}")
        return getattr(self._clients[i], op)(**kw)

    def _fanout(self, op: str, **kw):
        """One op against every replica: ``(results, failed)`` by
        index.  Rejoin detection rides along: a SUSPECT replica that
        answers again gets an anti-entropy resync before the result is
        used further."""
        results: Dict[int, object] = {}
        failed: Dict[int, Exception] = {}
        for i in range(self.n):
            try:
                results[i] = self._call_replica(i, op, **kw)
            except (OSError, ConnectionError) as e:
                failed[i] = e
        rejoined = []
        with self._lock:
            if not self._in_resync:
                rejoined = [i for i in results if i in self._suspect]
            self._suspect -= set(results)
            self._suspect |= set(failed)
        for i in rejoined:
            self._resync_replica(i)
        return results, failed

    def _require_quorum(self, op: str, results: dict) -> None:
        if len(results) >= self.majority:
            with self._lock:
                self._quorum_ok = True
            return
        self._quorum_lost(op, reachable=len(results))

    def _quorum_lost(self, op: str, reachable: int) -> None:
        """The lost-majority seam (covered by ``check_obs_events``):
        journal QUORUM_LOST on the healthy→lost EDGE (a partitioned
        worker retries every op; one event per outage, not per call)
        and raise the typed refusal either way."""
        with self._lock:
            first = self._quorum_ok
            self._quorum_ok = False
        if first:
            self._emit("QUORUM_LOST", op=str(op), reachable=int(reachable),
                       needed=int(self.majority),
                       replicas=list(self.addresses))
        raise CoordinationUnavailable(
            f"quorum lost: {reachable}/{self.n} replicas reachable for "
            f"{op!r}, need {self.majority} — refusing a minority answer")

    # -- quorum read + read-repair ------------------------------------------

    @staticmethod
    def _winner(results: dict):
        """The quorum read's winning record: highest version; among
        same-version variants, the one on most replicas (then the
        lexicographically smallest (stamp, owner), so every reader
        converges on the same pick).  Returns ``(rec, age, holders)``
        with ``age`` the MIN age reported for the winning variant."""
        top_v = 0
        for rec in results.values():
            if rec is not None:
                top_v = max(top_v, int(rec["version"]))
        if top_v == 0:
            return None, None, []
        variants: dict = {}
        for i, rec in results.items():
            if rec is None or int(rec["version"]) != top_v:
                continue
            ident = (rec["owner"], float(rec["stamp"]))
            variants.setdefault(ident, []).append(i)
        (owner, stamp), holders = min(
            variants.items(),
            key=lambda kv: (-len(kv[1]), kv[0][1],
                            kv[0][0] is not None, kv[0][0] or ""))
        ages = [results[i]["age"] for i in holders
                if results[i]["age"] is not None]
        rec = {"owner": owner, "stamp": stamp, "version": top_v}
        return rec, (min(ages) if ages else None), holders

    def _quorum_get(self, key: int, repair: bool = True, now=None):
        results, _failed = self._fanout("get", key=int(key), now=now)
        self._require_quorum("get", results)
        win, age, holders = self._winner(results)
        if win is not None and repair:
            stale = [i for i, rec in results.items()
                     if (0 if rec is None else int(rec["version"]))
                     < win["version"]]
            if stale:
                self._read_repair(int(key), win, stale)
        return win, age

    def _read_repair(self, key: int, win: dict, stale: list) -> None:
        """Push the winning record to replicas observed behind it (the
        per-key half of anti-entropy; journaled REPLICA_RESYNC, covered
        by ``check_obs_events``).  Best-effort: a replica that refuses
        or drops mid-repair is repaired again on the next read."""
        repaired = []
        for i in stale:
            try:
                if self._call_replica(i, "put_rec", key=key,
                                      owner=win["owner"],
                                      stamp=win["stamp"],
                                      version=win["version"]):
                    repaired.append(self.addresses[i])
            except (OSError, ConnectionError):
                continue
        if repaired:
            with self._lock:
                self.read_repairs += len(repaired)
            self._emit("REPLICA_RESYNC", mode="read_repair", key=int(key),
                       version=int(win["version"]), replicas=repaired)

    # -- anti-entropy resync (replica rejoin) --------------------------------

    def _resync_replica(self, i: int) -> None:
        """Full-map repair of one rejoined replica (journaled
        REPLICA_RESYNC, covered by ``check_obs_events``): merge every
        reachable peer's dump by the same winner rule and push each
        record the rejoined replica is missing or holds stale."""
        with self._lock:
            if self._in_resync:
                return
            self._in_resync = True
        try:
            dumps, _failed = self._fanout("dump")
            if len(dumps) < self.majority or i not in dumps:
                return
            have = {int(k): int(v) for k, _o, _t, v in dumps[i]}
            merged: dict = {}
            for j, rows in dumps.items():
                for k, owner, stamp, version in rows:
                    k, version = int(k), int(version)
                    cur = merged.get(k)
                    if cur is None or version > cur[2]:
                        merged[k] = (owner, float(stamp), version)
            pushed = 0
            for k, (owner, stamp, version) in merged.items():
                if have.get(k, 0) >= version:
                    continue
                try:
                    if self._call_replica(i, "put_rec", key=k, owner=owner,
                                          stamp=stamp, version=version):
                        pushed += 1
                except (OSError, ConnectionError):
                    return
            with self._lock:
                self.resyncs += 1
            self._emit("REPLICA_RESYNC", mode="anti_entropy",
                       replica=self.addresses[i], pushed=int(pushed),
                       keys=len(merged))
        finally:
            with self._lock:
                self._in_resync = False

    # -- conditional writes --------------------------------------------------

    def _cond_write(self, op: str, key: int, owner: Optional[str],
                    expect_version: int) -> bool:
        """Majority-ack conditional put at ``expect_version + 1``.
        Fewer than a majority of ACKS (not merely of responses) means a
        racing writer won the version — the election's loser."""
        version = int(expect_version) + 1
        results, _failed = self._fanout(
            "put_rec", key=int(key), owner=owner,
            stamp=float(self._clock()), version=version)
        self._require_quorum(op, results)
        acks = sum(1 for r in results.values() if r)
        return acks >= self.majority

    # -- the LeaseBackend trait ----------------------------------------------

    def try_acquire(self, key: int, owner: str) -> bool:
        win, _age = self._quorum_get(key)
        if win is not None and win["owner"] is not None:
            return False
        expect = 0 if win is None else int(win["version"])
        return self._cond_write("try_acquire", key, str(owner), expect)

    def release(self, key: int, owner: Optional[str] = None) -> bool:
        win, _age = self._quorum_get(key)
        if win is None or win["owner"] is None:
            return False
        if owner is not None and win["owner"] != str(owner):
            return False
        return self._cond_write("release", key, None, int(win["version"]))

    def heartbeat(self, key: int, owner: str) -> bool:
        win, _age = self._quorum_get(key)
        if win is None or win["owner"] != str(owner):
            return False
        return self._cond_write("heartbeat", key, str(owner),
                                int(win["version"]))

    def age_s(self, key: int, now=None) -> Optional[float]:
        # ``now`` rides the versioned read to every replica, which
        # computes age against it instead of its own clock — the trait's
        # single-clock semantics (backward-clock clamp, skew drills)
        # hold verbatim on the quorum; the winner's age is still the
        # MIN over the winning variant's holders.
        win, age = self._quorum_get(key, now=now)
        if win is None or win["owner"] is None:
            return None
        return age

    def break_stale(self, key: int, ttl_s: float, now=None) -> bool:
        win, age = self._quorum_get(key, now=now)
        if win is None or win["owner"] is None or age is None:
            return False
        if age <= float(ttl_s) + self.skew_tolerance_s:
            return False
        # the version guard IS the reclaim-vs-heartbeat close: a beat
        # that landed after our read bumped the version, so our
        # conditional put collides and the majority refuses it
        return self._cond_write("break_stale", key, None,
                                int(win["version"]))

    def owner_of(self, key: int) -> Optional[str]:
        win, _age = self._quorum_get(key)
        return None if win is None else win["owner"]

    def list_keys(self) -> List[int]:
        dumps, _failed = self._fanout("dump")
        self._require_quorum("dump", dumps)
        merged: dict = {}
        for rows in dumps.values():
            for k, owner, stamp, version in rows:
                k, version = int(k), int(version)
                cur = merged.get(k)
                if cur is None or version > cur[1]:
                    merged[k] = (owner, version)
        return sorted(k for k, (owner, _v) in merged.items()
                      if owner is not None)

    def backdate(self, key: int, dt_s: float) -> None:
        """Test hook: age the lease on EVERY replica (strict — a
        partially-backdated quorum would make staleness tests flaky)."""
        _results, failed = self._fanout("backdate", key=int(key),
                                        dt_s=float(dt_s))
        if failed:
            raise ConnectionError(
                f"backdate could not reach replicas "
                f"{sorted(failed)}: {list(failed.values())[0]}")

    def reachable(self) -> int:
        """How many replicas answer a ping right now (health probe)."""
        results, _failed = self._fanout("ping")
        return len(results)

    def close(self) -> None:
        for c in self._clients:
            c.close()


# -- replica process harness (ISSUE 18) --------------------------------------


class ReplicaSet:
    """Spawn/kill/restart ``2f+1`` durable CAS replica PROCESSES (the
    ``serve.lease`` replica entry point) — the DR drills' and
    ``--dr-smoke``'s substrate.  Each replica gets its own WAL+snapshot
    directory and journal under ``root``; ``spec`` is the
    ``replicated:...`` spelling workers consume.  Ports are pinned
    after the first spawn so a RESTARTED replica comes back at the same
    address and clients simply re-dial."""

    def __init__(self, root: str, n: int = 3, snapshot_every: int = 64,
                 ready_timeout_s: float = 60.0):
        if n < 1 or n % 2 == 0:
            raise ValueError(f"replica count must be odd (2f+1), got {n}")
        self.root = str(root)
        self.n = int(n)
        self.snapshot_every = int(snapshot_every)
        self.ready_timeout_s = float(ready_timeout_s)
        self.data_dirs = [os.path.join(self.root, f"replica{i}")
                          for i in range(self.n)]
        self.journals = [os.path.join(self.root, f"replica{i}.journal")
                         for i in range(self.n)]
        self.procs: List[Optional[subprocess.Popen]] = [None] * self.n
        self.ports: List[Optional[int]] = [None] * self.n

    @property
    def spec(self) -> str:
        ports = [p for p in self.ports if p is not None]
        if len(ports) != self.n:
            raise RuntimeError("replica set not fully started")
        return "replicated:" + ",".join(
            f"127.0.0.1:{p}" for p in self.ports)

    def addresses(self) -> List[str]:
        return [f"127.0.0.1:{p}" for p in self.ports if p is not None]

    def start(self) -> "ReplicaSet":
        for i in range(self.n):
            self.start_replica(i)
        return self

    def start_replica(self, i: int) -> None:
        """Spawn replica ``i`` (fresh or RESTART over its surviving
        data dir — recovery is the replica's own WAL replay)."""
        os.makedirs(self.data_dirs[i], exist_ok=True)
        cmd = [sys.executable, "-m", "aiyagari_hark_tpu.serve.lease",
               "--port", str(self.ports[i] or 0),
               "--data-dir", self.data_dirs[i],
               "--journal", self.journals[i],
               "--snapshot-every", str(self.snapshot_every)]
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        # the replica runs `-m aiyagari_hark_tpu.serve.lease`: make the
        # package importable even when the CALLER found it via sys.path
        # rather than cwd (a path-hacked harness in another directory)
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        env["PYTHONPATH"] = (pkg_root + os.pathsep + env["PYTHONPATH"]
                             if env.get("PYTHONPATH") else pkg_root)
        proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                stderr=subprocess.DEVNULL, text=True,
                                env=env)
        port = self._await_ready(proc, i)
        self.procs[i] = proc
        self.ports[i] = port

    def _await_ready(self, proc: subprocess.Popen, i: int) -> int:
        import selectors

        sel = selectors.DefaultSelector()
        sel.register(proc.stdout, selectors.EVENT_READ)
        deadline = time.monotonic() + self.ready_timeout_s  # timing-ok: readiness deadline, not a measurement
        buf = ""
        try:
            while time.monotonic() < deadline:  # timing-ok: readiness deadline, not a measurement
                if proc.poll() is not None:
                    raise RuntimeError(
                        f"CAS replica {i} exited rc={proc.returncode} "
                        "before CAS_READY (corrupt WAL refuses typed — "
                        "check its data dir)")
                if not sel.select(timeout=0.2):
                    continue
                chunk = proc.stdout.readline()
                if not chunk:
                    continue
                buf = chunk.strip()
                if buf.startswith("CAS_READY"):
                    return int(buf.split("port=")[1].split()[0])
            raise TimeoutError(
                f"CAS replica {i} not ready after "
                f"{self.ready_timeout_s:.0f}s (last line: {buf!r})")
        finally:
            sel.close()

    def alive(self, i: int) -> bool:
        p = self.procs[i]
        return p is not None and p.poll() is None

    def kill(self, i: int, sig: int = signal.SIGKILL) -> None:
        p = self.procs[i]
        if p is not None and p.poll() is None:
            p.send_signal(sig)
            p.wait(timeout=30)

    def kill_all(self, sig: int = signal.SIGKILL) -> None:
        for i in range(self.n):
            self.kill(i, sig=sig)

    def restart(self, i: int) -> None:
        self.kill(i)
        self.start_replica(i)

    def returncode(self, i: int):
        p = self.procs[i]
        return None if p is None else p.poll()

    def stop(self) -> None:
        """Orderly teardown: SIGTERM, wait, SIGKILL stragglers."""
        for i in range(self.n):
            p = self.procs[i]
            if p is not None and p.poll() is None:
                p.terminate()
        for i in range(self.n):
            p = self.procs[i]
            if p is None:
                continue
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait(timeout=10)

    def __enter__(self) -> "ReplicaSet":
        return self.start()

    def __exit__(self, exc_type, exc, tb) -> None:
        self.stop()
