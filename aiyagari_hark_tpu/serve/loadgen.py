"""Deterministic open-loop load harness for the serving engine (ISSUE 8,
DESIGN §11).

Nothing in the repo could *generate* overload before this module: the
serve tests exercise saturation with hand-placed submits, and the bench
smoke replays fixed cell lists.  The harness closes that gap with a
seeded, replayable traffic model driven ENTIRELY by the injectable
clock:

* **open-loop arrivals** — inter-arrival times drawn from a seeded
  exponential stream at ``rate`` arrivals per clock second; an open
  loop keeps submitting on schedule regardless of how far the service
  has fallen behind (the regime where admission control earns its keep
  — a closed loop self-throttles and can never overload anything).
* **Zipf-mixed cells** — query popularity over the lattice follows a
  Zipf(``zipf_s``) rank distribution (the ROADMAP's
  millions-of-users traffic model): a few hot calibrations dominate
  (exact hits must stay µs), with a long cold tail.
* **mixed classes** — priorities, per-query deadlines, and
  ``degraded_ok`` consent drawn from seeded mixes, so every typed
  overload outcome is reachable in one run.
* **modeled service time** — the service runs in manual (no-worker)
  mode on a ``ManualClock``; each launched batch occupies the modeled
  server for ``batch_service_s`` clock units, so "capacity" is exactly
  ``max_batch / batch_service_s`` cold queries per clock second and a
  ``rate`` above it genuinely overloads the queue.  All admission
  decisions read the same clock (pin ``AdmissionPolicy.est_batch_s``
  for bit-reproducible decisions), which makes an entire overload run
  REPLAYABLE: same spec + same seed ⇒ the same per-arrival outcome
  sequence, fingerprinted in ``LoadReport.digest``.

The report records what the acceptance criteria need: every arrival's
typed outcome (zero unresolved futures is an invariant, checked), p50/
p99 clock latency per serving path, shed/reject/degrade counts, queue-
depth percentiles, and the breaker transition timeline.

No jax imports at module scope; solves happen inside the service.
"""

from __future__ import annotations

import hashlib
import json
from typing import List, NamedTuple, Optional, Tuple

import numpy as np

from ..utils.timing import stopwatch
from .service import EquilibriumService, ServeError, make_query


class ManualClock:
    """The harness's injectable clock: a plain float the event loop
    advances.  Also handy as the deterministic fake clock in tests."""

    def __init__(self, t: float = 0.0):
        self.t = float(t)

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += float(dt)


class Arrival(NamedTuple):
    """One scheduled query of the open-loop trace."""

    t: float
    cell: Tuple[float, float, float]
    priority: int
    deadline: Optional[float]
    degraded_ok: bool


class LoadSpec(NamedTuple):
    """One replayable load scenario (everything the digest covers).

    ``cells`` is the query lattice in Zipf *rank order* (index 0 is the
    hottest); ``model_kwargs`` the solver configuration every query
    shares; ``warm_frac`` pre-solves the hottest fraction of the
    lattice into the store before the clock starts, so exact hits and
    degraded-answer donors exist.  Capacity is
    ``max_batch / batch_service_s`` cold queries per clock second —
    pick ``rate`` relative to it."""

    cells: Tuple[Tuple[float, float, float], ...]
    model_kwargs: dict
    n_queries: int = 200
    seed: int = 0
    rate: float = 400.0
    zipf_s: float = 1.1
    priority_mix: Tuple[float, float, float] = (0.6, 0.3, 0.1)
    deadline_frac: float = 0.0
    deadline_s: float = 0.05
    degraded_frac: float = 0.0
    batch_service_s: float = 0.01
    warm_frac: float = 0.0


class LoadReport(NamedTuple):
    """One load run's record (see ``run_load``)."""

    arrivals: int
    outcomes: List[str]         # per arrival, in submission order
    counts: dict                # outcome -> count
    digest: str                 # fingerprint of the outcome sequence
    unresolved: int             # futures left unresolved (MUST be 0)
    p50_ms: dict                # clock-unit latency p50 per path
    p99_ms: dict                # clock-unit latency p99 per path
    queue_depth_p50: Optional[float]
    queue_depth_p99: Optional[float]
    queue_depth_peak: int
    breaker_transitions: List[tuple]
    hit_wall_ms: List[float]    # REAL-time exact-hit submit latencies
    snapshot: dict              # full ServeMetrics snapshot


def generate_arrivals(spec: LoadSpec) -> List[Arrival]:
    """The seeded open-loop trace: deterministic for a given spec (one
    ``default_rng(seed)`` stream drawn in a fixed order)."""
    if not spec.cells:
        raise ValueError("LoadSpec.cells must be non-empty")
    rng = np.random.default_rng(spec.seed)
    n_cells = len(spec.cells)
    ranks = np.arange(1, n_cells + 1, dtype=np.float64)
    p = ranks ** -float(spec.zipf_s)
    p /= p.sum()
    mix = np.asarray(spec.priority_mix, dtype=np.float64)
    mix = mix / mix.sum()
    out = []
    t = 0.0
    for _ in range(int(spec.n_queries)):
        t += float(rng.exponential(1.0 / spec.rate))
        cell = spec.cells[int(rng.choice(n_cells, p=p))]
        priority = int(rng.choice(len(mix), p=mix))
        deadline = (float(spec.deadline_s)
                    if rng.random() < spec.deadline_frac else None)
        degraded_ok = bool(rng.random() < spec.degraded_frac)
        out.append(Arrival(t=t, cell=tuple(float(c) for c in cell),
                           priority=priority, deadline=deadline,
                           degraded_ok=degraded_ok))
    return out


def _drain(svc: EquilibriumService, clk: ManualClock, busy_until: float,
           until: Optional[float], service_s: float) -> float:
    """Advance the modeled server up to ``until`` (None = run the queue
    dry): whenever the server is free and a batch is due, jump the
    clock there, pump, and occupy the server for ``launches x
    service_s``.  Returns the new busy-until instant."""
    for _ in range(1_000_000):
        if svc.batcher.depth() == 0:
            break
        t_free = max(clk.t, busy_until)
        if svc.batcher.ready(t_free):
            start = t_free
        else:
            nd = svc.batcher.next_deadline()
            if nd is None:
                break
            start = max(t_free, nd)
        if until is not None and start > until:
            break
        clk.t = start
        launched = svc.pump()
        if launched == 0:
            # modeling mismatch guard: nudge past the next deadline
            nd = svc.batcher.next_deadline()
            if nd is None or (until is not None and nd > until):
                break
            clk.t = max(clk.t, nd)
            continue
        busy_until = clk.t + launched * service_s
    else:
        raise RuntimeError("load harness failed to drain the queue")
    if until is not None and clk.t < until:
        clk.t = until
    return busy_until


def run_load(spec: LoadSpec, admission=None, obs=None,
             max_batch: int = 4, ladder: Optional[tuple] = (1, 2, 4),
             max_queue: int = 256, max_wait_s: float = 0.005,
             measure_hit_wall: bool = False) -> LoadReport:
    """Replay one load scenario against a fresh manual-mode service and
    classify every arrival into a typed outcome.

    Outcome vocabulary (the digest input): ``served:<path>`` (hit /
    near / cold / the tagged ``degraded_neighbor``), ``reject:<Error>``
    (raised at submit: ``Overloaded`` / ``CircuitOpen`` /
    ``DeadlineExceeded``), ``fail:<Error>`` (the future failed:
    ``LoadShed`` / ``DeadlineExceeded`` at a seam /
    ``EquilibriumSolveFailed`` / ...), ``unresolved`` (a future left
    hanging — the invariant the soak pins to zero).

    Same spec (+ policy with a pinned ``est_batch_s``) ⇒ bit-identical
    ``digest``: every scheduling, admission, shedding, and breaker
    decision reads only the manual clock and seeded streams."""
    clk = ManualClock()
    svc = EquilibriumService(start_worker=False, clock=clk,
                             admission=admission, obs=obs,
                             max_batch=max_batch, ladder=ladder,
                             max_queue=max_queue, max_wait_s=max_wait_s)
    try:
        n_warm = int(round(spec.warm_frac * len(spec.cells)))
        for cell in spec.cells[:n_warm]:
            svc.query(cell[0], cell[1], labor_sd=cell[2],
                      **spec.model_kwargs)
        arrivals = generate_arrivals(spec)
        busy_until = clk.t
        slots: list = [None] * len(arrivals)
        hit_wall_ms: List[float] = []
        for i, a in enumerate(arrivals):
            busy_until = _drain(svc, clk, busy_until, a.t,
                                spec.batch_service_s)
            q = make_query(a.cell[0], a.cell[1], labor_sd=a.cell[2],
                           priority=a.priority,
                           degraded_ok=a.degraded_ok,
                           **spec.model_kwargs)
            try:
                with stopwatch() as sw:
                    fut = svc.submit(q, deadline=a.deadline)
                if measure_hit_wall and fut.done():
                    if (fut.exception() is None
                            and fut.result().path == "hit"):
                        hit_wall_ms.append(sw.seconds * 1e3)
                slots[i] = fut
            except ServeError as e:
                slots[i] = e
        _drain(svc, clk, busy_until, None, spec.batch_service_s)
    finally:
        svc.close()

    outcomes = []
    unresolved = 0
    for slot in slots:
        if isinstance(slot, ServeError):
            outcomes.append(f"reject:{type(slot).__name__}")
        elif not slot.done():
            unresolved += 1
            outcomes.append("unresolved")
        elif slot.exception() is not None:
            outcomes.append(f"fail:{type(slot.exception()).__name__}")
        else:
            res = slot.result()
            outcomes.append("served:" + (res.quality
                                         if res.quality != "exact"
                                         else res.path))
    counts: dict = {}
    for o in outcomes:
        counts[o] = counts.get(o, 0) + 1
    # digest over the scenario AND the per-arrival outcome sequence —
    # the replay-bit-reproducibility fingerprint (no wall times inside)
    trace = [[round(a.t, 9), list(a.cell), a.priority,
              a.deadline, a.degraded_ok] for a in arrivals]
    digest = hashlib.blake2b(
        json.dumps([trace, outcomes], sort_keys=True).encode(),
        digest_size=16).hexdigest()

    m = svc.metrics

    def _pct(hist, q):
        v = hist.percentile(q)
        return None if v is None else round(v * 1e3, 4)

    p50 = {p: _pct(m.latency[p], 50) for p in m.latency}
    p99 = {p: _pct(m.latency[p], 99) for p in m.latency}
    p50["all"] = _pct(m.latency_all, 50)
    p99["all"] = _pct(m.latency_all, 99)
    depth_p50 = m.depth_hist.percentile(50)
    depth_p99 = m.depth_hist.percentile(99)
    return LoadReport(
        arrivals=len(arrivals), outcomes=outcomes, counts=counts,
        digest=digest, unresolved=unresolved, p50_ms=p50, p99_ms=p99,
        queue_depth_p50=depth_p50, queue_depth_p99=depth_p99,
        queue_depth_peak=m.queue_depth_peak,
        breaker_transitions=(svc.breaker.transitions()
                             if svc.breaker is not None else []),
        hit_wall_ms=hit_wall_ms, snapshot=m.snapshot())
