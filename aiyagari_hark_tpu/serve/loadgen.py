"""Deterministic open-loop load harness for the serving engine (ISSUE 8,
DESIGN §11).

Nothing in the repo could *generate* overload before this module: the
serve tests exercise saturation with hand-placed submits, and the bench
smoke replays fixed cell lists.  The harness closes that gap with a
seeded, replayable traffic model driven ENTIRELY by the injectable
clock:

* **open-loop arrivals** — inter-arrival times drawn from a seeded
  exponential stream at ``rate`` arrivals per clock second; an open
  loop keeps submitting on schedule regardless of how far the service
  has fallen behind (the regime where admission control earns its keep
  — a closed loop self-throttles and can never overload anything).
* **Zipf-mixed cells** — query popularity over the lattice follows a
  Zipf(``zipf_s``) rank distribution (the ROADMAP's
  millions-of-users traffic model): a few hot calibrations dominate
  (exact hits must stay µs), with a long cold tail.
* **mixed classes** — priorities, per-query deadlines, and
  ``degraded_ok`` consent drawn from seeded mixes, so every typed
  overload outcome is reachable in one run.
* **modeled service time** — the service runs in manual (no-worker)
  mode on a ``ManualClock``; each launched batch occupies the modeled
  server for ``batch_service_s`` clock units, so "capacity" is exactly
  ``max_batch / batch_service_s`` cold queries per clock second and a
  ``rate`` above it genuinely overloads the queue.  All admission
  decisions read the same clock (pin ``AdmissionPolicy.est_batch_s``
  for bit-reproducible decisions), which makes an entire overload run
  REPLAYABLE: same spec + same seed ⇒ the same per-arrival outcome
  sequence, fingerprinted in ``LoadReport.digest``.

The report records what the acceptance criteria need: every arrival's
typed outcome (zero unresolved futures is an invariant, checked), p50/
p99 clock latency per serving path, shed/reject/degrade counts, queue-
depth percentiles, and the breaker transition timeline.

No jax imports at module scope; solves happen inside the service.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
from typing import List, NamedTuple, Optional, Tuple

import numpy as np

from ..utils.timing import Stopwatch, stopwatch
from .service import EquilibriumService, ServeError, make_query


class ManualClock:
    """The harness's injectable clock: a plain float the event loop
    advances.  Also handy as the deterministic fake clock in tests."""

    def __init__(self, t: float = 0.0):
        self.t = float(t)

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += float(dt)


class Arrival(NamedTuple):
    """One scheduled query of the open-loop trace."""

    t: float
    cell: Tuple[float, float, float]
    priority: int
    deadline: Optional[float]
    degraded_ok: bool


class LoadSpec(NamedTuple):
    """One replayable load scenario (everything the digest covers).

    ``cells`` is the query lattice in Zipf *rank order* (index 0 is the
    hottest); ``model_kwargs`` the solver configuration every query
    shares; ``warm_frac`` pre-solves the hottest fraction of the
    lattice into the store before the clock starts, so exact hits and
    degraded-answer donors exist.  Capacity is
    ``max_batch / batch_service_s`` cold queries per clock second —
    pick ``rate`` relative to it."""

    cells: Tuple[Tuple[float, float, float], ...]
    model_kwargs: dict
    n_queries: int = 200
    seed: int = 0
    rate: float = 400.0
    zipf_s: float = 1.1
    priority_mix: Tuple[float, float, float] = (0.6, 0.3, 0.1)
    deadline_frac: float = 0.0
    deadline_s: float = 0.05
    degraded_frac: float = 0.0
    batch_service_s: float = 0.01
    warm_frac: float = 0.0
    # continuous-parameter mix (ISSUE 17): this fraction of arrivals
    # queries a seeded uniform draw over the lattice's bounding hull
    # instead of a lattice point — the surrogate tier's traffic model.
    # The extra rng draws happen ONLY when the fraction is positive, so
    # every pre-surrogate spec's digest is bit-identical.
    offlattice_frac: float = 0.0


class LoadReport(NamedTuple):
    """One load run's record (see ``run_load``)."""

    arrivals: int
    outcomes: List[str]         # per arrival, in submission order
    counts: dict                # outcome -> count
    digest: str                 # fingerprint of the outcome sequence
    unresolved: int             # futures left unresolved (MUST be 0)
    p50_ms: dict                # clock-unit latency p50 per path
    p99_ms: dict                # clock-unit latency p99 per path
    queue_depth_p50: Optional[float]
    queue_depth_p99: Optional[float]
    queue_depth_peak: int
    breaker_transitions: List[tuple]
    hit_wall_ms: List[float]    # REAL-time exact-hit submit latencies
    snapshot: dict              # full ServeMetrics snapshot


def generate_arrivals(spec: LoadSpec) -> List[Arrival]:
    """The seeded open-loop trace: deterministic for a given spec (one
    ``default_rng(seed)`` stream drawn in a fixed order)."""
    if not spec.cells:
        raise ValueError("LoadSpec.cells must be non-empty")
    rng = np.random.default_rng(spec.seed)
    n_cells = len(spec.cells)
    ranks = np.arange(1, n_cells + 1, dtype=np.float64)
    p = ranks ** -float(spec.zipf_s)
    p /= p.sum()
    mix = np.asarray(spec.priority_mix, dtype=np.float64)
    mix = mix / mix.sum()
    # continuous-parameter hull (ISSUE 17): off-lattice arrivals sample
    # the lattice's axis-aligned bounding box.  Computed only when the
    # fraction is positive — the frac=0 stream must draw EXACTLY the
    # pre-surrogate sequence (digest bit-identity).
    off = float(spec.offlattice_frac)
    if off > 0.0:
        hull = np.asarray(spec.cells, dtype=np.float64)
        lo, hi = hull.min(axis=0), hull.max(axis=0)
    out = []
    t = 0.0
    for _ in range(int(spec.n_queries)):
        t += float(rng.exponential(1.0 / spec.rate))
        cell = spec.cells[int(rng.choice(n_cells, p=p))]
        if off > 0.0 and rng.random() < off:
            cell = tuple(float(c) for c in
                         lo + rng.random(lo.shape[0]) * (hi - lo))
        priority = int(rng.choice(len(mix), p=mix))
        deadline = (float(spec.deadline_s)
                    if rng.random() < spec.deadline_frac else None)
        degraded_ok = bool(rng.random() < spec.degraded_frac)
        out.append(Arrival(t=t, cell=tuple(float(c) for c in cell),
                           priority=priority, deadline=deadline,
                           degraded_ok=degraded_ok))
    return out


def _drain(svc: EquilibriumService, clk: ManualClock, busy_until: float,
           until: Optional[float], service_s: float) -> float:
    """Advance the modeled server up to ``until`` (None = run the queue
    dry): whenever the server is free and a batch is due, jump the
    clock there, pump, and occupy the server for ``launches x
    service_s``.  Returns the new busy-until instant."""
    for _ in range(1_000_000):
        if svc.batcher.depth() == 0:
            break
        t_free = max(clk.t, busy_until)
        if svc.batcher.ready(t_free):
            start = t_free
        else:
            nd = svc.batcher.next_deadline()
            if nd is None:
                break
            start = max(t_free, nd)
        if until is not None and start > until:
            break
        clk.t = start
        launched = svc.pump()
        if launched == 0:
            # modeling mismatch guard: nudge past the next deadline
            nd = svc.batcher.next_deadline()
            if nd is None or (until is not None and nd > until):
                break
            clk.t = max(clk.t, nd)
            continue
        busy_until = clk.t + launched * service_s
    else:
        raise RuntimeError("load harness failed to drain the queue")
    if until is not None and clk.t < until:
        clk.t = until
    return busy_until


def run_load(spec: LoadSpec, admission=None, obs=None,
             max_batch: int = 4, ladder: Optional[tuple] = (1, 2, 4),
             max_queue: int = 256, max_wait_s: float = 0.005,
             measure_hit_wall: bool = False,
             surrogate=None) -> LoadReport:
    """Replay one load scenario against a fresh manual-mode service and
    classify every arrival into a typed outcome.

    Outcome vocabulary (the digest input): ``served:<path>`` (hit /
    near / cold / the tagged ``degraded_neighbor``), ``reject:<Error>``
    (raised at submit: ``Overloaded`` / ``CircuitOpen`` /
    ``DeadlineExceeded``), ``fail:<Error>`` (the future failed:
    ``LoadShed`` / ``DeadlineExceeded`` at a seam /
    ``EquilibriumSolveFailed`` / ...), ``unresolved`` (a future left
    hanging — the invariant the soak pins to zero).

    Same spec (+ policy with a pinned ``est_batch_s``) ⇒ bit-identical
    ``digest``: every scheduling, admission, shedding, and breaker
    decision reads only the manual clock and seeded streams."""
    clk = ManualClock()
    svc = EquilibriumService(start_worker=False, clock=clk,
                             admission=admission, obs=obs,
                             max_batch=max_batch, ladder=ladder,
                             max_queue=max_queue, max_wait_s=max_wait_s,
                             surrogate=surrogate)
    try:
        n_warm = int(round(spec.warm_frac * len(spec.cells)))
        for cell in spec.cells[:n_warm]:
            # warmup MUST solve (surrogate_ok=False): with a surrogate
            # policy the later lattice cells would otherwise be answered
            # by interpolation over the first few instead of populating
            # the store the run is warming
            fut = svc.submit(make_query(cell[0], cell[1],
                                        labor_sd=cell[2],
                                        surrogate_ok=False,
                                        **spec.model_kwargs))
            if not fut.done():
                svc.flush()
            fut.result()
        arrivals = generate_arrivals(spec)
        busy_until = clk.t
        slots: list = [None] * len(arrivals)
        hit_wall_ms: List[float] = []
        for i, a in enumerate(arrivals):
            busy_until = _drain(svc, clk, busy_until, a.t,
                                spec.batch_service_s)
            q = make_query(a.cell[0], a.cell[1], labor_sd=a.cell[2],
                           priority=a.priority,
                           degraded_ok=a.degraded_ok,
                           **spec.model_kwargs)
            try:
                with stopwatch() as sw:
                    fut = svc.submit(q, deadline=a.deadline)
                if measure_hit_wall and fut.done():
                    if (fut.exception() is None
                            and fut.result().path == "hit"):
                        hit_wall_ms.append(sw.seconds * 1e3)
                slots[i] = fut
            except ServeError as e:
                slots[i] = e
        _drain(svc, clk, busy_until, None, spec.batch_service_s)
    finally:
        svc.close()

    outcomes = []
    unresolved = 0
    for slot in slots:
        if isinstance(slot, ServeError):
            outcomes.append(f"reject:{type(slot).__name__}")
        elif not slot.done():
            unresolved += 1
            outcomes.append("unresolved")
        elif slot.exception() is not None:
            outcomes.append(f"fail:{type(slot.exception()).__name__}")
        else:
            res = slot.result()
            outcomes.append("served:" + (res.quality
                                         if res.quality != "exact"
                                         else res.path))
    counts: dict = {}
    for o in outcomes:
        counts[o] = counts.get(o, 0) + 1
    # digest over the scenario AND the per-arrival outcome sequence —
    # the replay-bit-reproducibility fingerprint (no wall times inside)
    trace = [[round(a.t, 9), list(a.cell), a.priority,
              a.deadline, a.degraded_ok] for a in arrivals]
    digest = hashlib.blake2b(
        json.dumps([trace, outcomes], sort_keys=True).encode(),
        digest_size=16).hexdigest()

    m = svc.metrics

    def _pct(hist, q):
        v = hist.percentile(q)
        return None if v is None else round(v * 1e3, 4)

    p50 = {p: _pct(m.latency[p], 50) for p in m.latency}
    p99 = {p: _pct(m.latency[p], 99) for p in m.latency}
    p50["all"] = _pct(m.latency_all, 50)
    p99["all"] = _pct(m.latency_all, 99)
    depth_p50 = m.depth_hist.percentile(50)
    depth_p99 = m.depth_hist.percentile(99)
    return LoadReport(
        arrivals=len(arrivals), outcomes=outcomes, counts=counts,
        digest=digest, unresolved=unresolved, p50_ms=p50, p99_ms=p99,
        queue_depth_p50=depth_p50, queue_depth_p99=depth_p99,
        queue_depth_peak=m.queue_depth_peak,
        breaker_transitions=(svc.breaker.transitions()
                             if svc.breaker is not None else []),
        hit_wall_ms=hit_wall_ms, snapshot=m.snapshot())


# -- fleet mode (ISSUE 15, DESIGN §14) --------------------------------------
#
# ``run_load`` models overload inside ONE process on an injected clock;
# ``run_fleet_load`` is its out-of-process sibling: N REAL worker
# processes (``serve.fleet`` workers over one shared disk store), each
# replayed a deterministic per-worker-seeded Zipf mix by its own client
# thread over HTTP, with fleet-wide aggregation — per-path p50/p99, the
# dedup ratio (cold solves / distinct cold fingerprints; 1.0 = the
# claim/lease election held exactly-once fleet-wide), prefetch
# hit-conversion, and the lease-leak audit.  Real wall time throughout:
# the subjects are separate processes no injected clock can reach, so
# outcome MIXES (not digests) are the replayable artifact — the arrival
# traces themselves are seed-deterministic and fingerprinted.


class FleetSpec(NamedTuple):
    """One fleet load scenario.

    ``cells`` is the query lattice in Zipf rank order; each of
    ``n_workers`` workers replays ``queries_per_worker`` arrivals drawn
    from ``Zipf(zipf_s)`` with stream seed ``seed + 1000 * worker``
    (deterministic per worker, different across workers).
    ``warm_count`` hottest cells are pre-published through worker 0
    before the replay.  ``sigterm_worker``/``sigterm_after`` drive the
    preemption drill: that worker receives SIGTERM after its client has
    dispatched that many arrivals (its remaining arrivals fail over to
    the survivors)."""

    cells: Tuple[Tuple[float, float, float], ...]
    model_kwargs: dict
    n_workers: int = 4
    queries_per_worker: int = 40
    seed: int = 0
    zipf_s: float = 0.9
    scenario: str = "aiyagari"
    priority_mix: Tuple[float, float] = (0.7, 0.3)  # INTERACTIVE, BATCH
    prefetch_k: int = 0
    lease_ttl_s: float = 2.0
    warm_count: int = 0
    max_batch: int = 4
    sigterm_worker: Optional[int] = None
    sigterm_after: Optional[int] = None
    # ISSUE 17: fraction of arrivals redrawn uniformly inside the
    # lattice's bounding hull (continuous-parameter queries for the
    # surrogate tier).  Extra RNG draws happen ONLY when positive, so
    # frac=0 traces stay bit-identical to pre-surrogate fleets.
    offlattice_frac: float = 0.0
    # SurrogatePolicy field overrides forwarded to every worker's
    # ``--surrogate`` flag (None = workers serve without a surrogate).
    surrogate: Optional[dict] = None
    # ISSUE 18: coordination backend spec forwarded to every worker's
    # ``--lease-backend`` flag (None = the shared-dir default).  The
    # harness's own lease audit uses the SAME spec, so a CAS-backed
    # fleet is audited against the CAS authority, not an empty dir.
    lease_backend: Optional[str] = None


class FleetReport(NamedTuple):
    """One fleet run's record (``run_fleet_load``)."""

    workers: int
    arrivals: int
    counts: dict                # outcome -> count, fleet-wide
    outcomes_by_worker: list    # per client thread, in dispatch order
    unresolved: int             # arrivals without a terminal outcome
    p50_ms: dict                # real-wall latency p50 per path
    p99_ms: dict
    cold_solves: int            # FLEET_PUBLISH events fleet-wide
    distinct_published: int     # |union of published keys|
    dedup_ratio: Optional[float]  # cold_solves / distinct (1.0 = exact)
    prefetch_issued: int
    prefetch_converted: int     # speculative-published keys later HIT
    remote_hits: int            # hits served from a peer's publish
    claims_won: int
    claims_lost: int
    lease_reclaims: int
    leases_leaked: int          # lease files left after the TTL sweep
    interrupted_rcs: dict       # worker index -> return code (drilled)
    interrupted_journaled: bool  # the SIGTERMed worker journaled typed
    trace_digest: str           # fingerprint of the arrival traces
    worker_snapshots: list      # /metrics of workers alive at the end
    served_values: dict         # key -> first served value fields (the
    #                             bit-identity acceptance input)
    value_divergence: int       # keys whose served VALUE fields ever
    #                             disagreed across responses (MUST be 0:
    #                             loser-serves-winner bit-identity)
    chaos: Optional[dict] = None  # the chaos campaign's ledger when a
    #                             ChaosPlan ran (ISSUE 16): per-drill
    #                             records, injected/detected counts,
    #                             drilled dedup ratio, availability and
    #                             churn/hedge accounting


def generate_fleet_arrivals(spec: FleetSpec, worker: int) -> list:
    """Worker ``worker``'s deterministic Zipf trace: a list of
    ``(cell, priority)`` drawn from one ``default_rng(seed + 1000 *
    worker)`` stream in a fixed order."""
    if not spec.cells:
        raise ValueError("FleetSpec.cells must be non-empty")
    rng = np.random.default_rng(spec.seed + 1000 * int(worker))
    n = len(spec.cells)
    p = np.arange(1, n + 1, dtype=np.float64) ** -float(spec.zipf_s)
    p /= p.sum()
    mix = np.asarray(spec.priority_mix, dtype=np.float64)
    mix = mix / mix.sum()
    off = float(spec.offlattice_frac)
    if off > 0.0:
        hull = np.asarray(spec.cells, dtype=np.float64)
        lo, hi = hull.min(axis=0), hull.max(axis=0)
    out = []
    for _ in range(int(spec.queries_per_worker)):
        cell = spec.cells[int(rng.choice(n, p=p))]
        if off > 0.0 and rng.random() < off:
            cell = tuple(float(c)
                         for c in lo + rng.random(lo.shape[0]) * (hi - lo))
        priority = int(rng.choice(len(mix), p=mix))
        out.append((tuple(float(c) for c in cell), priority))
    return out


def _spawn_worker(spec: FleetSpec, store_dir: str, journal_path: str,
                  owner: str, chaos: bool = False):
    """Start ONE ``serve.fleet`` worker process over the shared store
    (does not wait for readiness — pair with ``_await_ready``)."""
    import json as _json
    import subprocess
    import sys

    cmd = [sys.executable, "-m", "aiyagari_hark_tpu.serve.fleet",
           "--store", store_dir, "--owner", owner,
           "--kwargs", _json.dumps(spec.model_kwargs),
           "--scenario", spec.scenario,
           "--lease-ttl", str(spec.lease_ttl_s),
           "--max-batch", str(spec.max_batch),
           "--journal", journal_path,
           "--max-seconds", "600"]
    if spec.prefetch_k > 0:
        cmd += ["--prefetch-k", str(spec.prefetch_k),
                "--prefetch-cells",
                _json.dumps([list(c) for c in spec.cells])]
    if spec.surrogate is not None:
        cmd += ["--surrogate", _json.dumps(spec.surrogate)]
    if spec.lease_backend is not None:
        cmd += ["--lease-backend", spec.lease_backend]
    if chaos:
        cmd += ["--chaos"]
    return subprocess.Popen(
        cmd, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        text=True)


def _await_ready(proc, label, watch: Stopwatch,
                 ready_timeout_s: float) -> int:
    """Block until one worker prints FLEET_READY; returns its port.
    ``watch`` carries the shared budget across a whole pool spawn."""
    import selectors

    sel = selectors.DefaultSelector()
    sel.register(proc.stdout, selectors.EVENT_READ)
    try:
        while True:
            # the timeout must bound the BLOCKED wait too: a
            # silent-but-alive worker (hung before its READY
            # print) would otherwise defeat it — readline alone
            # only returns on a line or on process exit
            left = ready_timeout_s - watch.elapsed()
            if left <= 0 or not sel.select(timeout=left):
                raise RuntimeError(
                    f"fleet worker {label} not ready in "
                    f"{ready_timeout_s:g}s")
            line = proc.stdout.readline()
            if not line:
                raise RuntimeError(
                    f"fleet worker {label} exited before "
                    f"FLEET_READY (rc={proc.poll()})")
            if line.startswith("FLEET_READY"):
                return int(line.split("port=")[1].split()[0])
    finally:
        sel.close()


def _spawn_fleet(spec: FleetSpec, store_dir: str,
                 journal_paths: list, ready_timeout_s: float,
                 chaos: bool = False):
    """Start ``n_workers`` ``serve.fleet`` worker processes over one
    shared store; returns ``(procs, urls)`` once every worker printed
    FLEET_READY."""
    procs, urls = [], []
    for i in range(spec.n_workers):
        procs.append(_spawn_worker(spec, store_dir, journal_paths[i],
                                   f"w{i}", chaos=chaos))
    watch = Stopwatch()
    try:
        for i, proc in enumerate(procs):
            port = _await_ready(proc, i, watch, ready_timeout_s)
            urls.append(f"http://127.0.0.1:{port}")
    except BaseException:
        for p in procs:
            p.kill()
        raise
    return procs, urls


class FleetCtl:
    """Live handle on a spawned worker pool: the interface the chaos
    drills (``serve.chaos.run_drills``) consume.  Everything goes
    through public surfaces — HTTP endpoints, process state, journal
    files — never through harness-private flags, so a drill's detection
    evidence is exactly what a postmortem would read."""

    def __init__(self, spec: FleetSpec, procs: list, urls: list,
                 journal_paths: list, store_dir: str,
                 timeout_s: float = 300.0):
        from .fleet import FleetClient

        self._spec = spec
        self.procs = procs
        self.urls = urls
        self.journal_paths = journal_paths
        self.store_dir = store_dir
        self.lease_ttl_s = float(spec.lease_ttl_s)
        # a BARE client (no retry/hedge): a drill's query must reach
        # exactly the worker it targets, with only connection failover
        self._client = FleetClient(list(urls), timeout=timeout_s)
        self._client.urls = urls   # live alias: joins become visible

    def alive(self, i: int) -> bool:
        return self.procs[i].poll() is None

    def returncode(self, i: int):
        return self.procs[i].poll()

    def kill(self, i: int, sig) -> None:
        self.procs[i].send_signal(sig)

    def two_live_workers(self):
        live = [i for i in range(len(self.procs)) if self.alive(i)]
        if len(live) < 2:
            from .chaos import DrillError

            raise DrillError(
                f"drill needs two live workers, have {len(live)}")
        return live[0], live[1]

    def query(self, cell, prefer=None) -> dict:
        return self._client.query(cell, self._spec.model_kwargs,
                                  scenario=self._spec.scenario,
                                  prefer=prefer)

    def post(self, worker: int, path: str, body: dict) -> dict:
        from urllib import request as _urlrequest

        data = json.dumps(body).encode("utf-8")
        req = _urlrequest.Request(
            self.urls[worker] + path, data=data,
            headers={"Content-Type": "application/json"})
        with _urlrequest.urlopen(req, timeout=30.0) as resp:
            return json.loads(resp.read().decode("utf-8"))

    def fleet_info(self, worker: int):
        """The worker's ``/fleet`` introspection dict, or None when it
        is dead/unreachable (a drill polling a dying victim)."""
        if not self.alive(worker):
            return None
        try:
            return self._client.get(self.urls[worker], "/fleet")
        except Exception:
            return None


def _publish_counts(journal_paths: list) -> dict:
    """FLEET_PUBLISH count per key across the pool's journals — the
    before/after ledger of the chaos recovery phase."""
    from ..obs.journal import read_journal

    counts: dict = {}
    for jp in list(journal_paths):
        if not os.path.exists(jp):
            continue
        for ev in read_journal(jp, event="FLEET_PUBLISH"):
            k = int(ev["key"])
            counts[k] = counts.get(k, 0) + 1
    return counts


def run_fleet_load(spec: FleetSpec, store_dir: str,
                   ready_timeout_s: float = 180.0,
                   client_timeout_s: float = 300.0,
                   chaos=None) -> FleetReport:
    """Replay one fleet scenario against a freshly spawned worker pool
    sharing ``store_dir`` and aggregate the fleet-wide record.

    Outcome vocabulary per arrival: ``served:<path>`` (hit / near /
    cold / degraded_neighbor), ``reject:<Error>`` (a typed error payload
    from a live worker), ``error:disconnected`` (every worker
    unreachable — only possible mid-drill).  The invariant ``unresolved
    == 0`` (every arrival reaches a terminal outcome even with a worker
    SIGTERMed mid-load) is part of the ISSUE 15 acceptance.

    Dedup accounting comes from the workers' event journals (one
    FLEET_PUBLISH per completed claim, key attached) — journals survive
    the drilled worker's death, so the killed worker's solves still
    count.

    ``chaos`` (ISSUE 16): a ``serve.chaos.ChaosPlan``.  Workers spawn
    with ``--chaos`` (the arm endpoint), the plan's churn schedule
    (join/leave) runs DURING the replay, the client gains the typed
    retry + hedging policies, and after the replay every drill runs
    sequentially against the live pool, followed by a recovery phase
    whose duplicate publishes are ledgered.  The campaign's record
    lands in ``FleetReport.chaos``; the headline ``dedup_ratio`` then
    covers NON-drill keys only (drill keys carry their own accounting,
    expected duplicates separated from violations)."""
    import signal

    from ..obs.journal import read_journal
    from .fleet import FleetClient, FleetHTTPError, HedgePolicy, RetryPolicy

    os.makedirs(store_dir, exist_ok=True)
    journal_paths = [os.path.join(store_dir, f"journal_w{i}.jsonl")
                     for i in range(spec.n_workers)]
    procs, urls = _spawn_fleet(spec, store_dir, journal_paths,
                               ready_timeout_s, chaos=chaos is not None)
    harness_obs = None
    if chaos is not None:
        from ..obs.runtime import ObsConfig, build_obs

        harness_obs = build_obs(ObsConfig(
            enabled=True,
            journal_path=os.path.join(store_dir,
                                      "journal_harness.jsonl")))
    client = (FleetClient(urls, timeout=client_timeout_s)
              if chaos is None else
              FleetClient(urls, timeout=client_timeout_s,
                          retry=RetryPolicy(), hedge=HedgePolicy(),
                          obs=harness_obs))
    traces = [generate_fleet_arrivals(spec, i)
              for i in range(spec.n_workers)]
    trace_digest = hashlib.blake2b(
        json.dumps([[list(c) + [pr] for c, pr in t] for t in traces],
                   sort_keys=True).encode(),
        digest_size=16).hexdigest()

    warm_keys: set = set()
    for cell in spec.cells[:spec.warm_count]:
        res = client.query(cell, spec.model_kwargs,
                           scenario=spec.scenario, prefer=0)
        warm_keys.add(int(res["key"]))

    if chaos is not None:
        client.urls = urls   # live alias: churn joins become visible

    outcomes_by_worker = [[] for _ in range(spec.n_workers)]
    walls_by_path: dict = {}
    hit_keys: set = set()
    served_values: dict = {}
    value_divergence = 0
    unresolved = 0
    dispatched = 0
    lock = threading.Lock()
    drill_fired = threading.Event()

    def _client_loop(i: int) -> None:
        nonlocal unresolved, value_divergence, dispatched
        for k, (cell, priority) in enumerate(traces[i]):
            with lock:
                dispatched += 1
            if (spec.sigterm_worker is not None
                    and i == spec.sigterm_worker
                    and k == spec.sigterm_after
                    and not drill_fired.is_set()):
                drill_fired.set()
                procs[spec.sigterm_worker].send_signal(signal.SIGTERM)
            try:
                with stopwatch() as sw:
                    res = client.query(cell, spec.model_kwargs,
                                       scenario=spec.scenario,
                                       priority=priority, prefer=i)
                path = (res["quality"] if res["quality"] != "exact"
                        else res["path"])
                outcome = f"served:{path}"
                with lock:
                    walls_by_path.setdefault(path, []).append(
                        sw.seconds * 1e3)
                    if path == "hit":
                        hit_keys.add(int(res["key"]))
                    # loser-serves-winner bit-identity: every response
                    # for one fingerprint must carry the SAME value
                    # fields (the exactly-once publish is the only
                    # source; counters ride the winner's solve too).
                    # Degraded answers are a DIFFERENT calibration's row
                    # served under that key on purpose — excluded.
                    # ``bracket_init`` is non-None exactly on the
                    # response that SOLVED the key (near/cold): keep it
                    # when seen, so the bit-identity acceptance can
                    # replay the same seed through reference_solve (the
                    # PR 4 contract is same-seed, and a warm-solved
                    # capital is evaluated under the warm seed).
                    if res["quality"] == "exact":
                        vals = {"cell": list(cell),
                                "r_star": res["r_star"],
                                "capital": res["capital"],
                                "labor": res["labor"],
                                "status": res["status"]}
                        key = int(res["key"])
                        rec = served_values.setdefault(
                            key, dict(vals, bracket_init=None))
                        if {k: rec[k] for k in vals} != vals:
                            value_divergence += 1
                        if res.get("bracket_init") is not None:
                            rec["bracket_init"] = res["bracket_init"]
            except FleetHTTPError as e:
                outcome = f"reject:{e.payload.get('error')}"
            except ConnectionError:
                outcome = "error:disconnected"
            except BaseException as e:
                with lock:
                    unresolved += 1
                outcome = f"unresolved:{type(e).__name__}"
            with lock:
                outcomes_by_worker[i].append(outcome)

    # elasticity schedule (ISSUE 16): scripted joins/leaves applied
    # while the replay is live, keyed on the fleet-wide dispatch count.
    # A leave SIGTERMs (graceful, exit 75, leases TTL-reclaimed); a
    # join spawns a fresh --chaos worker into the pool (reachable via
    # failover and hedges).  Both are journaled to the harness journal.
    churn_counts = {"joins": 0, "leaves": 0}
    churn_left: set = set()
    churn_stop = threading.Event()
    churn_thread = None

    def _churn_loop() -> None:
        import time as _time

        for after, action, widx in sorted(chaos.churn):
            while not churn_stop.is_set():
                with lock:
                    if dispatched >= int(after):
                        break
                _time.sleep(0.02)
            else:
                return   # replay over before this event came due
            if action == "leave":
                w = widx if widx is not None else len(procs) - 1
                if procs[w].poll() is None:
                    churn_left.add(w)
                    procs[w].send_signal(signal.SIGTERM)
                    churn_counts["leaves"] += 1
                    harness_obs.event("WORKER_LEAVE", worker=w,
                                      owner=f"w{w}", after=int(after))
            elif action == "join":
                idx = len(procs)
                jp = os.path.join(store_dir, f"journal_w{idx}.jsonl")
                proc = _spawn_worker(spec, store_dir, jp, f"w{idx}",
                                     chaos=True)
                try:
                    port = _await_ready(proc, idx, Stopwatch(),
                                        ready_timeout_s)
                except Exception:
                    proc.kill()
                    raise
                journal_paths.append(jp)
                procs.append(proc)
                urls.append(f"http://127.0.0.1:{port}")
                churn_counts["joins"] += 1
                harness_obs.event("WORKER_JOIN", worker=idx,
                                  owner=f"w{idx}", after=int(after))

    if chaos is not None and chaos.churn:
        churn_thread = threading.Thread(target=_churn_loop,
                                        name="fleet-churn", daemon=True)
        churn_thread.start()

    threads = [threading.Thread(target=_client_loop, args=(i,),
                                name=f"fleet-client-{i}")
               for i in range(spec.n_workers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(client_timeout_s + 60.0)
        if t.is_alive():
            unresolved += 1
    churn_stop.set()
    if churn_thread is not None:
        churn_thread.join(ready_timeout_s)

    # chaos campaign (ISSUE 16): every drill sequentially against the
    # live pool, then a recovery phase whose duplicate publishes are
    # ledgered (a re-publish of an already-published key after the
    # drills is an exactly-once violation, not noise)
    drill_info = None
    recovery_served = recovery_errors = recovery_dup = 0
    if chaos is not None:
        from .chaos import run_drills

        ctl = FleetCtl(spec, procs, urls, list(journal_paths) + [
            os.path.join(store_dir, "journal_harness.jsonl")],
            store_dir, timeout_s=client_timeout_s)
        try:
            drill_info = run_drills(chaos, ctl)
            pubs_before = _publish_counts(journal_paths)
            for k in range(int(chaos.recovery_queries)):
                cell = spec.cells[k % len(spec.cells)]
                try:
                    ctl.query(cell)
                    recovery_served += 1
                except Exception:
                    recovery_errors += 1
            pubs_after = _publish_counts(journal_paths)
            recovery_dup = sum(
                pubs_after[k] - n for k, n in pubs_before.items()
                if pubs_after.get(k, 0) > n)
        except BaseException:
            for p in procs:
                if p.poll() is None:
                    p.kill()
            raise

    # final snapshots from live workers, then graceful shutdown
    worker_snapshots = []
    for i, url in enumerate(urls):
        if procs[i].poll() is not None:
            continue
        try:
            worker_snapshots.append(client.get(url, "/metrics"))
        except Exception:
            pass
    rcs: dict = {}
    for i, proc in enumerate(procs):
        # the drilled worker already received its SIGTERM (so did any
        # churn-departed worker); a second one landing after its
        # preemption_guard exited (handlers restored) would kill it
        # mid-cleanup with the default action
        if proc.poll() is None and i not in churn_left \
                and not (drill_fired.is_set()
                         and i == spec.sigterm_worker):
            proc.send_signal(signal.SIGTERM)
    for i, proc in enumerate(procs):
        try:
            rcs[i] = proc.wait(60.0)
        except Exception:
            proc.kill()
            rcs[i] = proc.wait()
        if proc.stdout is not None:
            proc.stdout.close()

    # journal-based fleet accounting (survives the drilled death)
    publishes, spec_published, prefetch_issued = [], set(), 0
    seed_by_key: dict = {}
    claims_won = claims_lost = reclaims = 0
    # vacuously true only when NO drill ran; a drilled worker whose
    # journal never materialized is a FAILED journaling leg, not a pass
    interrupted_journaled = spec.sigterm_worker is None
    for i, jp in enumerate(journal_paths):
        if not os.path.exists(jp):
            continue
        for ev in read_journal(jp, event="FLEET_PUBLISH"):
            publishes.append(int(ev["key"]))
            if ev.get("speculative"):
                spec_published.add(int(ev["key"]))
            if ev.get("seed") is not None:
                seed_by_key[int(ev["key"])] = ev["seed"]
        claims_won += len(read_journal(jp, event="FLEET_CLAIM"))
        reclaims += len(read_journal(jp, event="FLEET_LEASE_RECLAIM"))
        prefetch_issued += len(read_journal(jp,
                                            event="PREFETCH_ISSUED"))
        if i == spec.sigterm_worker:
            interrupted_journaled = bool(
                read_journal(jp, event="INTERRUPTED"))

    # lease-leak audit through the store's own API (the canonical lease
    # spelling lives in ONE place): anything a dead worker still held
    # goes stale within the TTL; sweep then count what survived (must
    # be zero)
    import time as _time

    from .store import SolutionStore

    # the audit MUST interrogate the same coordination authority the
    # workers used (ISSUE 18): auditing a CAS-backed fleet against the
    # shared directory would vacuously find zero leases
    audit_backend = None
    if spec.lease_backend is not None:
        from .lease import make_backend

        audit_backend = make_backend(spec.lease_backend, root=store_dir)
    audit = SolutionStore(disk_path=store_dir, shared=True,
                          lease_ttl_s=spec.lease_ttl_s, owner="audit",
                          lease_backend=audit_backend)
    deadline = Stopwatch()
    while (audit.lease_files()
           and deadline.elapsed() < spec.lease_ttl_s + 10.0):
        audit.gc_stale_leases()
        if audit.lease_files():
            _time.sleep(0.2)
    leaked = len(audit.lease_files())
    audit.close()

    # every published solve's exact seed came through its journal, so
    # keys whose solving RESPONSE no client saw (prefetch solves, a
    # drilled worker's lost reply) still compare same-seed downstream
    for key, rec in served_values.items():
        if rec.get("bracket_init") is None and key in seed_by_key:
            rec["bracket_init"] = seed_by_key[key]

    counts: dict = {}
    for seq in outcomes_by_worker:
        for o in seq:
            counts[o] = counts.get(o, 0) + 1
    arrivals = sum(len(s) for s in outcomes_by_worker)
    # headline dedup stays the CLEAN ledger: when a chaos campaign ran,
    # its drill keys (which legitimately re-publish under torn-entry /
    # stalled-winner / skewed-election faults) get their own accounting
    # below — mixing them in would make the exactly-once invariant
    # unfalsifiable
    drill_keys = (set() if drill_info is None
                  else set(drill_info["drill_keys"]))
    main_pubs = [k for k in publishes if k not in drill_keys]
    distinct = len(set(main_pubs))
    converted = len({k for k in spec_published
                     if k in hit_keys and k not in warm_keys})

    def _pctl(vals, q):
        if not vals:
            return None
        s = sorted(vals)
        return round(s[min(len(s) - 1,
                           max(0, round(q / 100.0 * (len(s) - 1))))], 4)

    remote_hits = sum(int(s.get("fleet_remote_hits", 0))
                      for s in worker_snapshots)
    claims_lost = sum(int(s.get("fleet_claims_lost", 0))
                      for s in worker_snapshots)
    all_walls = [w for v in walls_by_path.values() for w in v]
    p50_ms = {p: _pctl(v, 50) for p, v in walls_by_path.items()}
    p99_ms = {p: _pctl(v, 99) for p, v in walls_by_path.items()}
    p50_ms["all"] = _pctl(all_walls, 50)
    p99_ms["all"] = _pctl(all_walls, 99)

    chaos_rec = None
    if drill_info is not None:
        served = sum(v for o, v in counts.items()
                     if o.startswith("served:"))
        # the DRILLED dedup ratio: every publish except the drills'
        # EXPECTED duplicates must still be exactly-once — what remains
        # above 1.0 is a real protocol violation
        expected = set(drill_info["expected_dup_keys"])
        honest = [k for k in publishes if k not in expected]
        chaos_rec = {
            "drills": drill_info["drills"],
            "injected": int(drill_info["injected"]),
            "detected": int(drill_info["detected"]),
            "dedup_ratio": (None if not honest else
                            round(len(honest) / len(set(honest)), 4)),
            "recovery_dup_publishes": int(recovery_dup),
            "recovery_served": int(recovery_served),
            "recovery_errors": int(recovery_errors),
            "availability": (None if arrivals == 0
                             else round(served / arrivals, 4)),
            "churn_p99_ms": p99_ms["all"],
            "joins": churn_counts["joins"],
            "leaves": churn_counts["leaves"],
            "kills": sum(1 for p in procs
                         if p.poll() == -int(signal.SIGKILL)),
            "hedges": client.hedge_counts(),
        }
    if harness_obs is not None:
        harness_obs.close()
    return FleetReport(
        workers=spec.n_workers, arrivals=arrivals, counts=counts,
        outcomes_by_worker=outcomes_by_worker, unresolved=unresolved,
        p50_ms=p50_ms, p99_ms=p99_ms,
        cold_solves=len(main_pubs), distinct_published=distinct,
        dedup_ratio=(None if distinct == 0
                     else round(len(main_pubs) / distinct, 4)),
        prefetch_issued=prefetch_issued, prefetch_converted=converted,
        remote_hits=remote_hits, claims_won=claims_won,
        claims_lost=claims_lost, lease_reclaims=reclaims,
        leases_leaked=leaked, interrupted_rcs=rcs,
        interrupted_journaled=interrupted_journaled,
        trace_digest=trace_digest, worker_snapshots=worker_snapshots,
        served_values=served_values, value_divergence=value_divergence,
        chaos=chaos_rec)
