"""Overload management primitives for the serving engine (ISSUE 8,
DESIGN §11): priority classes, predicted-work weights, and the regional
circuit breaker.

The serving layer already types every other failure family — numerical
(PR 1), preemption (PR 3), corruption (PR 6), per-query deadlines — but
saturation used to be an untyped state: a full ``MicroBatcher`` either
blocked the caller or raised a bare ``ServeQueueFull``, every query was
equal priority, and a region of (σ, ρ, sd)-space whose cells repeatedly
failed was re-attempted at full cost forever.  This module holds the
host-side mechanics the ``EquilibriumService`` composes into typed
overload behavior (the knobs ride ``utils.config.AdmissionPolicy``):

* ``Priority`` — the query classes, most to least important.  Admission
  budgets are nested per class and shedding displaces strictly-lower
  classes only, so background sweep traffic can never starve an
  interactive caller.
* ``predicted_work`` — queue slots are weighted by predicted solve work
  (the PR 2 scheduler's cost model, ``heuristic_cell_work``), so ten
  cheap high-ρ cells and ten slow-mixing ρ=0 cells occupy the queue
  honestly rather than as "ten slots" each.
* ``CircuitBreaker`` — per-region (quantized (σ, ρ, sd) neighborhood
  within a solver group) failure breaker: open after K failures
  (``CircuitOpen`` fast-fail at submit), half-open probe on a
  deterministic cooldown schedule (doubling per reopen, capped), close
  on a certified success.  Purely host-side state driven by the
  service's injected clock — no wall-time reads, so breaker behavior is
  property-testable and replayable with a fake clock.

No jax imports; nothing here touches device state.
"""

from __future__ import annotations

import threading
from typing import List, Optional, Tuple

import numpy as np

from ..utils.config import AdmissionPolicy  # noqa: F401  (re-export)


class Priority:
    """Query priority classes, most (0) to least (2) important.

    Plain ints so they ride ``EquilibriumQuery`` (a NamedTuple hashed by
    fingerprints) without enum baggage; ``priority`` never enters the
    solution fingerprint — two queries for the same calibration at
    different priorities address the same cached answer."""

    INTERACTIVE = 0
    BATCH = 1
    SPECULATIVE = 2


PRIORITY_NAMES = ("INTERACTIVE", "BATCH", "SPECULATIVE")
N_PRIORITIES = len(PRIORITY_NAMES)


def priority_name(p: int) -> str:
    p = int(p)
    if 0 <= p < N_PRIORITIES:
        return PRIORITY_NAMES[p]
    return f"UNKNOWN({p})"


def predicted_work(cell, scenario: str = "aiyagari") -> float:
    """Predicted relative solve work for one cell — the PR 2 scheduler's
    cold-start cost model, supplied per model family by the scenario's
    ``CellSpace.work`` (ISSUE 9), reused as the admission layer's
    queue-slot weight so occupancy is measured in work, not request
    count."""
    from ..scenarios.registry import get_scenario

    work = get_scenario(scenario).cells.work
    return float(work(np.asarray([cell]))[0])


class _RegionState:
    """Mutable per-region breaker state (lock held by the breaker)."""

    __slots__ = ("state", "failures", "opened_at", "reopens", "probing")

    def __init__(self):
        self.state = "closed"
        self.failures = 0
        self.opened_at = 0.0
        self.reopens = 0
        self.probing = False


class CircuitBreaker:
    """Per-region circuit breaker over the serving cold path.

    A *region* is a quantized (σ, ρ, sd) neighborhood within one solver
    group (``region_key``): the PR 6 observation that certification and
    NONFINITE failures cluster in parameter space (bracket-edge loss of
    contraction, slow-mixing corners) means one bad cell predicts its
    neighbors — so after ``failures`` consecutive failures the whole
    region fast-fails typed instead of burning a full solve per retry.

    State machine (all transitions returned to the caller so the service
    can journal them — this class stays observability-free):

    * CLOSED — normal; a success resets the failure count.
    * OPEN — every ``admit`` returns ``"open"`` (the service raises the
      typed ``CircuitOpen``) until the cooldown elapses.  The cooldown
      doubles per reopen up to ``backoff_cap`` x — a deterministic
      schedule, driven entirely by the ``now`` values the caller passes
      (the service's injected clock).
    * HALF-OPEN — the first ``admit`` at/after the cooldown returns
      ``"probe"`` exactly once: that query is admitted as the probe
      while everything else keeps fast-failing.  A certified success
      closes the region (full reset); a failure reopens it with the
      next backoff step; an aborted probe (shed, expired, drained)
      returns the region to plain OPEN so the next due ``admit`` can
      probe again.

    Thread-safe; every method is O(1) per region.
    """

    def __init__(self, failures: int = 3, cooldown_s: float = 1.0,
                 backoff_cap: int = 8,
                 region_scale: Tuple[float, float, float] = (2.0, 0.3, 0.1)):
        if failures < 1:
            raise ValueError(f"failures must be >= 1, got {failures}")
        self.failures = int(failures)
        self.cooldown_s = float(cooldown_s)
        self.backoff_cap = max(1, int(backoff_cap))
        self.region_scale = tuple(float(s) for s in region_scale)
        self._lock = threading.Lock()
        self._regions: dict = {}
        self._transitions: List[tuple] = []

    @classmethod
    def from_policy(cls, policy: AdmissionPolicy) -> "CircuitBreaker":
        return cls(failures=policy.breaker_failures,
                   cooldown_s=policy.breaker_cooldown_s,
                   backoff_cap=policy.breaker_backoff_cap,
                   region_scale=policy.breaker_region_scale)

    def region_key(self, cell, group: int) -> tuple:
        """Quantize a cell into its breaker region: the solver group plus
        each axis rounded to the region scale — neighbors in the same
        quantization bucket share one breaker."""
        return (int(group),) + tuple(
            int(round(float(c) / s))
            for c, s in zip(cell, self.region_scale))

    def _cooldown(self, st: _RegionState) -> float:
        return self.cooldown_s * min(2 ** st.reopens, self.backoff_cap)

    def _log(self, now: float, region: tuple, what: str) -> None:
        self._transitions.append((float(now), region, what))

    # -- admission ---------------------------------------------------------

    def admit(self, region: tuple, now: float) -> str:
        """Gate one arrival: ``"ok"`` (closed region), ``"open"``
        (fast-fail), or ``"probe"`` (admitted as the half-open probe)."""
        with self._lock:
            st = self._regions.get(region)
            if st is None or st.state == "closed":
                return "ok"
            if st.probing:
                return "open"
            if now >= st.opened_at + self._cooldown(st):
                st.probing = True
                self._log(now, region, "probe")
                return "probe"
            return "open"

    def retry_after(self, region: tuple, now: float) -> float:
        """Clock units until the region's next probe window (0.0 for a
        closed region) — the ``CircuitOpen`` retry-after payload."""
        with self._lock:
            st = self._regions.get(region)
            if st is None or st.state == "closed":
                return 0.0
            return max(0.0, st.opened_at + self._cooldown(st) - now)

    # -- outcome hooks -----------------------------------------------------

    def record_failure(self, region: tuple, now: float) -> Optional[str]:
        """One solve/certification failure in the region.  Returns the
        transition (``"opened"`` / ``"reopened"``) or None."""
        with self._lock:
            st = self._regions.setdefault(region, _RegionState())
            if st.probing:
                st.probing = False
                st.state = "open"
                st.opened_at = now
                st.reopens += 1
                self._log(now, region, "reopened")
                return "reopened"
            if st.state == "open":
                return None
            st.failures += 1
            if st.failures >= self.failures:
                st.state = "open"
                st.opened_at = now
                self._log(now, region, "opened")
                return "opened"
            return None

    def record_success(self, region: tuple, now: float) -> Optional[str]:
        """One certified success.  Closes an open/probing region (full
        reset, ``"closed"`` returned); resets the failure count of a
        closed one."""
        with self._lock:
            st = self._regions.get(region)
            if st is None:
                return None
            if st.state == "open" or st.probing:
                del self._regions[region]
                self._log(now, region, "closed")
                return "closed"
            st.failures = 0
            return None

    def abort_probe(self, region: tuple) -> None:
        """The in-flight probe left the system without a result (shed,
        deadline-expired, drained): return the region to plain OPEN so
        the next due ``admit`` probes again."""
        with self._lock:
            st = self._regions.get(region)
            if st is not None and st.probing:
                st.probing = False

    # -- introspection -----------------------------------------------------

    def state(self, region: tuple) -> str:
        """``"closed"`` / ``"open"`` / ``"half_open"`` (probe in flight)."""
        with self._lock:
            st = self._regions.get(region)
            if st is None or st.state == "closed":
                return "closed"
            return "half_open" if st.probing else "open"

    def transitions(self) -> List[tuple]:
        """The ordered ``(now, region, what)`` transition log — the load
        harness's breaker-timeline record."""
        with self._lock:
            return list(self._transitions)

    def open_regions(self) -> List[tuple]:
        with self._lock:
            return [r for r, st in self._regions.items()
                    if st.state == "open"]
