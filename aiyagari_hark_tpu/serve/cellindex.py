"""Sublinear nearest-neighbor index over stored solution cells.

``CellIndex`` (ISSUE 17, DESIGN §15) replaces the O(N) linear scan that
every donor nomination, degraded-answer selection, prefetch enumeration
and surrogate k-NN lookup paid per query (``store.nominate``/``nearest``
re-materialized the full cell matrix each call) with a grid-bucket
structure over NORMALIZED CellSpace coordinates: each stored cell lands
in the bucket ``floor(cell[i] / scale[i] / width)``, and a query
gathers candidates from expanding Chebyshev rings of buckets until the
ring lower bound proves no unexplored bucket can hold a closer (or
equally-close — ties matter) neighbor.

Bitwise contract — the index is an OPTIMIZATION, never a semantics
change, so ``nearest_k`` must return exactly what the linear scan
returns:

* distances are computed by the SAME ``parallel.sweep.neighbor_distance``
  expression (elementwise float64 ops, so a subset gather produces
  bit-identical values to the full-matrix scan);
* ties resolve by METADATA-DICT INSERTION ORDER, the order the linear
  scan's ``np.argsort(d, kind="stable")`` / first-``argmin`` resolves
  them in.  Every item carries a per-group monotone sequence number
  assigned on first insertion (a re-``put`` of a live key keeps its
  number, mirroring how a dict update keeps its position; a remove +
  re-add gets a fresh one, mirroring re-insertion at the dict tail),
  and candidates sort by ``np.lexsort((seq, d))``.

The ring search is exact: after exhausting every ring ``<= r``, any
unexplored bucket lies at Chebyshev ring ``>= r+1`` whose points are at
normalized-L1 distance ``>= r*width``; the search continues while that
bound could still admit a closer-or-tied candidate and stops only when
it cannot (with an ulp-scale slack so bucket-assignment rounding can
never cut off an exact-distance tie).

Query fast path: the 3x3x3 neighborhood BLOCK of the query's own bucket
(rings 0–1, the minimum any exact answer must examine — a ring-1 bucket
can hold a point at distance 0⁺) is concatenated ONCE and memoized per
bucket, invalidated by a per-group mutation generation, so a steady-
state query is one dict probe + one vectorized distance over the local
candidates.  Only when the k-th best cannot be proven inside the block
(sparse region, huge k) does the general ring loop run.

Bucket width self-tunes: ``bucket_width=None`` derives the width from
the occupied bounding box and item count at (re)build time targeting
``_TARGET_OCCUPANCY`` items per bucket, and a group that has grown 4x
since its last build is rebuilt on the next query — growth degrades
smoothly instead of silently going linear.  Rebuilds (restart index
load, scale change, re-width) invoke ``on_rebuild(group, n, reason)``
so the owning store can journal ``INDEX_REBUILD``.
"""

from __future__ import annotations

import math
from typing import Optional

import numpy as np

_TARGET_OCCUPANCY = 8.0     # items per bucket the auto-width aims for
_MIN_WIDTH = 1e-3           # normalized-unit floor for the auto width
_REBUILD_GROWTH = 4         # re-width when a group grows this factor

# Chebyshev shell offsets, cached per (radius, dim): shell r is every
# offset with max(|c|) == r; the r<=1 shells union to the 3^dim block.
_SHELLS: dict = {}
_BLOCKS: dict = {}


def _shell(r: int, dim: int):
    got = _SHELLS.get((r, dim))
    if got is not None:
        return got
    rng = range(-r, r + 1)
    out = []

    def rec(prefix):
        if len(prefix) == dim:
            if max(abs(c) for c in prefix) == r:
                out.append(tuple(prefix))
            return
        for c in rng:
            rec(prefix + [c])

    rec([])
    _SHELLS[(r, dim)] = out
    return out


def _block_offsets(dim: int):
    got = _BLOCKS.get(dim)
    if got is None:
        got = _BLOCKS[dim] = _shell(0, dim) + _shell(1, dim)
    return got


class _Bucket:
    """One grid cell: parallel item columns plus a lazily-built numpy
    cache invalidated on every mutation — query paths touch arrays,
    never lists."""

    __slots__ = ("keys", "cells", "r_star", "cert", "seq", "cache")

    def __init__(self):
        self.keys = []
        self.cells = []
        self.r_star = []
        self.cert = []
        self.seq = []
        self.cache = None

    def arrays(self):
        if self.cache is None:
            self.cache = (
                np.asarray(self.cells, dtype=np.float64),
                np.asarray(self.seq, dtype=np.int64),
                np.asarray(self.r_star, dtype=np.float64),
                np.asarray(self.cert, dtype=np.int64),
                np.asarray(self.keys, dtype=np.int64),
            )
        return self.cache


class _GroupIndex:
    """Per-solver-group sub-index: insertion-ordered item table plus
    the lazily-built bucket grid (built on first query, when the
    querying scenario's ``scale`` becomes known)."""

    __slots__ = ("items", "next_seq", "scale", "width", "buckets",
                 "bbox_lo", "bbox_hi", "built_n", "gen", "blocks")

    def __init__(self):
        # key -> [cell_tuple, r_star, cert_level, seq, bucket_or_None]
        self.items: dict = {}
        self.next_seq = 0
        self.scale = None       # normalization the grid was built with
        self.width = None
        self.buckets: Optional[dict] = None
        self.bbox_lo = None     # occupied bucket-coordinate bounds
        self.bbox_hi = None
        self.built_n = 0
        self.gen = 0            # bumped on every mutation
        # bucket -> memoized 3x3x3 neighborhood candidate arrays:
        # (gen, cells, seqs, keys, finite_mask|None, cert_mask, n)
        self.blocks: dict = {}


class CellIndex:
    """Incrementally-maintained grid-bucket k-NN index (one per store).

    ``add``/``remove`` mirror every metadata mutation; ``nearest_k`` is
    the query.  Not thread-safe on its own — the owning store's lock
    serializes access (the same lock that already guards ``_meta``)."""

    def __init__(self, bucket_width: Optional[float] = None,
                 on_rebuild=None):
        self.bucket_width = bucket_width
        self.on_rebuild = on_rebuild
        self.rebuilds = 0
        self._groups: dict = {}
        # identity-keyed memo of the last scale conversion: callers pass
        # the same module-constant / CellSpace tuple every query
        self._scale_obj = None
        self._scale_t = None

    # -- maintenance --------------------------------------------------------

    def __len__(self) -> int:
        return sum(len(g.items) for g in self._groups.values())

    def group_size(self, group: int) -> int:
        g = self._groups.get(int(group))
        return 0 if g is None else len(g.items)

    def add(self, key: int, cell, group: int, r_star: float,
            cert_level: int) -> None:
        """Insert or refresh one item.  A live key keeps its insertion
        sequence number (dict-update semantics); a new key is appended
        at the tail of the group's order."""
        key = int(key)
        group = int(group)
        g = self._groups.get(group)
        if g is None:
            g = self._groups[group] = _GroupIndex()
        cell = tuple(float(c) for c in cell)
        r_star = float(r_star)
        cert_level = int(cert_level)
        g.gen += 1
        item = g.items.get(key)
        if item is not None:
            if item[0] == cell:
                # value refresh in place: same bucket, same seq
                item[1] = r_star
                item[2] = cert_level
                b = item[4]
                if b is not None:
                    i = b.keys.index(key)
                    b.r_star[i] = r_star
                    b.cert[i] = cert_level
                    b.cache = None
                return
            self._drop(g, key, item)
            item = None
        seq = g.next_seq
        g.next_seq += 1
        entry = [cell, r_star, cert_level, seq, None]
        g.items[key] = entry
        if g.buckets is not None:
            self._place(g, key, entry)

    def remove(self, key: int, group: int) -> None:
        key, group = int(key), int(group)
        g = self._groups.get(group)
        if g is None:
            return
        item = g.items.get(key)
        if item is not None:
            g.gen += 1
            self._drop(g, key, item)
            del g.items[key]

    def clear(self) -> None:
        self._groups = {}

    def _drop(self, g: _GroupIndex, key: int, item) -> None:
        b = item[4]
        if b is None:
            return
        i = b.keys.index(key)
        for col in (b.keys, b.cells, b.r_star, b.cert, b.seq):
            col.pop(i)
        b.cache = None
        item[4] = None

    def _coords(self, g: _GroupIndex, cell):
        w = g.width
        return tuple(math.floor(c / s / w)
                     for c, s in zip(cell, g.scale))

    def _place(self, g: _GroupIndex, key: int, item) -> None:
        bc = self._coords(g, item[0])
        b = g.buckets.get(bc)
        if b is None:
            b = g.buckets[bc] = _Bucket()
        b.keys.append(key)
        b.cells.append(item[0])
        b.r_star.append(item[1])
        b.cert.append(item[2])
        b.seq.append(item[3])
        b.cache = None
        item[4] = b
        g.bbox_lo = (bc if g.bbox_lo is None
                     else tuple(map(min, g.bbox_lo, bc)))
        g.bbox_hi = (bc if g.bbox_hi is None
                     else tuple(map(max, g.bbox_hi, bc)))

    # -- build --------------------------------------------------------------

    def _auto_width(self, g: _GroupIndex) -> float:
        n = max(1, len(g.items))
        cells = np.asarray([it[0] for it in g.items.values()],
                           dtype=np.float64)
        z = cells / np.asarray(g.scale, dtype=np.float64)
        span = z.max(axis=0) - z.min(axis=0)
        # width from the SPANNED axes only: a degenerate axis (a lattice
        # slice at one sd) contributes a constant bucket coordinate, so
        # folding its ~0 span into the volume would collapse the width
        # to the floor and scatter every item into its own bucket —
        # defeating the 3^dim block fast path for slice-shaped stores
        live = span > 1e-6
        if not live.any():
            return 1.0      # all items at one point: any width works
        vol = float(np.prod(span[live]))
        dim_eff = int(live.sum())
        return max(_MIN_WIDTH,
                   float((vol * _TARGET_OCCUPANCY / n)
                         ** (1.0 / dim_eff)))

    def _build(self, g: _GroupIndex, group: int, scale,
               reason: str) -> None:
        g.scale = tuple(float(s) for s in scale)
        g.buckets = {}
        g.blocks = {}
        g.bbox_lo = g.bbox_hi = None
        g.gen += 1
        g.width = (self.bucket_width if self.bucket_width is not None
                   else self._auto_width(g) if g.items else 1.0)
        for key, item in g.items.items():
            self._place(g, key, item)
        g.built_n = len(g.items)
        self.rebuilds += 1
        if self.on_rebuild is not None:
            self.on_rebuild(group, len(g.items), reason)

    def _build_block(self, g: _GroupIndex, b0):
        """Concatenate the 3^dim bucket neighborhood of ``b0`` into one
        candidate-array tuple, memoized until the group mutates."""
        parts = []
        for off in _block_offsets(len(b0)):
            b = g.buckets.get(tuple(c + o for c, o in zip(b0, off)))
            if b is not None and b.keys:
                parts.append(b.arrays())
        if not parts:
            blk = (g.gen, None, None, None, None, None, 0)
        else:
            cells = np.concatenate([p[0] for p in parts])
            seqs = np.concatenate([p[1] for p in parts])
            rst = np.concatenate([p[2] for p in parts])
            certs = np.concatenate([p[3] for p in parts])
            keys = np.concatenate([p[4] for p in parts])
            finite = np.isfinite(rst)
            blk = (g.gen, cells, seqs, keys,
                   None if bool(finite.all()) else finite,
                   certs >= 0, len(keys))
        g.blocks[b0] = blk
        return blk

    # -- query --------------------------------------------------------------

    def nearest_k(self, cell, group: int, k: Optional[int],
                  scale, require_certified: bool = False):
        """The k nearest stored items of ``group`` to ``cell`` in
        normalized-L1 distance — bitwise the linear scan's answer:
        ``[(key, distance), ...]`` ordered by ``(distance, insertion
        order)``, at most ``k`` long (``k=None`` ranks everything).
        Items with non-finite r* are skipped (the scan's NaN-row rule);
        ``require_certified`` keeps only ``cert_level >= 0`` donors."""
        from ..parallel.sweep import neighbor_distance

        group = int(group)
        g = self._groups.get(group)
        if g is None or not g.items:
            return []
        if scale is self._scale_obj:
            scale_t = self._scale_t
        else:
            scale_t = tuple(float(s) for s in scale)
            self._scale_obj, self._scale_t = scale, scale_t
        if g.buckets is None or g.scale != scale_t:
            self._build(g, group, scale_t, reason=(
                "first_query" if g.buckets is None else "scale_change"))
        elif len(g.items) > max(64, _REBUILD_GROWTH * max(1, g.built_n)):
            self._build(g, group, scale_t, reason="rewidth")
        n_total = len(g.items)
        if k is None:
            k = n_total
        cell = tuple(float(c) for c in cell)
        b0 = self._coords(g, cell)
        # the farthest occupied ring; beyond it there is nothing left
        lo, hi = g.bbox_lo, g.bbox_hi
        max_ring = 0
        for i in range(len(b0)):
            a = b0[i] - lo[i]
            if a > max_ring:
                max_ring = a
            a = hi[i] - b0[i]
            if a > max_ring:
                max_ring = a
        blk = g.blocks.get(b0)
        if blk is None or blk[0] != g.gen:
            blk = self._build_block(g, b0)
        _, cells, seqs, keys, finite, cert_ok, nblk = blk
        if nblk:
            mask = finite
            if require_certified:
                mask = cert_ok if mask is None else (mask & cert_ok)
            if mask is not None:
                cells_m = cells[mask]
                seqs_m = seqs[mask]
                keys_m = keys[mask]
            else:
                cells_m, seqs_m, keys_m = cells, seqs, keys
            cand_n = cells_m.shape[0]
            if cand_n:
                d = neighbor_distance(cell, cells_m, scale=g.scale)
                # unexplored rings >= 2 hold points at distance >=
                # 1*width; the epsilon slack keeps ulp-level rounding
                # in the bucket assignment from cutting off a tie
                exhaustive = max_ring <= 1 or nblk >= n_total
                if k == 1:
                    dmin = d.min()
                    if (exhaustive or g.width * (1.0 - 1e-9) - 1e-12
                            > float(dmin)):
                        ties = np.flatnonzero(d == dmin)
                        i = (int(ties[0]) if ties.shape[0] == 1 else
                             int(ties[int(np.argmin(seqs_m[ties]))]))
                        return [(int(keys_m[i]), float(d[i]))]
                else:
                    if exhaustive:
                        done = True
                    elif cand_n >= k:
                        kth = float(np.partition(d, k - 1)[k - 1]
                                    if cand_n > k else d.max())
                        done = g.width * (1.0 - 1e-9) - 1e-12 > kth
                    else:
                        done = False
                    if done:
                        order = np.lexsort((seqs_m, d))[:k]
                        return [(int(keys_m[i]), float(d[i]))
                                for i in order]
        elif max_ring <= 1:
            return []
        return self._ring_scan(g, cell, b0, k, require_certified,
                               neighbor_distance)

    def _ring_scan(self, g: _GroupIndex, cell, b0, k: int,
                   require_certified: bool, neighbor_distance):
        """The general expanding-ring search (the exactness backstop for
        sparse regions and large k; the block fast path answers the
        common case).  Walks ONLY the occupied buckets, in Chebyshev
        ring order — enumerating shell offsets is O(r^2) per ring and
        explodes when a degenerate item cluster forces a tiny width
        while the query sits far outside the occupied box (ring counts
        in the thousands); sorting the occupied buckets is O(B log B)
        regardless of how far away the query is."""
        ordered = sorted(
            ((max(abs(c - o) for c, o in zip(bc, b0)), bc)
             for bc, b in g.buckets.items() if b.keys),
            key=lambda t: t[0])
        parts = []          # per-bucket array tuples gathered so far
        gathered = 0
        i = 0
        nb = len(ordered)
        while i < nb:
            r = ordered[i][0]
            while i < nb and ordered[i][0] == r:
                b = g.buckets[ordered[i][1]]
                parts.append(b.arrays())
                gathered += len(b.keys)
                i += 1
            done = i >= nb        # every occupied bucket is in hand
            if not done and gathered < k:
                continue          # cannot finish yet: gather more first
            cells = np.concatenate([p[0] for p in parts])
            seqs = np.concatenate([p[1] for p in parts])
            rst = np.concatenate([p[2] for p in parts])
            certs = np.concatenate([p[3] for p in parts])
            keys = np.concatenate([p[4] for p in parts])
            mask = np.isfinite(rst)
            if require_certified:
                mask &= certs >= 0
            cand_n = int(mask.sum())
            d = (neighbor_distance(cell, cells[mask], scale=g.scale)
                 if cand_n else None)
            if not done and cand_n >= k:
                kth = float(np.partition(d, k - 1)[k - 1]
                            if cand_n > k else d.max())
                # an unexplored bucket lies at ring >= r_next, whose
                # points are at normalized-L1 distance >= (r_next-1) *
                # width (ulp slack as above)
                r_next = ordered[i][0]
                if (float(r_next - 1) * g.width * (1.0 - 1e-9)
                        - 1e-12 > kth):
                    done = True
            if done:
                if cand_n == 0:
                    return []
                order = np.lexsort((seqs[mask], d))[:k]
                keys_m = keys[mask]
                return [(int(keys_m[i]), float(d[i]))
                        for i in order]
        return []


def linear_nearest_k(cell, cells, seqs, k: Optional[int], scale):
    """The reference linear scan over a prebuilt (n, dim) cell matrix —
    the comparator the index is property-pinned (and speed-graded)
    against.  ``seqs`` carries insertion order for tie-breaking; returns
    ``[(row_index, distance), ...]``."""
    from ..parallel.sweep import neighbor_distance

    n = cells.shape[0]
    if n == 0:
        return []
    d = neighbor_distance(tuple(float(c) for c in cell), cells,
                          scale=scale)
    order = np.lexsort((np.asarray(seqs), d))[:(n if k is None else k)]
    return [(int(i), float(d[i])) for i in order]
