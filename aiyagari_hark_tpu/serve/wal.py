"""Crash-durable CAS state: write-ahead log + snapshot compaction
(ISSUE 18, DESIGN §16).

``MemoryCASBackend`` holds the fleet's entire coordination truth — every
lease, every version — in one process's dict: a SIGKILL loses all of it
at once, which is exactly the failure mode the chaos drills of PR 15
could not survive.  ``DurableCASBackend`` keeps the dict (reads and the
conditional-write decision logic are unchanged and memory-speed) and
makes every MUTATION durable before the caller's ack:

* **WAL** (``cas.wal``): one checksummed JSONL record per mutation —
  the key's POST-state ``(owner, stamp, version)`` plus a monotonic
  ``seq`` — appended through the blessed ``utils.checkpoint
  .append_jsonl`` with ``durable=True`` (fsync file, and the directory
  on create).  State-based, not operation-based, on purpose: replay
  never re-runs an op against a clock, it re-applies exact records, so
  a recovered replica's version map is BIT-identical to the dead one's.
* **Snapshot** (``cas.snapshot.json``): every ``snapshot_every``
  mutations the full map is written via ``atomic_write_json`` (tmp +
  rename + fsync) with the covered ``seq`` and a whole-body checksum,
  then the WAL is atomically emptied.  A crash between the two leaves
  records with ``seq <= snapshot.seq`` in the WAL — replay filters
  them, so compaction is crash-consistent at every instruction.
* **Replay** (construction over a non-empty ``data_dir``): snapshot
  first (checksum-verified; a corrupt snapshot REFUSES typed — its WAL
  suffix is gone, recovery cannot pretend), then every WAL record with
  a newer ``seq``.  A torn FINAL line (the ``append_jsonl`` crash
  contract) is skipped LOUDLY; a corrupt record MID-log means external
  damage and refuses typed (``WALCorruptionError``) — the operator
  resyncs the replica from its quorum peers instead of serving a
  silently-wrong prefix.  Every recovery journals ``WAL_REPLAY``.

Disk faults (ENOSPC/EIO — injected by ``utils.checkpoint
.arm_disk_fault`` or real) degrade AVAILABILITY-first and loudly: a
failed WAL append or snapshot write warns + journals but the in-memory
op still serves (the replica's durability is degraded, its quorum's is
not — the other 2f replicas still log), and compaction re-arms after
another ``snapshot_every`` mutations.
"""

from __future__ import annotations

import contextlib
import json
import os
import warnings
import zlib
from typing import Optional

from ..utils.checkpoint import append_jsonl, atomic_write_json
from .lease import MemoryCASBackend, _Rec

WAL_NAME = "cas.wal"
SNAPSHOT_NAME = "cas.snapshot.json"


class WALCorruptionError(ValueError):
    """The WAL or snapshot is damaged beyond the crash contract (a
    corrupt record MID-log, a snapshot failing its checksum): recovery
    REFUSES rather than serve a silently-wrong prefix.  Typed so a
    supervisor can catch exactly this and re-seed the replica from its
    quorum peers (anti-entropy owns the rest)."""


def _checksum(payload: dict) -> int:
    """One canonical spelling for record/snapshot checksums: crc32 of
    the sorted, separator-minimal JSON of everything but ``ck``."""
    body = json.dumps(payload, sort_keys=True, separators=(",", ":"))
    return zlib.crc32(body.encode("utf-8")) & 0xFFFFFFFF


def _read_wal(path: str):
    """Parse a WAL back: ``(records, torn_tail)``.

    Unlike ``read_jsonl_tolerant`` (skip anywhere), a WAL's tolerance
    is POSITIONAL: only the final line may be torn (the ``append_jsonl``
    crash artifact).  An unparseable or checksum-failing line anywhere
    else is external corruption — ``WALCorruptionError``."""
    with open(path, "rb") as f:
        raw_lines = [ln for ln in (r.strip() for r in f) if ln]
    records = []
    torn = 0
    for i, raw in enumerate(raw_lines):
        last = i == len(raw_lines) - 1
        try:
            rec = json.loads(raw.decode("utf-8"))
            ck = rec.pop("ck")
            if ck != _checksum(rec):
                raise ValueError("record checksum mismatch")
        except (ValueError, KeyError, UnicodeDecodeError) as e:
            if last:
                torn += 1
                break
            raise WALCorruptionError(
                f"CAS WAL {path}: unreadable record at line {i + 1} of "
                f"{len(raw_lines)} ({e}) — mid-log corruption is outside "
                "the torn-tail crash contract; refusing to replay a "
                "silently-wrong prefix (resync this replica from its "
                "quorum peers)") from e
        records.append(rec)
    return records, torn


def _read_snapshot(path: str) -> Optional[dict]:
    """The snapshot dict, or None when absent.  A snapshot that parses
    but fails its checksum refuses typed — its WAL suffix was truncated
    at compaction, so 'skip it' would silently lose every record it
    covered."""
    try:
        with open(path, "rb") as f:
            snap = json.loads(f.read().decode("utf-8"))
    except FileNotFoundError:
        return None
    except (ValueError, OSError, UnicodeDecodeError) as e:
        raise WALCorruptionError(
            f"CAS snapshot {path} is unreadable ({e}); its compacted "
            "WAL records are unrecoverable locally — resync this "
            "replica from its quorum peers") from e
    ck = snap.pop("ck", None)
    if ck != _checksum(snap):
        raise WALCorruptionError(
            f"CAS snapshot {path} failed its checksum (stored {ck}, "
            f"content hashes to {_checksum(snap)}) — silent corruption; "
            "resync this replica from its quorum peers")
    return snap


class DurableCASBackend(MemoryCASBackend):
    """A ``MemoryCASBackend`` whose every mutation is write-ahead
    logged, with periodic atomic snapshot compaction; construction over
    a directory with prior state replays it exactly.  See the module
    docstring for the format and crash contract."""

    name = "durable-cas"

    def __init__(self, data_dir: str, clock=None,
                 skew_tolerance_s: float = 0.0,
                 snapshot_every: int = 256, obs=None):
        super().__init__(clock=clock, skew_tolerance_s=skew_tolerance_s)
        self.data_dir = str(data_dir)
        os.makedirs(self.data_dir, exist_ok=True)
        self.wal_path = os.path.join(self.data_dir, WAL_NAME)
        self.snapshot_path = os.path.join(self.data_dir, SNAPSHOT_NAME)
        self.snapshot_every = max(1, int(snapshot_every))
        self._obs = obs
        self._seq = 0                 # last seq written (or recovered)
        self._since_snapshot = 0
        self._replaying = False
        self.wal_faults = 0           # degraded appends/snapshots
        self._recover_state()

    # -- observability ------------------------------------------------------

    def _emit(self, etype: str, **attrs) -> None:
        if self._obs is not None:
            self._obs.event(etype, **attrs)
            return
        from ..obs.runtime import emit_event

        emit_event(etype, **attrs)

    def _scope(self):
        """Activate this backend's obs around the checkpoint writers:
        a ``DISK_FAULT`` firing inside them (``_fire_disk_fault`` emits
        through the ACTIVE scope) must land in the replica's journal
        even from a server handler thread that never activated one."""
        return (self._obs.activate() if self._obs is not None
                else contextlib.nullcontext())

    # -- recovery (construction) -------------------------------------------

    def _recover_state(self) -> None:
        """Rebuild the version map from snapshot + WAL suffix (the
        ``WAL_REPLAY`` seam, covered by ``check_obs_events``).  A fresh
        directory recovers nothing and journals nothing."""
        snap = _read_snapshot(self.snapshot_path)
        had_wal = os.path.exists(self.wal_path)
        if snap is None and not had_wal:
            return
        snap_seq = 0
        with self._lock:
            self._replaying = True
            try:
                if snap is not None:
                    snap_seq = int(snap["seq"])
                    for k, owner, stamp, version in snap["recs"]:
                        self._recs[int(k)] = _Rec(
                            owner, float(stamp), int(version))
                records, torn = ([], 0)
                if had_wal:
                    records, torn = _read_wal(self.wal_path)
                applied = 0
                max_seq = snap_seq
                for rec in records:
                    seq = int(rec["seq"])
                    if seq <= snap_seq:
                        continue      # compaction already covers it
                    self._recs[int(rec["k"])] = _Rec(
                        rec["o"], float(rec["t"]), int(rec["v"]))
                    applied += 1
                    max_seq = max(max_seq, seq)
                self._seq = max_seq
            finally:
                self._replaying = False
        if torn:
            warnings.warn(
                f"CAS WAL {self.wal_path}: skipped {torn} torn final "
                "record (hard-kill crash artifact); every acknowledged "
                "earlier record was replayed", stacklevel=2)
        self._emit("WAL_REPLAY", path=self.wal_path,
                   snapshot_seq=snap_seq, applied=applied,
                   torn_skipped=torn, seq=self._seq,
                   keys=len(self._recs))

    # -- the write path -----------------------------------------------------

    def _mutated(self, key: int) -> None:
        """Every base-class mutation lands here (lock held, post-state
        committed in memory): append the key's new record to the WAL,
        then maybe compact.  A disk fault degrades loudly — the op
        still serves; the quorum's other logs carry the durability."""
        if self._replaying:
            return
        rec = self._recs[int(key)]
        self._seq += 1
        payload = {"seq": self._seq, "k": int(key), "o": rec.owner,
                   "t": rec.stamp, "v": rec.version}
        payload["ck"] = _checksum(payload)
        try:
            with self._scope():
                append_jsonl(self.wal_path, [json.dumps(payload)],
                             durable=True)
        except OSError as e:
            self.wal_faults += 1
            warnings.warn(
                f"CAS WAL append degraded ({e}); serving from memory — "
                "this replica's durability is reduced until the disk "
                "recovers", stacklevel=3)
            return
        self._since_snapshot += 1
        if self._since_snapshot >= self.snapshot_every:
            self._compact()

    def _compact(self) -> None:
        """Snapshot + WAL truncation (lock held) — the
        ``SNAPSHOT_COMPACT`` seam, covered by ``check_obs_events``.
        Crash-consistent at every step: the snapshot write is atomic
        and durable, and until the WAL is emptied its stale prefix is
        filtered by ``seq`` on replay."""
        snap = {"seq": self._seq,
                "recs": [[int(k), r.owner, r.stamp, r.version]
                         for k, r in sorted(self._recs.items())]}
        snap["ck"] = _checksum({"seq": snap["seq"], "recs": snap["recs"]})
        try:
            with self._scope():
                atomic_write_json(self.snapshot_path, snap, durable=True)
                from ..utils.checkpoint import atomic_write_text

                atomic_write_text(self.wal_path, "", durable=True)
        except OSError as e:
            self.wal_faults += 1
            self._since_snapshot = 0     # retry after another window
            warnings.warn(
                f"CAS snapshot compaction degraded ({e}); the WAL keeps "
                "growing and compaction retries after "
                f"{self.snapshot_every} more mutations", stacklevel=4)
            return
        self._since_snapshot = 0
        self._emit("SNAPSHOT_COMPACT", path=self.snapshot_path,
                   seq=self._seq, keys=len(self._recs))

    def compact(self) -> None:
        """Force one compaction now (drill/test hook)."""
        with self._lock:
            self._compact()
