"""Serving observability: counters, latency histograms, compile counts.

The serving analogue of the bench record fields: every number the
``--serve-smoke`` bench mode emits (``serve_hit_rate``, ``serve_p50_ms``,
``serve_batch_occupancy``, ``serve_compiles``, ...) is accumulated here,
thread-safely, by the ``EquilibriumService`` hot path.  Kept deliberately
dependency-free (no jax import at module scope): recording a hit must cost
microseconds — the exact-hit latency budget is < 1 ms end to end.
"""

from __future__ import annotations

import threading

from ..utils.timing import CompileCounter

# Served-request paths, in cache-goodness order.
PATHS = ("hit", "near", "cold")


class LatencyHistogram:
    """Bounded latency sample set with exact percentiles.

    Samples beyond ``cap`` are dropped by decimation (every other kept),
    so long soaks stay O(cap) memory while early AND late samples keep
    representation; ``count`` always reflects every observation."""

    def __init__(self, cap: int = 8192):
        self.cap = int(cap)
        self.samples: list = []
        self.count = 0
        self._stride = 1
        self._seen = 0

    def add(self, seconds: float) -> None:
        self.count += 1
        self._seen += 1
        if self._seen % self._stride:
            return
        self.samples.append(float(seconds))
        if len(self.samples) >= self.cap:
            self.samples = self.samples[::2]
            self._stride *= 2

    def percentile(self, q: float):
        """q in [0, 100]; None when no samples were recorded."""
        if not self.samples:
            return None
        s = sorted(self.samples)
        idx = min(len(s) - 1, max(0, round(q / 100.0 * (len(s) - 1))))
        return s[idx]


class ServeMetrics:
    """Thread-safe accumulator for one ``EquilibriumService``'s lifetime.

    * per-path request counts and latencies (submit -> future resolved);
    * batch shape accounting: real lanes vs padded ladder shape
      (``serve_batch_occupancy`` is mean real/shape over launches);
    * queue depth peak;
    * XLA compile activity via ``utils.timing.CompileCounter`` — the
      service holds ``compile`` entered around every device launch, so
      ``serve_compiles`` counts backend compile requests attributable to
      serving (an in-memory executable reuse fires nothing: the
      zero-compiles-after-warmup contract's number).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self.served = {p: 0 for p in PATHS}
        self.failures = 0
        self.batches = 0
        self.lanes_real = 0
        self.lanes_padded = 0
        self.queue_depth_peak = 0
        self.latency = {p: LatencyHistogram() for p in PATHS}
        self.latency_all = LatencyHistogram()
        self.compile = CompileCounter()
        # precision-ladder phase accounting (DESIGN §5): per-phase inner
        # steps of every solved (non-hit) query, and how many inner fixed
        # points escalated descent -> reference
        self.descent_steps = 0
        self.polish_steps = 0
        self.precision_escalations = 0

    def record_served(self, path: str, latency_s: float) -> None:
        with self._lock:
            self.served[path] += 1
            self.latency[path].add(latency_s)
            self.latency_all.add(latency_s)

    def record_phases(self, descent: int, polish: int,
                      escalations: int) -> None:
        with self._lock:
            self.descent_steps += int(descent)
            self.polish_steps += int(polish)
            self.precision_escalations += int(escalations)

    def record_failure(self, latency_s: float) -> None:
        with self._lock:
            self.failures += 1
            self.latency_all.add(latency_s)

    def record_batch(self, n_real: int, shape: int) -> None:
        with self._lock:
            self.batches += 1
            self.lanes_real += int(n_real)
            self.lanes_padded += int(shape)

    def note_queue_depth(self, depth: int) -> None:
        with self._lock:
            if depth > self.queue_depth_peak:
                self.queue_depth_peak = depth

    @staticmethod
    def _ms(value):
        return None if value is None else round(value * 1e3, 4)

    def snapshot(self) -> dict:
        """The serving record fields, bench-JSON ready (``serve_*``)."""
        with self._lock:
            n = sum(self.served.values()) + self.failures
            total = max(n, 1)
            occ = (self.lanes_real / self.lanes_padded
                   if self.lanes_padded else None)
            return {
                "serve_requests": n,
                "serve_hit_rate": round(self.served["hit"] / total, 4),
                "serve_near_rate": round(self.served["near"] / total, 4),
                "serve_cold_rate": round(self.served["cold"] / total, 4),
                "serve_failures": self.failures,
                "serve_batches": self.batches,
                "serve_batch_occupancy": (None if occ is None
                                          else round(occ, 4)),
                "serve_queue_depth_peak": self.queue_depth_peak,
                "serve_p50_ms": self._ms(self.latency_all.percentile(50)),
                "serve_p95_ms": self._ms(self.latency_all.percentile(95)),
                "serve_hit_p50_ms": self._ms(
                    self.latency["hit"].percentile(50)),
                "serve_hit_p95_ms": self._ms(
                    self.latency["hit"].percentile(95)),
                "serve_compiles": self.compile.compile_events,
                "serve_compile_cache_misses": self.compile.cache_misses,
                "serve_compile_s": round(self.compile.compile_seconds, 3),
                "serve_descent_steps": self.descent_steps,
                "serve_polish_steps": self.polish_steps,
                "serve_polish_frac": (
                    None if self.descent_steps + self.polish_steps == 0
                    else round(self.polish_steps
                               / (self.descent_steps + self.polish_steps),
                               4)),
                "serve_precision_escalations": self.precision_escalations,
            }
