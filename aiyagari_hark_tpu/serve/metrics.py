"""Serving observability: counters, latency histograms, compile counts.

The serving analogue of the bench record fields: every number the
``--serve-smoke`` bench mode emits (``serve_hit_rate``, ``serve_p50_ms``,
``serve_batch_occupancy``, ``serve_compiles``, ...) is accumulated here,
thread-safely, by the ``EquilibriumService`` hot path.  Kept deliberately
dependency-free (no jax import at module scope): recording a hit must cost
microseconds — the exact-hit latency budget is < 1 ms end to end.
"""

from __future__ import annotations

import threading
import weakref

from ..utils.timing import CompileCounter

# Served-request paths, in cache-goodness order.  "degraded" is the
# overload brown-out path (ISSUE 8): a nearest-neighbor answer served
# from the store under pressure, tagged ``quality="degraded_neighbor"``.
# "surrogate" is the continuous-parameter interpolation tier (ISSUE 17):
# an off-lattice answer fit over the k nearest certified stored
# solutions, tagged ``quality="surrogate"`` with its error bound.
PATHS = ("hit", "near", "cold", "degraded", "surrogate")


class LatencyHistogram:
    """Bounded latency sample set with exact percentiles.

    Samples beyond ``cap`` are dropped by decimation (every other kept),
    so long soaks stay O(cap) memory while early AND late samples keep
    representation; ``count`` always reflects every observation."""

    def __init__(self, cap: int = 8192):
        self.cap = int(cap)
        self.samples: list = []
        self.count = 0
        self._stride = 1
        self._seen = 0

    def add(self, seconds: float) -> None:
        self.count += 1
        self._seen += 1
        if self._seen % self._stride:
            return
        self.samples.append(float(seconds))
        if len(self.samples) >= self.cap:
            self.samples = self.samples[::2]
            self._stride *= 2

    def percentile(self, q: float):
        """q in [0, 100]; None when no samples were recorded."""
        if not self.samples:
            return None
        s = sorted(self.samples)
        idx = min(len(s) - 1, max(0, round(q / 100.0 * (len(s) - 1))))
        return s[idx]


class ServeMetrics:
    """Thread-safe accumulator for one ``EquilibriumService``'s lifetime.

    * per-path request counts and latencies (submit -> future resolved);
    * batch shape accounting: real lanes vs padded ladder shape
      (``serve_batch_occupancy`` is mean real/shape over launches);
    * queue depth peak;
    * XLA compile activity via ``utils.timing.CompileCounter`` — the
      service holds ``compile`` entered around every device launch, so
      ``serve_compiles`` counts backend compile requests attributable to
      serving (an in-memory executable reuse fires nothing: the
      zero-compiles-after-warmup contract's number).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self.served = {p: 0 for p in PATHS}
        # scenario disaggregation (ISSUE 9 satellite): per-(scenario,
        # path) served counts, so a multi-scenario service's traffic mix
        # is visible in snapshot()/prometheus_text() instead of blending
        # model families into one rate
        self.served_by_scenario: dict = {}
        self.failures = 0
        self.batches = 0
        self.lanes_real = 0
        self.lanes_padded = 0
        self.queue_depth_peak = 0
        self.latency = {p: LatencyHistogram() for p in PATHS}
        self.latency_all = LatencyHistogram()
        self.compile = CompileCounter()
        # precision-ladder phase accounting (DESIGN §5): per-phase inner
        # steps of every solved (non-hit) query, and how many inner fixed
        # points escalated descent -> reference
        self.descent_steps = 0
        self.polish_steps = 0
        self.precision_escalations = 0
        # integrity layer (ISSUE 6, DESIGN §9): deadline expirations at
        # batch seams, per-level certificate verdicts of certified
        # queries, and the store's corrupt-eviction counter (provided by
        # the SolutionStore so the metrics module stays dependency-free)
        self.deadline_expirations = 0
        self.certificates = {"certified": 0, "marginal": 0, "failed": 0}
        # overload layer (ISSUE 8, DESIGN §11): fail-fast admission
        # rejections, displaced (shed) pendings, breaker activity, and
        # submit-time deadline rejections (counted APART from the seam
        # expirations above — a rejected query never held a queue slot).
        # ``depth_hist`` samples the queue depth at submit AND at pop
        # (pre-pop depth), closing the drain-heavy understatement the
        # submit-only peak had.
        self.overloaded = 0
        self.load_sheds = 0
        self.circuit_rejects = 0
        self.deadline_rejects = 0
        self.breaker = {"opened": 0, "reopened": 0, "closed": 0,
                        "probe": 0}
        self.depth_hist = LatencyHistogram()
        # speculative neighbor prefetch (ISSUE 15): queries the service
        # issued at Priority.SPECULATIVE around misses, how many of
        # their stored solutions later converted a would-be miss into an
        # exact hit, and how many issues the overload layer suppressed
        # (prefetch is best-effort by construction — a rejected
        # speculative submit is working as designed, not an error)
        self.prefetch_issued = 0
        self.prefetch_converted = 0
        self.prefetch_suppressed = 0
        # surrogate tier (ISSUE 17): interpolated answers served, the
        # per-reason escalation counts (too_few_donors / donor_too_far /
        # bound_exceeded / audit), seeded-audit outcomes (an audit
        # failure = the real solve landed OUTSIDE the surrogate's own
        # reported bound — loud by design), lattice refinement points
        # published from escalated solves, and the reported error bound
        # distribution (reusing the latency-histogram percentiles)
        self.surrogate_escalations: dict = {}
        self.audits = 0
        self.audit_failures = 0
        self.lattice_refinements = 0
        self.surrogate_bounds = LatencyHistogram()
        # fleet tier (ISSUE 15): exact hits served from a PEER worker's
        # publish (discovered at the claim gate or the waiter poll)
        self.fleet_remote_hits = 0
        # provider id -> [WeakMethod, last dict] — the store's fleet
        # claim/publish/reclaim counters, merged like the eviction
        # counter below (weak, accumulate-across-stores)
        self._fleet_counts: dict = {}
        self._retired_fleet: dict = {}
        # provider id -> [WeakMethod, last-seen eviction count]: weak so
        # a long-lived shared metrics object cannot pin dead services'
        # stores (each bound provider strongly references its store's
        # whole memory tier); last-seen so a garbage-collected store's
        # final observed count stays in the sum (folded into the retired
        # total when its id is reused by a new store)
        self._store_counts: dict = {}
        self._retired_evictions = 0

    def attach_store(self, counts_provider) -> None:
        """Register a ``SolutionStore.integrity_counts`` provider whose
        counters ``snapshot`` merges (``store_corrupt_evictions``).
        Providers ACCUMULATE: a ``ServeMetrics`` shared by several
        services reports the SUM over their stores (a re-registered
        provider — e.g. two services over one store — counts once);
        holds only a weak reference."""
        with self._lock:
            key = id(counts_provider.__self__)
            entry = self._store_counts.get(key)
            if entry is not None:
                if entry[0]() is not None:
                    return      # same live store, already tracked
                # CPython id reuse: a NEW store was allocated at a
                # garbage-collected store's address — retire the dead
                # provider's final observed count (it must stay in the
                # sum) and track the new store from zero
                self._retired_evictions += entry[1]
            self._store_counts[key] = [weakref.WeakMethod(
                counts_provider), 0]

    def attach_fleet(self, counts_provider) -> None:
        """Register a ``SolutionStore.fleet_counts`` provider whose
        claim/publish/reclaim counters ``snapshot`` merges — the same
        weak, accumulate-across-stores semantics as ``attach_store``."""
        with self._lock:
            key = id(counts_provider.__self__)
            entry = self._fleet_counts.get(key)
            if entry is not None:
                if entry[0]() is not None:
                    return
                for k, v in entry[1].items():
                    self._retired_fleet[k] = (
                        self._retired_fleet.get(k, 0) + v)
            self._fleet_counts[key] = [weakref.WeakMethod(
                counts_provider), {}]

    def _fleet_totals(self) -> dict:
        totals = dict(self._retired_fleet)
        for entry in self._fleet_counts.values():
            provider = entry[0]()
            if provider is not None:
                entry[1] = provider()
            for k, v in entry[1].items():
                totals[k] = totals.get(k, 0) + v
        for k in ("fleet_claims_won", "fleet_claims_lost",
                  "fleet_publishes", "fleet_lease_reclaims"):
            totals.setdefault(k, 0)
        return totals

    def record_prefetch_issued(self) -> None:
        """One speculative neighbor query was enqueued."""
        with self._lock:
            self.prefetch_issued += 1

    def record_prefetch_converted(self) -> None:
        """One exact hit was served from a solution a prefetch stored —
        a would-be cold miss converted (counted once per stored key)."""
        with self._lock:
            self.prefetch_converted += 1

    def record_prefetch_suppressed(self) -> None:
        """One prefetch issue was declined by the overload layer or a
        full queue (best-effort by construction)."""
        with self._lock:
            self.prefetch_suppressed += 1

    def record_remote_hit(self) -> None:
        """One exact hit served from a peer worker's publish (fleet)."""
        with self._lock:
            self.fleet_remote_hits += 1

    def record_surrogate_bound(self, bound: float) -> None:
        """One surrogate answer's reported error bound (r* units)."""
        with self._lock:
            self.surrogate_bounds.add(float(bound))

    def record_surrogate_escalated(self, reason: str) -> None:
        """One surrogate-eligible query escalated to a real solve."""
        with self._lock:
            self.surrogate_escalations[str(reason)] = (
                self.surrogate_escalations.get(str(reason), 0) + 1)

    def record_audit(self, ok: bool) -> None:
        """One seeded-audit escalation resolved: the real solve landed
        inside (ok) or outside (FAILED — loud) the surrogate's own
        reported error bound."""
        with self._lock:
            self.audits += 1
            if not ok:
                self.audit_failures += 1

    def record_lattice_refined(self) -> None:
        """One escalated solve was published as a parameter-space
        refinement point (the lattice densified where the surrogate
        failed)."""
        with self._lock:
            self.lattice_refinements += 1

    def _store_evictions(self) -> int:
        total = self._retired_evictions
        for entry in self._store_counts.values():
            provider = entry[0]()
            if provider is not None:
                entry[1] = provider()["store_corrupt_evictions"]
            total += entry[1]
        return total

    def record_expired(self, latency_s: float) -> None:
        """One query failed with ``DeadlineExceeded`` at a batch seam."""
        with self._lock:
            self.deadline_expirations += 1
            self.latency_all.add(latency_s)

    def record_deadline_reject(self) -> None:
        """One query was rejected at SUBMIT because its deadline had
        already effectively passed, or (deadline-aware admission) could
        not be met given the queue — no slot was ever held."""
        with self._lock:
            self.deadline_rejects += 1

    def record_overloaded(self) -> None:
        """One arrival was rejected fail-fast by admission control."""
        with self._lock:
            self.overloaded += 1

    def record_shed(self, waited_s: float) -> None:
        """One queued pending was displaced by a higher-priority arrival
        (typed ``LoadShed`` on its future)."""
        with self._lock:
            self.load_sheds += 1
            self.latency_all.add(waited_s)

    def record_circuit_reject(self) -> None:
        """One arrival fast-failed on an open regional breaker."""
        with self._lock:
            self.circuit_rejects += 1

    def record_breaker(self, transition: str) -> None:
        """One breaker transition: opened/reopened/closed/probe."""
        with self._lock:
            self.breaker[transition] += 1

    def record_certificate(self, level: int) -> None:
        """One cold-miss solution was certified (``certify_before_cache``)."""
        name = ("certified", "marginal", "failed")[max(0, min(2,
                                                              int(level)))]
        with self._lock:
            self.certificates[name] += 1

    def record_served(self, path: str, latency_s: float,
                      scenario: str = "aiyagari") -> None:
        with self._lock:
            self.served[path] += 1
            per = self.served_by_scenario.setdefault(
                str(scenario), {p: 0 for p in PATHS})
            per[path] += 1
            self.latency[path].add(latency_s)
            self.latency_all.add(latency_s)

    def record_phases(self, descent: int, polish: int,
                      escalations: int) -> None:
        with self._lock:
            self.descent_steps += int(descent)
            self.polish_steps += int(polish)
            self.precision_escalations += int(escalations)

    def record_failure(self, latency_s: float) -> None:
        with self._lock:
            self.failures += 1
            self.latency_all.add(latency_s)

    def record_batch(self, n_real: int, shape: int) -> None:
        with self._lock:
            self.batches += 1
            self.lanes_real += int(n_real)
            self.lanes_padded += int(shape)

    def note_queue_depth(self, depth: int) -> None:
        """One queue-depth sample — taken at submit AND at every pop
        (the pre-pop depth), so drain-heavy loads no longer understate
        the peak (ISSUE 8 satellite); every sample also feeds the depth
        histogram (``serve_queue_depth_p50``/``p99``)."""
        with self._lock:
            if depth > self.queue_depth_peak:
                self.queue_depth_peak = depth
            self.depth_hist.add(float(depth))

    @staticmethod
    def _ms(value):
        return None if value is None else round(value * 1e3, 4)

    def publish(self, registry) -> None:
        """Mirror the snapshot into an ``obs.MetricsRegistry`` (ISSUE 7)
        without changing this class's public API: every numeric
        ``serve_*`` field becomes an ``aiyagari_``-prefixed gauge
        (gauges, not counters — a snapshot is a level, and rates/
        percentiles go down).  The ``EquilibriumService`` publishes on
        ``close()`` when observability is enabled; callers wanting a
        live scrape call this before ``registry.prometheus_text()``."""
        if registry is None:
            return
        for name, value in self.snapshot().items():
            if isinstance(value, bool) or not isinstance(value,
                                                         (int, float)):
                continue
            registry.gauge(f"aiyagari_{name}").set(float(value))
        # per-quality served gauges (ISSUE 17): one gauge per serving
        # path so a scrape splits the answer-quality mix directly
        with self._lock:
            served = dict(self.served)
        for path, n in served.items():
            registry.gauge(f"aiyagari_serve_served_{path}").set(float(n))
        # per-scenario disaggregation (ISSUE 9 satellite): one gauge per
        # (scenario, path) so prometheus_text() splits the traffic mix
        # by model family
        with self._lock:
            per = {s: dict(c) for s, c in self.served_by_scenario.items()}
        for scenario, counts in per.items():
            for path, n in counts.items():
                if n:
                    registry.gauge(
                        f"aiyagari_serve_served_{path}_scenario_"
                        f"{scenario}").set(float(n))

    def snapshot(self) -> dict:
        """The serving record fields, bench-JSON ready (``serve_*``)."""
        with self._lock:
            n = sum(self.served.values()) + self.failures
            total = max(n, 1)
            occ = (self.lanes_real / self.lanes_padded
                   if self.lanes_padded else None)
            return {
                "serve_requests": n,
                "serve_hit_rate": round(self.served["hit"] / total, 4),
                "serve_near_rate": round(self.served["near"] / total, 4),
                "serve_cold_rate": round(self.served["cold"] / total, 4),
                "serve_failures": self.failures,
                "serve_batches": self.batches,
                "serve_batch_occupancy": (None if occ is None
                                          else round(occ, 4)),
                "serve_queue_depth_peak": self.queue_depth_peak,
                "serve_p50_ms": self._ms(self.latency_all.percentile(50)),
                "serve_p95_ms": self._ms(self.latency_all.percentile(95)),
                "serve_hit_p50_ms": self._ms(
                    self.latency["hit"].percentile(50)),
                "serve_hit_p95_ms": self._ms(
                    self.latency["hit"].percentile(95)),
                "serve_compiles": self.compile.compile_events,
                "serve_compile_cache_misses": self.compile.cache_misses,
                "serve_compile_s": round(self.compile.compile_seconds, 3),
                "serve_descent_steps": self.descent_steps,
                "serve_polish_steps": self.polish_steps,
                "serve_polish_frac": (
                    None if self.descent_steps + self.polish_steps == 0
                    else round(self.polish_steps
                               / (self.descent_steps + self.polish_steps),
                               4)),
                "serve_precision_escalations": self.precision_escalations,
                "serve_deadline_expirations": self.deadline_expirations,
                "serve_degraded_rate": round(
                    self.served["degraded"] / total, 4),
                "serve_overloaded": self.overloaded,
                "serve_load_sheds": self.load_sheds,
                "serve_circuit_rejects": self.circuit_rejects,
                "serve_deadline_rejects_submit": self.deadline_rejects,
                "serve_breaker_opens": self.breaker["opened"],
                "serve_breaker_reopens": self.breaker["reopened"],
                "serve_breaker_closes": self.breaker["closed"],
                "serve_breaker_probes": self.breaker["probe"],
                "serve_queue_depth_p50": self.depth_hist.percentile(50),
                "serve_queue_depth_p99": self.depth_hist.percentile(99),
                "serve_certified": self.certificates["certified"],
                "serve_marginal_certificates": self.certificates["marginal"],
                "serve_failed_certificates": self.certificates["failed"],
                "store_corrupt_evictions": self._store_evictions(),
                # surrogate tier (ISSUE 17): hit rate over ALL requests
                # (UP is better — interpolation displacing cold solves),
                # escalation rate over surrogate-ELIGIBLE requests
                # (DOWN), seeded-audit outcomes, refinement publishes,
                # and the reported error-bound percentiles (r* units,
                # NOT milliseconds — DOWN is better)
                "surrogate_hit_rate": round(
                    self.served["surrogate"] / total, 4),
                "surrogate_escalation_rate": round(
                    sum(self.surrogate_escalations.values())
                    / max(self.served["surrogate"]
                          + sum(self.surrogate_escalations.values()),
                          1), 4),
                "surrogate_escalations": sum(
                    self.surrogate_escalations.values()),
                "surrogate_audits": self.audits,
                "surrogate_audit_failures": self.audit_failures,
                "surrogate_refinements": self.lattice_refinements,
                "surrogate_bound_p50": self.surrogate_bounds.percentile(50),
                "surrogate_bound_p95": self.surrogate_bounds.percentile(95),
                "surrogate_p50_ms": self._ms(
                    self.latency["surrogate"].percentile(50)),
                "surrogate_p95_ms": self._ms(
                    self.latency["surrogate"].percentile(95)),
                # speculative prefetch + fleet tier (ISSUE 15)
                "serve_prefetch_issued": self.prefetch_issued,
                "serve_prefetch_converted": self.prefetch_converted,
                "serve_prefetch_suppressed": self.prefetch_suppressed,
                "fleet_remote_hits": self.fleet_remote_hits,
                **self._fleet_totals(),
                # per-scenario served counts (ISSUE 9): {scenario:
                # {path: n}} — JSON-ready; publish() mirrors the nonzero
                # cells as per-scenario gauges
                "serve_scenarios": {s: dict(c) for s, c in
                                    self.served_by_scenario.items()},
            }
