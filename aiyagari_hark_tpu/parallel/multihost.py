"""Multi-host (multi-process) execution: the distributed backend.

The reference has no distributed story at all (single-process NumPy,
SURVEY.md §2.4).  This framework's cross-device communication is XLA
collectives over a ``jax.sharding.Mesh`` — ``pmean`` inside the sharded
panel scan, the result gather of the cell-sharded sweep — which ride ICI
within a slice and DCN across hosts once the *processes* are connected.
Connecting them is all this module does: ``jax.distributed.initialize``
with environment autodetection, plus the small host-side conventions
(process-0 guard, global mesh construction) a multi-host sweep needs.

Typical multi-host Table II run (one process per host, all hosts run the
same script):

    from aiyagari_hark_tpu.parallel import multihost, make_mesh
    from aiyagari_hark_tpu.parallel.sweep import run_table2_sweep

    multihost.initialize()                    # no-op when single-process
    mesh = make_mesh(("cells",))              # ALL hosts' devices
    res = run_table2_sweep(mesh=mesh, axis="cells")
    if multihost.is_coordinator():
        print(res.table())

Cells are communication-free until the final gather, so the only DCN
traffic is scalars at the end — the sweep scales to as many hosts as
there are cells.  (On TPU pods the coordinator address/process ids come
from the runtime environment and ``initialize()`` needs no arguments;
elsewhere pass them explicitly or via ``JAX_COORDINATOR_ADDRESS`` /
``JAX_NUM_PROCESSES`` / ``JAX_PROCESS_ID``.)
"""

from __future__ import annotations

import os
from typing import Optional

import jax


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> bool:
    """Connect this process to the multi-host job; returns True if a
    multi-process runtime was initialized, False for the single-process
    no-op (so scripts work unchanged on one host).

    Resolution order per argument: explicit parameter, then the
    ``JAX_COORDINATOR_ADDRESS`` / ``JAX_NUM_PROCESSES`` / ``JAX_PROCESS_ID``
    environment variables, then the platform's own autodetection (TPU pod
    runtimes publish these — ``jax.distributed.initialize()`` with no
    arguments is the documented call there).
    """
    coordinator_address = (coordinator_address
                           or os.environ.get("JAX_COORDINATOR_ADDRESS"))
    if num_processes is None and "JAX_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["JAX_NUM_PROCESSES"])
    if process_id is None and "JAX_PROCESS_ID" in os.environ:
        process_id = int(os.environ["JAX_PROCESS_ID"])

    explicit = coordinator_address is not None
    on_pod_runtime = any(v in os.environ for v in
                         ("TPU_WORKER_HOSTNAMES", "MEGASCALE_COORDINATOR_ADDRESS"))
    if not explicit and not on_pod_runtime:
        if num_processes is not None and num_processes > 1:
            raise ValueError(
                f"num_processes={num_processes} requested but no "
                "coordinator address (argument or JAX_COORDINATOR_ADDRESS) "
                "and no pod runtime detected — refusing to silently run "
                f"{num_processes} independent duplicate single-process jobs")
        return False   # single-process run: nothing to connect
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
    return True


def is_coordinator() -> bool:
    """True on process 0 — guard host-side side effects (printing, file
    writes) so a multi-host sweep emits one copy of its outputs."""
    return jax.process_index() == 0


def process_count() -> int:
    return jax.process_count()
