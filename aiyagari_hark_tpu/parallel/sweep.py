"""The Table II calibration sweep as batched, device-sharded XLA programs.

The reference runs Aiyagari's Table II (σ ∈ {1,3,5} × ρ ∈ {0,0.3,0.6,0.9})
**manually, one notebook cell at a time**, editing the parameter dicts between
runs (SURVEY.md §2.4) — each cell costing a ~27-minute ``economy.solve()``.
Here a sweep is data: arrays of (σ, ρ, sd) triples — ``labor_sd`` as a
tuple batches BOTH of Aiyagari's panels — vmapped through the jitted
bisection equilibrium (``models.equilibrium``) and sharded over the ``cells``
mesh axis.  No communication between cells — XLA places one subset of cells
per device and the only cross-device traffic is the final result gather.

Scheduling (ISSUE 2): one lock-step launch prices every lane at the SLOWEST
cell (measured total-work skew 2.6 at 12 lanes growing to 5.3 at 96,
``bench_tpu_last.json:lanes_scaling``) — the load-imbalance pathology
high-dimensional DSGE solvers schedule around (Scheidegger et al.,
arXiv:2202.06555).  Per-cell work is *predictable* from (σ, ρ, sd) (the
asymptotic-linearity structure of the consumption policy, Ma–Stachurski–Toda
arXiv:2002.09108) or, better, from a prior run's counters, so the
``schedule="balanced"`` path sorts cells by predicted work into
work-homogeneous BUCKETS solved as separate launches of one shared
executable (same shape ⇒ same compiled program, different data), balances
per-DEVICE total work — not lane count — inside each bucket, optionally
warm-starts each cell's bisection bracket by verified dyadic descent toward
a known root, and un-permutes before ``SweepResult`` so the output is
bit-order-identical to the lock-step path.
"""

from __future__ import annotations

import warnings
from dataclasses import dataclass
from functools import lru_cache
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..models.equilibrium import solve_calibration_lean
from ..obs.runtime import NULL_OBS, resolve_obs
from ..solver_health import CONVERGED, NONFINITE, is_failure, status_name
from ..utils.checkpoint import (
    CORRUPT_NPZ_ERRORS,
    CheckpointMismatchError,
    load_sweep_sidecar,
    save_sweep_sidecar,
)
from ..utils.fingerprint import (
    IntegrityError,
    hashable_kwargs,
    ledger_fingerprint,
    solution_fingerprint,
    work_fingerprint,
)
from ..utils.config import SweepConfig
from ..utils.timing import stopwatch
from ..utils.resilience import (
    LedgerState,
    RetryPolicy,
    TransientInjector,
    fire_preemption,
    raise_if_interrupted,
    retry_transient,
)
from .mesh import (
    STATE_AXIS,
    active_state_mesh,
    balanced_lane_order,
    current_state_mesh,
    mesh_axis_size,
    pad_to_multiple,
    resolve_mesh,
    sharded_launcher,
    sharding,
    state_mesh,
)


@dataclass
class SweepResult:
    """Per-cell equilibrium objects, cell-major ([C] leading axis).

    ``excess`` mixes household supply evaluated at the *last bisection
    midpoint* with firm demand at ``r_star`` (the lean solver never
    re-solves at ``r_star``), so it is a market-clearing residual accurate
    only to O(r_tol) — a bracket-width effect, not a solver error.

    ``egm_iters``/``dist_iters`` are each cell's total inner-loop work.
    Under vmap-of-while, every lane runs until the slowest converges, so
    ``iteration_skew()`` (max/min total work) bounds the wasted compute —
    the supporting model for multi-chip scaling claims (VERDICT r1 #9).
    ``scheduled_iteration_skew()`` is the straggler ratio AFTER bucketed
    scheduling — the waste the hardware actually sees when the sweep ran
    ``schedule="balanced"`` (``bucket`` records each cell's launch group,
    ``predicted_work`` the scheduler's cost model).

    Solver health: ``status`` holds each cell's final ``solver_health``
    code and ``retries`` how many quarantine retries it consumed (0 =
    solved in the batched pass).  A cell that failed every retry keeps
    its failing status and its value fields (``r_star_pct``,
    ``saving_rate_pct``, ``capital``, ``excess``) are NaN-masked — a
    failed cell must poison its own entries loudly, never the table
    silently.  Check ``failed_cells()`` before trusting aggregates.

    Precision ladder (DESIGN §5): ``descent_steps``/``polish_steps``
    split each cell's inner work by ladder phase (all-polish under the
    default "reference" policy) — ``polish_frac()`` is the share of
    steps that ran at reference precision — and ``precision_escalations``
    counts fixed points whose descent phase fell back to a pure-reference
    solve (``solver_health.PRECISION_ESCALATED``; the escalation is
    absorbed before quarantine, so a non-zero count with a healthy
    status is informational, not a failure).
    """

    crra: np.ndarray          # [C]
    labor_ar: np.ndarray      # [C]
    labor_sd: np.ndarray      # [C] (one value per panel; 0.2 in panel A)
    r_star_pct: np.ndarray    # [C] net return, percent (Table II units)
    saving_rate_pct: np.ndarray  # [C] δK/Y, percent
    capital: np.ndarray       # [C]
    excess: np.ndarray        # [C] market-clearing residual, O(r_tol) exact
    bisect_iters: np.ndarray  # [C] excess evaluations actually performed
    egm_iters: np.ndarray     # [C] total EGM steps across all midpoints
    dist_iters: np.ndarray    # [C] total distribution-iteration steps
    wall_seconds: float = float("nan")
    dist_method: str = "auto"   # the distribution method that actually ran
    egm_method: str = "xla"     # the policy-loop engine that actually ran
    status: Optional[np.ndarray] = None   # [C] solver_health codes (final)
    retries: Optional[np.ndarray] = None  # [C] quarantine attempts used
    bucket: Optional[np.ndarray] = None   # [C] scheduled launch group
    #                                       (None = lock-step single batch)
    predicted_work: Optional[np.ndarray] = None  # [C] scheduler work model
    descent_steps: Optional[np.ndarray] = None   # [C] cheap-phase steps
    polish_steps: Optional[np.ndarray] = None    # [C] reference-phase steps
    precision_escalations: Optional[np.ndarray] = None  # [C] ladder
    #                                       descent→reference fallbacks
    # -- integrity layer (ISSUE 6, DESIGN §9) ------------------------------
    sdc_suspected: Optional[np.ndarray] = None  # [C] bool — the SDC spot
    #   recheck saw a bitwise mismatch for this cell (recorded BEFORE the
    #   quarantine ladder re-solved it; None = recheck not run)
    cert_level: Optional[np.ndarray] = None  # [C] verify certificate level
    #   (CERTIFIED/MARGINAL/FAILED; None = certification not run)
    recheck_wall_seconds: float = 0.0   # SDC recheck launches (outside
    #                                     wall_seconds — defense overhead)
    certify_wall_seconds: float = 0.0   # certification launches (ditto)

    def polish_frac(self) -> float:
        """Share of inner-loop steps that ran at reference precision —
        1.0 for a "reference"-policy sweep, and the ladder's headline
        economy under "mixed" (ISSUE 5 acceptance: <= 0.25 on the
        12-cell sweep)."""
        if self.descent_steps is None or self.polish_steps is None:
            return 1.0
        total = float(self.descent_steps.sum() + self.polish_steps.sum())
        return float(self.polish_steps.sum()) / max(total, 1.0)

    def failed_cells(self) -> np.ndarray:
        """Indices of cells whose final status is a failure (MAX_ITER or
        NONFINITE) — quarantined, retried, and still not certified."""
        if self.status is None:
            return np.asarray([], dtype=np.int64)
        return np.nonzero(is_failure(self.status))[0]

    def total_work(self) -> np.ndarray:
        """Per-cell inner-loop step count (EGM + distribution iterations)."""
        return self.egm_iters + self.dist_iters

    def iteration_skew(self) -> float:
        """max/min of per-cell total work — how unevenly vmap-of-while lanes
        finish (1.0 = perfectly balanced; the batch runs at the max)."""
        w = self.total_work()
        return float(w.max() / max(w.min(), 1))

    def scheduled_iteration_skew(self) -> float:
        """The straggler ratio the hardware actually paid: with bucketed
        scheduling each bucket is its own lock-step launch, so the binding
        ratio is the WORST within-bucket max/min (equals
        ``iteration_skew()`` for a lock-step sweep, where the single
        launch is the single bucket)."""
        if self.bucket is None:
            return self.iteration_skew()
        w = self.total_work()
        worst = 1.0
        for b in np.unique(self.bucket[self.bucket >= 0]):
            wb = w[self.bucket == b]
            worst = max(worst, float(wb.max() / max(wb.min(), 1)))
        return worst

    def table(self) -> str:
        """Aiyagari Table II layout: rows ρ, columns σ, entries r* (%);
        one block per stationary-s.d. panel when the sweep carries both."""
        sigmas = np.unique(self.crra)
        rhos = np.unique(self.labor_ar)
        sds = np.unique(self.labor_sd)
        lines = []
        for sd in sds:
            if len(sds) > 1:
                lines.append(f"panel sd={sd:g}")
            lines.append("rho\\sigma "
                         + "  ".join(f"{s:7.1f}" for s in sigmas))
            for rho in rhos:
                row = []
                for s in sigmas:
                    m = ((self.crra == s) & (self.labor_ar == rho)
                         & (self.labor_sd == sd))
                    row.append(f"{float(self.r_star_pct[m][0]):7.4f}"
                               if m.any() else "      –")
                lines.append(f"{rho:9.2f} " + "  ".join(row))
        return "\n".join(lines)


def _canonical_dtype(dtype):
    """Normalize a sweep dtype request to the concrete dtype the program
    will run in, so ``dtype=None`` and an explicitly-passed default cannot
    produce two ``_batched_solver`` cache entries — two identical XLA
    compiles — for the same program (ISSUE 2 satellite)."""
    from jax import dtypes as jax_dtypes

    return jax_dtypes.canonicalize_dtype(
        np.float64 if dtype is None else np.dtype(dtype))


def _state_geometry_token(kwargs_items):
    """Memo-key component capturing the active state-mesh geometry.

    The state mesh rides a thread-local (``active_state_mesh``) and is read
    at TRACE time, so it is invisible to ``_batched_solver``'s memo key on
    its own: the same ``(dtype, kwargs_items)`` under state_shards=2 and
    state_shards=4 would otherwise reuse one executable with the first
    geometry baked in (ISSUE 20).  The token is the Mesh itself (hashable:
    device grid + axis names) — but ONLY when the program would actually
    consult it, i.e. ``state="sharded"`` is in the kwargs AND a >1-shard
    state mesh is active.  Replicated programs keep a ``None`` token so the
    pre-existing cache behaviour (and entry count) is unchanged.
    """
    if dict(kwargs_items).get("state", "replicated") == "replicated":
        return None
    smesh = current_state_mesh()
    if smesh is None or mesh_axis_size(smesh, STATE_AXIS) <= 1:
        return None
    return smesh


def _batched_solver(dtype, kwargs_items=(), fault_mode=None, warm=False):
    """See ``_batched_solver_impl``.  This thin wrapper folds the active
    state-mesh geometry into the memo key (``_state_geometry_token``) —
    everything else passes through unchanged."""
    return _batched_solver_impl(dtype, kwargs_items, fault_mode, warm,
                                _state_geometry_token(kwargs_items))


@lru_cache(maxsize=None)
def _batched_solver_impl(dtype, kwargs_items=(), fault_mode=None,
                         warm=False, state_geometry=None):
    """Jitted vmapped cell solver, memoized so repeated sweeps (benchmarks,
    resumed runs, every bucket of a scheduled sweep) hit the jit cache
    instead of rebuilding the closure.  Cached entries (jitted closures)
    live for the process — call ``_batched_solver.cache_clear()`` to drop
    them.  ``dtype`` must already be canonical (``_canonical_dtype``) so
    aliases cannot split the cache.

    The stationary s.d. is a vmapped axis alongside (σ, ρ), so both
    Table II panels batch into one program.  Uses the lean bisection
    (supply carried through the loop state, no post-loop re-solve) so the
    compiled program stays small; wage, demand, excess, and the saving
    rate are closed forms in (r*, K, L) computed host-side in
    ``run_table2_sweep``.

    ``fault_mode`` (static) compiles in the deterministic fault-injection
    hook: the returned callable then takes an extra per-cell array of
    bisection trip indices (negative = healthy lane) — see
    ``solve_equilibrium_lean``.  ``None`` (the production default) keeps
    the hook compiled out.

    ``warm`` (static) compiles in the warm-started bracket continuation:
    the callable takes three extra per-cell arrays ``(lo0, hi0, it0)`` —
    verified dyadic bracket seeds (``solve_equilibrium_lean``'s
    ``bracket_init``).  A scheduled sweep therefore uses at most TWO
    executables (cold + warm) regardless of bucket count: every bucket is
    padded to one shared shape, so later launches are pure cache hits.
    """
    model_kwargs = dict(kwargs_items)

    def pack(res):
        # ONE stacked output -> ONE device->host materialization: through
        # the tunneled TPU every np.asarray is its own RPC round trip, so
        # separate outputs put one round trip EACH inside the timed wall —
        # a lane-count-independent cost the lanes_scaling fit measured as
        # ~0.7 s fixed overhead (VERDICT r4 weak-item 5).  The iteration
        # counters and the status code ride along exactly in the float
        # dtype (values ≪ 2^24); the host side casts them back to int64.
        # Layout: config.PACKED_ROW_FIELDS — shared with the resume
        # ledger and the serving store.
        f = res.r_star.dtype
        return jnp.stack([res.r_star, res.capital, res.labor,
                          res.bisect_iters.astype(f),
                          res.egm_iters.astype(f),
                          res.dist_iters.astype(f),
                          res.status.astype(f),
                          res.descent_steps.astype(f),
                          res.polish_steps.astype(f),
                          res.escalations.astype(f)])

    def solve_cell(crra, rho, sd, bracket_init=None, fault_it=None):
        extra = {} if bracket_init is None else {"bracket_init": bracket_init}
        if fault_mode is not None:
            extra.update(fault_iter=fault_it, fault_mode=fault_mode)
        return pack(solve_calibration_lean(crra, rho, labor_sd=sd,
                                           dtype=dtype, **extra,
                                           **model_kwargs))

    if fault_mode is None and not warm:
        def solve_one(crra, rho, sd):
            return solve_cell(crra, rho, sd)
    elif fault_mode is None:
        def solve_one(crra, rho, sd, lo0, hi0, it0):
            return solve_cell(crra, rho, sd, bracket_init=(lo0, hi0, it0))
    elif not warm:
        def solve_one(crra, rho, sd, fault_it):
            return solve_cell(crra, rho, sd, fault_it=fault_it)
    else:
        def solve_one(crra, rho, sd, lo0, hi0, it0, fault_it):
            return solve_cell(crra, rho, sd, bracket_init=(lo0, hi0, it0),
                              fault_it=fault_it)

    return jax.jit(jax.vmap(solve_one))


# Keep the public memo-management surface on the wrapper: bench harnesses
# and tests call ``_batched_solver.cache_clear()`` between legs.
_batched_solver.cache_clear = _batched_solver_impl.cache_clear
_batched_solver.cache_info = _batched_solver_impl.cache_info


# Quarantine retry ladder (bounded, host-side, in escalation order): each
# rung re-runs a failed cell serially with progressively safer settings —
# pure bisection (no Illinois secant jumps), an ALTERNATE distribution
# method (a Mosaic/extrapolation pathology in one method is invisible to
# another — and the SAME alternate is kept on later rungs: re-running the
# failing method with damping would retry the pathology, not escape it),
# the lock-step XLA policy loop (same reasoning for an EGM-kernel
# pathology), then plain damped iteration (``accel_every=0`` — the
# Anderson extrapolation is the main non-finite risk in the inner loops),
# then a 10x-padded bracket that keeps the bisection away from the
# singular endpoints where the supply map loses contraction (ISSUE refs:
# Cao-Luo-Nie 1905.13045, Ma-Stachurski-Toda 1812.01320).
def _retry_ladder(model_kwargs: dict) -> tuple:
    prior = model_kwargs.get("dist_method", "auto")
    alternate = "dense" if prior in ("auto", "scatter") else "scatter"
    rungs = (
        {"dist_method": alternate, "root_method": "bisect"},
        {"dist_method": alternate, "root_method": "bisect",
         "egm_method": "xla", "accel_every": 0},
        {"dist_method": alternate, "root_method": "bisect",
         "egm_method": "xla", "accel_every": 0, "bracket_pad": 10.0},
    )
    # A non-reference precision policy retries at FULL reference precision
    # on every rung: the in-ladder escalation already retried the cheap
    # phase's own failures, so a cell that still reaches quarantine needs
    # the one configuration the goldens certify — belt and braces on top
    # of the same never-retry-the-pathology reasoning as the alternate
    # distribution method (DESIGN §5).
    if model_kwargs.get("precision", "reference") != "reference":
        rungs = tuple({**r, "precision": "reference"} for r in rungs)
    # Same rule for a non-reference GRID policy (DESIGN §5b): quarantine
    # escalates to the DENSE REFERENCE grid — the in-program
    # GRID_ESCALATED fallback already retried the coarse phase on the
    # compact grid, so the rungs must re-solve at the one grid layout the
    # goldens certify.
    if model_kwargs.get("grid", "reference") != "reference":
        rungs = tuple({**r, "grid": "reference"} for r in rungs)
    # And for a non-reference KERNEL policy (ISSUE 13, DESIGN §4c):
    # quarantine escalates to the launch-per-loop reference engines — a
    # fused-kernel pathology (Mosaic lowering, VMEM residency, the tiled
    # contraction) is invisible to the XLA paths, and the rungs must
    # re-solve on the one engine the goldens certify.
    if model_kwargs.get("kernel", "reference") != "reference":
        rungs = tuple({**r, "kernel": "reference"} for r in rungs)
    # And for a non-default STATE policy (ISSUE 20, DESIGN §6b):
    # quarantine escalates to the REPLICATED layout — a sharded-contraction
    # pathology (collective placement, row-block reduction order) is
    # invisible to the replicated path, and the rungs must re-solve on the
    # one layout the goldens certify.
    if model_kwargs.get("state", "replicated") != "replicated":
        rungs = tuple({**r, "state": "replicated"} for r in rungs)
    return rungs


# Canonical kwargs normalization — lives in ``utils.fingerprint`` now (the
# serving store hashes the same spelling); the private name stays for
# existing callers (models.fiscal, tests).
_hashable_kwargs = hashable_kwargs


# ---------------------------------------------------------------------------
# Work-balanced scheduling (ISSUE 2 tentpole).
# ---------------------------------------------------------------------------

def heuristic_cell_work(cells: np.ndarray) -> np.ndarray:
    """Relative per-cell inner-loop work predicted from (σ, ρ, sd) alone —
    the scheduler's cold-start cost model.

    Empirics (CPU f64 counter records, this repo): total work is dominated
    by distribution iterations, whose count is the wealth chain's mixing
    time; measured WORK falls strongly in ρ (persistent income lets the
    wealth distribution settle in far fewer push-forwards), strongly in
    sd, and mildly in σ — equivalently, inverse work RISES approximately
    affinely in each, which is the form fitted below.  Only the RANKING
    matters for bucketing, and a prior-run sidecar replaces this model
    with measured counters cell-for-cell whenever one is available
    (``run_table2_sweep``)."""
    cells = np.asarray(cells, dtype=np.float64)
    sig, rho, sd = cells[:, 0], cells[:, 1], cells[:, 2]
    # measured work FALLS in rho, sd, and (mildly) sigma, so the fitted
    # INVERSE work RISES approximately affinely in each — keep the signs
    # paired with test_heuristic_work_model_ranks when recalibrating
    inv = 1.0 + 0.81 * rho + 6.6 * (sd - 0.2) + 0.02 * (sig - 1.0)
    return 1.0 / np.maximum(inv, 0.05)


# Sidecar validity key: the solver configuration that shaped the counters
# (method choices, tolerances, grid sizes) plus the dtype.  Cell triples
# are NOT part of the key — rows are matched per cell, so a sidecar from a
# coarser lattice still warm-starts the cells it has.  Shared with the
# serving store's donor groups via ``utils.fingerprint.work_fingerprint``.
_work_fingerprint = work_fingerprint


def _load_sidecar(path, fingerprint):
    """Best-effort sidecar read: a missing, corrupt, or stale-fingerprint
    file degrades to the heuristic — never kills a sweep
    (``checkpoint.CORRUPT_NPZ_ERRORS`` is the one shared encoding of what
    a trashed npz raises)."""
    if path is None:
        return None
    try:
        return load_sweep_sidecar(path, fingerprint)
    except CheckpointMismatchError as e:
        warnings.warn(f"sweep sidecar ignored: {e}", stacklevel=5)
        return None
    except IntegrityError as e:
        # silent corruption (DESIGN §9): the file parsed and carried the
        # right fingerprint, but its content no longer hashes to its
        # solve-time checksum — degrade to the heuristic, loudly
        warnings.warn(f"sweep sidecar failed integrity verification: {e}",
                      stacklevel=5)
        return None
    except CORRUPT_NPZ_ERRORS:
        return None


def _predict_work(cells: np.ndarray, side,
                  heuristic=heuristic_cell_work) -> np.ndarray:
    """Per-cell predicted work: sidecar counters where available (scaled
    into the heuristic's units via the median ratio over matched cells, so
    mixed predictions stay comparable), heuristic elsewhere.
    ``heuristic`` is the scenario's ``CellSpace.work`` cost model."""
    pred = heuristic(cells)
    if side is None:
        return pred
    measured = np.full(len(cells), np.nan)
    work = side.total_work()
    for i, cell in enumerate(cells):
        j = side.lookup(cell)
        if j is not None and work[j] > 0:
            measured[i] = float(work[j])
    have = np.isfinite(measured)
    if have.any():
        scale = float(np.median(measured[have] / pred[have]))
        pred = pred * max(scale, 1e-12)
        pred[have] = measured[have]
    return pred


def _host_bracket(model_kwargs: dict, dtype):
    """The economic bisection bracket in host arithmetic, bit-identical to
    ``equilibrium._bisection_setup``'s (same Python-float expression, one
    cast to ``dtype``) — required so dyadic descent replays the device's
    exact endpoint bits."""
    ft = np.dtype(dtype).type
    disc_fac = float(model_kwargs.get("disc_fac", 0.96))
    depr_fac = float(model_kwargs.get("depr_fac", 0.08))
    pad = float(model_kwargs.get("bracket_pad", 1.0))
    return (ft(-depr_fac + 1e-3 * pad),
            ft(1.0 / disc_fac - 1.0 - 1e-4 * pad))


def _host_r_tol(model_kwargs: dict, dtype) -> float:
    """The effective bracket tolerance (``_bisection_setup`` defaults)."""
    rt = model_kwargs.get("r_tol")
    if rt is not None:
        return float(rt)
    return 1e-10 if np.dtype(dtype) == np.float64 else 1e-6


def dyadic_bracket(r_lo, r_hi, target: float, margin: float,
                   max_levels: int, dtype):
    """Descend the bisection's dyadic tree toward ``target``, in the SAME
    floating-point arithmetic the compiled loop uses (``mid = 0.5*(lo+hi)``
    in ``dtype``), keeping a safety ball of ``margin`` around the target
    inside the bracket.  Returns ``(lo, hi, levels)`` — a bracket whose
    endpoints are bit-exact dyadic descendants of ``(r_lo, r_hi)``, so a
    continuation from it replays the cold bisection's remaining midpoint
    sequence exactly (``solve_equilibrium_lean``'s ``bracket_init``
    contract)."""
    ft = np.dtype(dtype).type
    lo, hi, half = ft(r_lo), ft(r_hi), ft(0.5)
    levels = 0
    while levels < max_levels:
        mid = half * (lo + hi)
        if target + margin < mid:
            hi = mid
        elif target - margin > mid:
            lo = mid
        else:
            break
        levels += 1
    return lo, hi, levels


def _plan_buckets(order: np.ndarray, n_buckets: int, n_shards: int = 1):
    """Split the work-sorted cell order into equal-size contiguous buckets
    (cheapest first).  0 = auto: ~C/3 buckets capped at 8 — small enough
    buckets to homogenize work, few enough launches to keep dispatch
    overhead negligible.  On a multi-device mesh the auto plan
    additionally keeps bucket size >= the device count (ISSUE 11):
    every bucket pads up to a device multiple, so a 3-cell bucket on an
    8-way mesh would launch 8 lanes to solve 3 — padding waste the
    planner, which knows both numbers, must not create.  An EXPLICIT
    ``n_buckets`` is honored as given.  NOTE the bit-identity interplay
    (DESIGN §6b): a mesh-dependent plan regroups cells, which on the
    default cold-bracket path changes nothing per lane, but under
    ``warm_brackets=True`` changes which already-solved neighbors seed
    which cells — warm sweeps carry the verified-seed tolerance
    contract across mesh geometries, not bitwise identity."""
    n = len(order)
    k = n_buckets if n_buckets > 0 else max(1, min(8, n // 3))
    if n_buckets <= 0 and n_shards > 1:
        k = max(1, min(k, n // n_shards))
    k = min(k, n)
    size = -(-n // k)
    return [order[i * size:(i + 1) * size]
            for i in range(k) if len(order[i * size:(i + 1) * size])], size


# Donor-ranking normalization of the (σ, ρ, sd) axes — the Table II
# lattice spans (≈4, 0.9, 0.4).  ONE rule shared by the sweep's in-batch
# neighbor seeding and the serving store's donor nomination
# (``serve.store.SolutionStore.nominate``), so batch and serving warm
# starts rank donors — and size their verified margins — identically and
# cannot drift apart (the ISSUE 4 fingerprint-consolidation rationale,
# applied to the seeding rule).
NEIGHBOR_CELL_SCALE = (4.0, 0.9, 0.4)


def neighbor_distance(cell, cells, scale=NEIGHBOR_CELL_SCALE) -> np.ndarray:
    """Normalized L1 distance from ``cell`` to each row of ``cells``.
    ``scale`` defaults to the Aiyagari lattice span; other scenarios pass
    their ``CellSpace.scale`` (one rule per family, shared by the sweep's
    in-batch seeding and the store's donor nomination)."""
    cell = np.asarray(cell, dtype=np.float64)
    cells = np.asarray(cells, dtype=np.float64)
    return sum(np.abs(cells[..., i] - cell[i]) / scale[i]
               for i in range(len(scale)))


def donor_margin(spread, width: float, r_tol: float) -> float:
    """Safety-ball half-width around a donated root: the r*-spread of the
    two nearest donors (how far the root plausibly moved) floored
    defensively; ``spread=None`` is the single-donor conservative case."""
    if spread is None:
        return float(max(0.08 * width, 64.0 * r_tol))
    return float(max(spread, 0.03 * width, 64.0 * r_tol))


def _neighbor_seed(cell, cells, r_solved, solved_ok, width, r_tol,
                   warm_margin, scale=NEIGHBOR_CELL_SCALE):
    """Bracket seed for ``cell`` from the nearest already-solved neighbor
    in normalized (σ, ρ, sd) space: target = neighbor's root, margin = the
    local r*-variation between the two nearest solved neighbors (how far
    the root plausibly moved), floored defensively.  None when nothing is
    solved yet."""
    idx = np.nonzero(solved_ok)[0]
    if len(idx) == 0:
        return None
    d = neighbor_distance(cell, cells[idx], scale=scale)
    near = idx[np.argsort(d, kind="stable")]
    target = float(r_solved[near[0]])
    if warm_margin > 0.0:
        return target, float(warm_margin)
    spread = (abs(float(r_solved[near[0]]) - float(r_solved[near[1]]))
              if len(near) > 1 else None)
    return target, donor_margin(spread, width, r_tol)


def _resilience_seam(ledger, record, progress, inject_preempt=None,
                     bucket_id=None) -> None:
    """The ONE seam protocol, shared by every safe boundary in the sweep
    (balanced bucket seams, the locked path's single launch, quarantine
    rungs) so their interruption/resume semantics cannot diverge: commit
    the just-completed work to the ledger FIRST (``record`` is the
    ledger-mutating thunk — an Interrupted must always leave the work
    durable), then fire the deterministic preemption injection if armed
    for this bucket, then poll the shutdown flag."""
    if ledger is not None:
        record(ledger)
        ledger.flush()
    if (inject_preempt is not None and bucket_id is not None
            and int(inject_preempt.get("after_bucket", -1)) == bucket_id):
        fire_preemption(inject_preempt.get("mode", "signal"))
    raise_if_interrupted(
        "table2 sweep", ledger.path if ledger is not None else None,
        progress=progress)


def _timed_launch(device_call, label, fn, args):
    """One guarded device launch whose reported wall covers ONLY the
    successful attempt — transient-retry backoff sleeps and failed
    duplicate launches must not be charged to the benchmark's honest
    wall (the retry warning is the marker that a fault occurred)."""
    t = [float("nan")]

    def timed():
        with stopwatch() as sw:
            out = np.asarray(fn(*args))
        t[0] = sw.seconds
        return out

    packed = device_call(label, timed)
    return packed, t[0]


def _solve_scheduled(scn, sweep: SweepConfig, cells_p, cells_nom,
                     fault_iters, fault_mode, mesh, axis, dtype,
                     kwargs_items, model_kwargs, perturb=0.0,
                     side=None, ledger=None, device_call=None,
                     inject_preempt=None, obs=NULL_OBS):
    """The work-balanced bucketed solve for one scenario ``scn``: returns
    per-cell packed results ``[C, scn.schema.width]`` in ORIGINAL cell
    order, the summed launch wall, the bucket assignment, and the
    predicted-work vector.  ``cells_p`` are the (possibly perturbed)
    solver inputs, ``cells_nom`` the nominal coordinates the work model,
    sidecar lookups, and neighbor distances use.

    Order of operations per bucket (cheapest predicted bucket first):
    warm-bracket seeds from the sidecar (same cell) or the nearest solved
    neighbor, lane layout balanced per device by predicted work (LPT), one
    launch of the shared executable, results un-permuted into place and
    made available as seeds for the next bucket.  Sidecar lookups, the
    work model, and neighbor distances all use the NOMINAL ρ (a benchmark
    ``perturb`` nudge must not break same-cell matching).

    Resilience (ISSUE 3): with a ``ledger`` (``resilience.LedgerState``)
    every completed bucket is flushed atomically before the next launch
    and the preemption flag is polled at each bucket seam; a resumed run
    restores completed buckets' rows from the ledger — IN LOOP ORDER, so
    later buckets' neighbor warm seeds see exactly the results an
    uninterrupted run would have had, preserving bit-identity.  Launches
    go through ``device_call`` (transient-fault retry)."""
    n_orig = len(cells_p)
    schema = scn.schema
    root_col = schema.idx(schema.root)
    status_col = schema.idx(schema.status)
    cells = np.asarray(cells_nom, dtype=np.float64)
    if device_call is None:
        def device_call(label, f):
            return f()
    # measured cost attribution (ISSUE 10): the ledger keys on the same
    # compile-cache identity the executables deduplicate on (work
    # fingerprint + cold/warm flavor + padded shape)
    prof = obs.cost_ledger
    prof_wf = (_work_fingerprint(kwargs_items, dtype, scenario=scn.name)
               if prof is not None else None)
    pred = _predict_work(cells, side, heuristic=scn.cells.work)
    if ledger is not None:
        ledger.pred = np.asarray(pred, dtype=np.float64)
    order = np.argsort(pred, kind="stable")
    n_shards = mesh_axis_size(mesh, axis)
    buckets, size = _plan_buckets(order, sweep.n_buckets,
                                  n_shards=n_shards)
    b_pad = size + (-size % n_shards)
    shard = None if mesh is None else sharding(mesh, axis)

    warm_enabled = sweep.warm_brackets and scn.warm is not None
    if warm_enabled:
        r_lo, r_hi = scn.warm.host_bracket(model_kwargs, dtype)
        width = float(r_hi) - float(r_lo)
        r_tol = scn.warm.host_r_tol(model_kwargs, dtype)
        max_levels = scn.warm.max_levels(model_kwargs)
        # Same-cell sidecar seeds descend DEEP: the prior root is exact to
        # r_tol for an identical configuration, and the expensive
        # evaluations are the near-root ones (slow-mixing distribution
        # fixed points cost a ~constant certification floor per evaluation
        # regardless of warm carry), so every level skipped near the root
        # saves a floor-cost solve.  2x r_tol keeps the verified ball
        # strictly containing the root; the continuation still performs
        # >= 2 certified evaluations.  The |perturb| term covers the
        # benchmark methodology: a perturbed timed rerun moves the root by
        # ~perturb * dr*/drho (dr*/drho is O(0.03) on the Table II
        # lattice, so 4|perturb| has ~100x slack) — without it an f64
        # rerun's margin (2e-10) sits INSIDE the root shift, every seed
        # fails verification, and the "warm" sweep pays cold work plus
        # two verification solves per lane.
        margin_same = (float(sweep.warm_margin) if sweep.warm_margin > 0.0
                       else max(2.0 * r_tol, 4.0 * abs(float(perturb)),
                                16.0 * np.finfo(np.dtype(dtype)).eps
                                * width))

    results = np.full((n_orig, schema.width), np.nan)
    solved = np.zeros(n_orig, dtype=bool)
    bucket_of = np.full(n_orig, -1, dtype=np.int64)
    # per-cell launch provenance for the SDC recheck (DESIGN §9): the
    # exact bracket seed each cell launched with (None = cold), and which
    # cells were restored from a resume ledger (their seeds are unknown,
    # so a warm-bracket recheck cannot replay them)
    seeds_used: list = [None] * n_orig
    restored = np.zeros(n_orig, dtype=bool)
    wall_total = 0.0

    for bi, bucket in enumerate(buckets):
        bucket_of[bucket] = bi
        if ledger is not None and ledger.solved[bucket].all():
            # completed in the interrupted run: restore its exact device
            # bits instead of relaunching — later buckets' neighbor seeds
            # then see what an uninterrupted run would have seen
            results[bucket] = ledger.packed[bucket]
            solved[bucket] = True
            restored[bucket] = True
            continue
        lanes = np.concatenate(
            [bucket, np.repeat(bucket[-1], b_pad - len(bucket))]
        ).astype(np.int64)
        if n_shards > 1:
            lanes = lanes[balanced_lane_order(pred[lanes], n_shards)]

        seeds = None
        if warm_enabled:
            status_so_far = np.rint(
                np.nan_to_num(results[:, status_col],
                              nan=3.0)).astype(np.int64)
            solved_ok = (solved & np.isfinite(results[:, root_col])
                         & ~is_failure(status_so_far))
            targets = []
            for li in lanes:
                seed = None
                if side is not None:
                    j = side.lookup(cells[li])
                    if j is not None and np.isfinite(side.r_star[j]):
                        seed = (float(side.r_star[j]), margin_same)
                if seed is None:
                    seed = _neighbor_seed(cells[li], cells,
                                          results[:, root_col],
                                          solved_ok, width, r_tol,
                                          float(sweep.warm_margin),
                                          scale=scn.cells.scale)
                targets.append(seed)
            known = [t for t in targets if t is not None]
            if known:
                # A lane with no seed of its own (e.g. its sidecar root is
                # NaN because the cell failed last run, and nothing is
                # solved yet to neighbor from) must not force the whole
                # bucket cold: give it a PSEUDO-seed at the median of its
                # bucket-mates' targets.  In-program verification decides
                # per lane — a wrong pseudo-bracket (or one that cannot
                # descend at all, it0 = 0) falls back to the exact cold
                # trajectory at the cost of two cheap-end evaluations.
                med = float(np.median([t[0] for t in known]))
                pseudo = (med, max(0.125 * width, 64.0 * r_tol))
                per_lane = []
                for t in targets:
                    tt = t if t is not None else pseudo
                    per_lane.append(dyadic_bracket(r_lo, r_hi, tt[0],
                                                   tt[1], max_levels,
                                                   dtype))
                seeds = per_lane

        warm = seeds is not None
        fn = scn.batched_solver(dtype, kwargs_items, fault_mode, warm)
        if n_shards > 1:
            # multi-chip launch (ISSUE 11): jit(shard_map(fn)) over the
            # lane axis — each device runs the identical per-lane program
            # on its contiguous lane block (the LPT layout above placed
            # work-balanced blocks), no cross-device traffic until the
            # output gather.  Memoized: every bucket reuses ONE wrapped
            # executable per (fn, mesh).
            fn = sharded_launcher(fn, mesh, axis)
        args = [jnp.asarray(cells_p[lanes, j], dtype=dtype)
                for j in range(cells_p.shape[1])]
        if warm:
            args += [jnp.asarray(np.asarray([s[0] for s in seeds]),
                                 dtype=dtype),
                     jnp.asarray(np.asarray([s[1] for s in seeds]),
                                 dtype=dtype),
                     jnp.asarray(np.asarray([s[2] for s in seeds],
                                            dtype=np.int32))]
        if fault_mode is not None:
            args.append(jnp.asarray(fault_iters[lanes]))
        if shard is not None:
            args = [jax.device_put(a, shard) for a in args]

        prof_key = None
        if prof is not None:
            flavor = "warm" if warm else "cold"
            prof_key = ("sweep", scn.name, prof_wf, flavor, b_pad,
                        fault_mode, n_shards)
            prof.capture(prof_key, fn, args,
                         label=f"sweep/{scn.name}/{flavor}{b_pad}"
                               + (f"x{n_shards}" if n_shards > 1 else ""))
        with obs.span("sweep/bucket", bucket=int(bi),
                      cells=len(bucket), lanes=len(lanes), warm=warm,
                      device_profile=True) as bsp:
            packed, launch_wall = _timed_launch(     # [B, W], one transfer
                device_call, f"sweep bucket {bi}", fn, args)
        wall_total += launch_wall
        if prof is not None:
            prof.record_launch(prof_key, launch_wall, tracer=obs.tracer)

        # un-permute: padding lanes duplicate a real lane's inputs, so the
        # duplicate rows carry identical bits and last-write-wins is exact
        results[lanes] = packed
        solved[bucket] = True
        # phase spans from RETURNED counters — no tracing inside jit
        # (DESIGN §10): descent/polish step totals subdivide the bucket
        # span proportionally as synthetic children
        bsp.annotate(wall_s=launch_wall)
        if schema.phases is not None:
            bsp.subdivide(
                {"descent": float(
                    results[bucket, schema.idx(schema.phases[0])].sum()),
                 "polish": float(
                     results[bucket, schema.idx(schema.phases[1])].sum())},
                prefix="sweep/phase/")
        obs.event("BUCKET_LAUNCH", bucket=int(bi), scenario=scn.name,
                  cells=[int(c) for c in bucket], warm=warm,
                  wall_s=launch_wall)
        obs.histogram("aiyagari_sweep_bucket_wall_seconds",
                      "per-bucket launch wall").observe(launch_wall)
        if obs.enabled:
            # per-bucket lane telemetry (ISSUE 10): how full the padded
            # launch really was, and how evenly the predicted work split
            # across devices — the numbers a 1->8-chip scaling claim
            # must show staying flat
            obs.gauge("aiyagari_sweep_bucket_lane_occupancy",
                      "real cells / padded lanes of the last bucket"
                      ).set(len(bucket) / float(len(lanes)))
            if n_shards > 1:
                per_dev = pred[lanes].reshape(n_shards, -1).sum(axis=1)
                dev_skew = float(per_dev.max() / max(per_dev.min(),
                                                     1e-12))
            else:
                dev_skew = 1.0
            obs.gauge("aiyagari_sweep_bucket_device_work_skew",
                      "max/min per-device predicted work of the last "
                      "bucket").set(dev_skew)
            obs.sample_devices(where=f"sweep/bucket{bi}")
        if warm:
            for pos, li in enumerate(lanes):
                seeds_used[li] = seeds[pos]
        _resilience_seam(
            ledger,
            lambda led: led.record_bucket(bucket, results[bucket], bi),
            progress={"completed_buckets": bi + 1,
                      "n_buckets": len(buckets)},
            inject_preempt=inject_preempt, bucket_id=bi)
    return results, wall_total, bucket_of, pred, seeds_used, restored


# ---------------------------------------------------------------------------
# SDC spot-checks (ISSUE 6, DESIGN §9): deterministic bitwise re-solve of a
# fingerprint-sampled cell subset in permuted lane positions.
# ---------------------------------------------------------------------------

def sdc_sample(cells: np.ndarray, kwargs_items: tuple, dtype,
               fraction: float, scenario: str = "aiyagari") -> np.ndarray:
    """The fingerprint-sampled recheck subset: rank cells by their
    ``solution_fingerprint`` (a content hash — uniform-ish over cells,
    deterministic per configuration, uncorrelated with lattice position)
    and take the ``ceil(fraction * C)`` smallest.  The same configuration
    always rechecks the same cells — reproducible defense, diffable
    across runs — while different configurations sample different
    subsets, so a fleet sweeping many configs covers the lattice."""
    c = len(cells)
    k = int(np.ceil(float(fraction) * c))
    if k <= 0:
        return np.asarray([], dtype=np.int64)
    ranks = np.asarray(
        [solution_fingerprint(cell[0], cell[1], cell[2], kwargs_items,
                              dtype, scenario=scenario)
         for cell in np.asarray(cells)],
        dtype=np.int64)
    return np.sort(np.argsort(ranks, kind="stable")[:min(k, c)])


def _sdc_recheck(scn, rows, cells_p, sample, seeds_used, fault_iters,
                 fault_mode, dtype, kwargs_items, device_call):
    """Re-solve the sampled cells through the SAME executable family and
    compare packed rows BITWISE against the batched results.

    Every launch prepends a duplicate of its first sampled cell, so every
    real cell solves at a different lane index than lane 0 — combined
    with the different batch shape/composition, the recheck exercises the
    packing-independence contract end to end (a per-lane computation must
    not depend on lane position or batchmates), which is what makes a
    bitwise mismatch a corruption signal rather than noise.  Cells that
    launched with a warm bracket seed replay their EXACT recorded seed
    (a different seed would legitimately change counters).  Returns
    (mismatched original-cell indices, summed recheck wall).

    Cost note: the sample-sized launch is its own XLA input shape, so
    the FIRST recheck at a given ``recheck_fraction`` pays one compile
    (amortized by the persistent compilation cache and by any warm-up
    run at the same fraction — the bench's integrity smoke warms it);
    steady-state rechecks are pure executable-cache hits."""
    wall = 0.0
    suspect: list = []
    groups: dict = {}
    for i in sample:
        groups.setdefault(seeds_used[int(i)] is not None,
                          []).append(int(i))
    for warm, idx in sorted(groups.items()):
        lanes = [idx[0]] + idx
        args = [jnp.asarray(cells_p[lanes, j], dtype=dtype)
                for j in range(cells_p.shape[1])]
        if warm:
            seeds = [seeds_used[i] for i in lanes]
            args += [jnp.asarray(np.asarray([s[0] for s in seeds]),
                                 dtype=dtype),
                     jnp.asarray(np.asarray([s[1] for s in seeds]),
                                 dtype=dtype),
                     jnp.asarray(np.asarray([s[2] for s in seeds],
                                            dtype=np.int32))]
        if fault_mode is not None:
            args.append(jnp.asarray(fault_iters[lanes]))
        fn = scn.batched_solver(dtype, kwargs_items, fault_mode, warm)
        packed, launch_wall = _timed_launch(
            device_call, f"sdc recheck [{len(lanes)}]", fn, args)
        wall += launch_wall
        re_rows = np.asarray(packed, dtype=np.float64)[1:]
        for pos, i in enumerate(idx):
            if (np.asarray(rows[i], dtype=np.float64).tobytes()
                    != re_rows[pos].tobytes()):
                suspect.append(i)
    return suspect, wall


_COMPILATION_CACHE_ON = False


def _ensure_compilation_cache() -> None:
    """Idempotently enable the persistent XLA compilation cache for sweep
    programs (``SweepConfig.compilation_cache``).  The kill switch
    (``AIYAGARI_COMPILATION_CACHE=0``) is parsed in exactly ONE place —
    ``utils.backend.enable_compilation_cache``, which returns "" without
    touching jax config when it is set.  Best-effort — an unwritable
    cache dir must not take down a solve."""
    global _COMPILATION_CACHE_ON
    if _COMPILATION_CACHE_ON:
        return
    try:
        from ..utils.backend import enable_compilation_cache

        enable_compilation_cache()
    except OSError as e:
        warnings.warn(f"persistent compilation cache unavailable: {e}",
                      stacklevel=5)
    _COMPILATION_CACHE_ON = True   # resolved either way: stop re-checking




# ---------------------------------------------------------------------------
# Scenario-generic sweep engine (ISSUE 9, DESIGN §12).  ``run_sweep`` runs
# ANY registered scenario through the full machinery built in PRs 1-8 —
# balanced scheduling, quarantine, durable resume, SDC rechecks,
# certification, obs — and ``run_table2_sweep`` is its Aiyagari
# instantiation (bit-identical to the pre-refactor behavior).
# ---------------------------------------------------------------------------

@dataclass
class ScenarioSweepResult:
    """Per-cell packed rows of one scenario sweep (``run_sweep``), in
    ORIGINAL cell order.

    ``rows`` is the final ``[C, W]`` float64 block in the scenario's
    ``RowSchema`` layout — batched results with quarantine outcomes
    applied, failed cells' ``mask_on_failure`` columns NaN-masked, and
    the status column synced with ``status``.  Read columns by NAME
    (``col``/``icol``): hard-coded indices are exactly the coupling the
    schema exists to remove.  Semantics of ``status``/``retries``/
    ``bucket``/``predicted_work``/``sdc_suspected``/``cert_level`` and
    the three wall clocks match ``SweepResult`` field-for-field."""

    scenario: str
    schema: object            # scenarios.base.RowSchema
    cells: np.ndarray         # [C, 3] nominal cell coordinates
    rows: np.ndarray          # [C, W] float64 final packed rows
    status: np.ndarray        # [C] int64 solver_health codes (final)
    retries: np.ndarray       # [C] quarantine attempts used
    wall_seconds: float
    methods: dict             # scenario-recorded method metadata
    bucket: Optional[np.ndarray] = None
    predicted_work: Optional[np.ndarray] = None
    sdc_suspected: Optional[np.ndarray] = None
    cert_level: Optional[np.ndarray] = None
    recheck_wall_seconds: float = 0.0
    certify_wall_seconds: float = 0.0

    def col(self, name: str) -> np.ndarray:
        """One named row column (float64 view)."""
        return self.rows[:, self.schema.idx(name)]

    def icol(self, name: str) -> np.ndarray:
        """One named counter/status column cast back to int64 (counters
        ride the device transfer exactly — values ≪ 2^24)."""
        return np.asarray(np.rint(self.col(name)), dtype=np.int64)

    def failed_cells(self) -> np.ndarray:
        return np.nonzero(is_failure(self.status))[0]

    def total_work(self) -> np.ndarray:
        """Per-cell inner-loop step count (the schema's work counters)."""
        return sum(self.icol(f) for f in self.schema.work)

    def iteration_skew(self) -> float:
        w = self.total_work()
        return float(w.max() / max(w.min(), 1))

    def scheduled_iteration_skew(self) -> float:
        if self.bucket is None:
            return self.iteration_skew()
        w = self.total_work()
        worst = 1.0
        for b in np.unique(self.bucket[self.bucket >= 0]):
            wb = w[self.bucket == b]
            worst = max(worst, float(wb.max() / max(wb.min(), 1)))
        return worst


def run_sweep(scenario, sweep: SweepConfig = SweepConfig(),
              cells=None, mesh=None, axis: str = "cells",
              dtype=None, timer=None, perturb: float = 0.0,
              quarantine: bool = True, max_retries: int = 3,
              inject_fault: Optional[dict] = None,
              resume_path: Optional[str] = None,
              retry: Optional[RetryPolicy] = None,
              inject_transient: Optional[dict] = None,
              inject_preempt: Optional[dict] = None,
              inject_sdc: Optional[dict] = None,
              cert_thresholds=None, obs=None,
              **model_kwargs) -> ScenarioSweepResult:
    """Solve a cell lattice for any registered ``scenario`` as batched
    program launches — the scenario-generic engine behind
    ``run_table2_sweep`` (whose docstring carries the full contract:
    scheduling, quarantine, resilience, integrity, and observability
    semantics are identical here, supplied per family by the
    ``scenarios.Scenario`` bundle).

    ``scenario`` is a registered name (``scenarios.scenario_names()``)
    or a ``Scenario`` instance; an unknown name raises the typed
    ``scenarios.UnknownScenarioError``.  ``cells`` is a ``[C, 3]`` array
    of cell coordinates in the scenario's ``CellSpace`` order (default:
    ``sweep.cells()`` — the (σ, ρ, sd) lattice every built-in family
    sweeps).  Scenario identity keys every fingerprint (sidecar, resume
    ledger, SDC sample, certification), so artifacts can never cross
    model families.

    ``mesh`` (ISSUE 11): a ``jax.sharding.Mesh`` shards the lane axis
    over ``axis`` via the ``mesh.sharded_launcher`` shard_map wrapper —
    every bucket padded to a device multiple, per-device work balanced
    by the LPT lane layout, and (on the default cold-bracket path) the
    root/status/counter/mask columns bit-identical to the 1-device run
    (property-tested; the one aggregate contraction — capital — agrees
    to reduction-order noise across program widths, DESIGN §6b).  With
    ``warm_brackets=True`` the mesh-AWARE auto bucket plan may group
    cells differently than a 1-device run, changing which neighbors
    seed which cells — warm sweeps keep only their usual verified-seed
    tolerance contract across mesh geometries, exactly as they already
    do across schedules.  ``"auto"`` builds a ``cells`` mesh over all
    local devices (None on a 1-device host); ``None`` (default) runs
    unsharded.  The mesh shape is hashed into the resume-ledger
    fingerprint, so an N-device ledger refuses-to-resume under M
    devices (warn + recompute)."""
    from ..scenarios.registry import get_scenario

    scn = get_scenario(scenario)
    if cells is None:
        cells = sweep.cells()
    cells = np.asarray(cells, dtype=np.float64)
    return _run_sweep_shell(
        scn, sweep, cells, mesh, axis, dtype, timer, perturb, quarantine,
        max_retries, inject_fault, resume_path, retry, inject_transient,
        inject_preempt, inject_sdc, cert_thresholds, obs, **model_kwargs)


def _run_sweep_shell(scn, sweep, cells, mesh, axis, dtype, timer, perturb,
                     quarantine, max_retries, inject_fault, resume_path,
                     retry, inject_transient, inject_preempt, inject_sdc,
                     cert_thresholds, obs, **model_kwargs):
    # The observability shell around the solve (ISSUE 7, DESIGN §10):
    # resolve the obs bundle (argument beats SweepConfig.obs; None is the
    # near-free NULL_OBS), make it the ACTIVE scope so deep seams
    # (retry_transient, ledger restore, checksum verification) journal
    # into this run, and wrap everything in the root "sweep/run" span.
    # A bundle built HERE from an ObsConfig is owned here — closed (trace
    # flushed, RUN_END journaled) even when the run exits via the typed
    # Interrupted; a caller-provided Obs spans multiple subsystems and
    # stays open.
    # NOTE: BOTH public entry points (run_sweep, run_table2_sweep) call
    # this shell directly, so the user's frame sits a uniform FOUR levels
    # above any warn inside the impl (user -> entry -> shell -> impl) —
    # every stacklevel-tuned warnings.warn below counts on it.
    obs, owned = resolve_obs(obs if obs is not None else sweep.obs)
    # SweepConfig.state_shards (ISSUE 20, DESIGN §6b): M > 1 builds the
    # 2-D (cells × state) mesh here and ACTIVATES it for the whole run —
    # the solvers read geometry from parallel.mesh.current_state_mesh,
    # never from a kwarg (Mesh objects must not enter fingerprint/jit
    # keys).  M = 1 activates None: a literal no-op.
    smesh = (state_mesh(sweep.state_shards, axis=axis)
             if sweep.state_shards > 1 else None)
    try:
        with obs.activate(), active_state_mesh(smesh), obs.span(
                "sweep/run", schedule=sweep.schedule,
                cells=len(cells), scenario=scn.name) as sp:
            res = _run_sweep_impl(
                scn, sweep, cells, mesh, axis, dtype, timer, perturb,
                quarantine, max_retries, inject_fault, resume_path, retry,
                inject_transient, inject_preempt, inject_sdc,
                cert_thresholds, obs, **model_kwargs)
            sp.annotate(wall_s=res.wall_seconds,
                        skew=res.scheduled_iteration_skew(),
                        failed_cells=len(res.failed_cells()))
            return res
    finally:
        if owned:
            obs.close()


def _run_sweep_impl(scn, sweep, cells_nom, mesh, axis, dtype, timer,
                    perturb, quarantine, max_retries, inject_fault,
                    resume_path, retry, inject_transient, inject_preempt,
                    inject_sdc, cert_thresholds, obs,
                    **model_kwargs) -> ScenarioSweepResult:
    schema = scn.schema
    status_col = schema.idx(schema.status)
    root_col = schema.idx(schema.root)
    # mesh contract (ISSUE 11): "auto" = all local devices (None on a
    # 1-device host); a real Mesh must define the lane axis — one rule,
    # shared with EquilibriumService (mesh.resolve_mesh)
    mesh = resolve_mesh(mesh, axis)
    cells_p = np.array(cells_nom, dtype=np.float64)   # solver inputs
    if perturb:
        cells_p[:, scn.cells.perturb_axis] = (
            cells_p[:, scn.cells.perturb_axis] + perturb)
    n_orig = cells_p.shape[0]
    dtype = _canonical_dtype(dtype)
    if sweep.compilation_cache:
        _ensure_compilation_cache()
    fault_mode = None
    fault_iters = None
    if inject_fault is not None:
        fault_mode = str(inject_fault.get("mode", "nan"))
        fault_iters = np.full(n_orig, -1, dtype=np.int32)
        fault_iters[int(inject_fault["cell"])] = int(
            inject_fault.get("at_iter", 0))

    # SweepConfig.grid (DESIGN §5b) is a model-kwarg DEFAULT: an explicit
    # run_sweep(..., grid=...) kwarg wins, and the resolved spelling rides
    # kwargs_items into every fingerprint below (hashable_kwargs drops an
    # explicit "reference", so the two default spellings cannot split a
    # cache or a ledger)
    if sweep.grid != "reference":
        model_kwargs.setdefault("grid", sweep.grid)
    # SweepConfig.kernel (ISSUE 13, DESIGN §4c): the same model-kwarg
    # DEFAULT rule as grid — an explicit run_sweep(..., kernel=...) kwarg
    # wins, and the resolved spelling rides kwargs_items into every
    # fingerprint (so the CostLedger keys fused executables apart from
    # reference ones for free)
    if sweep.kernel != "reference":
        model_kwargs.setdefault("kernel", sweep.kernel)
    # SweepConfig.state_shards (ISSUE 20, DESIGN §6b): the same model-kwarg
    # DEFAULT rule — an explicit run_sweep(..., state=...) kwarg wins.  The
    # 2-D mesh itself was activated by the shell (active_state_mesh); lane
    # dispatch demotes to unsharded because shard_map's manual-SPMD regions
    # and GSPMD state constraints cannot nest — state sharding exists for
    # the regime where ONE cell's state exceeds a device, where replicating
    # it per lane is unaffordable anyway.
    if sweep.state_shards > 1:
        model_kwargs.setdefault("state", "sharded")
        mesh = None
    # family-level sweep kwarg defaults (e.g. Aiyagari's backend-aware
    # dist_method/egm_method selection) applied IN PLACE; the returned
    # metadata records what actually runs
    methods = dict(scn.prepare_kwargs(model_kwargs) or {})

    kwargs_items = _hashable_kwargs(model_kwargs)
    schedule = sweep.schedule
    if schedule == "auto":
        # Balanced by default only where dispatch is cheap: through the
        # tunneled TPU every launch costs ~0.7 s round trip
        # (bench ``dispatch_roundtrip_s``), so bucketing a small batch
        # there trades straggler waste for a larger fixed cost — and the
        # pallas lane grid already de-stragglers the dominant
        # distribution loop per lane.  Accelerator callers opt in
        # explicitly (the bench's warm-scheduled phase does).
        on_accel = jax.default_backend() in ("tpu", "axon")
        schedule = "balanced" if (n_orig >= 8 and not on_accel) else "locked"
    if schedule not in ("balanced", "locked"):
        raise ValueError(f"schedule must be 'auto', 'balanced' or "
                         f"'locked', got {sweep.schedule!r}")

    # -- resilience plumbing (ISSUE 3): sidecar hoisted up here because
    # the resume ledger's fingerprint must cover its CONTENT (warm seeds
    # read it live, so a sidecar swapped between interrupt and resume
    # would silently change trajectories); transient-retry wrapper around
    # every device launch; the per-bucket resume ledger itself.
    side = None
    if schedule == "balanced" and sweep.work_model in ("auto", "sidecar"):
        side = _load_sidecar(sweep.sidecar_path,
                             _work_fingerprint(kwargs_items, dtype,
                                               scenario=scn.name))
        if sweep.work_model == "sidecar" and side is None:
            warnings.warn("work_model='sidecar' but no valid sidecar at "
                          f"{sweep.sidecar_path!r}; using the heuristic",
                          stacklevel=4)
    retry_policy = retry if retry is not None else RetryPolicy()
    injector = (TransientInjector.from_spec(inject_transient)
                if inject_transient is not None else None)

    def device_call(label, f):
        return retry_transient(f, retry_policy, inject=injector,
                               label=label)

    if resume_path is None:
        resume_path = sweep.resume_path
    ledger = None
    if resume_path is not None:
        ledger_fp = ledger_fingerprint(
            cells_p, kwargs_items, dtype, schedule,
            sweep.n_buckets, sweep.warm_brackets, sweep.warm_margin,
            fault_mode, fault_iters, max_retries, quarantine, side,
            scenario=scn.name, row_fields=schema.fields,
            mesh_shards=mesh_axis_size(mesh, axis),
            state_shards=mesh_axis_size(current_state_mesh(), STATE_AXIS))
        ledger = LedgerState.resume(resume_path, ledger_fp, n_orig,
                                    width=schema.width)

    bucket_of = None
    pred = None
    seeds_used: list = [None] * n_orig
    restored_mask = np.zeros(n_orig, dtype=bool)
    if schedule == "balanced":
        (packed, wall, bucket_of, pred, seeds_used,
         restored_mask) = _solve_scheduled(
            scn, sweep, cells_p, cells_nom, fault_iters, fault_mode,
            mesh, axis, dtype, kwargs_items, model_kwargs,
            perturb=perturb, side=side, ledger=ledger,
            device_call=device_call, inject_preempt=inject_preempt,
            obs=obs)
        sl = slice(0, n_orig)
    elif ledger is not None and ledger.solved.all():
        # locked path, fully solved by the interrupted run: restore the
        # batched phase from the ledger (quarantine may still be pending)
        packed = ledger.packed
        wall = 0.0
        sl = slice(0, n_orig)
    else:
        n_shards = mesh_axis_size(mesh, axis)
        if mesh is not None:
            shard = sharding(mesh, axis)
            cols = []
            for j in range(cells_p.shape[1]):
                col_d, _ = pad_to_multiple(cells_p[:, j], n_shards)
                cols.append(jax.device_put(
                    jnp.asarray(col_d, dtype=dtype), shard))
            fault_d = None
            if fault_iters is not None:
                # edge-replication padding may duplicate the LAST cell; pad
                # with healthy -1 lanes instead so a fault is injected
                # exactly once
                pad = cols[0].shape[0] - n_orig
                fault_d = np.concatenate(
                    [fault_iters, np.full(pad, -1, dtype=np.int32)])
                fault_d = jax.device_put(jnp.asarray(fault_d), shard)
        else:
            cols = [jnp.asarray(cells_p[:, j], dtype=dtype)
                    for j in range(cells_p.shape[1])]
            fault_d = (None if fault_iters is None
                       else jnp.asarray(fault_iters))

        fn = scn.batched_solver(dtype, kwargs_items, fault_mode, False)
        if n_shards > 1:
            # multi-chip lock-step launch (ISSUE 11): same shard_map
            # wrapper as the scheduled path — one padded launch, each
            # device solving its lane block, gather at the end
            fn = sharded_launcher(fn, mesh, axis)
        args = tuple(cols) if fault_d is None else (*cols, fault_d)
        prof = obs.cost_ledger
        prof_key = None
        if prof is not None:
            shape0 = int(np.asarray(args[0]).shape[0])
            prof_key = ("sweep", scn.name,
                        _work_fingerprint(kwargs_items, dtype,
                                          scenario=scn.name),
                        "cold", shape0, fault_mode, n_shards)
            prof.capture(prof_key, fn, args,
                         label=f"sweep/{scn.name}/cold{shape0}"
                               + (f"x{n_shards}" if n_shards > 1 else ""))
        with obs.span("sweep/bucket", bucket=0, cells=n_orig,
                      warm=False, device_profile=True) as bsp:
            packed, wall = _timed_launch(       # [C, W], one transfer
                device_call, "sweep launch", fn, args)
        if prof is not None:
            prof.record_launch(prof_key, wall, tracer=obs.tracer)
        bsp.annotate(wall_s=wall)
        if schema.phases is not None:
            d_col = schema.idx(schema.phases[0])
            p_col = schema.idx(schema.phases[1])
            bsp.subdivide(
                {"descent": float(np.asarray(packed)[:n_orig, d_col].sum()),
                 "polish": float(np.asarray(packed)[:n_orig, p_col].sum())},
                prefix="sweep/phase/")
        obs.event("BUCKET_LAUNCH", bucket=0, scenario=scn.name,
                  cells=list(range(n_orig)), warm=False, wall_s=wall)
        obs.histogram("aiyagari_sweep_bucket_wall_seconds",
                      "per-bucket launch wall").observe(wall)
        if obs.enabled:
            obs.gauge("aiyagari_sweep_bucket_lane_occupancy",
                      "real cells / padded lanes of the last bucket"
                      ).set(n_orig / float(np.asarray(args[0]).shape[0]))
            obs.sample_devices(where="sweep/bucket0")
        # the single lock-step launch is bucket 0 of 1 to the seam protocol
        _resilience_seam(
            ledger,
            lambda led: led.record_bucket(np.arange(n_orig),
                                          np.asarray(packed)[:n_orig], 0),
            progress={"completed_buckets": 1, "n_buckets": 1},
            inject_preempt=inject_preempt, bucket_id=0)
        sl = slice(0, n_orig)
    if timer is not None:
        timer(wall)

    # ONE host copy of the packed rows (the device transfer's buffer is
    # read-only; the injection/quarantine paths write rows in place)
    rows = np.array(np.asarray(packed), dtype=np.float64)[sl]

    def cell_attrs(i):
        # per-cell event attributes named by the scenario's axes (the
        # Aiyagari space keeps the historical crra/rho/sd keys)
        return {name: float(cells_nom[i, j])
                for j, name in enumerate(scn.cells.names)}

    # -- SDC injection + spot recheck (DESIGN §9) ---------------------------
    # Injection corrupts the host copy AFTER the solve (and after the
    # ledger recorded the true bits) — the silent-data-corruption model:
    # finite numbers, healthy status, wrong bits.
    if inject_sdc is not None:
        ci = int(inject_sdc["cell"])
        if "bit" in inject_sdc:
            from ..verify.inject import flip_row_bit

            rows[ci] = flip_row_bit(rows[ci],
                                    field=int(inject_sdc.get("field", 0)),
                                    bit=int(inject_sdc["bit"]))
        else:
            rows[ci, int(inject_sdc.get("field", 0))] += float(
                inject_sdc.get("amplitude", 1e-6))
    sdc_suspected = None
    recheck_wall = 0.0
    if sweep.recheck_fraction > 0.0:
        sample = sdc_sample(cells_nom, kwargs_items, dtype,
                            sweep.recheck_fraction, scenario=scn.name)
        # Two classes of ledger-restored cell cannot be bitwise-rechecked
        # against a fresh batched launch, and are skipped LOUDLY, never
        # silently: warm-bracket cells whose launch seeds were not
        # recorded, and quarantine-RETRIED cells — their restored row is
        # the serial quarantine outcome, which the batched executable can
        # never reproduce (a mismatch there would be a false alarm, not
        # corruption).
        skipped = []
        if sweep.warm_brackets and restored_mask.any():
            skipped += [int(i) for i in sample if restored_mask[i]
                        and seeds_used[int(i)] is None]
        if ledger is not None and ledger.retried.any():
            skipped += [int(i) for i in sample
                        if ledger.retried[i] and int(i) not in skipped]
        if skipped:
            warnings.warn(
                f"sdc recheck: skipping ledger-restored cell(s) "
                f"{sorted(skipped)} (warm seeds unknown, or the row is a "
                f"serial quarantine outcome)", stacklevel=4)
            sample = np.asarray([i for i in sample
                                 if int(i) not in set(skipped)],
                                dtype=np.int64)
        with obs.span("sweep/sdc_recheck", sampled=len(sample)) as rsp:
            suspects, recheck_wall = _sdc_recheck(
                scn, rows, cells_p, sample, seeds_used, fault_iters,
                fault_mode, dtype, kwargs_items, device_call)
        rsp.annotate(wall_s=recheck_wall, suspects=len(suspects))
        sdc_suspected = np.zeros(n_orig, dtype=bool)
        sdc_suspected[suspects] = True
        for i in suspects:
            obs.event("SDC_SUSPECTED", cell=int(i), scenario=scn.name,
                      **cell_attrs(i))
        obs.counter("aiyagari_sweep_sdc_suspected_total",
                    "bitwise recheck mismatches").inc(len(suspects))
        if suspects:
            warnings.warn(
                "sdc recheck: bitwise mismatch for cell(s) "
                + ", ".join(str(i) for i in suspects)
                + " — silent data corruption suspected; routing through "
                "the quarantine ladder", stacklevel=4)

    # The counters and status rode the device transfer in the float dtype
    # (exact — values ≪ 2^24, which f32 represents without rounding); the
    # status array is the int64 authority from here on and is synced back
    # into the rows' status column before anything downstream reads them.
    status = np.asarray(np.rint(rows[:, status_col]), dtype=np.int64)
    retries = np.zeros(n_orig, dtype=np.int64)

    # Host-side escalation: quarantine failed cells and walk the bounded
    # retry ladder serially (never re-injecting a fault, never reusing a
    # warm bracket seed).  Runs after the timed batched solve —
    # wall_seconds stays the honest batched-program wall.
    # Cells whose quarantine ladder already completed in an interrupted
    # run: restore the final outcome (recovered values or the exhausted
    # failing status) and the rung count bit-exactly — a recovered cell's
    # ledger row holds a HEALTHY status, so it must be excluded from the
    # failure scan below, not re-walked.
    restored_retry = np.zeros(n_orig, dtype=bool)
    if ledger is not None and quarantine:
        for i in np.nonzero(ledger.retried)[0]:
            rows[i] = ledger.packed[i]
            status[i] = int(np.rint(rows[i, status_col]))
            retries[i] = int(ledger.retries[i])
            restored_retry[i] = True
    demoted = np.zeros(n_orig, dtype=bool)
    if sdc_suspected is not None:
        # a suspected cell's batched numbers are untrusted no matter how
        # healthy its status looks: demote it to NONFINITE (corrupt bits
        # ARE garbage) so the quarantine ladder re-solves it; whatever
        # the ladder cannot recover is purged wholesale after it runs
        demoted = sdc_suspected & ~restored_retry
        status[demoted] = NONFINITE
    failed = is_failure(status) & ~restored_retry
    if quarantine and (failed.any() or restored_retry.any()):
        ladder = tuple(scn.retry_rungs(model_kwargs))[
            :max(0, int(max_retries))]
        for i in np.nonzero(failed)[0]:
            status_before = int(status[i])
            for attempt, overrides in enumerate(ladder, start=1):
                retries[i] = attempt
                with obs.span("sweep/quarantine", cell=int(i),
                              rung=attempt):
                    row_new = device_call(
                        f"quarantine retry cell {int(i)}",
                        lambda: scn.eager_row(
                            cells_p[i], dtype,
                            {**model_kwargs, **overrides}))
                row_new = np.asarray(row_new, dtype=np.float64)
                cell_status = int(np.rint(row_new[status_col]))
                if not is_failure(cell_status):
                    rows[i] = row_new
                    status[i] = cell_status
                    break
            obs.event("QUARANTINE", cell=int(i), scenario=scn.name,
                      **cell_attrs(i),
                      status_before=status_name(status_before),
                      status_after=status_name(int(status[i])),
                      recovered=not bool(is_failure(int(status[i]))),
                      retries=int(retries[i]))
            obs.counter("aiyagari_sweep_quarantined_cells_total",
                        "cells routed through the retry ladder").inc()
            # quarantine seam: the outcome (recovered or exhausted) is
            # final for this run — same commit-then-poll protocol as the
            # launch seams
            row_led = rows[i].copy()
            row_led[status_col] = float(status[i])
            _resilience_seam(
                ledger,
                lambda led: led.record_retry(int(i), row_led,
                                             int(retries[i])),
                progress={"retried_cell": int(i)})
        still = np.nonzero(is_failure(status))[0]
        # NaN-mask what the retries could not certify: a failed cell must
        # read as failed everywhere, not as a plausible number
        for f in schema.mask_on_failure:
            rows[still, schema.idx(f)] = np.nan
        if len(still):
            warnings.warn(
                f"{scn.name} sweep: cells "
                + ", ".join(f"{int(i)} ({status_name(status[i])})"
                            for i in still)
                + " failed every quarantine retry; their values are "
                "NaN-masked in the result", stacklevel=4)
            # typed failure past the quarantine ladder: dump the flight
            # recorder as the post-mortem artifact (ISSUE 10 — the ring
            # holds the run's recent spans/events; the dump embeds the
            # metrics snapshot), journaled as FLIGHT_RECORD_DUMP
            obs.dump_flight(
                f"{scn.name} sweep: {len(still)} cell(s) exhausted the "
                "quarantine ladder",
                cells=[int(i) for i in still],
                statuses=[status_name(int(status[i])) for i in still])

    # KNOWN-corrupt cells no retry recovered (or that had no ladder to
    # run) must not leak ANY field into the result or the sidecar work
    # model: an honest MAX_ITER best-iterate keeps its labor/counters,
    # corrupt bits keep nothing — the sidecar's warm-seed rule trusts
    # any finite root and its bucket planner trusts the counters.
    zero_fields = tuple(schema.counters) + tuple(schema.phases or ())
    value_fields = tuple(f for f in schema.fields
                         if f != schema.status and f not in zero_fields)
    purge = demoted & is_failure(status)
    if purge.any():
        for f in value_fields:
            rows[purge, schema.idx(f)] = np.nan
        for f in zero_fields:
            rows[purge, schema.idx(f)] = 0.0

    # sync the int64 status authority back into the packed rows: every
    # downstream consumer (sidecar, certifier, ledger already handled,
    # the returned result) reads ONE consistent block
    rows[:, status_col] = status.astype(np.float64)

    # Precision-ladder escalations (DESIGN §5) as journal events: the
    # counter rode the packed row out of the jitted program; the journal
    # line is where "which cell abandoned its cheap descent" becomes
    # greppable next to the bucket that ran it.
    escal = None
    if schema.phases is not None:
        escal = np.asarray(np.rint(rows[:, schema.idx(schema.phases[2])]),
                           dtype=np.int64)
        for i in np.nonzero(escal > 0)[0]:
            obs.event("PRECISION_ESCALATED", cell=int(i),
                      scenario=scn.name, **cell_attrs(i),
                      escalations=int(escal[i]))

    if sweep.sidecar_path is not None:
        # persist this run's counters/roots for the next run's scheduler
        # (work model + warm brackets); best-effort — an unwritable path
        # must not take down a finished solve
        c0, c1, c2 = (np.asarray(np.rint(rows[:, schema.idx(c)]),
                                 dtype=np.int64) for c in schema.counters)
        phase_kw = {}
        if schema.phases is not None:
            phase_kw = dict(
                descent_steps=np.asarray(
                    np.rint(rows[:, schema.idx(schema.phases[0])]),
                    dtype=np.int64),
                polish_steps=np.asarray(
                    np.rint(rows[:, schema.idx(schema.phases[1])]),
                    dtype=np.int64))
        try:
            save_sweep_sidecar(
                sweep.sidecar_path, cells_nom, rows[:, root_col],
                c0, c1, c2, status,
                _work_fingerprint(kwargs_items, dtype, scenario=scn.name),
                **phase_kw)
        except OSError as e:
            warnings.warn(f"could not write sweep sidecar "
                          f"{sweep.sidecar_path!r}: {e}", stacklevel=4)

    # -- a posteriori certification (DESIGN §9) -----------------------------
    # Runs on the FINAL values (quarantine outcomes included), outside
    # the timed wall: one vmapped recompute-certifier launch over the
    # healthy cells; failed cells certify FAILED trivially.  Runs BEFORE
    # ledger.complete() and through device_call (transient retry), so a
    # certification-time fault cannot cost a completed sweep its resume
    # state — a restarted run restores every cell and re-certifies.
    cert_level = None
    certify_wall = 0.0
    if sweep.certify:
        if scn.certify_rows is None:
            raise ValueError(
                f"scenario {scn.name!r} has no certify_rows hook; "
                "run without SweepConfig(certify=True)")
        with stopwatch() as cert_sw:
            with obs.span("sweep/certify", cells=n_orig) as csp:
                certs = device_call(
                    "a posteriori certification",
                    lambda: scn.certify_rows(
                        rows, cells_p, dtype, kwargs_items,
                        thresholds=cert_thresholds))
        cert_level = np.asarray([c.level for c in certs], dtype=np.int64)
        certify_wall = cert_sw.seconds
        csp.annotate(wall_s=certify_wall,
                     failed=int((cert_level == 2).sum()))
        for i in np.nonzero(cert_level == 2)[0]:
            obs.event("CERT_FAILED", cell=int(i), scenario=scn.name,
                      **cell_attrs(i), summary=certs[int(i)].summary())
        obs.counter("aiyagari_sweep_cert_failed_total",
                    "cells whose certificate graded FAILED").inc(
            int((cert_level == 2).sum()))

    if ledger is not None:
        # the run completed: a finished ledger must not satisfy the next
        # run's launches silently
        ledger.complete()

    # Mirror the run's counters into the metrics registry (ISSUE 7): the
    # result dataclass keeps its API; the registry is where the same
    # numbers become scrapeable/snapshot-able alongside serve's.
    work_total = sum(
        np.asarray(np.rint(rows[:, schema.idx(f)]), dtype=np.int64)
        for f in schema.work)
    obs.counter("aiyagari_sweep_cells_total",
                "cells solved by sweeps this run").inc(n_orig)
    obs.counter("aiyagari_sweep_inner_steps_total",
                "EGM + distribution inner steps").inc(
        float(work_total.sum()))
    obs.counter("aiyagari_sweep_quarantine_retries_total",
                "quarantine ladder rungs consumed").inc(
        int(retries.sum()))
    if escal is not None:
        obs.counter("aiyagari_sweep_precision_escalations_total",
                    "ladder descent->reference fallbacks").inc(
            int(escal.sum()))
    obs.gauge("aiyagari_sweep_wall_seconds",
              "last sweep's honest batched wall").set(wall)

    return ScenarioSweepResult(
        scenario=scn.name, schema=schema,
        cells=np.asarray(cells_nom, dtype=np.float64), rows=rows,
        status=status, retries=retries, wall_seconds=wall,
        methods=methods, bucket=bucket_of, predicted_work=pred,
        sdc_suspected=sdc_suspected, cert_level=cert_level,
        recheck_wall_seconds=recheck_wall,
        certify_wall_seconds=certify_wall)


def run_table2_sweep(sweep: SweepConfig = SweepConfig(),
                     mesh=None, axis: str = "cells",
                     dtype=None, timer=None, perturb: float = 0.0,
                     quarantine: bool = True, max_retries: int = 3,
                     inject_fault: Optional[dict] = None,
                     resume_path: Optional[str] = None,
                     retry: Optional[RetryPolicy] = None,
                     inject_transient: Optional[dict] = None,
                     inject_preempt: Optional[dict] = None,
                     inject_sdc: Optional[dict] = None,
                     cert_thresholds=None, obs=None,
                     **model_kwargs) -> SweepResult:
    """Solve every (σ, ρ, sd) cell as batched program launches — the
    Aiyagari Table II instantiation of the scenario-generic ``run_sweep``
    (ISSUE 9: this wrapper IS ``run_sweep(scenario="aiyagari", ...)``
    plus the Table II closed forms, bit-identical to the pre-scenario
    engine).

    Scheduling: ``sweep.schedule`` picks between the single lock-step
    launch ("locked" — every lane runs until the slowest cell converges)
    and the work-balanced bucketed path ("balanced" — cells sorted by
    predicted work into ``sweep.n_buckets`` equal-shape launches of one
    shared executable, cheapest bucket first, per-device work balanced
    inside each bucket, optional verified warm-started brackets); "auto"
    (default) buckets batches of >= 8 cells.  The scheduled path's output
    is un-permuted before ``SweepResult`` — bit-order-identical to the
    lock-step path (and, with ``warm_brackets`` off, bit-IDENTICAL: the
    per-lane computation does not depend on batch size or lane position).
    With ``sweep.sidecar_path`` set, per-cell counters and roots persist
    across runs (``utils.checkpoint.SweepSidecar``): the next sweep
    buckets on measured work and, with ``warm_brackets=True``, descends
    each cell's bracket toward its known root — skipping the expensive
    wide-bracket bisection trips while keeping the certified ``r_tol``
    contract (every seed is verified in-program; a bad seed falls back to
    the cold bracket, see ``solve_equilibrium_lean``).

    Solver health: every cell returns a ``solver_health`` status code.
    With ``quarantine`` on (the default), failed cells (MAX_ITER /
    NONFINITE — a single diverged calibration must not poison the batch)
    are NaN-masked and re-run serially on the host through the bounded
    scenario retry ladder (up to ``max_retries`` rungs;
    ``scenarios.Scenario.retry_rungs`` — for Aiyagari: alternate
    distribution method — reused on every rung, never the known-failing
    one — damped updates, padded bracket); a recovered cell's values and
    counters replace the quarantined ones, a cell that exhausts the
    ladder stays NaN with its failing status recorded.  The retries run
    AFTER the timed batched solve, so ``wall_seconds`` stays the honest
    batched-program wall.

    ``inject_fault``: deterministic fault injection for exercising that
    machinery — ``{"cell": i, "at_iter": k, "mode": "nan"|"stall"}``
    poisons cell ``i`` at its k-th bisection trip inside the jitted
    program (``solve_equilibrium_lean``); all other lanes run the same
    masked iterations they run uninjected, so their results stay
    bit-identical.  Retries never re-inject.  Cell indices refer to the
    ORIGINAL ``sweep.cells()`` order under any schedule.

    Resilience (ISSUE 3, ``utils.resilience``): with ``resume_path``
    (argument or ``SweepConfig.resume_path``) the sweep persists a
    fingerprinted per-bucket ledger — solved buckets' packed rows plus
    quarantine outcomes — atomically after every bucket launch and every
    quarantine rung; a restarted call with the same configuration skips
    the completed work and the assembled ``SweepResult`` is
    BIT-IDENTICAL to an uninterrupted run (statuses and iteration
    counters included).  The ledger is deleted on successful completion;
    a stale/mismatched ledger warns and recomputes.  Inside a
    ``resilience.preemption_guard()`` a SIGTERM/SIGINT is honored at the
    next bucket seam or quarantine rung: the ledger is flushed and the
    typed ``resilience.Interrupted`` raised instead of dying mid-write.
    Every device launch (and each serial quarantine solve) runs under
    ``retry_transient``: transient device/RPC/compile faults are retried
    on the deterministic backoff schedule of ``retry``
    (default ``RetryPolicy()``) — but a solver-health ``NONFINITE`` is
    NEVER retried by this layer (that is the quarantine ladder's job).
    ``inject_transient={"at_call": k, "times": n}`` and
    ``inject_preempt={"after_bucket": b, "mode": "signal"|"flag"}`` are
    the deterministic fault hooks exercising those paths in CPU tests.

    Integrity (ISSUE 6, DESIGN §9): ``sweep.recheck_fraction`` re-solves
    a fingerprint-sampled cell subset in permuted lane positions after
    the batched solve and compares packed rows bitwise (``sdc_sample`` /
    ``_sdc_recheck``); a mismatch records ``SweepResult.sdc_suspected``
    and the cell routes through the quarantine ladder for a trusted
    re-solve.  ``sweep.certify`` runs a posteriori certification
    (``verify.certify_equilibrium`` recompute path) on every final cell,
    recording ``SweepResult.cert_level``; ``cert_thresholds`` overrides
    the configuration-scaled defaults.  Both run AFTER the timed batched
    solve — their cost is reported separately
    (``recheck_wall_seconds``/``certify_wall_seconds``), never inside
    ``wall_seconds``.  ``inject_sdc={"cell": i, "bit": b}`` (bit flip)
    or ``{"cell": i, "field": f, "amplitude": a}`` (perturbation)
    deterministically corrupts one cell's packed row post-solve,
    pre-recheck — the silent-data-corruption drill.

    With ``mesh`` given, cells are sharded over ``axis`` (padded by edge
    replication to divide the axis size); under "balanced" each bucket is
    additionally laid out so per-device TOTAL PREDICTED WORK — not lane
    count — balances (``mesh.balanced_lane_order``).  Without a mesh it
    is the same program on one device.

    ``wall_seconds`` is an HONEST wall: the clock stops only after every
    output has materialized on the host (``np.asarray``), because through
    the tunneled TPU ``block_until_ready`` alone does not reliably block
    for XLA executables; the scheduled path reports the SUM of its launch
    walls (host-side planning between launches is excluded — it is
    microseconds against seconds of solve).  Benchmark callers should
    also pass a tiny ``perturb`` (added to the ρ inputs, e.g. 1e-6 — it
    must survive the f32 cast: f32 spacing at ρ=0.3 is ~3e-8) on the
    timed call so an identical-execution cache anywhere in the stack
    cannot serve the warm-up run's results — same compiled program, same
    fixed point to within the perturbation (methodology of
    ``scripts/pallas_ab.py``).

    Observability (ISSUE 7, DESIGN §10): with ``obs`` (argument or
    ``SweepConfig.obs`` — an ``obs.ObsConfig`` or a shared ``obs.Obs``
    bundle) the sweep records a ``sweep/run`` span containing per-bucket
    launch spans (subdivided into descent/polish phase children from the
    returned counters — nothing traces inside jit), quarantine-rung and
    recheck/certify spans, journals typed lifecycle events
    (BUCKET_LAUNCH, QUARANTINE, SDC_SUSPECTED, PRECISION_ESCALATED,
    CERT_FAILED, RETRY_TRANSIENT, INTERRUPTED, RESUME_RESTORE) under one
    ``run_id``, and mirrors the sweep counters into the metrics
    registry.  Disabled (default) is near-free and changes zero solver
    bits — ``wall_seconds`` semantics are untouched either way (spans
    bracket the same clock reads the honest wall already makes).
    """
    from ..scenarios.registry import get_scenario

    # calls the SHELL, not run_sweep, so warnings raised inside the impl
    # sit the same number of frames below a run_table2_sweep caller as
    # below a run_sweep caller (see the depth NOTE on _run_sweep_shell)
    res = _run_sweep_shell(
        get_scenario("aiyagari"), sweep,
        np.asarray(sweep.cells(), dtype=np.float64), mesh, axis, dtype,
        timer, perturb, quarantine, max_retries, inject_fault,
        resume_path, retry, inject_transient, inject_preempt, inject_sdc,
        cert_thresholds, obs, **model_kwargs)

    # value columns by schema NAME (the coupling RowSchema removes must
    # not sneak back in as literal indices here)
    r = res.col("r_star").copy()
    K = res.col("capital").copy()
    L = res.col("labor").copy()
    # The counters and status rode the device transfer in the float dtype
    # (exact — values ≪ 2^24, which f32 represents without rounding); cast
    # back to integers HERE so downstream consumers (total_work sums,
    # jsonified bench records, status comparisons) never see counters
    # silently become floats (ADVICE r5 #2).
    iters = res.icol("bisect_iters")
    egm_it = res.icol("egm_iters")
    dist_it = res.icol("dist_iters")
    desc_it = res.icol("descent_steps")
    pol_it = res.icol("polish_steps")
    escal = res.icol("precision_escalations")

    # Host-side closed forms (firm.py identities in numpy — numpy, not jnp,
    # so nothing touches the device after the solve): demand from the
    # inverted marginal product of capital, Y from Cobb-Douglas,
    # s = delta*K/Y.
    alpha = model_kwargs.get("cap_share", 0.36)
    delta = model_kwargs.get("depr_fac", 0.08)
    prod = model_kwargs.get("prod", 1.0)
    demand = ((r + delta) / (prod * alpha)) ** (1.0 / (alpha - 1.0)) * L
    output = prod * K ** alpha * L ** (1.0 - alpha)
    srate = delta * K / output
    return SweepResult(
        crra=res.cells[:, 0], labor_ar=res.cells[:, 1],
        labor_sd=res.cells[:, 2],
        r_star_pct=r * 100.0, saving_rate_pct=srate * 100.0,
        capital=K, excess=K - demand,
        bisect_iters=iters, egm_iters=egm_it, dist_iters=dist_it,
        wall_seconds=res.wall_seconds,
        dist_method=str(res.methods.get("dist_method", "auto")),
        egm_method=str(res.methods.get("egm_method", "xla")),
        status=res.status, retries=res.retries, bucket=res.bucket,
        predicted_work=res.predicted_work, descent_steps=desc_it,
        polish_steps=pol_it, precision_escalations=escal,
        sdc_suspected=res.sdc_suspected, cert_level=res.cert_level,
        recheck_wall_seconds=res.recheck_wall_seconds,
        certify_wall_seconds=res.certify_wall_seconds)
