"""The Table II calibration sweep as one batched, device-sharded XLA program.

The reference runs Aiyagari's Table II (σ ∈ {1,3,5} × ρ ∈ {0,0.3,0.6,0.9})
**manually, one notebook cell at a time**, editing the parameter dicts between
runs (SURVEY.md §2.4) — each cell costing a ~27-minute ``economy.solve()``.
Here a sweep is data: arrays of (σ, ρ, sd) triples — ``labor_sd`` as a
tuple batches BOTH of Aiyagari's panels — vmapped through the jitted
bisection equilibrium (``models.equilibrium``) and sharded over the ``cells``
mesh axis.  No communication between cells — XLA places one subset of cells
per device and the only cross-device traffic is the final result gather.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import lru_cache
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from ..models.equilibrium import solve_calibration_lean
from ..utils.config import SweepConfig
from .mesh import pad_to_multiple, sharding


@dataclass
class SweepResult:
    """Per-cell equilibrium objects, cell-major ([C] leading axis).

    ``excess`` mixes household supply evaluated at the *last bisection
    midpoint* with firm demand at ``r_star`` (the lean solver never
    re-solves at ``r_star``), so it is a market-clearing residual accurate
    only to O(r_tol) — a bracket-width effect, not a solver error.

    ``egm_iters``/``dist_iters`` are each cell's total inner-loop work.
    Under vmap-of-while, every lane runs until the slowest converges, so
    ``iteration_skew()`` (max/min total work) bounds the wasted compute —
    the supporting model for multi-chip scaling claims (VERDICT r1 #9).
    """

    crra: np.ndarray          # [C]
    labor_ar: np.ndarray      # [C]
    labor_sd: np.ndarray      # [C] (one value per panel; 0.2 in panel A)
    r_star_pct: np.ndarray    # [C] net return, percent (Table II units)
    saving_rate_pct: np.ndarray  # [C] δK/Y, percent
    capital: np.ndarray       # [C]
    excess: np.ndarray        # [C] market-clearing residual, O(r_tol) exact
    bisect_iters: np.ndarray  # [C]
    egm_iters: np.ndarray     # [C] total EGM steps across all midpoints
    dist_iters: np.ndarray    # [C] total distribution-iteration steps
    wall_seconds: float = float("nan")
    dist_method: str = "auto"   # the distribution method that actually ran

    def total_work(self) -> np.ndarray:
        """Per-cell inner-loop step count (EGM + distribution iterations)."""
        return self.egm_iters + self.dist_iters

    def iteration_skew(self) -> float:
        """max/min of per-cell total work — how unevenly vmap-of-while lanes
        finish (1.0 = perfectly balanced; the batch runs at the max)."""
        w = self.total_work()
        return float(w.max() / max(w.min(), 1))

    def table(self) -> str:
        """Aiyagari Table II layout: rows ρ, columns σ, entries r* (%);
        one block per stationary-s.d. panel when the sweep carries both."""
        sigmas = np.unique(self.crra)
        rhos = np.unique(self.labor_ar)
        sds = np.unique(self.labor_sd)
        lines = []
        for sd in sds:
            if len(sds) > 1:
                lines.append(f"panel sd={sd:g}")
            lines.append("rho\\sigma "
                         + "  ".join(f"{s:7.1f}" for s in sigmas))
            for rho in rhos:
                row = []
                for s in sigmas:
                    m = ((self.crra == s) & (self.labor_ar == rho)
                         & (self.labor_sd == sd))
                    row.append(f"{float(self.r_star_pct[m][0]):7.4f}"
                               if m.any() else "      –")
                lines.append(f"{rho:9.2f} " + "  ".join(row))
        return "\n".join(lines)


@lru_cache(maxsize=None)
def _batched_solver(dtype, kwargs_items=()):
    """Jitted vmapped cell solver, memoized so repeated sweeps (benchmarks,
    resumed runs) hit the jit cache instead of rebuilding the closure.
    Cached entries (jitted closures) live for the process — call
    ``_batched_solver.cache_clear()`` to drop them.

    The stationary s.d. is a vmapped axis alongside (σ, ρ), so both
    Table II panels batch into one program.  Uses the lean bisection
    (supply carried through the loop state, no post-loop re-solve) so the
    compiled program stays small; wage, demand, excess, and the saving
    rate are closed forms in (r*, K, L) computed host-side in
    ``run_table2_sweep``.
    """
    model_kwargs = dict(kwargs_items)

    def solve_one(crra, rho, sd):
        res = solve_calibration_lean(crra, rho, labor_sd=sd,
                                     dtype=dtype, **model_kwargs)
        # ONE stacked output -> ONE device->host materialization: through
        # the tunneled TPU every np.asarray is its own RPC round trip, so
        # six separate outputs put ~6 round trips inside the timed wall —
        # a lane-count-independent cost the lanes_scaling fit measured as
        # ~0.7 s fixed overhead (VERDICT r4 weak-item 5).  The iteration
        # counters ride along exactly in the float dtype (values ≪ 2^24).
        f = res.r_star.dtype
        return jnp.stack([res.r_star, res.capital, res.labor,
                          res.bisect_iters.astype(f),
                          res.egm_iters.astype(f),
                          res.dist_iters.astype(f)])

    return jax.jit(jax.vmap(solve_one))


def _hashable_kwargs(model_kwargs: dict) -> tuple:
    """Normalize sweep kwargs into an ``lru_cache``-safe key: sequences
    become tuples, and anything still unhashable gets a clear error instead
    of ``lru_cache``'s bare TypeError."""
    items = []
    for k, v in sorted(model_kwargs.items()):
        if isinstance(v, (list, np.ndarray)):
            arr = np.asarray(v)
            if arr.ndim > 1:
                raise TypeError(
                    f"sweep kwarg {k!r} has shape {arr.shape}; only scalars "
                    "and 1-D sequences can be forwarded to the cell solver")
            v = tuple(arr.tolist())
        try:
            hash(v)
        except TypeError:
            raise TypeError(
                f"sweep kwarg {k!r}={v!r} is not hashable; pass scalars or "
                "tuples (grids are rebuilt per cell from scalar settings)"
            ) from None
        items.append((k, v))
    return tuple(items)


def run_table2_sweep(sweep: SweepConfig = SweepConfig(),
                     mesh: Optional[Mesh] = None, axis: str = "cells",
                     dtype=None, timer=None, perturb: float = 0.0,
                     **model_kwargs) -> SweepResult:
    """Solve every (σ, ρ, sd) cell as one batched program.

    With ``mesh`` given, cells are sharded over ``axis`` (padded by edge
    replication to divide the axis size); the batch is one ``jit`` whose
    per-cell ``while_loop``s run until the *slowest* cell converges —
    the usual vmap-of-while semantics.  Measured straggler cost: ~2.5x
    total-work skew within one panel, ~3.5x across both Table II panels
    (the high-risk sd=0.4 cells mix slowest) — still far cheaper than
    separate launches.  Without a mesh it is the same program on one
    device.

    ``wall_seconds`` is an HONEST wall: the clock stops only after every
    output has materialized on the host (``np.asarray``), because through
    the tunneled TPU ``block_until_ready`` alone does not reliably block
    for XLA executables.  Benchmark callers should also pass a tiny
    ``perturb`` (added to the ρ inputs, e.g. 1e-6 — it must survive the
    f32 cast: f32 spacing at ρ=0.3 is ~3e-8) on the timed call so
    an identical-execution cache anywhere in the stack cannot serve the
    warm-up run's results — same compiled program, same fixed point to
    within the perturbation (methodology of ``scripts/pallas_ab.py``).
    """
    cells = np.asarray(sweep.cells(), dtype=np.float64)  # [C, 3] (σ, ρ, sd)
    crra, rho, sd = cells[:, 0], cells[:, 1], cells[:, 2]
    rho_label = rho             # result metadata keeps the nominal ρ values
    if perturb:
        rho = rho + perturb
    n_orig = crra.shape[0]
    if mesh is not None:
        shard = sharding(mesh, axis)
        n_shards = mesh.shape[axis]
        crra, _ = pad_to_multiple(crra, n_shards)
        rho, _ = pad_to_multiple(rho, n_shards)
        sd, _ = pad_to_multiple(sd, n_shards)
        crra = jax.device_put(jnp.asarray(crra, dtype=dtype), shard)
        rho = jax.device_put(jnp.asarray(rho, dtype=dtype), shard)
        sd = jax.device_put(jnp.asarray(sd, dtype=dtype), shard)
    else:
        crra = jnp.asarray(crra, dtype=dtype)
        rho = jnp.asarray(rho, dtype=dtype)
        sd = jnp.asarray(sd, dtype=dtype)

    if "dist_method" not in model_kwargs:
        # Sweep-level default, distinct from stationary_wealth's "auto".
        # On accelerators: "pallas" — the lane-grid kernel (one program
        # instance per cell via the custom_vmap batching rule,
        # ``household._pallas_fixed_point_vmappable``) lets every cell's
        # distribution fixed point exit at its OWN convergence instead of
        # vmap-of-while lock-step, measured 1.26 s vs dense's 2.16 s on
        # the 12-cell sweep (one v5e chip, identical r*).  Fallback
        # "dense" (batched MXU matvecs) when Mosaic can't compile the
        # kernel.  NOT "solve" — with the EGM Anderson acceleration and
        # the stall exit in place, iterating the dense operator beats
        # paying a (D*N)^3 LU per midpoint (measured: dense 2.8s vs solve
        # 4.8s).  On CPU, "auto" (scatter) — dense/LU/pallas are the
        # wrong trade there.
        if jax.default_backend() in ("tpu", "axon"):
            from ..ops.pallas_kernels import pallas_grid_tpu_available
            model_kwargs["dist_method"] = (
                "pallas" if pallas_grid_tpu_available() else "dense")
        else:
            model_kwargs["dist_method"] = "auto"

    fn = _batched_solver(dtype, _hashable_kwargs(model_kwargs))
    import time
    t0 = time.perf_counter()
    packed = np.asarray(fn(crra, rho, sd))        # [C, 6], one transfer
    wall = time.perf_counter() - t0
    r, K, L, iters, egm_it, dist_it = packed.T
    if timer is not None:
        timer(wall)

    sl = slice(0, n_orig)
    r = np.asarray(r, dtype=np.float64)[sl]
    K = np.asarray(K, dtype=np.float64)[sl]
    L = np.asarray(L, dtype=np.float64)[sl]
    # Host-side closed forms (firm.py identities in numpy — numpy, not jnp,
    # so nothing touches the device after the solve): demand from the
    # inverted marginal product of capital, Y from Cobb-Douglas, s = delta*K/Y.
    alpha = model_kwargs.get("cap_share", 0.36)
    delta = model_kwargs.get("depr_fac", 0.08)
    prod = model_kwargs.get("prod", 1.0)
    demand = ((r + delta) / (prod * alpha)) ** (1.0 / (alpha - 1.0)) * L
    output = prod * K ** alpha * L ** (1.0 - alpha)
    srate = delta * K / output
    return SweepResult(
        crra=np.asarray(crra)[sl], labor_ar=rho_label[sl],
        labor_sd=np.asarray(sd)[sl],
        r_star_pct=r * 100.0, saving_rate_pct=srate * 100.0,
        capital=K, excess=K - demand,
        bisect_iters=np.asarray(iters)[sl],
        egm_iters=np.asarray(egm_it)[sl],
        dist_iters=np.asarray(dist_it)[sl], wall_seconds=wall,
        dist_method=str(model_kwargs["dist_method"]))
