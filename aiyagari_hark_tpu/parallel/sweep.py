"""The Table II calibration sweep as one batched, device-sharded XLA program.

The reference runs Aiyagari's Table II (σ ∈ {1,3,5} × ρ ∈ {0,0.3,0.6,0.9})
**manually, one notebook cell at a time**, editing the parameter dicts between
runs (SURVEY.md §2.4) — each cell costing a ~27-minute ``economy.solve()``.
Here a sweep is data: arrays of (σ, ρ, sd) triples — ``labor_sd`` as a
tuple batches BOTH of Aiyagari's panels — vmapped through the jitted
bisection equilibrium (``models.equilibrium``) and sharded over the ``cells``
mesh axis.  No communication between cells — XLA places one subset of cells
per device and the only cross-device traffic is the final result gather.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import lru_cache
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from ..models.equilibrium import solve_calibration_lean
from ..solver_health import CONVERGED, is_failure, status_name
from ..utils.config import SweepConfig
from .mesh import pad_to_multiple, sharding


@dataclass
class SweepResult:
    """Per-cell equilibrium objects, cell-major ([C] leading axis).

    ``excess`` mixes household supply evaluated at the *last bisection
    midpoint* with firm demand at ``r_star`` (the lean solver never
    re-solves at ``r_star``), so it is a market-clearing residual accurate
    only to O(r_tol) — a bracket-width effect, not a solver error.

    ``egm_iters``/``dist_iters`` are each cell's total inner-loop work.
    Under vmap-of-while, every lane runs until the slowest converges, so
    ``iteration_skew()`` (max/min total work) bounds the wasted compute —
    the supporting model for multi-chip scaling claims (VERDICT r1 #9).

    Solver health: ``status`` holds each cell's final ``solver_health``
    code and ``retries`` how many quarantine retries it consumed (0 =
    solved in the batched pass).  A cell that failed every retry keeps
    its failing status and its value fields (``r_star_pct``,
    ``saving_rate_pct``, ``capital``, ``excess``) are NaN-masked — a
    failed cell must poison its own entries loudly, never the table
    silently.  Check ``failed_cells()`` before trusting aggregates.
    """

    crra: np.ndarray          # [C]
    labor_ar: np.ndarray      # [C]
    labor_sd: np.ndarray      # [C] (one value per panel; 0.2 in panel A)
    r_star_pct: np.ndarray    # [C] net return, percent (Table II units)
    saving_rate_pct: np.ndarray  # [C] δK/Y, percent
    capital: np.ndarray       # [C]
    excess: np.ndarray        # [C] market-clearing residual, O(r_tol) exact
    bisect_iters: np.ndarray  # [C]
    egm_iters: np.ndarray     # [C] total EGM steps across all midpoints
    dist_iters: np.ndarray    # [C] total distribution-iteration steps
    wall_seconds: float = float("nan")
    dist_method: str = "auto"   # the distribution method that actually ran
    status: Optional[np.ndarray] = None   # [C] solver_health codes (final)
    retries: Optional[np.ndarray] = None  # [C] quarantine attempts used

    def failed_cells(self) -> np.ndarray:
        """Indices of cells whose final status is a failure (MAX_ITER or
        NONFINITE) — quarantined, retried, and still not certified."""
        if self.status is None:
            return np.asarray([], dtype=np.int64)
        return np.nonzero(is_failure(self.status))[0]

    def total_work(self) -> np.ndarray:
        """Per-cell inner-loop step count (EGM + distribution iterations)."""
        return self.egm_iters + self.dist_iters

    def iteration_skew(self) -> float:
        """max/min of per-cell total work — how unevenly vmap-of-while lanes
        finish (1.0 = perfectly balanced; the batch runs at the max)."""
        w = self.total_work()
        return float(w.max() / max(w.min(), 1))

    def table(self) -> str:
        """Aiyagari Table II layout: rows ρ, columns σ, entries r* (%);
        one block per stationary-s.d. panel when the sweep carries both."""
        sigmas = np.unique(self.crra)
        rhos = np.unique(self.labor_ar)
        sds = np.unique(self.labor_sd)
        lines = []
        for sd in sds:
            if len(sds) > 1:
                lines.append(f"panel sd={sd:g}")
            lines.append("rho\\sigma "
                         + "  ".join(f"{s:7.1f}" for s in sigmas))
            for rho in rhos:
                row = []
                for s in sigmas:
                    m = ((self.crra == s) & (self.labor_ar == rho)
                         & (self.labor_sd == sd))
                    row.append(f"{float(self.r_star_pct[m][0]):7.4f}"
                               if m.any() else "      –")
                lines.append(f"{rho:9.2f} " + "  ".join(row))
        return "\n".join(lines)


@lru_cache(maxsize=None)
def _batched_solver(dtype, kwargs_items=(), fault_mode=None):
    """Jitted vmapped cell solver, memoized so repeated sweeps (benchmarks,
    resumed runs) hit the jit cache instead of rebuilding the closure.
    Cached entries (jitted closures) live for the process — call
    ``_batched_solver.cache_clear()`` to drop them.

    The stationary s.d. is a vmapped axis alongside (σ, ρ), so both
    Table II panels batch into one program.  Uses the lean bisection
    (supply carried through the loop state, no post-loop re-solve) so the
    compiled program stays small; wage, demand, excess, and the saving
    rate are closed forms in (r*, K, L) computed host-side in
    ``run_table2_sweep``.

    ``fault_mode`` (static) compiles in the deterministic fault-injection
    hook: the returned callable then takes a fourth per-cell array of
    bisection trip indices (negative = healthy lane) — see
    ``solve_equilibrium_lean``.  ``None`` (the production default) keeps
    the three-argument program with the hook compiled out.
    """
    model_kwargs = dict(kwargs_items)

    def pack(res):
        # ONE stacked output -> ONE device->host materialization: through
        # the tunneled TPU every np.asarray is its own RPC round trip, so
        # seven separate outputs put ~7 round trips inside the timed wall —
        # a lane-count-independent cost the lanes_scaling fit measured as
        # ~0.7 s fixed overhead (VERDICT r4 weak-item 5).  The iteration
        # counters and the status code ride along exactly in the float
        # dtype (values ≪ 2^24); the host side casts them back to int64.
        f = res.r_star.dtype
        return jnp.stack([res.r_star, res.capital, res.labor,
                          res.bisect_iters.astype(f),
                          res.egm_iters.astype(f),
                          res.dist_iters.astype(f),
                          res.status.astype(f)])

    if fault_mode is None:
        def solve_one(crra, rho, sd):
            return pack(solve_calibration_lean(crra, rho, labor_sd=sd,
                                               dtype=dtype, **model_kwargs))
    else:
        def solve_one(crra, rho, sd, fault_it):
            return pack(solve_calibration_lean(
                crra, rho, labor_sd=sd, dtype=dtype, fault_iter=fault_it,
                fault_mode=fault_mode, **model_kwargs))

    return jax.jit(jax.vmap(solve_one))


# Quarantine retry ladder (bounded, host-side, in escalation order): each
# rung re-runs a failed cell serially with progressively safer settings —
# pure bisection (no Illinois secant jumps), an ALTERNATE distribution
# method (a Mosaic/extrapolation pathology in one method is invisible to
# another), then plain damped iteration (``accel_every=0`` — the Anderson
# extrapolation is the main non-finite risk in the inner loops), then a
# 10x-padded bracket that keeps the bisection away from the singular
# endpoints where the supply map loses contraction (ISSUE refs:
# Cao-Luo-Nie 1905.13045, Ma-Stachurski-Toda 1812.01320).
def _retry_ladder(model_kwargs: dict) -> tuple:
    prior = model_kwargs.get("dist_method", "auto")
    alternate = "dense" if prior in ("auto", "scatter") else "scatter"
    return (
        {"dist_method": alternate, "root_method": "bisect"},
        {"dist_method": "scatter", "root_method": "bisect",
         "accel_every": 0},
        {"dist_method": "scatter", "root_method": "bisect",
         "accel_every": 0, "bracket_pad": 10.0},
    )


def _hashable_kwargs(model_kwargs: dict) -> tuple:
    """Normalize sweep kwargs into an ``lru_cache``-safe key: sequences
    become tuples, and anything still unhashable gets a clear error instead
    of ``lru_cache``'s bare TypeError."""
    items = []
    for k, v in sorted(model_kwargs.items()):
        if isinstance(v, (list, np.ndarray)):
            arr = np.asarray(v)
            if arr.ndim > 1:
                raise TypeError(
                    f"sweep kwarg {k!r} has shape {arr.shape}; only scalars "
                    "and 1-D sequences can be forwarded to the cell solver")
            v = tuple(arr.tolist())
        try:
            hash(v)
        except TypeError:
            raise TypeError(
                f"sweep kwarg {k!r}={v!r} is not hashable; pass scalars or "
                "tuples (grids are rebuilt per cell from scalar settings)"
            ) from None
        items.append((k, v))
    return tuple(items)


def run_table2_sweep(sweep: SweepConfig = SweepConfig(),
                     mesh: Optional[Mesh] = None, axis: str = "cells",
                     dtype=None, timer=None, perturb: float = 0.0,
                     quarantine: bool = True, max_retries: int = 3,
                     inject_fault: Optional[dict] = None,
                     **model_kwargs) -> SweepResult:
    """Solve every (σ, ρ, sd) cell as one batched program.

    Solver health: every cell returns a ``solver_health`` status code.
    With ``quarantine`` on (the default), failed cells (MAX_ITER /
    NONFINITE — a single diverged calibration must not poison the batch)
    are NaN-masked and re-run serially on the host through the bounded
    ``_retry_ladder`` (up to ``max_retries`` rungs: alternate
    distribution method, damped updates, padded bracket); a recovered
    cell's values and counters replace the quarantined ones, a cell that
    exhausts the ladder stays NaN with its failing status recorded.  The
    retries run AFTER the timed batched solve, so ``wall_seconds`` stays
    the honest batched-program wall.

    ``inject_fault``: deterministic fault injection for exercising that
    machinery — ``{"cell": i, "at_iter": k, "mode": "nan"|"stall"}``
    poisons cell ``i`` at its k-th bisection trip inside the jitted
    program (``solve_equilibrium_lean``); all other lanes run the same
    lock-step masked iterations they run uninjected, so their results
    stay bit-identical.  Retries never re-inject.

    With ``mesh`` given, cells are sharded over ``axis`` (padded by edge
    replication to divide the axis size); the batch is one ``jit`` whose
    per-cell ``while_loop``s run until the *slowest* cell converges —
    the usual vmap-of-while semantics.  Measured straggler cost: ~2.5x
    total-work skew within one panel, ~3.5x across both Table II panels
    (the high-risk sd=0.4 cells mix slowest) — still far cheaper than
    separate launches.  Without a mesh it is the same program on one
    device.

    ``wall_seconds`` is an HONEST wall: the clock stops only after every
    output has materialized on the host (``np.asarray``), because through
    the tunneled TPU ``block_until_ready`` alone does not reliably block
    for XLA executables.  Benchmark callers should also pass a tiny
    ``perturb`` (added to the ρ inputs, e.g. 1e-6 — it must survive the
    f32 cast: f32 spacing at ρ=0.3 is ~3e-8) on the timed call so
    an identical-execution cache anywhere in the stack cannot serve the
    warm-up run's results — same compiled program, same fixed point to
    within the perturbation (methodology of ``scripts/pallas_ab.py``).
    """
    cells = np.asarray(sweep.cells(), dtype=np.float64)  # [C, 3] (σ, ρ, sd)
    crra, rho, sd = cells[:, 0], cells[:, 1], cells[:, 2]
    rho_label = rho             # result metadata keeps the nominal ρ values
    if perturb:
        rho = rho + perturb
    n_orig = crra.shape[0]
    fault_mode = None
    fault_iters = None
    if inject_fault is not None:
        fault_mode = str(inject_fault.get("mode", "nan"))
        fault_iters = np.full(n_orig, -1, dtype=np.int32)
        fault_iters[int(inject_fault["cell"])] = int(
            inject_fault.get("at_iter", 0))
    if mesh is not None:
        shard = sharding(mesh, axis)
        n_shards = mesh.shape[axis]
        crra, _ = pad_to_multiple(crra, n_shards)
        rho, _ = pad_to_multiple(rho, n_shards)
        sd, _ = pad_to_multiple(sd, n_shards)
        crra = jax.device_put(jnp.asarray(crra, dtype=dtype), shard)
        rho = jax.device_put(jnp.asarray(rho, dtype=dtype), shard)
        sd = jax.device_put(jnp.asarray(sd, dtype=dtype), shard)
        if fault_iters is not None:
            # edge-replication padding may duplicate the LAST cell; pad
            # with healthy -1 lanes instead so a fault is injected exactly
            # once
            pad = crra.shape[0] - n_orig
            fault_iters = np.concatenate(
                [fault_iters, np.full(pad, -1, dtype=np.int32)])
            fault_iters = jax.device_put(jnp.asarray(fault_iters), shard)
    else:
        crra = jnp.asarray(crra, dtype=dtype)
        rho = jnp.asarray(rho, dtype=dtype)
        sd = jnp.asarray(sd, dtype=dtype)
        if fault_iters is not None:
            fault_iters = jnp.asarray(fault_iters)

    if "dist_method" not in model_kwargs:
        # Sweep-level default, distinct from stationary_wealth's "auto".
        # On accelerators: "pallas" — the lane-grid kernel (one program
        # instance per cell via the custom_vmap batching rule,
        # ``household._pallas_fixed_point_vmappable``) lets every cell's
        # distribution fixed point exit at its OWN convergence instead of
        # vmap-of-while lock-step, measured 1.26 s vs dense's 2.16 s on
        # the 12-cell sweep (one v5e chip, identical r*).  Fallback
        # "dense" (batched MXU matvecs) when Mosaic can't compile the
        # kernel.  NOT "solve" — with the EGM Anderson acceleration and
        # the stall exit in place, iterating the dense operator beats
        # paying a (D*N)^3 LU per midpoint (measured: dense 2.8s vs solve
        # 4.8s).  On CPU, "auto" (scatter) — dense/LU/pallas are the
        # wrong trade there.
        if jax.default_backend() in ("tpu", "axon"):
            from ..ops.pallas_kernels import pallas_grid_tpu_available
            model_kwargs["dist_method"] = (
                "pallas" if pallas_grid_tpu_available() else "dense")
        else:
            model_kwargs["dist_method"] = "auto"

    fn = _batched_solver(dtype, _hashable_kwargs(model_kwargs), fault_mode)
    import time
    args = (crra, rho, sd) if fault_iters is None else (crra, rho, sd,
                                                        fault_iters)
    t0 = time.perf_counter()
    packed = np.asarray(fn(*args))                # [C, 7], one transfer
    wall = time.perf_counter() - t0
    r, K, L, iters, egm_it, dist_it, status_f = packed.T
    if timer is not None:
        timer(wall)

    sl = slice(0, n_orig)
    # explicit copies: the device transfer's buffer is read-only and the
    # quarantine path writes recovered cells back in place
    r = np.array(r, dtype=np.float64)[sl]
    K = np.array(K, dtype=np.float64)[sl]
    L = np.array(L, dtype=np.float64)[sl]
    # The counters and status rode the device transfer in the float dtype
    # (exact — values ≪ 2^24, which f32 represents without rounding); cast
    # back to integers HERE so downstream consumers (total_work sums,
    # jsonified bench records, status comparisons) never see counters
    # silently become floats (ADVICE r5 #2).
    iters = np.asarray(np.rint(iters), dtype=np.int64)[sl]
    egm_it = np.asarray(np.rint(egm_it), dtype=np.int64)[sl]
    dist_it = np.asarray(np.rint(dist_it), dtype=np.int64)[sl]
    status = np.asarray(np.rint(status_f), dtype=np.int64)[sl]
    retries = np.zeros(n_orig, dtype=np.int64)

    # Host-side escalation: quarantine failed cells and walk the bounded
    # retry ladder serially (never re-injecting a fault).  Runs after the
    # timed batched solve — wall_seconds stays the batched-program wall.
    failed = is_failure(status)
    if quarantine and failed.any():
        crra_h = np.asarray(crra, dtype=np.float64)[sl]
        rho_h = np.asarray(rho, dtype=np.float64)[sl]
        sd_h = np.asarray(sd, dtype=np.float64)[sl]
        ladder = _retry_ladder(model_kwargs)[:max(0, int(max_retries))]
        for i in np.nonzero(failed)[0]:
            for attempt, overrides in enumerate(ladder, start=1):
                retries[i] = attempt
                lean = solve_calibration_lean(
                    crra_h[i], rho_h[i], labor_sd=sd_h[i], dtype=dtype,
                    **{**model_kwargs, **overrides})
                cell_status = int(lean.status)
                if not is_failure(cell_status):
                    r[i] = float(lean.r_star)
                    K[i] = float(lean.capital)
                    L[i] = float(lean.labor)
                    iters[i] = int(lean.bisect_iters)
                    egm_it[i] = int(lean.egm_iters)
                    dist_it[i] = int(lean.dist_iters)
                    status[i] = cell_status
                    break
        still = np.nonzero(is_failure(status))[0]
        # NaN-mask what the retries could not certify: a failed cell must
        # read as failed everywhere, not as a plausible number
        r[still] = np.nan
        K[still] = np.nan
        if len(still):
            import warnings
            warnings.warn(
                "table2 sweep: cells "
                + ", ".join(f"{int(i)} ({status_name(status[i])})"
                            for i in still)
                + " failed every quarantine retry; their values are "
                "NaN-masked in the SweepResult", stacklevel=2)

    # Host-side closed forms (firm.py identities in numpy — numpy, not jnp,
    # so nothing touches the device after the solve): demand from the
    # inverted marginal product of capital, Y from Cobb-Douglas, s = delta*K/Y.
    alpha = model_kwargs.get("cap_share", 0.36)
    delta = model_kwargs.get("depr_fac", 0.08)
    prod = model_kwargs.get("prod", 1.0)
    demand = ((r + delta) / (prod * alpha)) ** (1.0 / (alpha - 1.0)) * L
    output = prod * K ** alpha * L ** (1.0 - alpha)
    srate = delta * K / output
    return SweepResult(
        crra=np.asarray(crra)[sl], labor_ar=rho_label[sl],
        labor_sd=np.asarray(sd)[sl],
        r_star_pct=r * 100.0, saving_rate_pct=srate * 100.0,
        capital=K, excess=K - demand,
        bisect_iters=iters, egm_iters=egm_it, dist_iters=dist_it,
        wall_seconds=wall,
        dist_method=str(model_kwargs["dist_method"]),
        status=status, retries=retries)
