"""Device-mesh helpers: mesh construction, the ``shard_map`` version shim,
and the sharded-launch wrapper every multi-chip dispatch rides (ISSUE 11).

The reference is single-process NumPy (SURVEY.md §2.4); its latent parallel
axes are the calibration sweep (embarrassingly parallel — the domain's "data
parallelism") and the agent panel (sharded with a mean-reduction each period).
Here those become named axes of a ``jax.sharding.Mesh``:

  * ``"cells"``  — Table II calibration cells (σ×ρ); no cross-cell
    communication, gather only at the end (DCN-friendly).
  * ``"agents"`` — the simulated household panel; each period ends in a
    cross-shard mean (``psum`` over ICI).

``sharded_launcher`` is the ONE way a batched per-lane program (the sweep's
``_batched_solver`` family, the serve batcher's flush executable) goes
multi-chip: ``jit(shard_map(fn))`` over the lane axis, each device running
the identical per-lane code on its contiguous lane block with NO cross-device
traffic until the output gather — manual SPMD, so GSPMD cannot invent
collectives inside the while loops.  Memoized per (fn, mesh, axis) so a
warmed process owns ONE sharded executable per underlying program, exactly
the shared-executable discipline of the 1-device paths.

Multi-chip hardware is exercised through ``--xla_force_host_platform_device_count``
virtual CPU devices in tests/bench (``utils.backend.force_cpu_platform``)
and through the driver's ``dryrun_multichip``.
"""

from __future__ import annotations

import re
import threading
from contextlib import contextmanager
from functools import lru_cache
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

#: The second, orthogonal mesh axis (ISSUE 20): partitions one cell's
#: STATE (distribution rows, wealth-operator row blocks) across devices,
#: where "cells" partitions the sweep lattice.  The 1-D ``cells_mesh``
#: is the degenerate ``state=1`` case — every pre-existing call site is
#: bit-identical by construction.
STATE_AXIS = "state"


def make_mesh(axis_names: Sequence[str] = ("cells",),
              axis_sizes: Optional[Sequence[int]] = None,
              devices=None) -> Mesh:
    """Build a mesh over the available devices.

    With ``axis_sizes=None`` all devices land on the first axis and the rest
    get size 1.  ``axis_sizes`` may leave one entry ``-1`` to absorb the
    remaining devices (numpy-reshape style).  An impossible grid — more
    than one ``-1``, or a device count not divisible by the known sizes —
    raises a ``ValueError`` naming both the requested grid and the device
    count (ISSUE 20 satellite; previously the multi-``-1`` path fell
    through to an inscrutable numpy reshape error).
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if axis_sizes is None:
        axis_sizes = [n] + [1] * (len(axis_names) - 1)
    axis_sizes = list(axis_sizes)
    requested = dict(zip(tuple(axis_names), tuple(axis_sizes)))
    if axis_sizes.count(-1) > 1:
        raise ValueError(
            f"mesh {requested} leaves more than one axis -1; at most one "
            f"axis may absorb the remaining devices")
    if -1 in axis_sizes:
        known = int(np.prod([s for s in axis_sizes if s != -1]))
        if known <= 0 or n % known:
            raise ValueError(
                f"cannot build mesh {requested} from {n} devices: the "
                f"device count is not divisible by the known axis sizes "
                f"(product {known})")
        axis_sizes[axis_sizes.index(-1)] = n // known
    total = int(np.prod(axis_sizes))
    if total > n:
        raise ValueError(f"mesh {requested} needs {total} devices, "
                         f"have {n}")
    grid = np.asarray(devices[:total]).reshape(axis_sizes)
    return Mesh(grid, tuple(axis_names))


def cells_mesh(devices=None, axis: str = "cells") -> Mesh:
    """One-axis mesh over ALL local devices (default) — the sweep/serve
    scale-out mesh (ISSUE 11).  On a TPU slice these are the real chips;
    on a host forced to N virtual CPU devices
    (``utils.backend.force_cpu_platform(n)``) they are the CPU smoke's
    stand-ins.  ``cells_mesh()`` on a 1-device host is a valid (trivial)
    mesh, so callers can pass it unconditionally."""
    return make_mesh((axis,), devices=devices)


def state_mesh(state_shards: int, devices=None,
               axis: str = "cells") -> Optional[Mesh]:
    """The 2-D ``(cells × state)`` mesh (ISSUE 20 tentpole): all local
    devices factored into ``n_devices // state_shards`` lane groups of
    ``state_shards`` state shards each.  ``state_shards=1`` returns the
    plain 1-D lane mesh (``None`` on a 1-device host) so every existing
    call site sees exactly the geometry it saw before; a device count not
    divisible by ``state_shards`` raises the typed ``make_mesh`` error
    naming both shapes."""
    state_shards = int(state_shards)
    if state_shards < 1:
        raise ValueError(f"state_shards must be >= 1, got {state_shards}")
    if state_shards == 1:
        return resolve_mesh("auto", axis=axis)
    return make_mesh((axis, STATE_AXIS), (-1, state_shards),
                     devices=devices)


def sharding(mesh: Mesh, *spec) -> NamedSharding:
    """``NamedSharding(mesh, PartitionSpec(*spec))`` shorthand."""
    return NamedSharding(mesh, PartitionSpec(*spec))


def shard_map_compat(fn, mesh, in_specs, out_specs):
    """``shard_map`` across jax versions — THE one spelling of the shim
    (ISSUE 11 satellite; previously private to ``parallel.panel``): the
    top-level ``jax.shard_map`` (with ``check_vma``) landed after 0.4.x;
    older jaxlibs ship it as ``jax.experimental.shard_map.shard_map``
    (with ``check_rep``).  The replication check is disabled in both
    spellings: the panel's per-period ``pmean`` already replicates its
    aggregates by construction, and the sweep/serve launchers have no
    replicated outputs at all (every output is lane-sharded)."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map

    return shard_map(fn, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs, check_rep=False)


def lane_specs(axis: str = "cells") -> PartitionSpec:
    """The batch-axis partition spec shared by every per-lane argument
    and output of a sharded launch: ``PartitionSpec(axis)`` used as a
    pytree PREFIX, so a rank-1 lane array shards its only dim and the
    packed ``[B, W]`` output shards its leading dim with the row
    replicated (the SNIPPETS [1] partition-rule pattern, collapsed to
    the one rule this program family needs: everything is lane-major)."""
    return PartitionSpec(axis)


@lru_cache(maxsize=None)
def sharded_launcher(fn, mesh: Mesh, axis: str = "cells"):
    """``jit(shard_map(fn))`` over the lane axis — the multi-chip launch
    wrapper for a batched per-lane program (ISSUE 11 tentpole).

    ``fn`` is a jitted vmapped ``(*per_lane_args) -> [B, W]`` program
    whose per-lane bits are independent of batch size, lane position, and
    batchmates (the packing-independence contract the sweep and serving
    layers property-test).  Each device therefore runs the IDENTICAL
    per-lane code on its contiguous ``B / n_devices`` lane block and the
    only cross-device traffic is the final output gather — which is what
    makes "sharded == 1-device bit-for-bit" a theorem about placement,
    not a numerical accident.  Every lane argument must have leading dim
    divisible by ``mesh.shape[axis]`` (pad with ``pad_to_multiple`` /
    the bucket planner's device-multiple padding first).

    Memoized per (fn, mesh, axis): ``fn`` comes from a memoized factory
    (``Scenario.batched_solver``) and ``Mesh`` hashes by device grid +
    axis names, so repeated launches — every bucket of a scheduled
    sweep, every warmed serve flush — reuse ONE wrapped executable and a
    replayed workload performs ZERO new XLA compiles."""
    spec = lane_specs(axis)
    return jax.jit(shard_map_compat(fn, mesh, in_specs=spec,
                                    out_specs=spec))


def mesh_axis_size(mesh: Optional[Mesh], axis: str) -> int:
    """Shard count of ``axis`` (1 for no mesh or an absent axis) — the
    one spelling of "how many ways is the lane axis split" shared by the
    sweep's bucket padding, the serve ladder rounding, and the resume
    ledger's mesh fingerprint."""
    if mesh is None:
        return 1
    return int(mesh.shape.get(axis, 1))


def resolve_mesh(mesh, axis: str = "cells") -> Optional[Mesh]:
    """The ONE spelling of the ``mesh=`` argument contract shared by
    ``run_sweep`` and ``EquilibriumService`` (ISSUE 11): ``None`` stays
    unsharded, ``"auto"`` builds the all-local-device lane mesh
    (trivially None on a 1-device host), any other string raises typed,
    and a real ``Mesh`` must actually DEFINE ``axis`` — a mesh without
    the lane axis would otherwise silently resolve to shard count 1 and
    run unsharded while the caller believes it is scaled out."""
    if mesh is None:
        return None
    if isinstance(mesh, str):
        if mesh != "auto":
            raise ValueError(f"mesh must be a Mesh, None, or 'auto', "
                             f"got {mesh!r}")
        return cells_mesh(axis=axis) if len(jax.devices()) > 1 else None
    if axis not in mesh.shape:
        raise ValueError(
            f"mesh axes {tuple(mesh.shape)} do not define the lane axis "
            f"{axis!r}; build one with cells_mesh(axis={axis!r}) or "
            f"make_mesh(({axis!r},), ...)")
    return mesh


def balanced_lane_order(work, n_shards: int) -> np.ndarray:
    """Lane permutation that balances per-device TOTAL WORK, not lane count.

    ``NamedSharding`` over a batch axis places CONTIGUOUS equal-size blocks
    of lanes on devices, so the only lever for load balance is the lane
    ORDER.  Given predicted per-lane work (len divisible by ``n_shards``),
    this assigns lanes to shards greedily — heaviest lane first, onto the
    currently lightest non-full shard (LPT scheduling, the classic 4/3-
    approximation) — and returns a permutation laying shard 0's lanes
    first, then shard 1's, etc.  Apply with ``x[perm]`` before
    ``device_put``; invert with ``np.argsort(perm)`` after the gather so
    results come back in caller order.

    With ``n_shards=1`` this is the identity-ordering no-op (single
    device: order cannot change total work)."""
    work = np.asarray(work, dtype=np.float64)
    n = work.shape[0]
    if n % n_shards:
        raise ValueError(f"{n} lanes not divisible by {n_shards} shards "
                         "(pad first: pad_to_multiple)")
    cap = n // n_shards
    if n_shards == 1:
        return np.arange(n)
    bins = [[] for _ in range(n_shards)]
    totals = np.zeros(n_shards)
    for lane in np.argsort(-work, kind="stable"):
        open_bins = [b for b in range(n_shards) if len(bins[b]) < cap]
        b = min(open_bins, key=lambda i: (totals[i], i))
        bins[b].append(int(lane))
        totals[b] += work[lane]
    return np.concatenate([np.asarray(b, dtype=np.int64) for b in bins])


# -- state-axis partition rules (ISSUE 20, DESIGN §6b) -----------------------
#
# The SNIPPETS [1] ``match_partition_rules`` pattern, scoped to the one
# tensor family this program needs: NAME the per-cell state tensors, match
# each name against a regex table, and let GSPMD place the collectives
# from ``with_sharding_constraint`` annotations.  Shapes (DESIGN §4):
#
#   distribution       [D, N]      wealth rows × labor states
#   wealth_operator    [N, D, D]   S[n, dest, src] — src is the
#                                  push-forward's contraction axis
#   policy             [..., K]    consumption knots, asset axis LAST
#
# Sharding the operator's SRC axis and the distribution's wealth rows
# the same way makes the einsum  "ndk,kn->dn"  a row-block contraction:
# each device holds 1/M of the operator and of the resident distribution
# and contributes a partial [D, N] product; the ONE all-reduce per step
# (psum / reduce-scatter, placed by GSPMD) restores the row-sharded
# iterate.  The labor-mixing matmul [D, N] × [N, N] stays row-sharded
# with no communication at all.

STATE_PARTITION_RULES = (
    (r"(^|/)distribution($|/)", PartitionSpec(STATE_AXIS, None)),
    (r"(^|/)wealth_operator($|/)", PartitionSpec(None, None, STATE_AXIS)),
    (r"(^|/)policy($|/)", PartitionSpec(None, STATE_AXIS)),
)


def match_partition_rules(name: str) -> PartitionSpec:
    """``PartitionSpec`` for a named state tensor — first
    ``STATE_PARTITION_RULES`` regex wins; an unknown name raises typed so
    a misspelled tensor cannot silently run replicated while the caller
    believes it is sharded."""
    for pattern, spec in STATE_PARTITION_RULES:
        if re.search(pattern, name):
            return spec
    known = tuple(p for p, _ in STATE_PARTITION_RULES)
    raise ValueError(
        f"no state partition rule matches {name!r}; rules: {known}")


def state_sharding(mesh: Mesh, name: str) -> NamedSharding:
    """``NamedSharding`` for a named state tensor on a state-axis mesh."""
    return NamedSharding(mesh, match_partition_rules(name))


def constrain_state(x, mesh: Optional[Mesh], name: str):
    """``with_sharding_constraint`` per the partition-rule table — the ONE
    way solver code pins a state tensor's layout (ISSUE 20).  A no-op
    (returns ``x`` untouched, zero trace difference) when there is no
    mesh or the mesh has no state axis of size > 1, which is what keeps
    the ``"replicated"`` path bit-identical by construction.  Must be
    applied INSIDE jitted code (the push closures) so the constraint
    propagates through ``lax.while_loop`` carries."""
    if mesh is None or mesh_axis_size(mesh, STATE_AXIS) <= 1:
        return x
    return jax.lax.with_sharding_constraint(x, state_sharding(mesh, name))


# The active state mesh rides a module-level context, not the kwarg
# plumbing: a ``Mesh`` is unhashable by ``utils.fingerprint.
# hashable_kwargs`` design (fingerprints hash the POLICY name plus the
# ledger's ``state_shards`` geometry instead), and threading a mesh
# through every solver signature would put device objects inside jit
# cache keys.  Thread-local so fleet workers / serve executors with
# different meshes cannot race each other's geometry.
_ACTIVE_STATE = threading.local()


@contextmanager
def active_state_mesh(mesh: Optional[Mesh]):
    """Activate ``mesh`` as the state-sharding geometry for the dynamic
    extent of the block (``None`` deactivates).  Solvers running
    ``state="sharded"`` read it via ``current_state_mesh()``; with no
    active mesh the sharded policy degrades to the replicated layout
    (``constrain_state`` no-ops)."""
    prev = getattr(_ACTIVE_STATE, "mesh", None)
    _ACTIVE_STATE.mesh = mesh
    try:
        yield mesh
    finally:
        _ACTIVE_STATE.mesh = prev


def current_state_mesh() -> Optional[Mesh]:
    """The mesh installed by the innermost ``active_state_mesh`` block
    (``None`` outside any block)."""
    return getattr(_ACTIVE_STATE, "mesh", None)


def pad_to_multiple(x, multiple: int, axis: int = 0):
    """Pad ``x`` along ``axis`` (edge-replicating) to a multiple of
    ``multiple``; returns (padded, original_length).  Sharded axes must divide
    the device count — sweep cells and agent panels are padded, solved, and
    sliced back."""
    n = x.shape[axis]
    rem = (-n) % multiple
    if rem == 0:
        return x, n
    pad_width = [(0, 0)] * x.ndim
    pad_width[axis] = (0, rem)
    return np.pad(np.asarray(x), pad_width, mode="edge"), n
