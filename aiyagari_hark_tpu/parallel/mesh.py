"""Device-mesh helpers.

The reference is single-process NumPy (SURVEY.md §2.4); its latent parallel
axes are the calibration sweep (embarrassingly parallel — the domain's "data
parallelism") and the agent panel (sharded with a mean-reduction each period).
Here those become named axes of a ``jax.sharding.Mesh``:

  * ``"cells"``  — Table II calibration cells (σ×ρ); no cross-cell
    communication, gather only at the end (DCN-friendly).
  * ``"agents"`` — the simulated household panel; each period ends in a
    cross-shard mean (``psum`` over ICI).

Multi-chip hardware is exercised through ``--xla_force_host_platform_device_count``
virtual CPU devices in tests and through the driver's ``dryrun_multichip``.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec


def make_mesh(axis_names: Sequence[str] = ("cells",),
              axis_sizes: Optional[Sequence[int]] = None,
              devices=None) -> Mesh:
    """Build a mesh over the available devices.

    With ``axis_sizes=None`` all devices land on the first axis and the rest
    get size 1.  ``axis_sizes`` may leave one entry ``-1`` to absorb the
    remaining devices (numpy-reshape style).
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if axis_sizes is None:
        axis_sizes = [n] + [1] * (len(axis_names) - 1)
    axis_sizes = list(axis_sizes)
    if -1 in axis_sizes:
        known = int(np.prod([s for s in axis_sizes if s != -1]))
        if n % known:
            raise ValueError(
                f"cannot infer -1 axis: {n} devices not divisible by the "
                f"known axis sizes (product {known})")
        axis_sizes[axis_sizes.index(-1)] = n // known
    total = int(np.prod(axis_sizes))
    if total > n:
        raise ValueError(f"mesh {tuple(axis_sizes)} needs {total} devices, "
                         f"have {n}")
    grid = np.asarray(devices[:total]).reshape(axis_sizes)
    return Mesh(grid, tuple(axis_names))


def sharding(mesh: Mesh, *spec) -> NamedSharding:
    """``NamedSharding(mesh, PartitionSpec(*spec))`` shorthand."""
    return NamedSharding(mesh, PartitionSpec(*spec))


def balanced_lane_order(work, n_shards: int) -> np.ndarray:
    """Lane permutation that balances per-device TOTAL WORK, not lane count.

    ``NamedSharding`` over a batch axis places CONTIGUOUS equal-size blocks
    of lanes on devices, so the only lever for load balance is the lane
    ORDER.  Given predicted per-lane work (len divisible by ``n_shards``),
    this assigns lanes to shards greedily — heaviest lane first, onto the
    currently lightest non-full shard (LPT scheduling, the classic 4/3-
    approximation) — and returns a permutation laying shard 0's lanes
    first, then shard 1's, etc.  Apply with ``x[perm]`` before
    ``device_put``; invert with ``np.argsort(perm)`` after the gather so
    results come back in caller order.

    With ``n_shards=1`` this is the identity-ordering no-op (single
    device: order cannot change total work)."""
    work = np.asarray(work, dtype=np.float64)
    n = work.shape[0]
    if n % n_shards:
        raise ValueError(f"{n} lanes not divisible by {n_shards} shards "
                         "(pad first: pad_to_multiple)")
    cap = n // n_shards
    if n_shards == 1:
        return np.arange(n)
    bins = [[] for _ in range(n_shards)]
    totals = np.zeros(n_shards)
    for lane in np.argsort(-work, kind="stable"):
        open_bins = [b for b in range(n_shards) if len(bins[b]) < cap]
        b = min(open_bins, key=lambda i: (totals[i], i))
        bins[b].append(int(lane))
        totals[b] += work[lane]
    return np.concatenate([np.asarray(b, dtype=np.int64) for b in bins])


def pad_to_multiple(x, multiple: int, axis: int = 0):
    """Pad ``x`` along ``axis`` (edge-replicating) to a multiple of
    ``multiple``; returns (padded, original_length).  Sharded axes must divide
    the device count — sweep cells and agent panels are padded, solved, and
    sliced back."""
    n = x.shape[axis]
    rem = (-n) % multiple
    if rem == 0:
        return x, n
    pad_width = [(0, 0)] * x.ndim
    pad_width[axis] = (0, rem)
    return np.pad(np.asarray(x), pad_width, mode="edge"), n
