"""Device-mesh helpers: mesh construction, the ``shard_map`` version shim,
and the sharded-launch wrapper every multi-chip dispatch rides (ISSUE 11).

The reference is single-process NumPy (SURVEY.md §2.4); its latent parallel
axes are the calibration sweep (embarrassingly parallel — the domain's "data
parallelism") and the agent panel (sharded with a mean-reduction each period).
Here those become named axes of a ``jax.sharding.Mesh``:

  * ``"cells"``  — Table II calibration cells (σ×ρ); no cross-cell
    communication, gather only at the end (DCN-friendly).
  * ``"agents"`` — the simulated household panel; each period ends in a
    cross-shard mean (``psum`` over ICI).

``sharded_launcher`` is the ONE way a batched per-lane program (the sweep's
``_batched_solver`` family, the serve batcher's flush executable) goes
multi-chip: ``jit(shard_map(fn))`` over the lane axis, each device running
the identical per-lane code on its contiguous lane block with NO cross-device
traffic until the output gather — manual SPMD, so GSPMD cannot invent
collectives inside the while loops.  Memoized per (fn, mesh, axis) so a
warmed process owns ONE sharded executable per underlying program, exactly
the shared-executable discipline of the 1-device paths.

Multi-chip hardware is exercised through ``--xla_force_host_platform_device_count``
virtual CPU devices in tests/bench (``utils.backend.force_cpu_platform``)
and through the driver's ``dryrun_multichip``.
"""

from __future__ import annotations

from functools import lru_cache
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec


def make_mesh(axis_names: Sequence[str] = ("cells",),
              axis_sizes: Optional[Sequence[int]] = None,
              devices=None) -> Mesh:
    """Build a mesh over the available devices.

    With ``axis_sizes=None`` all devices land on the first axis and the rest
    get size 1.  ``axis_sizes`` may leave one entry ``-1`` to absorb the
    remaining devices (numpy-reshape style).
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if axis_sizes is None:
        axis_sizes = [n] + [1] * (len(axis_names) - 1)
    axis_sizes = list(axis_sizes)
    if -1 in axis_sizes:
        known = int(np.prod([s for s in axis_sizes if s != -1]))
        if n % known:
            raise ValueError(
                f"cannot infer -1 axis: {n} devices not divisible by the "
                f"known axis sizes (product {known})")
        axis_sizes[axis_sizes.index(-1)] = n // known
    total = int(np.prod(axis_sizes))
    if total > n:
        raise ValueError(f"mesh {tuple(axis_sizes)} needs {total} devices, "
                         f"have {n}")
    grid = np.asarray(devices[:total]).reshape(axis_sizes)
    return Mesh(grid, tuple(axis_names))


def cells_mesh(devices=None, axis: str = "cells") -> Mesh:
    """One-axis mesh over ALL local devices (default) — the sweep/serve
    scale-out mesh (ISSUE 11).  On a TPU slice these are the real chips;
    on a host forced to N virtual CPU devices
    (``utils.backend.force_cpu_platform(n)``) they are the CPU smoke's
    stand-ins.  ``cells_mesh()`` on a 1-device host is a valid (trivial)
    mesh, so callers can pass it unconditionally."""
    return make_mesh((axis,), devices=devices)


def sharding(mesh: Mesh, *spec) -> NamedSharding:
    """``NamedSharding(mesh, PartitionSpec(*spec))`` shorthand."""
    return NamedSharding(mesh, PartitionSpec(*spec))


def shard_map_compat(fn, mesh, in_specs, out_specs):
    """``shard_map`` across jax versions — THE one spelling of the shim
    (ISSUE 11 satellite; previously private to ``parallel.panel``): the
    top-level ``jax.shard_map`` (with ``check_vma``) landed after 0.4.x;
    older jaxlibs ship it as ``jax.experimental.shard_map.shard_map``
    (with ``check_rep``).  The replication check is disabled in both
    spellings: the panel's per-period ``pmean`` already replicates its
    aggregates by construction, and the sweep/serve launchers have no
    replicated outputs at all (every output is lane-sharded)."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map

    return shard_map(fn, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs, check_rep=False)


def lane_specs(axis: str = "cells") -> PartitionSpec:
    """The batch-axis partition spec shared by every per-lane argument
    and output of a sharded launch: ``PartitionSpec(axis)`` used as a
    pytree PREFIX, so a rank-1 lane array shards its only dim and the
    packed ``[B, W]`` output shards its leading dim with the row
    replicated (the SNIPPETS [1] partition-rule pattern, collapsed to
    the one rule this program family needs: everything is lane-major)."""
    return PartitionSpec(axis)


@lru_cache(maxsize=None)
def sharded_launcher(fn, mesh: Mesh, axis: str = "cells"):
    """``jit(shard_map(fn))`` over the lane axis — the multi-chip launch
    wrapper for a batched per-lane program (ISSUE 11 tentpole).

    ``fn`` is a jitted vmapped ``(*per_lane_args) -> [B, W]`` program
    whose per-lane bits are independent of batch size, lane position, and
    batchmates (the packing-independence contract the sweep and serving
    layers property-test).  Each device therefore runs the IDENTICAL
    per-lane code on its contiguous ``B / n_devices`` lane block and the
    only cross-device traffic is the final output gather — which is what
    makes "sharded == 1-device bit-for-bit" a theorem about placement,
    not a numerical accident.  Every lane argument must have leading dim
    divisible by ``mesh.shape[axis]`` (pad with ``pad_to_multiple`` /
    the bucket planner's device-multiple padding first).

    Memoized per (fn, mesh, axis): ``fn`` comes from a memoized factory
    (``Scenario.batched_solver``) and ``Mesh`` hashes by device grid +
    axis names, so repeated launches — every bucket of a scheduled
    sweep, every warmed serve flush — reuse ONE wrapped executable and a
    replayed workload performs ZERO new XLA compiles."""
    spec = lane_specs(axis)
    return jax.jit(shard_map_compat(fn, mesh, in_specs=spec,
                                    out_specs=spec))


def mesh_axis_size(mesh: Optional[Mesh], axis: str) -> int:
    """Shard count of ``axis`` (1 for no mesh or an absent axis) — the
    one spelling of "how many ways is the lane axis split" shared by the
    sweep's bucket padding, the serve ladder rounding, and the resume
    ledger's mesh fingerprint."""
    if mesh is None:
        return 1
    return int(mesh.shape.get(axis, 1))


def resolve_mesh(mesh, axis: str = "cells") -> Optional[Mesh]:
    """The ONE spelling of the ``mesh=`` argument contract shared by
    ``run_sweep`` and ``EquilibriumService`` (ISSUE 11): ``None`` stays
    unsharded, ``"auto"`` builds the all-local-device lane mesh
    (trivially None on a 1-device host), any other string raises typed,
    and a real ``Mesh`` must actually DEFINE ``axis`` — a mesh without
    the lane axis would otherwise silently resolve to shard count 1 and
    run unsharded while the caller believes it is scaled out."""
    if mesh is None:
        return None
    if isinstance(mesh, str):
        if mesh != "auto":
            raise ValueError(f"mesh must be a Mesh, None, or 'auto', "
                             f"got {mesh!r}")
        return cells_mesh(axis=axis) if len(jax.devices()) > 1 else None
    if axis not in mesh.shape:
        raise ValueError(
            f"mesh axes {tuple(mesh.shape)} do not define the lane axis "
            f"{axis!r}; build one with cells_mesh(axis={axis!r}) or "
            f"make_mesh(({axis!r},), ...)")
    return mesh


def balanced_lane_order(work, n_shards: int) -> np.ndarray:
    """Lane permutation that balances per-device TOTAL WORK, not lane count.

    ``NamedSharding`` over a batch axis places CONTIGUOUS equal-size blocks
    of lanes on devices, so the only lever for load balance is the lane
    ORDER.  Given predicted per-lane work (len divisible by ``n_shards``),
    this assigns lanes to shards greedily — heaviest lane first, onto the
    currently lightest non-full shard (LPT scheduling, the classic 4/3-
    approximation) — and returns a permutation laying shard 0's lanes
    first, then shard 1's, etc.  Apply with ``x[perm]`` before
    ``device_put``; invert with ``np.argsort(perm)`` after the gather so
    results come back in caller order.

    With ``n_shards=1`` this is the identity-ordering no-op (single
    device: order cannot change total work)."""
    work = np.asarray(work, dtype=np.float64)
    n = work.shape[0]
    if n % n_shards:
        raise ValueError(f"{n} lanes not divisible by {n_shards} shards "
                         "(pad first: pad_to_multiple)")
    cap = n // n_shards
    if n_shards == 1:
        return np.arange(n)
    bins = [[] for _ in range(n_shards)]
    totals = np.zeros(n_shards)
    for lane in np.argsort(-work, kind="stable"):
        open_bins = [b for b in range(n_shards) if len(bins[b]) < cap]
        b = min(open_bins, key=lambda i: (totals[i], i))
        bins[b].append(int(lane))
        totals[b] += work[lane]
    return np.concatenate([np.asarray(b, dtype=np.int64) for b in bins])


def pad_to_multiple(x, multiple: int, axis: int = 0):
    """Pad ``x`` along ``axis`` (edge-replicating) to a multiple of
    ``multiple``; returns (padded, original_length).  Sharded axes must divide
    the device count — sweep cells and agent panels are padded, solved, and
    sliced back."""
    n = x.shape[axis]
    rem = (-n) % multiple
    if rem == 0:
        return x, n
    pad_width = [(0, 0)] * x.ndim
    pad_width[axis] = (0, rem)
    return np.pad(np.asarray(x), pad_width, mode="edge"), n
