"""Parallel layer: device meshes, the sharded Table II calibration sweep,
and device-sharded agent panels (SURVEY.md §2.4's latent axes made
first-class)."""

from . import multihost
from .mesh import (
    balanced_lane_order,
    cells_mesh,
    make_mesh,
    mesh_axis_size,
    pad_to_multiple,
    resolve_mesh,
    shard_map_compat,
    sharded_launcher,
    sharding,
)
from .panel import initial_panel_sharded, simulate_panel_sharded
from .sweep import (
    ScenarioSweepResult,
    SweepResult,
    run_sweep,
    run_table2_sweep,
)

__all__ = [
    "balanced_lane_order", "cells_mesh", "make_mesh", "mesh_axis_size",
    "pad_to_multiple", "resolve_mesh", "shard_map_compat",
    "sharded_launcher", "sharding",
    "initial_panel_sharded", "simulate_panel_sharded",
    "ScenarioSweepResult", "SweepResult", "run_sweep",
    "run_table2_sweep",
]
