"""Device-sharded agent panels: the Monte-Carlo simulation with the household
panel split across chips and the per-period aggregation riding a ``pmean``
collective over ICI.

The reference aggregates with ``np.mean`` over a single in-process array
(``Aiyagari_Support.py:1868``; SURVEY.md §5 "Distributed communication
backend").  Here the panel is sharded over the ``agents`` mesh axis with
``shard_map``; each scan step computes a local mean and a ``pmean``, so the
factor prices every shard sees are identical and the history is replicated.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
# The P(...) below are shard_map leaf LAYOUTS for this manual-SPMD
# region (per-argument specs, not ambient geometry); the Mesh name is
# only a type annotation — every mesh arrives already built by
# parallel.mesh.
from jax.sharding import Mesh, PartitionSpec as P  # mesh-ok: see above

from ..models.ks_model import KSCalibration, KSPolicy
from ..models.simulate import PanelState, initial_panel, simulate_panel
from .mesh import shard_map_compat

# The version shim lives in ``mesh.shard_map_compat`` now (ISSUE 11
# satellite: one shim, shared by panel/sweep/serve); the private name
# stays for existing callers.
_shard_map = shard_map_compat


def initial_panel_sharded(cal: KSCalibration, agent_count: int,
                          mrkv_init: int, key: jax.Array, mesh: Mesh,
                          axis: str = "agents") -> PanelState:
    """Birth a panel of ``agent_count`` agents sharded over ``axis``.

    ``agent_count`` must divide evenly (pad upstream with
    ``mesh.pad_to_multiple`` if not).  The global birth invariants (labor
    states spread evenly, employment at the state's unemployment rate) hold
    per shard, hence globally — but the *exact-count* employment machinery
    rounds per shard, so the realized global unemployment rate matches the
    target only to within n_shards/agent_count.  Keep at least ~100 agents
    per shard for that rounding bias to stay below other Monte-Carlo noise
    (tiny per-shard panels, e.g. the 8/shard in ``dryrun_multichip``, are
    for compile validation, not statistics).
    """
    n_shards = mesh.shape[axis]
    if agent_count % n_shards:
        raise ValueError(f"agent_count {agent_count} must divide the "
                         f"'{axis}' axis size {n_shards}")
    local = agent_count // n_shards
    keys = jax.random.split(key, n_shards)

    def birth(k):
        return initial_panel(cal, local, mrkv_init, k[0])

    spec_state = PanelState(assets=P(axis), labor_state=P(axis),
                            employed=P(axis), M_now=P(), R_now=P(),
                            W_now=P(), mrkv=P())
    return _shard_map(birth, mesh=mesh, in_specs=P(axis),
                      out_specs=spec_state)(keys)


def simulate_panel_sharded(policy: KSPolicy, cal: KSCalibration,
                           mrkv_hist: jnp.ndarray, init: PanelState,
                           key: jax.Array, mesh: Mesh, axis: str = "agents"):
    """``models.simulate.simulate_panel`` with the agent axis sharded.

    Returns the same (PanelHistory, final PanelState) contract; the history
    is replicated across shards (every shard computed identical aggregates
    through the ``pmean``), the final panel state stays sharded.
    """
    n_shards = mesh.shape[axis]
    keys = jax.random.split(key, n_shards)

    def run(mh, local_init, ks):
        return simulate_panel(policy, cal, mh, local_init, ks[0],
                              axis_name=axis)

    spec_state = PanelState(assets=P(axis), labor_state=P(axis),
                            employed=P(axis), M_now=P(), R_now=P(),
                            W_now=P(), mrkv=P())
    fn = _shard_map(
        run, mesh=mesh,
        in_specs=(P(), spec_state, P(axis)),
        out_specs=(P(), spec_state))
    return fn(mrkv_hist, init, keys)
