"""Batched piecewise-linear interpolation — the workhorse kernel.

The reference represents policies as trees of HARK interpolator objects
(``LinearInterp`` leaves under a ``LinearInterpOnInterp1D``, rebuilt 28x16
times per EGM step, ``Aiyagari_Support.py:1509-1516``) and pays Python
dispatch per state per evaluation.  Here a policy is *data*: knot arrays of
fixed shape, and evaluation is one fused searchsorted+gather+lerp, vmappable
over any batch axes and compiled by XLA into a handful of kernels.

Semantics match HARK's ``LinearInterp``: linear interpolation between knots,
**linear extrapolation** beyond both ends using the terminal segment slopes
(evaluation below the first knot only ever happens inside the prepended
borrowing-constraint segment in this framework, where the linear rule is the
exact constrained policy).  The two-level evaluation matches
``LinearInterpOnInterp1D``: interpolate in ``m`` within the two bracketing
M-columns, then linearly in ``M`` (with linear extrapolation in ``M`` too).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def interp1d(x: jnp.ndarray, xp: jnp.ndarray, fp: jnp.ndarray) -> jnp.ndarray:
    """Linear interpolation with linear extrapolation at both ends.

    ``x``: any shape of query points; ``xp``: [K] sorted knots; ``fp``: [K]
    values.  Clipping the bracket index to [0, K-2] makes queries outside the
    knot span ride the first/last segment's line — HARK ``LinearInterp``
    extrapolation semantics.
    """
    i = jnp.clip(jnp.searchsorted(xp, x, side="right") - 1, 0, xp.shape[0] - 2)
    x0 = xp[i]
    f0 = fp[i]
    slope = (fp[i + 1] - f0) / (xp[i + 1] - x0)
    return f0 + slope * (x - x0)


# vmapped over leading batch axes of (x, xp, fp) together: each row of queries
# gets its own knot vector — the "per-column endogenous grid" pattern of EGM.
interp1d_rowwise = jax.vmap(interp1d, in_axes=(0, 0, 0))


def interp_on_interp(m: jnp.ndarray, M: jnp.ndarray, Mgrid: jnp.ndarray,
                     m_knots: jnp.ndarray, f_knots: jnp.ndarray) -> jnp.ndarray:
    """Two-level policy evaluation at scalar aggregate state ``M``.

    ``m``: [...] idiosyncratic queries; ``Mgrid``: [Mc]; ``m_knots``/
    ``f_knots``: [Mc, K] per-M-column knot vectors.  Only the two bracketing
    M-columns are evaluated (the reference's ``LinearInterpOnInterp1D``
    evaluates the same two and lerps, ``Aiyagari_Support.py:1512-1513``).
    """
    j = jnp.clip(jnp.searchsorted(Mgrid, M, side="right") - 1, 0, Mgrid.shape[0] - 2)
    w = (M - Mgrid[j]) / (Mgrid[j + 1] - Mgrid[j])
    v0 = interp1d(m, m_knots[j], f_knots[j])
    v1 = interp1d(m, m_knots[j + 1], f_knots[j + 1])
    return v0 + w * (v1 - v0)


def eval_policy_agents(m: jnp.ndarray, state_idx: jnp.ndarray, M: jnp.ndarray,
                       Mgrid: jnp.ndarray, m_knots: jnp.ndarray,
                       f_knots: jnp.ndarray) -> jnp.ndarray:
    """Evaluate a state-indexed policy for a whole agent panel at once.

    ``m``: [N] market resources; ``state_idx``: [N] int discrete states;
    ``M``: scalar aggregate resources; ``m_knots``/``f_knots``: [S, Mc, K].
    Replaces the reference's 14 masked interpolator calls per simulated period
    (``Aiyagari_Support.py:1367-1408``) with two gathered rowwise interps.
    """
    j = jnp.clip(jnp.searchsorted(Mgrid, M, side="right") - 1, 0, Mgrid.shape[0] - 2)
    w = (M - Mgrid[j]) / (Mgrid[j + 1] - Mgrid[j])
    v0 = interp1d_rowwise(m, m_knots[state_idx, j], f_knots[state_idx, j])
    v1 = interp1d_rowwise(m, m_knots[state_idx, j + 1], f_knots[state_idx, j + 1])
    return v0 + w * (v1 - v0)


def append_tail_knot(m_knots: jnp.ndarray, c_knots: jnp.ndarray, slope):
    """Close a knot-array policy with an ANALYTIC linear tail (ISSUE 12,
    DESIGN §5b): append one knot per state at a span beyond the last
    endogenous knot, placed on the line of the given ``slope``.

    Because ``interp1d`` extrapolates beyond the last knot along the
    terminal segment, every evaluation above the previous top knot —
    interior of the tail segment and beyond it alike — then rides
    ``c(m) = c_top + slope * (m - m_top)``: the asymptotic linear form
    (slope = the model's MPC limit, ``ops.utility.asymptotic_mpc``)
    replaces grid interpolation above the compaction knee.  The span is
    scale-proportional (one grid-width past the top knot, floored at 1)
    so the synthetic knot stays strictly monotone in ``m`` for any
    borrow limit; its exact position is immaterial — the segment and its
    extrapolation share one slope.

    ``m_knots``/``c_knots``: [N, K]; ``slope`` a (possibly traced)
    scalar in (0, 1).  Returns [N, K+1] arrays.
    """
    m_top = m_knots[:, -1:]
    span = jnp.maximum(m_top - m_knots[:, :1], 1.0)
    m_tail = m_top + span
    c_tail = c_knots[:, -1:] + slope * span
    return (jnp.concatenate([m_knots, m_tail], axis=1),
            jnp.concatenate([c_knots, c_tail], axis=1))


def locate_in_grid(x: jnp.ndarray, grid: jnp.ndarray):
    """Bracket index and right-neighbor weight for histogram (Young-method)
    lotteries: ``x`` lands between ``grid[i]`` and ``grid[i+1]`` with weight
    ``w`` on the right neighbor.  Queries are clipped into the grid span so
    probability mass never leaves the histogram."""
    i = jnp.clip(jnp.searchsorted(grid, x, side="right") - 1, 0, grid.shape[0] - 2)
    w = (x - grid[i]) / (grid[i + 1] - grid[i])
    return i, jnp.clip(w, 0.0, 1.0)
