"""Grid construction utilities — and the grid-policy resolution seam.

TPU-native reimplementation of the grid semantics the reference relies on
(`/root/reference/Aiyagari_Support.py:875-890` constructs the asset grid with
HARK's ``make_grid_exp_mult(aMin, aMax, aCount, aNestFac)``).  The
multi-exponential grid is a standard HARK/econ-ark utility: apply
``x -> log(1+x)`` to the endpoints ``nest`` times, space linearly in that
transformed coordinate, then invert.  Points therefore cluster near the lower
endpoint, where the consumption function has curvature.

Grid COMPACTION (ISSUE 12, DESIGN §5b): the consumption function is
asymptotically linear in wealth (Ma-Stachurski-Toda arXiv:2002.09108), so
the dense high-wealth region of the reference grids buys nothing — the
curved region is confined to low wealth.  ``build_asset_grids`` is the ONE
resolution seam from a ``utils.config.GridSpec`` to concrete grids:
"reference" reproduces the historical grids bit-identically; "compact"/
"adaptive" spend the (smaller) point budget only below a knee ``a_hat``
and close the top either with an ANALYTIC linear tail (the solver appends
a tail knot at the asymptotic MPC slope — ``models.household``) or with
sparse geometric ANCHORS (the structural variant for solvers without a
tail contract).  Solver hot paths must route through this seam —
``scripts/check_grid_discipline.py`` bans direct ``make_asset_grid``/
``make_grid_exp_mult`` calls there (waiver ``# grid-ok``).

Grids are calibration constants with static sizes — they are built **once on
host in NumPy float64** (so the nested log/exp roundtrip doesn't erode the
endpoints) and cast to the requested device dtype at the end.  Never called
inside jit (under a trace they produce concrete constants: every input is
static configuration).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

# The grid-policy vocabulary lives next to the precision policy's in
# utils.config (host-side, importable by utils.fingerprint without jax);
# re-exported here because this module is the policy's resolution seam.
from ..utils.config import GRID_POLICIES, GridSpec, resolve_grid

__all__ = [
    "GRID_POLICIES", "GridSpec", "resolve_grid",
    "make_grid_exp_mult", "make_asset_grid",
    "compact_knee", "build_asset_grids", "grid_point_counts",
]


def _exp_mult_host(ming: float, maxg: float, ng: int,
                   timestonest: int) -> np.ndarray:
    """The host-side float64 exp-mult grid (the shared core of
    ``make_grid_exp_mult`` and the compact builders)."""
    ming = np.float64(ming)
    maxg = np.float64(maxg)
    if timestonest > 0:
        lo, hi = ming, maxg
        for _ in range(timestonest):
            lo = np.log(lo + 1.0)
            hi = np.log(hi + 1.0)
        grid = np.linspace(lo, hi, ng)
        for _ in range(timestonest):
            grid = np.exp(grid) - 1.0
    else:
        grid = np.exp(np.linspace(np.log(ming), np.log(maxg), ng))
    return grid


def make_grid_exp_mult(ming: float, maxg: float, ng: int, timestonest: int = 20,
                       dtype=None) -> jnp.ndarray:
    """Multi-exponentially spaced grid on [ming, maxg] with ``ng`` points.

    Matches the behavior of HARK's ``make_grid_exp_mult`` (called at
    ``Aiyagari_Support.py:880`` with ``timestonest = aNestFac``): with
    ``timestonest > 0`` the endpoints are pushed through ``log(1+x)`` that many
    times, a linear grid is laid out in the nested-log coordinate, and the
    transform is inverted pointwise.  ``timestonest == 0`` falls back to a
    plain exponential (log-linear) grid.

    Domain: both branches take logs of the lower endpoint — ``log(ming)``
    directly at ``timestonest == 0``, ``log(1 + ming)`` nested otherwise —
    so ``ming <= 0`` (resp. ``ming <= -1``) would silently produce
    NaN/-inf gridpoints that poison every downstream fixed point.  Raise
    the typed ``ValueError`` here instead (ISSUE 12 satellite).
    """
    if ng < 2:
        raise ValueError("need at least two grid points")
    if maxg <= ming:
        raise ValueError(
            f"grid endpoints must be ordered: ming={ming!r} >= maxg={maxg!r}")
    if timestonest > 0:
        if ming <= -1.0:
            raise ValueError(
                f"make_grid_exp_mult needs ming > -1 (log(1+x) nesting), "
                f"got ming={ming!r}")
    elif ming <= 0.0:
        raise ValueError(
            f"make_grid_exp_mult with timestonest=0 needs ming > 0 "
            f"(log-linear spacing takes log(ming)), got ming={ming!r}")
    return jnp.asarray(_exp_mult_host(ming, maxg, ng, timestonest),
                       dtype=dtype)


def make_asset_grid(a_min: float, a_max: float, a_count: int, nest_fac: int = 2,
                    dtype=None) -> jnp.ndarray:
    """End-of-period asset grid, reference defaults (0.001, 50, 32, nest 2)."""
    return make_grid_exp_mult(a_min, a_max, a_count, nest_fac, dtype=dtype)


# ---------------------------------------------------------------------------
# Compacted grids (ISSUE 12 tentpole).
# ---------------------------------------------------------------------------

def compact_knee(spec: GridSpec, a_min: float, span: float, a_count: int,
                 nest_fac: int) -> float:
    """The knee ``a_hat`` separating the curved low-wealth region (dense
    points) from the asymptotically-linear tail, on the UNSHIFTED span
    ``[a_min, a_min + span]`` (the borrow-limit shift is applied by the
    caller, exactly like the reference builders).

    Static ``knee_frac`` places it at that fraction of the span;
    ``knee_frac=None`` derives it from the reference grid's own density:
    the gridpoint below which the reference exp-mult grid already spends
    ``knee_density`` of its points — adaptive in the sense that a
    finer/more-nested reference profile moves the knee with it."""
    a_max = float(a_min) + float(span)
    if spec.knee_frac is not None:
        a_hat = float(a_min) + float(spec.knee_frac) * float(span)
    else:
        ref = _exp_mult_host(a_min, a_max, max(int(a_count), 2),
                             nest_fac)
        j = int(np.ceil(spec.knee_density * (len(ref) - 1)))
        a_hat = float(ref[min(j, len(ref) - 2)])
    # the knee must leave a real tail AND a real curved region
    lo = float(a_min) + 0.05 * float(span)
    hi = float(a_min) + 0.8 * float(span)
    return float(min(max(a_hat, lo), hi))


def _thin_tail(tail_ref: np.ndarray, n_keep: int) -> np.ndarray:
    """Evenly-thinned subset of the reference tail points, FIRST and LAST
    always kept (the top point is the support span — dropping it would
    silently shrink the domain savings are clipped into)."""
    n_keep = max(2, min(int(n_keep), len(tail_ref)))
    idx = np.unique(np.round(
        np.linspace(0, len(tail_ref) - 1, n_keep)).astype(int))
    return tail_ref[idx]


def _compact_host_grids(spec: GridSpec, a_min: float, span: float,
                        a_count: int, nest_fac: int, dist_count: int,
                        tail: str):
    """Host-side compact (solver points, histogram inner points, knee) —
    truncation of the reference grids (see ``build_asset_grids``)."""
    a_hat = compact_knee(spec, a_min, span, a_count, nest_fac)
    ref_a = _exp_mult_host(a_min, span, a_count, nest_fac)
    curved = ref_a[ref_a <= a_hat]
    if len(curved) < 4:
        curved = ref_a[:4]
    if tail == "anchors":
        tail_a = ref_a[len(curved):]
        if len(tail_a):
            curved = np.concatenate(
                [curved, _thin_tail(tail_a, spec.tail_points)])
    ref_d = _exp_mult_host(a_min, span, dist_count - 1, nest_fac)
    low = ref_d[ref_d <= a_hat]
    tail_d = ref_d[len(low):]
    if len(tail_d):
        n_keep = max(spec.tail_points,
                     int(np.ceil(spec.dist_tail_frac * len(tail_d))))
        inner = np.concatenate([low, _thin_tail(tail_d, n_keep)])
    else:
        inner = low
    return curved, inner, a_hat


def build_asset_grids(grid, a_min: float, a_max: float, a_count: int,
                      nest_fac: int, dist_count: int,
                      borrow_limit: float = 0.0, dtype=None,
                      tail: str = "analytic"):
    """THE grid-policy resolution seam (DESIGN §5b): concrete
    (end-of-period asset grid, wealth-histogram support) for one model
    build.  Returns ``(a_grid, dist_grid, a_hat)`` with ``a_hat`` the
    knee (``None`` under "reference").

    ``grid="reference"`` reproduces ``models.household.build_simple_model``'s
    historical construction BIT-identically (same calls, same order, same
    dtype casts).  Under "compact"/"adaptive" the compaction is a
    TRUNCATION of those same reference grids — the kept points are
    bit-identical subsets, so the curved region's discretization (and
    its contribution to r*) is exactly the goldens' own:

    * the solver grid keeps every reference point below the knee
      ``a_hat`` and drops the tail.  With ``tail="analytic"`` the solver
      closes the top with an analytic linear-tail knot at the asymptotic
      MPC slope (``models.household.egm_step`` — evaluation above the
      knee rides the asymptotic linear form instead of grid
      interpolation); with ``tail="anchors"`` an evenly-thinned subset
      of the reference tail points closes [a_hat, a_max] structurally
      (solvers without a tail contract: the anchors are exact solution
      points and the long segments between them are near-exact by
      asymptotic linearity);
    * the histogram support keeps its full reference density below the
      knee and crosses the tail on an evenly-thinned reference subset
      (``dist_tail_frac``; the top point is always kept) — the
      two-point lottery preserves the MEAN of assets exactly and the
      policy is asymptotically linear there, so tail coarseness is a
      second-order (curvature x spacing^2) effect.

    ``borrow_limit`` b <= 0 shifts both grids exactly as the reference
    construction does.
    """
    spec = resolve_grid(grid)
    span = a_max - borrow_limit

    if not spec.compact:
        a_grid = borrow_limit + make_asset_grid(a_min, span, a_count,
                                                nest_fac, dtype=dtype)
        inner = make_grid_exp_mult(a_min, span, dist_count - 1,
                                   nest_fac, dtype=dtype)
        dist_grid = borrow_limit + jnp.concatenate(
            [jnp.zeros((1,), dtype=inner.dtype), inner])
        return a_grid, dist_grid, None

    if tail not in ("analytic", "anchors"):
        raise ValueError(f"tail must be 'analytic' or 'anchors', "
                         f"got {tail!r}")
    curved, inner, a_hat = _compact_host_grids(
        spec, a_min, span, a_count, nest_fac, dist_count, tail)
    a_grid = borrow_limit + jnp.asarray(curved, dtype=dtype)
    inner = jnp.asarray(inner, dtype=dtype)
    dist_grid = borrow_limit + jnp.concatenate(
        [jnp.zeros((1,), dtype=inner.dtype), inner])
    return a_grid, dist_grid, float(a_hat)


def grid_point_counts(grid, a_count: int, dist_count: int,
                      a_min: float = 0.001, a_max: float = 50.0,
                      nest_fac: int = 2, borrow_limit: float = 0.0,
                      tail: str = "analytic") -> tuple:
    """Host-side (solver points, histogram points) one model build will
    use under ``grid`` — the bench's gridpoint-reduction accounting,
    computed without building a model."""
    spec = resolve_grid(grid)
    if not spec.compact:
        return int(a_count), int(dist_count)
    curved, inner, _ = _compact_host_grids(
        spec, a_min, a_max - borrow_limit, a_count, nest_fac,
        dist_count, tail)
    return int(len(curved)), int(len(inner)) + 1
