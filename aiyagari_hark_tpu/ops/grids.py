"""Grid construction utilities.

TPU-native reimplementation of the grid semantics the reference relies on
(`/root/reference/Aiyagari_Support.py:875-890` constructs the asset grid with
HARK's ``make_grid_exp_mult(aMin, aMax, aCount, aNestFac)``).  The
multi-exponential grid is a standard HARK/econ-ark utility: apply
``x -> log(1+x)`` to the endpoints ``nest`` times, space linearly in that
transformed coordinate, then invert.  Points therefore cluster near the lower
endpoint, where the consumption function has curvature.

Grids are calibration constants with static sizes — they are built **once on
host in NumPy float64** (so the nested log/exp roundtrip doesn't erode the
endpoints) and cast to the requested device dtype at the end.  Never called
inside jit.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp


def make_grid_exp_mult(ming: float, maxg: float, ng: int, timestonest: int = 20,
                       dtype=None) -> jnp.ndarray:
    """Multi-exponentially spaced grid on [ming, maxg] with ``ng`` points.

    Matches the behavior of HARK's ``make_grid_exp_mult`` (called at
    ``Aiyagari_Support.py:880`` with ``timestonest = aNestFac``): with
    ``timestonest > 0`` the endpoints are pushed through ``log(1+x)`` that many
    times, a linear grid is laid out in the nested-log coordinate, and the
    transform is inverted pointwise.  ``timestonest == 0`` falls back to a
    plain exponential (log-linear) grid.
    """
    if ng < 2:
        raise ValueError("need at least two grid points")
    ming = np.float64(ming)
    maxg = np.float64(maxg)
    if timestonest > 0:
        lo, hi = ming, maxg
        for _ in range(timestonest):
            lo = np.log(lo + 1.0)
            hi = np.log(hi + 1.0)
        grid = np.linspace(lo, hi, ng)
        for _ in range(timestonest):
            grid = np.exp(grid) - 1.0
    else:
        grid = np.exp(np.linspace(np.log(ming), np.log(maxg), ng))
    return jnp.asarray(grid, dtype=dtype)


def make_asset_grid(a_min: float, a_max: float, a_count: int, nest_fac: int = 2,
                    dtype=None) -> jnp.ndarray:
    """End-of-period asset grid, reference defaults (0.001, 50, 32, nest 2)."""
    return make_grid_exp_mult(a_min, a_max, a_count, nest_fac, dtype=dtype)
