"""Markov-chain numerics: Tauchen discretization, stationary distributions,
and the composite transition matrices of the Aiyagari/Krusell-Smith state space.

The reference builds these in three places:
  * Tauchen AR(1) discretization via HARK's ``make_tauchen_ar1`` —
    ``/root/reference/Aiyagari_Support.py:885-887, 1694-1696`` (called with
    ``sigma = LaborSD * sqrt(1 - LaborAR**2)`` so that ``LaborSD`` is the
    *stationary* standard deviation, and ``bound = 3.0``).
  * A 2x2 aggregate matrix and a 4x4 employment-conditional matrix from mean
    durations — ``Aiyagari_Support.py:1647-1683``.
  * The full idiosyncratic transition matrix as a Kronecker blow-up of the
    Tauchen matrix with the employment matrix, written out as 49 literal
    blocks in the reference (``Aiyagari_Support.py:1715-1780``); here it is a
    single ``jnp.kron`` for any number of labor states (fixes the hard-coded
    N=7 quirk, SURVEY.md §3.6-2).

State ordering convention (identical to the reference): full state
``s = 4*labor_state + k`` with ``k`` in (Bad-Unemployed, Bad-Employed,
Good-Unemployed, Good-Employed).
"""

from __future__ import annotations

import math
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.scipy.stats import norm


class TauchenResult(NamedTuple):
    """Grid of (log-)states and row-stochastic transition matrix."""

    grid: jnp.ndarray        # [n] equally spaced points in the log state
    transition: jnp.ndarray  # [n, n]; transition[j, k] = P(next=k | cur=j)


def tauchen_ar1(n: int, sigma: float, ar_1: float, bound: float = 3.0,
                dtype=None) -> TauchenResult:
    """Tauchen (1986) discretization of ``y' = ar_1 * y + sigma * eps``.

    Matches HARK's ``make_tauchen_ar1(N, sigma, ar_1, bound)`` semantics
    (call sites ``Aiyagari_Support.py:887, 1696``): the grid spans
    ``± bound * sigma / sqrt(1 - ar_1^2)`` (i.e. ``bound`` stationary standard
    deviations), interior transition masses are normal CDF differences over
    half-bin widths, and the edge columns absorb the tails.
    """
    if n == 1:
        # Degenerate chain (deterministic income): one state at the
        # unconditional mean.  The general formulas below break here — with
        # a size-1 grid, ``grid[1]`` silently clamps to ``grid[0]`` (step 0)
        # and the absorbing-edge overwrites leave a non-stochastic [[~0.93]].
        return TauchenResult(grid=jnp.zeros((1,), dtype=dtype),
                             transition=jnp.ones((1, 1), dtype=dtype))
    sigma = jnp.asarray(sigma, dtype=dtype)
    ar_1 = jnp.asarray(ar_1, dtype=dtype)
    y_max = bound * sigma / jnp.sqrt(1.0 - ar_1 ** 2)
    grid = jnp.linspace(-y_max, y_max, n, dtype=dtype)
    step = grid[1] - grid[0]
    # z[j, k] = (grid[k] - ar_1 * grid[j]) / sigma, the standardized distance
    # from the conditional mean to each target gridpoint.
    cond_mean = ar_1 * grid[:, None]
    upper = norm.cdf((grid[None, :] + step / 2.0 - cond_mean) / sigma)
    lower = norm.cdf((grid[None, :] - step / 2.0 - cond_mean) / sigma)
    probs = upper - lower
    # Edge columns: everything below the first half-bin / above the last.
    probs = probs.at[:, 0].set(norm.cdf((grid[0] + step / 2.0 - cond_mean[:, 0]) / sigma))
    probs = probs.at[:, -1].set(1.0 - norm.cdf((grid[-1] - step / 2.0 - cond_mean[:, 0]) / sigma))
    return TauchenResult(grid=grid, transition=probs)


def tauchen_labor_process(n_states: int, labor_ar: float, labor_sd: float,
                          bound: float = 3.0, dtype=None) -> TauchenResult:
    """The reference's labor-supply process: AR(1) in logs with *stationary*
    s.d. ``labor_sd`` — innovation s.d. is ``labor_sd * sqrt(1 - ar^2)``
    (``Aiyagari_Support.py:885-887``)."""
    sigma_innov = labor_sd * (1.0 - labor_ar ** 2) ** 0.5
    return tauchen_ar1(n_states, sigma_innov, labor_ar, bound=bound, dtype=dtype)


def normalized_labor_states(tauchen_grid: jnp.ndarray) -> jnp.ndarray:
    """Labor-supply levels: ``exp(grid) / mean(exp(grid))``.

    NOTE: the reference normalizes by the *unweighted* mean over gridpoints
    (``Aiyagari_Support.py:985, 1265``), not the stationary-distribution mean;
    we reproduce that exactly for parity.
    """
    levels = jnp.exp(tauchen_grid)
    return levels / jnp.mean(levels)


def stationary_distribution(transition: jnp.ndarray, iters: int = 2000,
                            precision: str = "reference") -> jnp.ndarray:
    """Stationary row vector of a row-stochastic matrix by power iteration.

    Power iteration (rather than an eigensolver) keeps this jit-able and
    backend-agnostic; ``iters`` matmuls of an [n,n] matrix are negligible.

    ``precision`` (DESIGN §5): "reference" AND "mixed" run every squaring
    at HIGHEST — TPU f32 matmuls default to bf16 inputs and repeated
    squaring amplifies that rounding into percent-level stationary-mass
    errors, and for a persistent chain no affordable fixed polish can
    contract that error back out (a power-step polish contracts at the
    subdominant eigenvalue rate, which is exactly what is close to 1
    here).  This fixed point is a handful of [n,n] (n<=28) matmuls, so a
    cheap descent phase has nothing to save: "mixed" deliberately equals
    "reference", keeping its certified-accuracy contract.  Only "fast"
    (tolerance contract relaxed by definition) runs the squarings at
    DEFAULT precision with a short HIGHEST power-step polish against the
    original matrix — approximate, for exploratory work.
    """
    from ..utils.config import resolve_precision

    spec = resolve_precision(precision)
    cheap = spec.two_phase and not spec.polish   # "fast" only; see above
    n = transition.shape[0]
    pi = jnp.full((n,), 1.0 / n, dtype=transition.dtype)
    # Squaring the matrix log2(iters) times converges geometrically faster
    # than repeated vector products and is still a handful of tiny matmuls.
    mat = transition
    steps = max(1, math.ceil(math.log2(iters)))
    sq_precision = (jax.lax.Precision.DEFAULT if cheap
                    else jax.lax.Precision.HIGHEST)
    for _ in range(steps):
        mat = jnp.matmul(mat, mat, precision=sq_precision,
                         preferred_element_type=mat.dtype)
        mat = mat / jnp.sum(mat, axis=1, keepdims=True)
    pi = jnp.matmul(pi, mat, precision=sq_precision,
                    preferred_element_type=pi.dtype)
    pi = pi / jnp.sum(pi)
    if cheap:
        # best-effort polish: HIGHEST power steps against the exact
        # one-step matrix (contracts at the subdominant rate — enough for
        # well-mixing chains, approximate for persistent ones)
        for _ in range(8):
            pi = jnp.matmul(pi, transition,
                            precision=jax.lax.Precision.HIGHEST,
                            preferred_element_type=pi.dtype)
            pi = pi / jnp.sum(pi)
    return pi


# ---------------------------------------------------------------------------
# Cell-batched MXU push-forward (ISSUE 13 leg 2, DESIGN §4c).
# ---------------------------------------------------------------------------

def tile_wealth_operator(S: jnp.ndarray) -> jnp.ndarray:
    """Re-lay the per-state lottery operator ``S [N, D, D]``
    (``models.household.dense_wealth_operator``) as ONE ``[D, N·D]``
    left factor for the tile-shaped push-forward below:
    ``S_t[:, n·D + k] = S[n, :, k]`` — state-n's columns occupy column
    block n.  Built once per policy, like ``S`` itself."""
    n, d, _ = S.shape
    return jnp.transpose(S, (1, 0, 2)).reshape(d, n * d)


def tiled_wealth_push_forward(dist, S_t, P,
                              matmul_precision=jax.lax.Precision.HIGHEST):
    """One distribution step as ONE tile-shaped MXU contraction
    (ISSUE 13 leg 2): the asset lottery AND the labor mixing fused into
    a single ``[D, N·D] × [N·D, N]`` matmul,

        out[d, m] = sum_{n,k} S[n, d, k] · dist[k, n] · P[n, m],

    instead of the reference layout's ``vmap``-of-``[D,D]×[D,1]``
    matvecs followed by the small ``[D,N]×[N,N]`` mix.  On the MXU a
    1-wide matvec RHS wastes 127/128 of the systolic array while costing
    the same cycles as a full tile, so trading the matvec op count for
    one contraction whose dims are real tiles (contraction length
    ``N·D``, output tile ``[D, N]``) is a win exactly on the hardware
    this targets; under a vmapped sweep the lane axis becomes the
    ``dot_general`` batch dim, so the batch (cells × labor-states)
    dimension lands in the contraction/tile dims as one
    ``[C, D, N·D] × [C, N·D, N]`` batched contraction per step.

    NOT bit-identical to ``models.household._push_forward_dense`` (the
    fused contraction reorders the reductions — float-fusion noise,
    ~1e-15 relative), so it runs only under ``kernel="fused"`` (DESIGN
    §4c); the reference layout stays the default.

    Args: ``dist [D, N]``, ``S_t [D, N·D]`` (``tile_wealth_operator``),
    ``P [N, N]``.  Returns the next distribution ``[D, N]``."""
    n = P.shape[0]
    d = dist.shape[0]
    # mixed[n·D + k, m] = dist[k, n] · P[n, m]: the dist⊗P right factor
    mixed = (dist.T[:, :, None] * P[:, None, :]).reshape(n * d, n)
    return jnp.matmul(S_t, mixed, precision=matmul_precision,
                      preferred_element_type=dist.dtype)


# ---------------------------------------------------------------------------
# State-sharded push-forward (ISSUE 20, DESIGN §6b).
# ---------------------------------------------------------------------------

def sharded_wealth_push_forward(dist, S, P, mesh,
                                matmul_precision=jax.lax.Precision.HIGHEST):
    """One distribution step as a ROW-BLOCK-SHARDED contraction over the
    state mesh axis (ISSUE 20): each device holds 1/M of the resident
    distribution's wealth rows (``P("state", None)``) and 1/M of the
    dense operator's SOURCE-wealth blocks (``P(None, None, "state")``),
    computes its partial

        moved[d, n] = sum_{k in my block} S[n, d, k] · dist[k, n],

    and GSPMD places the ONE all-reduce per step that the contraction
    over the sharded ``k`` axis requires; re-constraining the output to
    row-sharded lets it fuse into a reduce-scatter.  The labor-mixing
    matmul ``[D, N] × [N, N]`` contracts over the REPLICATED labor axis,
    so it stays row-sharded with zero communication.  The fixed point
    therefore iterates on sharded residents — no gather until the solved
    distribution leaves the loop.

    NOT bit-identical to ``models.household._push_forward_dense``: the
    row-block contraction reorders the wealth-axis reduction (the same
    carve-out as ``tiled_wealth_push_forward``), so it runs only under
    ``state="sharded"`` and the replicated layout stays the default.

    Sharding constraints come from ``parallel.mesh.constrain_state`` (the
    one seam, per ``scripts/check_mesh_discipline.py``); with ``mesh``
    None or a degenerate state axis every constraint is a literal no-op
    and this IS the dense reference contraction.

    Args: ``dist [D, N]``, ``S [N, D, D]``
    (``models.household.dense_wealth_operator``), ``P [N, N]``.  Returns
    the next distribution ``[D, N]``."""
    from ..parallel.mesh import constrain_state

    dist = constrain_state(dist, mesh, "distribution")
    S = constrain_state(S, mesh, "wealth_operator")
    moved = jnp.einsum("ndk,kn->dn", S, dist,
                       precision=matmul_precision,
                       preferred_element_type=dist.dtype)
    moved = constrain_state(moved, mesh, "distribution")
    out = jnp.matmul(moved, P, precision=matmul_precision,
                     preferred_element_type=dist.dtype)
    return constrain_state(out, mesh, "distribution")


def aggregate_markov_matrix(dur_mean_b: float, dur_mean_g: float,
                            dtype=None) -> jnp.ndarray:
    """2x2 aggregate (Bad/Good) transition matrix from mean state durations
    (``Aiyagari_Support.py:1647-1651``): exit probability = 1 / duration."""
    prob_bg = 1.0 / dur_mean_b
    prob_gb = 1.0 / dur_mean_g
    return jnp.asarray(
        [[1.0 - prob_bg, prob_bg],
         [prob_gb, 1.0 - prob_gb]], dtype=dtype)


def employment_markov_matrix(dur_mean_b: float, dur_mean_g: float,
                             spell_mean_b: float, spell_mean_g: float,
                             urate_b: float, urate_g: float,
                             rel_prob_bg: float, rel_prob_gb: float,
                             dtype=None) -> jnp.ndarray:
    """4x4 joint (aggregate x employment) transition matrix, Krusell-Smith
    calibration identities (``Aiyagari_Support.py:1655-1683``).

    Row/column order: (Bad-Unemp, Bad-Emp, Good-Unemp, Good-Emp).  Rows sum to
    one; the within-quadrant entries are pinned down by mean unemployment-spell
    lengths and the requirement that unemployment rates stay at their
    state-specific levels; cross-quadrant entries use the relative-probability
    fudge factors of the original KS calibration.
    """
    prob_bg = 1.0 / dur_mean_b
    prob_gb = 1.0 / dur_mean_g
    prob_bb = 1.0 - prob_bg
    prob_gg = 1.0 - prob_gb

    m = jnp.zeros((4, 4), dtype=dtype)
    # Bad -> Bad quadrant: leave unemployment with prob 1/spell length.
    m = m.at[0, 1].set(prob_bb / spell_mean_b)
    m = m.at[0, 0].set(prob_bb * (1.0 - 1.0 / spell_mean_b))
    m = m.at[1, 0].set(urate_b / (1.0 - urate_b) * m[0, 1])
    m = m.at[1, 1].set(prob_bb - m[1, 0])
    # Good -> Good quadrant.
    m = m.at[2, 3].set(prob_gg / spell_mean_g)
    m = m.at[2, 2].set(prob_gg * (1.0 - 1.0 / spell_mean_g))
    m = m.at[3, 2].set(urate_g / (1.0 - urate_g) * m[2, 3])
    m = m.at[3, 3].set(prob_gg - m[3, 2])
    # Bad -> Good quadrant.
    m = m.at[0, 2].set(rel_prob_bg * m[2, 2] / prob_gg * prob_bg)
    m = m.at[0, 3].set(prob_bg - m[0, 2])
    m = m.at[1, 2].set((prob_bg * urate_g - urate_b * m[0, 2]) / (1.0 - urate_b))
    m = m.at[1, 3].set(prob_bg - m[1, 2])
    # Good -> Bad quadrant.
    m = m.at[2, 0].set(rel_prob_gb * m[0, 0] / prob_bb * prob_gb)
    m = m.at[2, 1].set(prob_gb - m[2, 0])
    m = m.at[3, 0].set((prob_gb * urate_b - urate_g * m[2, 0]) / (1.0 - urate_g))
    m = m.at[3, 1].set(prob_gb - m[3, 0])
    return m


def full_idiosyncratic_matrix(tauchen_transition: jnp.ndarray,
                              employment_matrix: jnp.ndarray) -> jnp.ndarray:
    """[4N, 4N] composite transition matrix.

    ``kron(P_tauchen, P_empl)`` — labor-state-major, employment-minor ordering,
    exactly the blow-up the reference spells out as 49 literal AuxMatrix blocks
    (``Aiyagari_Support.py:1712-1780``), valid for any number of labor states.
    """
    return jnp.kron(tauchen_transition, employment_matrix)
